"""bench_compare: diff the newest BENCH_r0*.json against the previous
run and print the full metric trajectory.

The driver snapshots every bench round as ``BENCH_r<NN>.json`` with the
shape ``{"n": round, "cmd": ..., "rc": ..., "tail": <stdout tail>}``
where ``tail`` holds the bench's JSON lines (one object per metric;
the headline line is re-emitted after every bench, so the LAST
occurrence of a metric wins). Nothing consumed those snapshots until
now — this tool turns them into:

- a **regression gate**: each metric in the newest round is compared
  against the previous round under a per-metric threshold (relative,
  direction-aware: tokens/s up is good, ms/token down is good), with
  exact gates for pass/fail parity metrics,
- a **trajectory table**: every metric's value across all rounds, so a
  slow drift is visible even when each single diff passes.

Usage::

    python -m tools.bench_compare [--dir REPO] [--threshold 0.25]
                                  [--strict] [--json]

``--strict`` exits 1 when any metric regresses (for CI); the default
always exits 0 so a noisy CPU round can't block a merge by itself.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["load_rounds", "parse_metrics", "compare", "trajectory",
           "main"]

# units where a SMALLER value is the improvement
_LOWER_BETTER_UNITS = {"ms"}
# metrics where a SMALLER value is the improvement regardless of unit
# (exposed-comm seconds: the T3 bucketed-backward overlap exists to
# shrink this number; checkpoint stall: the async save path exists to
# shrink it; quant wire ratio: compressed/uncompressed bytes-on-wire —
# quant_comm exists to shrink it; quant loss gap: int8+error-feedback
# final-loss drift vs the fp32 sync on the same deterministic horizon;
# sampler overhead: wall seconds the durable metrics-journal sampler
# costs the run — the observability tax must trend toward zero)
_LOWER_BETTER_METRICS = {"gpt13b_hybrid_grad_sync_exposed_seconds",
                         "ckpt_save_overlap_stall_seconds",
                         "gpt13b_hybrid_quant_wire_ratio",
                         "gpt13b_hybrid_quant_loss_gap",
                         "gpt13b_hybrid_sampler_overhead_seconds",
                         "serving_mixed_sampler_overhead_seconds"}
# metrics that must stay exactly at their expected value
_EXACT = {"pallas_kernel_parity_interpret": 1.0,
          "pallas_kernel_parity_onchip": 1.0,
          # MoE-on-mesh loss parity vs the single-device dense-dispatch
          # golden (<= 1e-5 on the CPU smoke) — pass/fail, never drifts
          "gpt_moe_hybrid_loss_parity": 1.0,
          # comm_overlap (bucketed grad sync) vs unbucketed on the same
          # program: bit-exact coalescing, <= 1e-5 gated — never drifts
          "gpt13b_hybrid_overlap_loss_parity": 1.0,
          # ZeRO stage-3 (shard-only params + bucketed just-in-time
          # gather) vs the stage-2 overlap line: the gather is pure
          # data movement, so the trajectory must match bit-on AND the
          # ledger's gather bytes must equal the (p-1) x shard closed
          # form (scan_trips-exact on the stacked seam) — never drifts
          "gpt13b_hybrid_stage3_loss_parity": 1.0,
          # stage-3 memory: measured state accounting == closed form
          # byte-for-byte, with the params component at exactly
          # 1/sharding_degree of the stage-2 replicated image
          "gpt13b_hybrid_stage3_mem_state_parity": 1.0,
          # host-offload tier vs the stage-3 line one knob apart: the
          # tier copies bytes (never re-derives), so the trajectory is
          # BIT-exact (max_abs_loss_diff == 0), the transfer ledger
          # pins to the per-slot shard-bytes closed form with d2h-h2d
          # conservation, and warm steps never recompile
          "gpt13b_hybrid_offload_loss_parity": 1.0,
          # offload memory: host_state component == closed form and
          # the device-resident image == stage3 minus host_state
          "gpt13b_hybrid_offload_mem_state_parity": 1.0,
          # the capability the tier buys: the 13B flagship geometry
          # over a 16 GB chip is trainable ONLY with the optimizer
          # tier offloaded (auto_tuner cost-model pricing)
          "gpt13b_hybrid_offload_overhbm_trainable": 1.0,
          # memory ledger: measured state accounting (shard_shape path)
          # == closed form (global shape / sharding degree), byte-for-
          # byte incl. ZeRO-2 scattered state + pp x vpp chunks
          # (observability/memledger.py) — exact on the CPU smoke
          "gpt13b_hybrid_mem_state_parity": 1.0,
          # serving KV pool: measured pool array bytes == page_bytes x
          # pool_pages closed form — exact everywhere
          "serving_mem_pool_parity": 1.0,
          # unified ragged paged-attention kernel vs its dense XLA
          # fallback on a mixed prefill-chunk/decode batch (chunk
          # straddling page boundaries) — pass/fail, never drifts
          "serving_ragged_kernel_parity": 1.0,
          # prefix-cache + greedy spec decode on the multi-tenant
          # trace: all three serves (prefix on / off / prefix+spec)
          # emit identical token streams, the fed+skipped token
          # ledgers partition the trace exactly, and the cache hit
          # rate clears its floor — pass/fail, never drifts
          "serving_prefix_spec_parity": 1.0,
          # disaggregated serving (ISSUE 20): the phase-split fleet
          # (prefill replicas streaming KV pages to decode replicas)
          # must emit token streams bit-identical to the unified
          # fleet on the same arrival trace — migration is pure data
          # movement, so any drift is corruption, never noise
          "serving_disagg_parity": 1.0,
          # migration wire bytes == pages x page_bytes + block-table
          # row, booked through the comm ledger's migrate axis — a
          # closed form of the served trace, exact everywhere
          "serving_disagg_migration_bytes": 1.0,
          # health monitor event counts on the DETERMINISTIC bench
          # lines: robust spike detection must stay silent on a clean
          # fixed-seed run — any event is a regression (either a real
          # numerical blow-up or a trigger-happy detector), never noise
          "gpt13b_hybrid_health_spike_events": 0.0,
          "ckpt_overlap_health_spike_events": 0.0}
# per-metric relative thresholds overriding the CLI default (CPU smoke
# lines are noisy; recompile counts are exact)
_THRESHOLDS = {
    "recompiles_after_warmup": 0.0,
    # quantized wire ratio is a closed form of static shapes — it only
    # moves when the bucket plan / quantized site set changes, so even
    # a small drift is a real structural change worth flagging
    "gpt13b_hybrid_quant_wire_ratio": 0.05,
    # int8+EF loss drift vs fp32 on a 6-step horizon is noise-scale
    # (~1e-4 abs on the smoke); the hard convergence gate (200-step
    # parity + EF-off divergence detection) lives in
    # tests/test_quant_comm.py — only a blow-up should flag here
    "gpt13b_hybrid_quant_loss_gap": 10.0,
    # the MoE hybrid smoke line runs a 3-way (dp x ep x mp) 8-vdev CPU
    # mesh — wall-clock noise is higher than single-axis smokes, so
    # only flag large tokens/s moves; on chip the default applies
    "gpt_moe_hybrid_smoke_tokens_per_sec": 0.5,
    # ms-scale exposed-comm timing on the CPU smoke swings with host
    # load; only a sustained blow-up should flag (on chip the exposed
    # tail is the headline, tracked by the trajectory table)
    "gpt13b_hybrid_grad_sync_exposed_seconds": 2.0,
    # checkpoint-save stall on the CPU smoke is ms-scale file I/O —
    # host-load noise dominates; the async_stall_lt_step bool on the
    # line carries the acceptance bound
    "ckpt_save_overlap_stall_seconds": 2.0,
    # TPOT p99 under the Poisson mixed-length stream ("ms" unit:
    # lower-better): on CPU the smoke value is host-scheduling noise
    # around ms-scale rounds, so only a sustained blow-up should flag;
    # on chip the chunked-on vs chunked-off ratio on the line itself
    # (vs_baseline > 1) carries the acceptance
    "serving_mixed_traffic_tpot_p99_ms": 1.0,
    # disagg fleet tail latencies + per-chip goodput ("ms" metrics are
    # lower-better): ms-scale rounds on the CPU smoke are host-
    # scheduling noise, and toy-scale migration overhead dominates the
    # goodput split — the unified-vs-disagg ratios on the lines
    # themselves carry the on-chip acceptance; the hard gates
    # (bit-parity, exact migration bytes) are the _EXACT rows above
    "serving_disagg_ttft_p99_ms": 1.0,
    "serving_disagg_tpot_p99_ms": 1.0,
    "serving_disagg_goodput_per_chip": 1.0,
    # TTFT p50 under the multi-tenant prefix trace ("ms" unit:
    # lower-better): ms-scale on the CPU smoke, so host-scheduling
    # noise dominates — the prefix-on vs prefix-off ratio on the line
    # itself (vs_baseline > 1) carries the acceptance
    "serving_prefix_ttft_p50_ms": 1.0,
    # cache hit rate is a closed form of the fixed-seed trace (system
    # prompt mix x page alignment) — it only moves when the admission
    # planner or eviction policy changes, so even small drift flags
    "serving_prefix_cache_hit_rate": 0.1,
    # committed tokens per verify step at the self-speculation
    # acceptance ceiling: a drop means the verify lattice is
    # rejecting drafts it should accept (or booking phantom rounds)
    "serving_spec_tokens_per_step": 0.1,
    # roofline HBM headroom (direction-aware: HIGHER is better — the
    # default direction — falling headroom means the config is walking
    # into the memory wall). 0 on CPU where peaks are unknown; on chip
    # batch/pool retunes legitimately move it, so gate loosely and let
    # tools/step_report.py's trajectory carry the narrative
    "gpt13b_hybrid_hbm_headroom_pct": 0.5,
    # run-level goodput (direction-aware: HIGHER is better — the
    # default direction — a falling percentage means wall time is
    # leaking into compile/stall/idle). The CPU smoke's absolute value
    # is compile-dominated at toy scale and swings with host load, so
    # gate loosely; tools/run_report.py and step_report --strict carry
    # the trajectory narrative
    "gpt13b_hybrid_goodput_pct": 0.5,
    "ckpt_overlap_goodput_pct": 0.5,
}
# line kinds that are status reports, not comparable measurements
_SKIP_UNITS = {"error", "needs_chips", "skipped", "ok"}


def load_rounds(directory: str) -> List[Tuple[int, str]]:
    """[(round_number, tail_text)] for every BENCH_r*.json, ascending."""
    out = []
    for name in sorted(os.listdir(directory)):
        m = re.match(r"BENCH_r(\d+)\.json$", name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        out.append((int(m.group(1)), str(doc.get("tail", ""))))
    out.sort()
    return out


def parse_metrics(tail: str) -> Dict[str, Dict[str, Any]]:
    """{metric: line-dict} from a round's stdout tail. Later lines win
    (the headline is re-emitted after every bench); status lines
    (error/needs_chips/...) are kept but marked unmeasurable."""
    out: Dict[str, Dict[str, Any]] = {}
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and "metric" in doc:
            out[str(doc["metric"])] = doc
    return out


def _measurable(line: Dict[str, Any]) -> bool:
    return line.get("unit") not in _SKIP_UNITS


def compare(prev: Dict[str, Dict[str, Any]],
            new: Dict[str, Dict[str, Any]],
            threshold: float) -> List[Dict[str, Any]]:
    """Per-metric diff of two rounds: value delta, relative change in
    the metric's GOOD direction, and a verdict in
    {improved, ok, regressed, new, gone, unmeasured}."""
    rows: List[Dict[str, Any]] = []
    for name in sorted(set(prev) | set(new)):
        a, b = prev.get(name), new.get(name)
        row: Dict[str, Any] = {"metric": name}
        if a is None or b is None:
            row.update(verdict="new" if a is None else "gone",
                       prev=a and a.get("value"),
                       value=b and b.get("value"))
            rows.append(row)
            continue
        if not (_measurable(a) and _measurable(b)):
            row.update(verdict="unmeasured", prev=a.get("value"),
                       value=b.get("value"),
                       note=b.get("error") or a.get("error") or "")
            rows.append(row)
            continue
        va, vb = float(a.get("value", 0.0)), float(b.get("value", 0.0))
        row.update(prev=va, value=vb, unit=b.get("unit", ""))
        if name in _EXACT:
            ok = vb == _EXACT[name]
            row["verdict"] = "ok" if ok else "regressed"
            row["why"] = "" if ok else f"expected {_EXACT[name]}"
            rows.append(row)
            continue
        lower_better = (b.get("unit") in _LOWER_BETTER_UNITS
                        or name in _LOWER_BETTER_METRICS)
        # relative change in the good direction: positive = improved
        base = abs(va) if va else 1.0
        rel = (va - vb) / base if lower_better else (vb - va) / base
        row["rel_change"] = round(rel, 4)
        thr = _THRESHOLDS.get(name, threshold)
        if rel < -thr:
            row["verdict"] = "regressed"
            row["why"] = (f"{rel * 100:+.1f}% vs previous "
                          f"(threshold -{thr * 100:.0f}%)")
        elif rel > thr:
            row["verdict"] = "improved"
        else:
            row["verdict"] = "ok"
        rows.append(row)
    return rows


def trajectory(rounds: List[Tuple[int, str]]
               ) -> Dict[str, List[Optional[float]]]:
    """{metric: [value per round, None where absent/unmeasurable]}."""
    parsed = [(n, parse_metrics(tail)) for n, tail in rounds]
    names = sorted({m for _, p in parsed for m in p})
    out: Dict[str, List[Optional[float]]] = {}
    for name in names:
        vals: List[Optional[float]] = []
        for _, p in parsed:
            line = p.get(name)
            vals.append(float(line["value"])
                        if line is not None and _measurable(line)
                        else None)
        out[name] = vals
    return out


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v:.4g}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_compare",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default .)")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="default relative regression threshold "
                         "(default 0.25 — CPU smoke lines are noisy)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any metric regresses")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the diff + trajectory as one JSON doc")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.dir)
    if len(rounds) < 2:
        print(f"bench_compare: need >= 2 BENCH_r*.json under "
              f"{args.dir!r}, found {len(rounds)}", file=sys.stderr)
        return 2
    (n_prev, t_prev), (n_new, t_new) = rounds[-2], rounds[-1]
    rows = compare(parse_metrics(t_prev), parse_metrics(t_new),
                   args.threshold)
    traj = trajectory(rounds)
    regressed = [r for r in rows if r["verdict"] == "regressed"]

    if args.as_json:
        print(json.dumps({"prev_round": n_prev, "new_round": n_new,
                          "diff": rows, "trajectory": traj,
                          "rounds": [n for n, _ in rounds],
                          "regressed": [r["metric"] for r in regressed]},
                         indent=1))
    else:
        print(f"bench_compare: r{n_prev:02d} -> r{n_new:02d}")
        width = max((len(r["metric"]) for r in rows), default=10)
        for r in rows:
            mark = {"regressed": "!!", "improved": "++", "ok": "  ",
                    "new": " +", "gone": " -",
                    "unmeasured": " ?"}[r["verdict"]]
            rel = r.get("rel_change")
            rel_s = f"{rel * 100:+7.1f}%" if rel is not None else \
                "        "
            print(f"{mark} {r['metric']:<{width}} "
                  f"{_fmt(r.get('prev')):>12} -> "
                  f"{_fmt(r.get('value')):>12} {rel_s} "
                  f"{r.get('why', r.get('note', ''))}")
        print(f"\ntrajectory ({', '.join(f'r{n:02d}' for n, _ in rounds)})")
        width = max((len(m) for m in traj), default=10)
        for name, vals in traj.items():
            print(f"   {name:<{width}} " +
                  " ".join(f"{_fmt(v):>12}" for v in vals))
        if regressed:
            print(f"\n{len(regressed)} regression(s): "
                  + ", ".join(r["metric"] for r in regressed))
    return 1 if (args.strict and regressed) else 0


if __name__ == "__main__":
    sys.exit(main())
