"""fleet_report: cross-host run view from per-host durable journals.

Each host of a fleet run writes two crash-durable journals under its
own run dir (typically the checkpoint base): ``goodput.jsonl`` (the
wall-clock attribution ledger, observability/goodput.py) and
``metrics.jsonl`` (the sampled metrics time-series,
observability/timeseries.py). This tool reads one directory per host
and renders the fleet-level picture no single host can see:

- **goodput lanes**: one lane per host — wall seconds, goodput_pct,
  restarts, per-segment split — plus the fleet min/max/mean goodput,
- **combined event timeline**: every host's health events, process
  (re)starts and recovery_restart segments merged onto one clock
  (t = seconds since the earliest run start across the fleet), each
  entry tagged with its host,
- **step-time skew**: per-host mean step seconds from the newest
  ``paddle_tpu_train_step_seconds`` journal sample; the headline skew
  is ``(slowest - median) / median`` — the straggler tax the
  synchronous step pays every iteration,
- **comm / offload byte totals**: per-host and fleet-summed
  ``paddle_tpu_comm_bytes_total`` and
  ``paddle_tpu_offload_transfer_bytes`` from the newest sample.

Usage::

    python -m tools.fleet_report <host-dir> [<host-dir> ...] [--json]

Host names are the directory basenames. Exit codes: 0 on success, 2
when no directory held any journal. Read-only, like run_report.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from paddle_tpu.observability import goodput as _gp
from paddle_tpu.observability import timeseries as _ts

__all__ = ["host_report", "fleet_report", "step_time_skew", "main"]

STEP_METRIC = "paddle_tpu_train_step_seconds"
BYTE_METRICS = ("paddle_tpu_comm_bytes_total",
                "paddle_tpu_offload_transfer_bytes")


def _host_name(d: str) -> str:
    return os.path.basename(os.path.normpath(d)) or d


def _last_sample(path: str) -> Optional[Dict[str, Any]]:
    if not os.path.isfile(path):
        return None
    samp = _ts.samples(_ts.read_journal(path))
    return samp[-1] if samp else None


def _series_total(sample: Dict[str, Any], name: str) -> Optional[float]:
    """Sum of every labelled series' value (counters/gauges) in one
    journal sample; None when the metric never appeared."""
    ent = (sample.get("m") or {}).get(name)
    if not ent:
        return None
    total = 0.0
    for _labels, val in ent.get("s", []):
        if isinstance(val, dict):       # histogram state: use sum
            total += float(val.get("sum", 0.0))
        else:
            total += float(val)
    return total


def _step_stats(sample: Dict[str, Any]) -> Optional[Dict[str, float]]:
    """Mean step seconds from the histogram state of the newest
    sample (all label series pooled)."""
    ent = (sample.get("m") or {}).get(STEP_METRIC)
    if not ent:
        return None
    count = 0
    total = 0.0
    for _labels, st in ent.get("s", []):
        if isinstance(st, dict):
            count += int(st.get("count", 0))
            total += float(st.get("sum", 0.0))
    if not count:
        return None
    return {"count": count, "sum": round(total, 6),
            "mean_s": round(total / count, 6)}


def host_report(d: str) -> Dict[str, Any]:
    """Everything one host dir's journals yield (missing pieces are
    None — a dir with neither journal reports both as None)."""
    out: Dict[str, Any] = {"dir": d, "host": _host_name(d),
                           "goodput": None, "timeline": [],
                           "step_time": None, "bytes": {}}
    gp_path = os.path.join(d, _gp.JOURNAL_NAME)
    if os.path.isfile(gp_path):
        records = _gp.read_journal(gp_path)
        if records:
            out["goodput"] = _gp.summarize(records)
            for r in records:
                if r.get("ev") == "run":
                    out["timeline"].append({
                        "ts": float(r["ts"]),
                        "what": "resume" if r.get("resumed")
                        else "start", "pid": r.get("pid")})
                elif r.get("ev") == "h":
                    e = {"ts": float(r.get("ts", 0.0)),
                         "what": r.get("kind", "event")}
                    for k in ("step", "value", "z", "reason"):
                        if k in r:
                            e[k] = r[k]
                    out["timeline"].append(e)
                elif (r.get("ev") == "e"
                        and r.get("seg") == "recovery_restart"):
                    out["timeline"].append({
                        "ts": float(r["t0"]),
                        "what": "recovery_restart",
                        "seconds": round(float(r["t1"])
                                         - float(r["t0"]), 3)})
    sample = _last_sample(os.path.join(d, _ts.JOURNAL_NAME))
    if sample is not None:
        out["step_time"] = _step_stats(sample)
        for name in BYTE_METRICS:
            total = _series_total(sample, name)
            if total is not None:
                out["bytes"][name] = round(total, 3)
    return out


def step_time_skew(hosts: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """``(slowest - median) / median`` over per-host mean step seconds
    — what the synchronous step loses to its slowest member."""
    means = sorted((h["step_time"]["mean_s"], h["host"])
                   for h in hosts if h.get("step_time"))
    if not means:
        return None
    vals = [m for m, _ in means]
    n = len(vals)
    median = (vals[n // 2] if n % 2
              else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
    worst, worst_host = means[-1]
    return {"median_s": round(median, 6), "max_s": round(worst, 6),
            "slowest_host": worst_host,
            "skew_pct": round(100.0 * (worst - median) / median, 2)
            if median else 0.0}


def fleet_report(dirs: List[str]) -> Dict[str, Any]:
    hosts = [host_report(d) for d in dirs]
    gp = [h["goodput"]["goodput_pct"] for h in hosts if h["goodput"]]
    t0 = min((e["ts"] for h in hosts for e in h["timeline"]),
             default=None)
    timeline: List[Dict[str, Any]] = []
    for h in hosts:
        for e in h["timeline"]:
            timeline.append({**e, "host": h["host"],
                             "t": round(e["ts"] - (t0 or 0.0), 3)})
    timeline.sort(key=lambda e: e["t"])
    for e in timeline:
        e.pop("ts", None)
    byte_totals: Dict[str, float] = {}
    for h in hosts:
        for name, v in h["bytes"].items():
            byte_totals[name] = round(byte_totals.get(name, 0.0) + v, 3)
    return {
        "hosts": hosts,
        "fleet": {
            "members": len(hosts),
            "goodput_pct": {
                "min": round(min(gp), 2), "max": round(max(gp), 2),
                "mean": round(sum(gp) / len(gp), 2)} if gp else None,
            "step_time_skew": step_time_skew(hosts),
            "bytes": byte_totals,
        },
        "timeline": timeline,
    }


def _print_report(rep: Dict[str, Any]) -> None:
    print(f"fleet_report: {rep['fleet']['members']} host(s)")
    width = max((len(h["host"]) for h in rep["hosts"]), default=4)
    print("\ngoodput lanes")
    for h in rep["hosts"]:
        s = h["goodput"]
        if s is None:
            print(f"  {h['host']:<{width}} (no goodput journal)")
            continue
        bar = "#" * int(round(0.4 * min(max(s["goodput_pct"], 0.0),
                                        100.0)))
        print(f"  {h['host']:<{width}} wall {s['wall_seconds']:>9.3f}s"
              f"  goodput {s['goodput_pct']:>6.2f}%  restarts "
              f"{s['restarts']}  {bar}")
    fl = rep["fleet"]
    if fl["goodput_pct"]:
        g = fl["goodput_pct"]
        print(f"  fleet goodput min {g['min']:.2f}%  max {g['max']:.2f}%"
              f"  mean {g['mean']:.2f}%")
    if fl["step_time_skew"]:
        sk = fl["step_time_skew"]
        print(f"\nstep-time skew: median {sk['median_s']:.6f}s  "
              f"max {sk['max_s']:.6f}s ({sk['slowest_host']})  "
              f"skew {sk['skew_pct']:.2f}%")
    if fl["bytes"]:
        print("\nfleet byte totals (newest sample per host, summed)")
        for name, v in sorted(fl["bytes"].items()):
            print(f"  {name:<42} {v:>16.0f}")
    if rep["timeline"]:
        print("\ncombined timeline (t = seconds since earliest start)")
        for e in rep["timeline"]:
            extra = " ".join(f"{k}={e[k]}" for k in
                             ("pid", "step", "value", "z", "seconds",
                              "reason") if k in e)
            print(f"  t+{e['t']:>10.3f}  {e['host']:<{width}} "
                  f"{e['what']:<18} {extra}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleet_report",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("dirs", nargs="+", metavar="host-dir",
                    help="one run dir per host (goodput.jsonl and/or "
                         "metrics.jsonl inside)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as one JSON doc")
    args = ap.parse_args(argv)

    rep = fleet_report(args.dirs)
    if (all(h["goodput"] is None and not h["bytes"]
            and h["step_time"] is None for h in rep["hosts"])):
        print("fleet_report: no goodput.jsonl or metrics.jsonl under "
              + ", ".join(repr(d) for d in args.dirs), file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(rep, indent=1))
        return 0
    _print_report(rep)
    return 0


if __name__ == "__main__":
    sys.exit(main())
