"""step_report: render the roofline verdicts + memory attribution of
every bench round as one trajectory table.

bench.py lines now carry a ``memory`` section (per-executable byte
classes from XLA's memory_analysis + the measured model-state
accounting with its analytic drift, observability/memledger.py) and a
``roofline`` verdict (compute-bound / hbm-bound / ici-bound with
per-resource headroom percentages). This tool joins them across the
driver's ``BENCH_r<NN>.json`` snapshots — the longitudinal view
``tools/bench_compare.py`` gives throughput numbers, for bottlenecks:

- **verdict table** (newest round): per bench line, the bound, the
  per-resource floor seconds, headroom percentages, and the measured
  step time they explain,
- **memory table** (newest round): per bench line, the executable's
  temp/argument/output bytes, the state-accounting components, and
  the analytic-vs-measured drift,
- **verdict trajectory**: one letter per round (C/H/I/?, for
  compute/hbm/ici/unknown) per metric, so a config drifting toward
  the memory wall is visible across rounds even while tokens/s holds.

Bench lines that carry a ``goodput`` section (run-level wall-clock
attribution, observability/goodput.py) additionally get a **goodput
column** next to the verdicts: ``goodput_pct`` plus the per-segment
percentage breakdown, so "compute-bound at 60% goodput" reads as one
line. ``--strict`` exits 1 when the newest round's ``goodput_pct``
regresses against the previous round by more than
``--goodput-drop-pp`` percentage points on any line (the roofline /
memory tables stay report-only; bench_compare owns the throughput
gates).

Usage::

    python -m tools.step_report [--dir REPO] [--json] [--strict]

Exit codes: 0 on success, 1 on a --strict goodput regression, 2 when
no BENCH_r*.json rounds exist.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from tools.bench_compare import load_rounds, parse_metrics

__all__ = ["roofline_rows", "memory_rows", "verdict_trajectory",
           "goodput_rows", "goodput_regressions", "main"]

_BOUND_LETTER = {"compute-bound": "C", "hbm-bound": "H",
                 "ici-bound": "I", "unknown": "?"}


def _mb(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v / 1e6:.2f}M"


def roofline_rows(metrics: Dict[str, Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Per bench line carrying a ``roofline`` section: the verdict and
    its per-resource floors/headrooms, flattened for the table."""
    rows = []
    for name, line in sorted(metrics.items()):
        roof = line.get("roofline")
        if not isinstance(roof, dict):
            continue
        rows.append({
            "metric": name,
            "bound": roof.get("bound", "unknown"),
            "step_seconds": roof.get("step_seconds", 0.0),
            "seconds": dict(roof.get("seconds", {})),
            "headroom_pct": dict(roof.get("headroom_pct", {})),
            "util_pct": dict(roof.get("util_pct", {})),
        })
    return rows


def memory_rows(metrics: Dict[str, Dict[str, Any]]
                ) -> List[Dict[str, Any]]:
    """Per bench line carrying a ``memory`` section: executable byte
    classes (the single-program form AND the serving multi-executable
    form) + the state accounting."""
    rows = []
    for name, line in sorted(metrics.items()):
        mem = line.get("memory")
        if not isinstance(mem, dict):
            continue
        execs: Dict[str, Dict[str, Any]] = {}
        if isinstance(mem.get("executable"), dict) and mem["executable"]:
            execs[mem["executable"].get("program", "program")] = \
                mem["executable"]
        for prog, led in (mem.get("executables") or {}).items():
            execs[prog] = led
        state = mem.get("state", {})
        comps = state.get("components", state)
        rows.append({
            "metric": name,
            "executables": {
                prog: {k: led.get(k) for k in
                       ("temp_bytes", "argument_bytes", "output_bytes",
                        "alias_bytes", "peak_bytes")}
                for prog, led in sorted(execs.items())},
            "state": {k: v for k, v in comps.items()
                      if isinstance(v, (int, float))},
            "analytic_drift": state.get("analytic_drift",
                                        mem.get("analytic_drift")),
        })
    return rows


def goodput_rows(metrics: Dict[str, Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """Per bench line carrying a ``goodput`` section: the headline
    percentage and the per-segment breakdown, flattened for the
    table."""
    rows = []
    for name, line in sorted(metrics.items()):
        gp = line.get("goodput")
        if not isinstance(gp, dict):
            continue
        rows.append({
            "metric": name,
            "goodput_pct": float(gp.get("goodput_pct", 0.0)),
            "wall_seconds": float(gp.get("wall_seconds", 0.0)),
            "restarts": int(gp.get("restarts", 0)),
            "segment_pct": dict(gp.get("segment_pct", {})),
        })
    return rows


def goodput_regressions(prev: Dict[str, Dict[str, Any]],
                        new: Dict[str, Dict[str, Any]],
                        drop_pp: float) -> List[Dict[str, Any]]:
    """Lines whose ``goodput_pct`` fell by more than ``drop_pp``
    percentage points between two rounds (the --strict gate)."""
    prev_rows = {r["metric"]: r for r in goodput_rows(prev)}
    out = []
    for r in goodput_rows(new):
        p = prev_rows.get(r["metric"])
        if p is None:
            continue
        drop = p["goodput_pct"] - r["goodput_pct"]
        if drop > drop_pp:
            out.append({"metric": r["metric"],
                        "prev": p["goodput_pct"],
                        "value": r["goodput_pct"],
                        "drop_pp": round(drop, 2)})
    return out


def verdict_trajectory(rounds: List[Tuple[int, str]]
                       ) -> Dict[str, List[str]]:
    """{metric: [bound letter per round]} over every line that ever
    carried a roofline section ('-' where the round lacks it)."""
    parsed = [(n, parse_metrics(tail)) for n, tail in rounds]
    names = sorted({m for _, p in parsed for m, line in p.items()
                    if isinstance(line.get("roofline"), dict)})
    out: Dict[str, List[str]] = {}
    for name in names:
        letters = []
        for _, p in parsed:
            roof = (p.get(name) or {}).get("roofline")
            letters.append(_BOUND_LETTER.get(
                (roof or {}).get("bound", "unknown"), "?")
                if isinstance(roof, dict) else "-")
        out[name] = letters
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="step_report",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default .)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as one JSON doc")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when goodput_pct regresses vs the "
                         "previous round")
    ap.add_argument("--goodput-drop-pp", type=float, default=5.0,
                    help="--strict tolerance: max goodput_pct drop in "
                         "percentage points (default 5.0 — CPU smoke "
                         "wall clocks are noisy)")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"step_report: no BENCH_r*.json under {args.dir!r}",
              file=sys.stderr)
        return 2
    n_new, tail = rounds[-1]
    metrics = parse_metrics(tail)
    roof = roofline_rows(metrics)
    mem = memory_rows(metrics)
    goodput = goodput_rows(metrics)
    traj = verdict_trajectory(rounds)
    regressions: List[Dict[str, Any]] = []
    if len(rounds) >= 2:
        regressions = goodput_regressions(
            parse_metrics(rounds[-2][1]), metrics,
            args.goodput_drop_pp)

    if args.as_json:
        print(json.dumps({"round": n_new, "roofline": roof,
                          "memory": mem, "goodput": goodput,
                          "goodput_regressions": regressions,
                          "verdict_trajectory": traj,
                          "rounds": [n for n, _ in rounds]}, indent=1))
        return 1 if (args.strict and regressions) else 0

    print(f"step_report: round r{n_new:02d}")
    if not roof and not mem:
        print("  (no memory/roofline sections in this round — rerun "
              "bench.py with the memory ledger on)")
    gp_by_name = {r["metric"]: r for r in goodput}
    if goodput:
        width = max(len(r["metric"]) for r in goodput)
        print("\ngoodput (run-level wall-clock attribution; "
              "tools/run_report.py has the full waterfall)")
        for r in goodput:
            segs = " ".join(
                f"{seg} {pct:.0f}%" for seg, pct in sorted(
                    r["segment_pct"].items(), key=lambda kv: -kv[1])
                if pct >= 0.5)
            print(f"  {r['metric']:<{width}} "
                  f"{r['goodput_pct']:>6.2f}%  wall "
                  f"{r['wall_seconds']:.3g}s  restarts "
                  f"{r['restarts']}  [{segs}]")
    if roof:
        width = max(len(r["metric"]) for r in roof)
        print("\nroofline verdicts "
              "(floor seconds | headroom% compute/hbm/ici)")
        for r in roof:
            s, h = r["seconds"], r["headroom_pct"]
            # the goodput column: a bench line carrying both sections
            # reads "hbm-bound at 61% goodput" in one row
            gp = gp_by_name.get(r["metric"])
            gp_s = (f"  goodput {gp['goodput_pct']:.1f}%"
                    if gp is not None else "")
            print(f"  {r['metric']:<{width}} {r['bound']:>13}  "
                  f"step {r['step_seconds']:.4g}s  "
                  f"c {s.get('compute', 0):.3g}s/{h.get('compute', 0):.0f}% "
                  f"h {s.get('hbm', 0):.3g}s/{h.get('hbm', 0):.0f}% "
                  f"i {s.get('ici', 0):.3g}s/{h.get('ici', 0):.0f}%"
                  f"{gp_s}")
    if mem:
        print("\nmemory (per-executable + state accounting)")
        for r in mem:
            print(f"  {r['metric']}")
            for prog, led in r["executables"].items():
                print(f"    [{prog}] temp {_mb(led.get('temp_bytes'))} "
                      f"arg {_mb(led.get('argument_bytes'))} "
                      f"out {_mb(led.get('output_bytes'))} "
                      f"peak {_mb(led.get('peak_bytes'))}")
            if r["state"]:
                comps = " ".join(f"{k} {_mb(v)}"
                                 for k, v in sorted(r["state"].items()))
                print(f"    state: {comps}")
            if r.get("analytic_drift") is not None:
                print(f"    analytic drift: {r['analytic_drift']:+.2%}")
    if traj:
        print("\nverdict trajectory "
              f"({', '.join(f'r{n:02d}' for n, _ in rounds)}; "
              "C=compute H=hbm I=ici ?=unknown -=absent)")
        width = max(len(m) for m in traj)
        for name, letters in traj.items():
            print(f"  {name:<{width}} {' '.join(letters)}")
    if regressions:
        print(f"\n{len(regressions)} goodput regression(s): "
              + ", ".join(f"{r['metric']} {r['prev']:.1f}% -> "
                          f"{r['value']:.1f}% (-{r['drop_pp']:.1f}pp)"
                          for r in regressions))
    return 1 if (args.strict and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
