"""step_report: render the roofline verdicts + memory attribution of
every bench round as one trajectory table.

bench.py lines now carry a ``memory`` section (per-executable byte
classes from XLA's memory_analysis + the measured model-state
accounting with its analytic drift, observability/memledger.py) and a
``roofline`` verdict (compute-bound / hbm-bound / ici-bound with
per-resource headroom percentages). This tool joins them across the
driver's ``BENCH_r<NN>.json`` snapshots — the longitudinal view
``tools/bench_compare.py`` gives throughput numbers, for bottlenecks:

- **verdict table** (newest round): per bench line, the bound, the
  per-resource floor seconds, headroom percentages, and the measured
  step time they explain,
- **memory table** (newest round): per bench line, the executable's
  temp/argument/output bytes, the state-accounting components, and
  the analytic-vs-measured drift,
- **verdict trajectory**: one letter per round (C/H/I/?, for
  compute/hbm/ici/unknown) per metric, so a config drifting toward
  the memory wall is visible across rounds even while tokens/s holds.

Usage::

    python -m tools.step_report [--dir REPO] [--json]

Exit codes mirror bench_compare: 0 on success, 2 when no BENCH_r*.json
rounds exist. The tool only reads; it never gates (bench_compare owns
regression verdicts — the memory/roofline metric lines are registered
there).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from tools.bench_compare import load_rounds, parse_metrics

__all__ = ["roofline_rows", "memory_rows", "verdict_trajectory", "main"]

_BOUND_LETTER = {"compute-bound": "C", "hbm-bound": "H",
                 "ici-bound": "I", "unknown": "?"}


def _mb(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v / 1e6:.2f}M"


def roofline_rows(metrics: Dict[str, Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Per bench line carrying a ``roofline`` section: the verdict and
    its per-resource floors/headrooms, flattened for the table."""
    rows = []
    for name, line in sorted(metrics.items()):
        roof = line.get("roofline")
        if not isinstance(roof, dict):
            continue
        rows.append({
            "metric": name,
            "bound": roof.get("bound", "unknown"),
            "step_seconds": roof.get("step_seconds", 0.0),
            "seconds": dict(roof.get("seconds", {})),
            "headroom_pct": dict(roof.get("headroom_pct", {})),
            "util_pct": dict(roof.get("util_pct", {})),
        })
    return rows


def memory_rows(metrics: Dict[str, Dict[str, Any]]
                ) -> List[Dict[str, Any]]:
    """Per bench line carrying a ``memory`` section: executable byte
    classes (the single-program form AND the serving multi-executable
    form) + the state accounting."""
    rows = []
    for name, line in sorted(metrics.items()):
        mem = line.get("memory")
        if not isinstance(mem, dict):
            continue
        execs: Dict[str, Dict[str, Any]] = {}
        if isinstance(mem.get("executable"), dict) and mem["executable"]:
            execs[mem["executable"].get("program", "program")] = \
                mem["executable"]
        for prog, led in (mem.get("executables") or {}).items():
            execs[prog] = led
        state = mem.get("state", {})
        comps = state.get("components", state)
        rows.append({
            "metric": name,
            "executables": {
                prog: {k: led.get(k) for k in
                       ("temp_bytes", "argument_bytes", "output_bytes",
                        "alias_bytes", "peak_bytes")}
                for prog, led in sorted(execs.items())},
            "state": {k: v for k, v in comps.items()
                      if isinstance(v, (int, float))},
            "analytic_drift": state.get("analytic_drift",
                                        mem.get("analytic_drift")),
        })
    return rows


def verdict_trajectory(rounds: List[Tuple[int, str]]
                       ) -> Dict[str, List[str]]:
    """{metric: [bound letter per round]} over every line that ever
    carried a roofline section ('-' where the round lacks it)."""
    parsed = [(n, parse_metrics(tail)) for n, tail in rounds]
    names = sorted({m for _, p in parsed for m, line in p.items()
                    if isinstance(line.get("roofline"), dict)})
    out: Dict[str, List[str]] = {}
    for name in names:
        letters = []
        for _, p in parsed:
            roof = (p.get(name) or {}).get("roofline")
            letters.append(_BOUND_LETTER.get(
                (roof or {}).get("bound", "unknown"), "?")
                if isinstance(roof, dict) else "-")
        out[name] = letters
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="step_report",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default .)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as one JSON doc")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"step_report: no BENCH_r*.json under {args.dir!r}",
              file=sys.stderr)
        return 2
    n_new, tail = rounds[-1]
    metrics = parse_metrics(tail)
    roof = roofline_rows(metrics)
    mem = memory_rows(metrics)
    traj = verdict_trajectory(rounds)

    if args.as_json:
        print(json.dumps({"round": n_new, "roofline": roof,
                          "memory": mem,
                          "verdict_trajectory": traj,
                          "rounds": [n for n, _ in rounds]}, indent=1))
        return 0

    print(f"step_report: round r{n_new:02d}")
    if not roof and not mem:
        print("  (no memory/roofline sections in this round — rerun "
              "bench.py with the memory ledger on)")
    if roof:
        width = max(len(r["metric"]) for r in roof)
        print("\nroofline verdicts "
              "(floor seconds | headroom% compute/hbm/ici)")
        for r in roof:
            s, h = r["seconds"], r["headroom_pct"]
            print(f"  {r['metric']:<{width}} {r['bound']:>13}  "
                  f"step {r['step_seconds']:.4g}s  "
                  f"c {s.get('compute', 0):.3g}s/{h.get('compute', 0):.0f}% "
                  f"h {s.get('hbm', 0):.3g}s/{h.get('hbm', 0):.0f}% "
                  f"i {s.get('ici', 0):.3g}s/{h.get('ici', 0):.0f}%")
    if mem:
        print("\nmemory (per-executable + state accounting)")
        for r in mem:
            print(f"  {r['metric']}")
            for prog, led in r["executables"].items():
                print(f"    [{prog}] temp {_mb(led.get('temp_bytes'))} "
                      f"arg {_mb(led.get('argument_bytes'))} "
                      f"out {_mb(led.get('output_bytes'))} "
                      f"peak {_mb(led.get('peak_bytes'))}")
            if r["state"]:
                comps = " ".join(f"{k} {_mb(v)}"
                                 for k, v in sorted(r["state"].items()))
                print(f"    state: {comps}")
            if r.get("analytic_drift") is not None:
                print(f"    analytic drift: {r['analytic_drift']:+.2%}")
    if traj:
        print("\nverdict trajectory "
              f"({', '.join(f'r{n:02d}' for n, _ in rounds)}; "
              "C=compute H=hbm I=ici ?=unknown -=absent)")
        width = max(len(m) for m in traj)
        for name, letters in traj.items():
            print(f"  {name:<{width}} {' '.join(letters)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
