"""Measure the compiled-GPipe pipeline schedule instead of asserting it.

Round-4 verdict: the vpp>1 raise in pp_layers.py argued (but never
measured) that raising microbatch count M beats implementing 1F1B /
interleaved-vpp on TPU. This script measures, on the 8-virtual-device
CPU mesh (and on real hardware when present), step time vs M for
pp=2,4, derives the REALIZED bubble fraction, and compares it to the
analytic schedule bounds:

    GPipe / 1F1B bubble    = (S-1) / (M + S-1)   (same bubble; 1F1B's
                             win is activation MEMORY, which the
                             compiled pipeline already gets from
                             per-tick remat — memory flat in M,
                             tests/test_pipeline_parallel.py)
    interleaved vpp bubble = (S-1) / (vpp*M + S-1)

Realized bubble at M uses the marginal per-microbatch time tau
(slope between the two largest M): bubble = 1 - M*tau / t(M).
If compiled-GPipe at feasible M realizes a bubble <= what interleave
would give at small M, "raise M" wins and the numbers are recorded
where the vpp error message cites them (PP_SCHEDULE.json).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python tools/pp_schedule_measure.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

# default to the CPU mesh; PP_MEASURE_TPU=1 opts into real hardware
# (probing jax.default_backend() would initialize — and hang/fail on —
# the axon backend when the tunnel is down)
if os.environ.get("PP_MEASURE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def measure(pp: int, M_list, steps=6):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    results = {}
    for M in M_list:
        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": pp}
        strategy.pipeline_configs = {"accumulate_steps": M,
                                     "micro_batch_size": 2}
        fleet._fleet_state.update(initialized=False, hcg=None,
                                  strategy=None)
        hcg = fleet.init(is_collective=True, strategy=strategy)
        cfg = GPTConfig(vocab_size=512, hidden_size=128,
                        num_layers=pp * 2, num_heads=4,
                        max_position_embeddings=64)
        model = GPTForCausalLMPipe(cfg)
        dist_model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-4,
                                   parameters=model.parameters()))
        r = np.random.RandomState(0)
        B, S = 2 * M, 32
        ids = r.randint(0, cfg.vocab_size, (B, S + 1))
        x = paddle.to_tensor(ids[:, :-1])
        y = paddle.to_tensor(ids[:, 1:])
        loss = dist_model.train_batch([x, y], opt)     # compile+warm
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = dist_model.train_batch([x, y], opt)
        float(loss)
        dt = (time.perf_counter() - t0) / steps
        results[M] = dt
        print(f"  pp={pp} M={M:3d}  step={dt*1e3:8.1f} ms", flush=True)
    return results


def main():
    out = {"backend": jax.default_backend(),
           "n_devices": jax.device_count(), "pp": {}}
    for pp in (2, 4):
        M_list = [pp, 2 * pp, 4 * pp, 8 * pp]
        res = measure(pp, M_list)
        Ms = sorted(res)
        # marginal per-microbatch time from the two largest M
        tau = (res[Ms[-1]] - res[Ms[-2]]) / (Ms[-1] - Ms[-2])
        rows = []
        for M in Ms:
            realized = max(0.0, 1.0 - M * tau / res[M])
            gpipe = (pp - 1) / (M + pp - 1)
            vpp2 = (pp - 1) / (2 * M + pp - 1)
            rows.append({
                "M": M, "step_ms": round(res[M] * 1e3, 2),
                "bubble_realized": round(realized, 4),
                "bubble_analytic_gpipe_1f1b": round(gpipe, 4),
                "bubble_analytic_vpp2": round(vpp2, 4),
            })
        out["pp"][str(pp)] = {"tau_ms": round(tau * 1e3, 3), "rows": rows}
        # the decision number: does M=8S beat interleave-vpp2 at M=2S?
        big_M = rows[-1]["bubble_realized"]
        vpp2_small = (pp - 1) / (2 * (2 * pp) + pp - 1)
        out["pp"][str(pp)]["raise_M_beats_vpp2_at_2S"] = \
            bool(big_M <= vpp2_small)
        print(f"pp={pp}: tau={tau*1e3:.2f}ms  bubble(M={Ms[-1]})="
              f"{big_M:.3f} vs analytic vpp2@M={2*pp}:"
              f" {vpp2_small:.3f}", flush=True)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PP_SCHEDULE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
