"""Measure the compiled pipeline schedules instead of asserting them.

Round-4 verdict: the (former) vpp>1 raise in pp_layers.py argued (but
never measured) that raising microbatch count M beats interleaved-vpp
on TPU. PR 5 implemented the circular interleaved schedule, so this
script now measures BOTH schedules on the 8-virtual-device CPU mesh
(and on real hardware when present) at vpp=1 and vpp=2 for pp=2,4:

- ``step_ms``: full ``train_batch`` wall time (throughput view — same
  instrument as the PR-4 file, includes loss/optimizer/dispatch);
- ``pipe_ms`` and the REALIZED bubble: the pipelined middle's
  fwd+backward program ALONE (``PipelineLayer._pipe_fn`` + jax.vjp,
  jitted under shard_map). The bubble is a property of the schedule's
  scan, so it is measured on exactly that program — timing the whole
  train step would fold the M-independent optimizer update, grad
  psums, and host dispatch into the "bubble" and bias it upward at
  small M (that bias is how the PR-4 numbers overstated the vpp=1
  bubble at M=2).

Analytic bounds the realized columns sit next to:

    GPipe / 1F1B bubble    = (S-1) / (M + S-1)   (same bubble; 1F1B's
                             win is activation MEMORY, which the
                             compiled pipeline already gets from
                             per-tick remat — memory flat in M,
                             tests/test_pipeline_parallel.py)
    circular vpp bubble    = (S-1) / (vpp*M + S-1)

Realized bubble at M: least-squares marginal per-microbatch time tau
over the (min-of-repeats) pipe-program curve, bubble =
1 - M*tau/(t(M) - c). The M-independent harness floor c (jit dispatch
+ buffer setup, host work that is not schedule) is estimated JOINTLY
from the two curves — both LS intercepts satisfy b_v = (S-1)*tick_v +
c with tick_1 = tau_1, tick_2 = tau_2/2 — and removed; the raw
uncorrected bubbles are kept in the bubble_raw_* columns. What stays
measured is the schedule content: whether vpp=2's ticks are really
about half of vpp=1's and whether the leftover beyond M*tau matches
the (S-1) bubble ticks the analytic formula predicts.

The checked-in decision flags (PP_SCHEDULE.json), both sides REALIZED:
  - ``vpp2_beats_vpp1_at_equal_M``: the circular schedule must realize
    a strictly smaller bubble at every equal M;
  - ``raise_M_beats_vpp2_at_2S``: does vpp=1 at its feasible M=8S
    still beat circular vpp=2 at small M=2S? (Pre-implementation this
    was decided against the vpp2 ANALYTIC bound; the realized
    comparison is the honest one.)

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python tools/pp_schedule_measure.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

# default to the CPU mesh; PP_MEASURE_TPU=1 opts into real hardware
# (probing jax.default_backend() would initialize — and hang/fail on —
# the axon backend when the tunnel is down)
if os.environ.get("PP_MEASURE_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

SEQ = 32
MICRO = 2          # rows per microbatch (B = MICRO * M)


def _build(pp: int, vpp: int, M: int):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    paddle.seed(0)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": pp,
        "pp_configs": {"num_virtual_pipeline_stages": vpp}}
    strategy.pipeline_configs = {"accumulate_steps": M,
                                 "micro_batch_size": MICRO}
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    hcg = fleet.init(is_collective=True, strategy=strategy)
    # pp*2 layers: divisible by pp*vpp for vpp in {1, 2}, and the SAME
    # model for both schedules so equal-M rows compare fairly (PR-4's
    # model family, so step_ms stays comparable across rounds)
    cfg = GPTConfig(vocab_size=512, hidden_size=128,
                    num_layers=pp * 2, num_heads=4,
                    max_position_embeddings=64)
    model = GPTForCausalLMPipe(cfg)
    return hcg, cfg, model


def _time_min(run, steps: int, repeats: int) -> float:
    """min over ``repeats`` of mean-of-``steps``: robust to host
    contention spikes (a single slow block would fake a bubble)."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = run()
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def measure_step(pp: int, M_list, vpp: int = 1, steps: int = 6,
                 repeats: int = 3):
    """Full train_batch wall time (throughput view, PR-4 instrument)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet

    results = {}
    for M in M_list:
        hcg, cfg, model = _build(pp, vpp, M)
        dist_model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-4,
                                   parameters=model.parameters()))
        r = np.random.RandomState(0)
        B = MICRO * M
        ids = r.randint(0, cfg.vocab_size, (B, SEQ + 1))
        x = paddle.to_tensor(ids[:, :-1])
        y = paddle.to_tensor(ids[:, 1:])
        float(dist_model.train_batch([x, y], opt))     # compile+warm

        def run():
            return dist_model.train_batch([x, y], opt)._value

        results[M] = _time_min(run, steps, repeats)
        print(f"  [step] pp={pp} vpp={vpp} M={M:3d}  "
              f"{results[M]*1e3:8.1f} ms", flush=True)
    return results


def measure_pipe_all(pp: int, M_list, steps: int = 8, rounds: int = 5):
    """The pipelined middle's fwd+bwd program alone — the schedule's
    scan + ppermute + per-tick remat, nothing else.

    All (vpp, M) programs are built/compiled/warmed UP FRONT, then
    timed in interleaved rounds taking the per-config min: process
    state (allocator, threadpool, frequency) drifts over a run, and
    measuring configs back-to-back per round makes every config see
    the same ambient conditions instead of the first-measured ones
    eating the cold phase."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import collective as C
    from paddle_tpu.distributed.engine import _shard_map, global_put

    runners = {}
    for vpp in (1, 2):
        for M in M_list:
            hcg, cfg, model = _build(pp, vpp, M)
            mesh = hcg.mesh
            model._num_microbatches = M
            sparams = model.parameters_in_stacked_blocks
            svals = tuple(p._value for p in sparams)
            sspecs = tuple(p.dist_attr for p in sparams)
            fn = model._pipe_fn(M, jnp.uint32(7), ("pp",))

            def fwdbwd(x, *sv, _fn=fn):
                from jax import lax

                with C.spmd_region():
                    y, vjp = jax.vjp(_fn, x, *sv)
                    grads = vjp(jnp.ones_like(y))
                    # scalar probe so the fwd result is live; grads
                    # carry the reverse schedule's cost
                    return lax.psum(jnp.sum(y), "pp"), grads[1:]

            sm = _shard_map(fwdbwd, mesh, (P(),) + sspecs, (P(), sspecs))
            jfn = jax.jit(sm)
            r = np.random.RandomState(0)
            B = MICRO * M
            x = global_put(
                r.standard_normal(
                    (B, SEQ, cfg.hidden_size)).astype("float32"),
                mesh, P())
            jax.block_until_ready(jfn(x, *svals))      # compile+warm

            def run(_jfn=jfn, _x=x, _sv=svals):
                return _jfn(_x, *_sv)[0]

            runners[(vpp, M)] = run

    best = {k: float("inf") for k in runners}
    for _ in range(rounds):
        for k, run in runners.items():
            t0 = time.perf_counter()
            for _ in range(steps):
                out = run()
            jax.block_until_ready(out)
            best[k] = min(best[k], (time.perf_counter() - t0) / steps)
    for (vpp, M), t in sorted(best.items()):
        print(f"  [pipe] pp={pp} vpp={vpp} M={M:3d}  {t*1e3:8.1f} ms",
              flush=True)
    return ({M: best[(1, M)] for M in M_list},
            {M: best[(2, M)] for M in M_list})


def _fit(res):
    """Least-squares (tau, intercept) of the min-timed t(M) curve."""
    Ms = sorted(res)
    xs = np.array(Ms, dtype=float)
    ys = np.array([res[M] for M in Ms])
    tau, b = np.polyfit(xs, ys, 1)
    return float(tau), float(b)


def _realized_pair(pipe1, pipe2, S):
    """Realized bubbles of both schedules, floor-corrected.

    Model: t_v(M) = tick_v * T_v(M) + c, with T_v = v*M + S - 1 ticks
    of tick_v = tau_v / v each, and c an M-independent harness floor
    (jit dispatch + buffer setup — host work, not schedule). Both
    curves share c, so the two LS intercepts b_v = (S-1)*tick_v + c
    give two independent floor estimates; their mean is removed before
    computing bubble = 1 - M*tau_v/(t_v(M) - c).

    The raw (uncorrected) bubbles are reported alongside — the
    correction only removes the harness floor, the schedule content
    (is tick_2 really ~tick_1/2? does the leftover match (S-1) ticks?)
    stays measured."""
    tau1, b1 = _fit(pipe1)
    tau2, b2 = _fit(pipe2)
    c1 = b1 - (S - 1) * tau1            # tick_1 = tau_1
    c2 = b2 - (S - 1) * tau2 / 2.0      # tick_2 = tau_2 / 2
    c = max(0.0, (c1 + c2) / 2.0)

    def bub(res, tau):
        return {M: max(0.0, 1.0 - M * tau / max(res[M] - c, 1e-9))
                for M in res}

    def raw(res, tau):
        return {M: max(0.0, 1.0 - M * tau / res[M]) for M in res}

    return {"tau1": tau1, "tau2": tau2, "floor": c,
            "real1": bub(pipe1, tau1), "real2": bub(pipe2, tau2),
            "raw1": raw(pipe1, tau1), "raw2": raw(pipe2, tau2)}


def main():
    out = {"backend": jax.default_backend(),
           "n_devices": jax.device_count(), "pp": {}}
    for pp in (2, 4):
        M_list = [pp, 2 * pp, 4 * pp, 8 * pp]
        step1 = measure_step(pp, M_list, vpp=1)
        step2 = measure_step(pp, M_list, vpp=2)
        pipe1, pipe2 = measure_pipe_all(pp, M_list)
        r = _realized_pair(pipe1, pipe2, pp)
        real1, real2 = r["real1"], r["real2"]
        rows = []
        for M in M_list:
            gpipe = (pp - 1) / (M + pp - 1)
            vpp2 = (pp - 1) / (2 * M + pp - 1)
            rows.append({
                "M": M,
                "step_ms": round(step1[M] * 1e3, 2),
                "step_ms_vpp2": round(step2[M] * 1e3, 2),
                "pipe_ms": round(pipe1[M] * 1e3, 2),
                "pipe_ms_vpp2": round(pipe2[M] * 1e3, 2),
                "bubble_realized": round(real1[M], 4),
                "bubble_realized_vpp2": round(real2[M], 4),
                "bubble_raw": round(r["raw1"][M], 4),
                "bubble_raw_vpp2": round(r["raw2"][M], 4),
                "bubble_analytic_gpipe_1f1b": round(gpipe, 4),
                "bubble_analytic_vpp2": round(vpp2, 4),
            })
        entry = {"tau_ms": round(r["tau1"] * 1e3, 3),
                 "tau_ms_vpp2": round(r["tau2"] * 1e3, 3),
                 "dispatch_floor_ms": round(r["floor"] * 1e3, 3),
                 "rows": rows}
        # decision numbers, both sides REALIZED now that the circular
        # schedule exists (see module docstring)
        big_M = real1[M_list[-1]]
        vpp2_small = real2[2 * pp]
        entry["raise_M_beats_vpp2_at_2S"] = bool(big_M <= vpp2_small)
        entry["vpp2_beats_vpp1_at_equal_M"] = bool(
            all(real2[M] < real1[M] for M in M_list))
        out["pp"][str(pp)] = entry
        print(f"pp={pp}: tau={r['tau1']*1e3:.2f}ms "
              f"tau_vpp2={r['tau2']*1e3:.2f}ms "
              f"floor={r['floor']*1e3:.2f}ms  "
              f"bubble(vpp1,M={M_list[-1]})={big_M:.3f} vs realized "
              f"vpp2@M={2*pp}: {vpp2_small:.3f}", flush=True)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PP_SCHEDULE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
