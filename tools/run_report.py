"""run_report: render a run's goodput waterfall and health-event
timeline from the crash-durable goodput journal (+ the BENCH_r*
goodput trajectory).

The goodput ledger (paddle_tpu/observability/goodput.py) journals every
second of a — possibly crash-interrupted — run into
``<checkpoint base>/goodput.jsonl``: closed wall-clock segments from a
fixed taxonomy (compile / step_compute / ckpt_stall / ckpt_async /
restore / recovery_restart / input_wait / idle), process (re)start
markers, and the health monitor's anomaly events. This tool is the
human-facing view:

- **waterfall**: per-segment seconds and percentages of the run's wall
  clock (idle synthesized as the unattributed remainder, ckpt_async
  shown separately as overlapped), plus the headline ``goodput_pct`` =
  productive step seconds / wall seconds — spanning every restart the
  journal absorbed,
- **event timeline**: health events (loss/grad spikes, stalls,
  restart signals) and process restarts in run-relative time,
- **BENCH trajectory**: every bench line carrying a ``goodput``
  section, its ``goodput_pct`` across all BENCH_r*.json rounds (the
  longitudinal column next to bench_compare's throughput and
  step_report's roofline verdicts).

Usage::

    python -m tools.run_report --run-dir <ckpt base> [--bench-dir REPO]
                               [--json]
    python -m tools.run_report --merge <host-dir> <host-dir>... [--json]

``--merge`` overlays several hosts' goodput journals into one fleet
waterfall: a per-host lane each (wall / goodput_pct / restarts /
segment split, host = dir basename) plus a combined restart-and-event
timeline on the fleet clock (seconds since the earliest start any
journal recorded). The full cross-host view (step-time skew, byte
totals from metrics.jsonl) lives in tools/fleet_report.py.

Exit codes: 0 on success, 2 when neither a journal nor bench rounds
were found. The tool only reads; regression gating lives in
tools/bench_compare.py (``goodput_pct`` higher-better,
``*_health_spike_events`` exact-0) and ``tools/step_report.py
--strict``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.observability import goodput as _gp
from tools.bench_compare import load_rounds, parse_metrics

__all__ = ["journal_report", "goodput_trajectory", "merge_report",
           "main"]

_BAR_WIDTH = 40


def journal_report(base_or_path: str) -> Optional[Dict[str, Any]]:
    """Summary + timeline of one goodput journal (a checkpoint base
    dir or the journal file itself); None when no journal exists."""
    path = base_or_path
    if os.path.isdir(path):
        path = os.path.join(path, _gp.JOURNAL_NAME)
    if not os.path.isfile(path):
        return None
    records = _gp.read_journal(path)
    if not records:
        return None
    summary = _gp.summarize(records)
    t0 = None
    for r in records:
        if r.get("ev") == "run":
            t0 = float(r["ts"]) if t0 is None else min(t0, float(r["ts"]))
    timeline: List[Dict[str, Any]] = []
    for r in records:
        if r.get("ev") == "run":
            timeline.append({
                "t": round(float(r["ts"]) - (t0 or 0.0), 3),
                "what": "resume" if r.get("resumed") else "start",
                "pid": r.get("pid")})
        elif r.get("ev") == "h":
            e = {"t": round(float(r.get("ts", 0.0)) - (t0 or 0.0), 3),
                 "what": r.get("kind", "event")}
            for k in ("step", "value", "median", "z", "reason"):
                if k in r:
                    e[k] = r[k]
            timeline.append(e)
        elif r.get("ev") == "e" and r.get("seg") == "recovery_restart":
            timeline.append({
                "t": round(float(r["t0"]) - (t0 or 0.0), 3),
                "what": "recovery_restart",
                "seconds": round(float(r["t1"]) - float(r["t0"]), 3)})
    timeline.sort(key=lambda e: e["t"])
    return {"journal": path, "summary": summary, "timeline": timeline}


def goodput_trajectory(rounds: List[Tuple[int, str]]
                       ) -> Dict[str, List[Optional[float]]]:
    """{metric: [goodput_pct per round]} over every bench line that
    ever carried a ``goodput`` section (None where a round lacks it)."""
    parsed = [(n, parse_metrics(tail)) for n, tail in rounds]
    names = sorted({m for _, p in parsed for m, line in p.items()
                    if isinstance(line.get("goodput"), dict)})
    out: Dict[str, List[Optional[float]]] = {}
    for name in names:
        vals: List[Optional[float]] = []
        for _, p in parsed:
            gp = (p.get(name) or {}).get("goodput")
            vals.append(float(gp["goodput_pct"])
                        if isinstance(gp, dict)
                        and "goodput_pct" in gp else None)
        out[name] = vals
    return out


def merge_report(dirs: List[str]) -> Dict[str, Any]:
    """Overlay several hosts' goodput journals (one dir per host, host
    name = dir basename): per-host lanes plus a combined restart/event
    timeline on the fleet clock (earliest run start = t 0)."""
    hosts: List[Dict[str, Any]] = []
    for d in dirs:
        name = os.path.basename(os.path.normpath(d)) or d
        path = d
        if os.path.isdir(path):
            path = os.path.join(path, _gp.JOURNAL_NAME)
        lane: Dict[str, Any] = {"host": name, "dir": d,
                                "summary": None, "events": []}
        records = _gp.read_journal(path) if os.path.isfile(path) else []
        if records:
            lane["summary"] = _gp.summarize(records)
            for r in records:
                if r.get("ev") == "run":
                    lane["events"].append({
                        "ts": float(r["ts"]),
                        "what": "resume" if r.get("resumed")
                        else "start", "pid": r.get("pid")})
                elif r.get("ev") == "h":
                    e = {"ts": float(r.get("ts", 0.0)),
                         "what": r.get("kind", "event")}
                    for k in ("step", "value", "z", "reason"):
                        if k in r:
                            e[k] = r[k]
                    lane["events"].append(e)
                elif (r.get("ev") == "e"
                        and r.get("seg") == "recovery_restart"):
                    lane["events"].append({
                        "ts": float(r["t0"]),
                        "what": "recovery_restart",
                        "seconds": round(float(r["t1"])
                                         - float(r["t0"]), 3)})
        hosts.append(lane)
    t0 = min((e["ts"] for h in hosts for e in h["events"]),
             default=None)
    timeline: List[Dict[str, Any]] = []
    for h in hosts:
        for e in h["events"]:
            timeline.append({
                "t": round(e["ts"] - (t0 or 0.0), 3), "host": h["host"],
                **{k: v for k, v in e.items() if k != "ts"}})
        h.pop("events", None)
    timeline.sort(key=lambda e: e["t"])
    gp = [h["summary"]["goodput_pct"] for h in hosts if h["summary"]]
    return {
        "hosts": hosts,
        "fleet_goodput_pct": {
            "min": round(min(gp), 2), "max": round(max(gp), 2),
            "mean": round(sum(gp) / len(gp), 2)} if gp else None,
        "timeline": timeline,
    }


def _bar(pct: float) -> str:
    n = int(round(_BAR_WIDTH * min(max(pct, 0.0), 100.0) / 100.0))
    return "#" * n


def _print_merge(rep: Dict[str, Any]) -> None:
    print(f"run_report --merge: {len(rep['hosts'])} host lane(s)")
    width = max((len(h["host"]) for h in rep["hosts"]), default=4)
    for h in rep["hosts"]:
        s = h["summary"]
        if s is None:
            print(f"  {h['host']:<{width}} (no goodput journal under "
                  f"{h['dir']!r})")
            continue
        print(f"  {h['host']:<{width}} wall {s['wall_seconds']:>9.3f}s"
              f"  goodput {s['goodput_pct']:>6.2f}%  restarts "
              f"{s['restarts']}  {_bar(s['goodput_pct'])}")
        segs = sorted(s["segments"].items(), key=lambda kv: -kv[1])
        lane = "  ".join(f"{seg} {s['segment_pct'].get(seg, 0.0):.1f}%"
                         for seg, _ in segs if s["segment_pct"].get(seg))
        if lane:
            print(f"  {'':<{width}}   {lane}")
    if rep["fleet_goodput_pct"]:
        g = rep["fleet_goodput_pct"]
        print(f"  fleet goodput min {g['min']:.2f}%  max {g['max']:.2f}%"
              f"  mean {g['mean']:.2f}%")
    if rep["timeline"]:
        print("\ncombined restart timeline "
              "(t = seconds since earliest start)")
        for e in rep["timeline"]:
            extra = " ".join(f"{k}={e[k]}" for k in
                             ("pid", "step", "value", "z", "seconds",
                              "reason") if k in e)
            print(f"  t+{e['t']:>10.3f}  {e['host']:<{width}} "
                  f"{e['what']:<18} {extra}")


def _print_report(rep: Optional[Dict[str, Any]],
                  traj: Dict[str, List[Optional[float]]],
                  rounds: List[Tuple[int, str]]) -> None:
    if rep is not None:
        s = rep["summary"]
        print(f"run_report: {rep['journal']}")
        print(f"  wall {s['wall_seconds']:.3f}s   goodput "
              f"{s['goodput_pct']:.1f}%   restarts {s['restarts']}   "
              f"events {s['events']}")
        print("\ngoodput waterfall (foreground segments sum to wall)")
        segs = sorted(s["segments"].items(), key=lambda kv: -kv[1])
        width = max((len(k) for k, _ in segs), default=8)
        for seg, sec in segs:
            pct = s["segment_pct"].get(seg, 0.0)
            print(f"  {seg:<{width}} {sec:>10.3f}s {pct:>6.2f}% "
                  f"{_bar(pct)}")
        if s["overlapped_seconds"]:
            over = "  ".join(f"{k} {v:.3f}s" for k, v in
                             s["overlapped_seconds"].items())
            print(f"  overlapped (off the critical path): {over}")
        if rep["timeline"]:
            print("\nevent timeline (t = seconds since run start)")
            for e in rep["timeline"]:
                extra = " ".join(f"{k}={e[k]}" for k in
                                 ("pid", "step", "value", "z",
                                  "seconds", "reason") if k in e)
                print(f"  t+{e['t']:>10.3f}  {e['what']:<18} {extra}")
    if traj:
        print("\nBENCH goodput_pct trajectory "
              f"({', '.join(f'r{n:02d}' for n, _ in rounds)})")
        width = max(len(m) for m in traj)
        for name, vals in traj.items():
            cells = " ".join(f"{v:>8.2f}" if v is not None else
                             f"{'-':>8}" for v in vals)
            print(f"  {name:<{width}} {cells}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="run_report",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--run-dir", default=None,
                    help="checkpoint base dir (or goodput.jsonl path) "
                         "holding the run's goodput journal")
    ap.add_argument("--bench-dir", default=".",
                    help="directory holding BENCH_r*.json (default .)")
    ap.add_argument("--merge", nargs="+", default=None,
                    metavar="host-dir",
                    help="overlay several hosts' goodput journals "
                         "(one dir per host) into one fleet waterfall")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as one JSON doc")
    args = ap.parse_args(argv)

    if args.merge:
        rep = merge_report(args.merge)
        if all(h["summary"] is None for h in rep["hosts"]):
            print("run_report: no goodput journal under "
                  + ", ".join(repr(d) for d in args.merge),
                  file=sys.stderr)
            return 2
        if args.as_json:
            print(json.dumps(rep, indent=1))
        else:
            _print_merge(rep)
        return 0

    rep = journal_report(args.run_dir) if args.run_dir else None
    rounds = load_rounds(args.bench_dir)
    traj = goodput_trajectory(rounds)
    if rep is None and not traj:
        print("run_report: no goodput journal"
              + (f" under {args.run_dir!r}" if args.run_dir else "")
              + f" and no BENCH goodput sections under "
                f"{args.bench_dir!r}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({
            "run": rep,
            "bench_goodput_trajectory": traj,
            "rounds": [n for n, _ in rounds]}, indent=1))
        return 0
    _print_report(rep, traj, rounds)
    return 0


if __name__ == "__main__":
    sys.exit(main())
