"""run_report: render a run's goodput waterfall and health-event
timeline from the crash-durable goodput journal (+ the BENCH_r*
goodput trajectory).

The goodput ledger (paddle_tpu/observability/goodput.py) journals every
second of a — possibly crash-interrupted — run into
``<checkpoint base>/goodput.jsonl``: closed wall-clock segments from a
fixed taxonomy (compile / step_compute / ckpt_stall / ckpt_async /
restore / recovery_restart / input_wait / idle), process (re)start
markers, and the health monitor's anomaly events. This tool is the
human-facing view:

- **waterfall**: per-segment seconds and percentages of the run's wall
  clock (idle synthesized as the unattributed remainder, ckpt_async
  shown separately as overlapped), plus the headline ``goodput_pct`` =
  productive step seconds / wall seconds — spanning every restart the
  journal absorbed,
- **event timeline**: health events (loss/grad spikes, stalls,
  restart signals) and process restarts in run-relative time,
- **BENCH trajectory**: every bench line carrying a ``goodput``
  section, its ``goodput_pct`` across all BENCH_r*.json rounds (the
  longitudinal column next to bench_compare's throughput and
  step_report's roofline verdicts).

Usage::

    python -m tools.run_report --run-dir <ckpt base> [--bench-dir REPO]
                               [--json]

Exit codes: 0 on success, 2 when neither a journal nor bench rounds
were found. The tool only reads; regression gating lives in
tools/bench_compare.py (``goodput_pct`` higher-better,
``*_health_spike_events`` exact-0) and ``tools/step_report.py
--strict``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.observability import goodput as _gp
from tools.bench_compare import load_rounds, parse_metrics

__all__ = ["journal_report", "goodput_trajectory", "main"]

_BAR_WIDTH = 40


def journal_report(base_or_path: str) -> Optional[Dict[str, Any]]:
    """Summary + timeline of one goodput journal (a checkpoint base
    dir or the journal file itself); None when no journal exists."""
    path = base_or_path
    if os.path.isdir(path):
        path = os.path.join(path, _gp.JOURNAL_NAME)
    if not os.path.isfile(path):
        return None
    records = _gp.read_journal(path)
    if not records:
        return None
    summary = _gp.summarize(records)
    t0 = None
    for r in records:
        if r.get("ev") == "run":
            t0 = float(r["ts"]) if t0 is None else min(t0, float(r["ts"]))
    timeline: List[Dict[str, Any]] = []
    for r in records:
        if r.get("ev") == "run":
            timeline.append({
                "t": round(float(r["ts"]) - (t0 or 0.0), 3),
                "what": "resume" if r.get("resumed") else "start",
                "pid": r.get("pid")})
        elif r.get("ev") == "h":
            e = {"t": round(float(r.get("ts", 0.0)) - (t0 or 0.0), 3),
                 "what": r.get("kind", "event")}
            for k in ("step", "value", "median", "z", "reason"):
                if k in r:
                    e[k] = r[k]
            timeline.append(e)
        elif r.get("ev") == "e" and r.get("seg") == "recovery_restart":
            timeline.append({
                "t": round(float(r["t0"]) - (t0 or 0.0), 3),
                "what": "recovery_restart",
                "seconds": round(float(r["t1"]) - float(r["t0"]), 3)})
    timeline.sort(key=lambda e: e["t"])
    return {"journal": path, "summary": summary, "timeline": timeline}


def goodput_trajectory(rounds: List[Tuple[int, str]]
                       ) -> Dict[str, List[Optional[float]]]:
    """{metric: [goodput_pct per round]} over every bench line that
    ever carried a ``goodput`` section (None where a round lacks it)."""
    parsed = [(n, parse_metrics(tail)) for n, tail in rounds]
    names = sorted({m for _, p in parsed for m, line in p.items()
                    if isinstance(line.get("goodput"), dict)})
    out: Dict[str, List[Optional[float]]] = {}
    for name in names:
        vals: List[Optional[float]] = []
        for _, p in parsed:
            gp = (p.get(name) or {}).get("goodput")
            vals.append(float(gp["goodput_pct"])
                        if isinstance(gp, dict)
                        and "goodput_pct" in gp else None)
        out[name] = vals
    return out


def _bar(pct: float) -> str:
    n = int(round(_BAR_WIDTH * min(max(pct, 0.0), 100.0) / 100.0))
    return "#" * n


def _print_report(rep: Optional[Dict[str, Any]],
                  traj: Dict[str, List[Optional[float]]],
                  rounds: List[Tuple[int, str]]) -> None:
    if rep is not None:
        s = rep["summary"]
        print(f"run_report: {rep['journal']}")
        print(f"  wall {s['wall_seconds']:.3f}s   goodput "
              f"{s['goodput_pct']:.1f}%   restarts {s['restarts']}   "
              f"events {s['events']}")
        print("\ngoodput waterfall (foreground segments sum to wall)")
        segs = sorted(s["segments"].items(), key=lambda kv: -kv[1])
        width = max((len(k) for k, _ in segs), default=8)
        for seg, sec in segs:
            pct = s["segment_pct"].get(seg, 0.0)
            print(f"  {seg:<{width}} {sec:>10.3f}s {pct:>6.2f}% "
                  f"{_bar(pct)}")
        if s["overlapped_seconds"]:
            over = "  ".join(f"{k} {v:.3f}s" for k, v in
                             s["overlapped_seconds"].items())
            print(f"  overlapped (off the critical path): {over}")
        if rep["timeline"]:
            print("\nevent timeline (t = seconds since run start)")
            for e in rep["timeline"]:
                extra = " ".join(f"{k}={e[k]}" for k in
                                 ("pid", "step", "value", "z",
                                  "seconds", "reason") if k in e)
                print(f"  t+{e['t']:>10.3f}  {e['what']:<18} {extra}")
    if traj:
        print("\nBENCH goodput_pct trajectory "
              f"({', '.join(f'r{n:02d}' for n, _ in rounds)})")
        width = max(len(m) for m in traj)
        for name, vals in traj.items():
            cells = " ".join(f"{v:>8.2f}" if v is not None else
                             f"{'-':>8}" for v in vals)
            print(f"  {name:<{width}} {cells}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="run_report",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--run-dir", default=None,
                    help="checkpoint base dir (or goodput.jsonl path) "
                         "holding the run's goodput journal")
    ap.add_argument("--bench-dir", default=".",
                    help="directory holding BENCH_r*.json (default .)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as one JSON doc")
    args = ap.parse_args(argv)

    rep = journal_report(args.run_dir) if args.run_dir else None
    rounds = load_rounds(args.bench_dir)
    traj = goodput_trajectory(rounds)
    if rep is None and not traj:
        print("run_report: no goodput journal"
              + (f" under {args.run_dir!r}" if args.run_dir else "")
              + f" and no BENCH goodput sections under "
                f"{args.bench_dir!r}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({
            "run": rep,
            "bench_goodput_trajectory": traj,
            "rounds": [n for n, _ in rounds]}, indent=1))
        return 0
    _print_report(rep, traj, rounds)
    return 0


if __name__ == "__main__":
    sys.exit(main())
