#!/usr/bin/env bash
# Pre-commit lint: tpulint over the files your diff touches.
#
# Findings are reported only for changed files, but the
# interprocedural facts (call graph, thread reachability, the lock
# graph, collective/donation taint) are always built from the whole
# tree — a changed caller is judged against unchanged callees.
#
# Install as a git hook:
#     ln -s ../../tools/precommit.sh .git/hooks/pre-commit
#
# Exit codes follow tpulint: 0 clean-vs-baseline, 1 new findings,
# 2 usage error. CI runs the same invocation with
# `--format sarif > tpulint.sarif` for inline PR annotations.
set -u
cd "$(dirname "$0")/.."
REF="${TPULINT_REF:-HEAD}"
exec python -m tools.tpulint paddle_tpu --changed "$REF" "$@"
