"""tpulint CLI: ``python -m tools.tpulint [paths] [options]``.

Exit codes: 0 = clean (every finding baselined or none), 1 = new
violations, 2 = usage error. ``--json`` emits one machine-readable
report on stdout (bench/verdict rounds track ``baseline_size`` /
``new`` from it).
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .core import (Finding, baseline_entry, iter_py_files, lint_paths,
                   load_baseline, relpath_for, split_by_baseline,
                   write_baseline, write_baseline_entries)
from .rules import ALL_RULES, select_rules

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="trace-safety & API-fidelity static analyzer for "
                    "paddle_tpu")
    ap.add_argument("paths", nargs="*", default=["paddle_tpu"],
                    help="files or directories to lint "
                         "(default: paddle_tpu)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit a machine-readable JSON report")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything as new)")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries whose fingerprints no "
                         "longer match any linted file (fixed/moved/"
                         "deleted), write the shrunk baseline, exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--root", type=Path, default=None,
                    help="root for relative paths (default: cwd)")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    ap = build_parser()
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:<22} {r.description}")
        return 0

    try:
        rules = select_rules(
            [r.strip() for r in args.select.split(",") if r.strip()])
    except KeyError as e:
        print(f"tpulint: {e.args[0]}", file=sys.stderr)
        return 2

    paths = [Path(p) for p in args.paths]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"tpulint: no such path: "
              f"{', '.join(str(p) for p in missing)}", file=sys.stderr)
        return 2

    findings = lint_paths(paths, rules, root=args.root)

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"tpulint: wrote {len(findings)} baseline entries to "
              f"{baseline_path}")
        return 0

    if args.prune_baseline:
        baseline = load_baseline(baseline_path) \
            if baseline_path.exists() else []
        root = (args.root or Path.cwd()).resolve()
        linted = {relpath_for(p, root) for p in iter_py_files(paths)}
        in_scope = [e for e in baseline if e["path"] in linted]
        out_scope = [e for e in baseline if e["path"] not in linted]
        # in-scope entries survive only if a current finding still
        # matches their fingerprint; out-of-scope entries survive only
        # while their file exists (an entry for a deleted file can
        # never match again)
        _, matched, stale = split_by_baseline(findings, in_scope)
        kept_out = [e for e in out_scope if (root / e["path"]).is_file()]
        kept = [baseline_entry(f) for f in matched] + kept_out
        dropped = len(baseline) - len(kept)
        write_baseline_entries(baseline_path, kept)
        print(f"tpulint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} "
              f"({len(baseline)} -> {len(kept)}) in {baseline_path}")
        return 0

    baseline = []
    if not args.no_baseline and baseline_path.exists():
        baseline = load_baseline(baseline_path)
        # when linting a subtree, baseline entries for files outside it
        # are out of scope — neither matchable nor stale
        root = (args.root or Path.cwd()).resolve()
        linted = {relpath_for(p, root) for p in iter_py_files(paths)}
        baseline = [e for e in baseline if e["path"] in linted]
    new, matched, stale = split_by_baseline(findings, baseline)

    if args.as_json:
        counts = {}
        for f in new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        report = {
            "version": 1,
            "rules": [r.id for r in rules],
            "total": len(findings),
            "new": len(new),
            "baselined": len(matched),
            "baseline_size": len(baseline),
            "baseline_stale": stale,
            "counts": counts,
            "findings": [f.as_dict(baselined=False) for f in new]
            + [f.as_dict(baselined=True) for f in matched],
        }
        print(json.dumps(report, indent=1))
        return 1 if new else 0

    for f in new:
        print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule}: {f.message} "
              f"[{f.symbol}]")
    if stale:
        print(f"\ntpulint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed or moved — "
              "shrink the baseline with --write-baseline):")
        for e in stale:
            print(f"  {e['rule']}: {e['path']} [{e['symbol']}] "
                  f"{e['line_text'][:60]}")
    print(f"\ntpulint: {len(findings)} finding(s): {len(new)} new, "
          f"{len(matched)} baselined"
          + (f", {len(stale)} stale baseline" if stale else ""))
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
