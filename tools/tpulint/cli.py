"""tpulint CLI: ``python -m tools.tpulint [paths] [options]``.

Exit codes: 0 = clean (every finding baselined or none), 1 = new
violations, 2 = usage error. ``--format json`` (alias ``--json``)
emits one machine-readable report on stdout (bench/verdict rounds
track ``baseline_size`` / ``new`` from it); ``--format sarif`` emits a
SARIF 2.1.0 log so CI renders findings as inline annotations (new
findings at ``warning``, baselined ones as ``note``/``unchanged``).

Incremental mode: ``--changed <git-ref>`` lints only the files changed
vs the ref (plus untracked files), but the interprocedural facts —
call graph, thread reachability, donation/collective taint — are still
built from the WHOLE tree, so a changed caller is judged against
unchanged callees. ``--stats`` appends a per-rule
hit/suppression summary for CI logs.

Baselining: every baseline entry must carry a ``justification`` string
— ``--write-baseline`` refuses entries lacking one (existing
justifications are carried over by fingerprint; supply
``--justification`` for the new entries).
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional

from .core import (Finding, baseline_entry, iter_py_files, lint_paths,
                   load_baseline, match_baseline_entries, relpath_for,
                   split_by_baseline, write_baseline_entries)
from .rules import ALL_RULES, select_rules

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="trace-safety, API-fidelity & concurrency-contract "
                    "static analyzer for paddle_tpu")
    ap.add_argument("paths", nargs="*", default=["paddle_tpu"],
                    help="files or directories to lint "
                         "(default: paddle_tpu)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit a machine-readable JSON report "
                         "(alias for --format json)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default=None,
                    help="output format (default: text; sarif emits a "
                         "SARIF 2.1.0 log for CI annotations)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything as new)")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--changed", metavar="GIT_REF", default=None,
                    help="lint only files changed vs GIT_REF (plus "
                         "untracked); interprocedural facts still "
                         "built from the whole tree")
    ap.add_argument("--stats", action="store_true",
                    help="append a per-rule hit/suppression summary")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "and exit 0; refuses entries lacking a "
                         "justification (see --justification)")
    ap.add_argument("--justification", default=None, metavar="TEXT",
                    help="justification applied to NEW baseline "
                         "entries on --write-baseline (existing "
                         "entries keep theirs)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries whose fingerprints no "
                         "longer match any linted file (fixed/moved/"
                         "deleted), write the shrunk baseline, exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--root", type=Path, default=None,
                    help="root for relative paths (default: cwd)")
    return ap


def _changed_paths(ref: str, root: Path) -> Optional[List[Path]]:
    """.py files changed vs ``ref`` plus untracked ones, as paths
    relative to cwd; None on git failure."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=root, capture_output=True, text=True, timeout=60)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=60)
        names = diff.stdout.splitlines() + (
            untracked.stdout.splitlines()
            if untracked.returncode == 0 else [])
    except (OSError, subprocess.SubprocessError):
        return None
    out = []
    for name in names:
        if not name.endswith(".py"):
            continue
        p = root / name
        if p.is_file():
            out.append(p)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = build_parser()
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:<26} {r.description}")
        return 0

    try:
        rules = select_rules(
            [r.strip() for r in args.select.split(",") if r.strip()])
    except KeyError as e:
        print(f"tpulint: {e.args[0]}", file=sys.stderr)
        return 2

    paths = [Path(p) for p in args.paths]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"tpulint: no such path: "
              f"{', '.join(str(p) for p in missing)}", file=sys.stderr)
        return 2
    root = (args.root or Path.cwd()).resolve()

    project_paths = None
    lint_targets = paths
    if args.changed is not None:
        changed = _changed_paths(args.changed, root)
        if changed is None:
            print(f"tpulint: --changed {args.changed}: git diff failed "
                  f"(not a repo, or unknown ref)", file=sys.stderr)
            return 2
        # restrict to the requested subtrees, facts from the full paths
        scoped = {relpath_for(p, root) for p in iter_py_files(paths)}
        lint_targets = [p for p in changed
                        if relpath_for(p, root) in scoped]
        project_paths = paths

    stats: Dict[str, Dict[str, int]] = {
        r.id: {"total": 0, "new": 0, "baselined": 0, "suppressed": 0}
        for r in rules}
    findings = lint_paths(lint_targets, rules, root=args.root,
                          project_paths=project_paths, stats=stats)
    for f in findings:
        if f.rule in stats:
            stats[f.rule]["total"] += 1

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        return _write_baseline(args, baseline_path, findings)

    if args.prune_baseline:
        baseline = load_baseline(baseline_path) \
            if baseline_path.exists() else []
        linted = {relpath_for(p, root)
                  for p in iter_py_files(lint_targets)}
        in_scope = [e for e in baseline if e["path"] in linted]
        out_scope = [e for e in baseline if e["path"] not in linted]
        # in-scope entries survive only if a current finding still
        # matches their fingerprint; out-of-scope entries survive only
        # while their file exists (an entry for a deleted file can
        # never match again)
        kept_in = match_baseline_entries(findings, in_scope)
        kept_out = [e for e in out_scope if (root / e["path"]).is_file()]
        kept = kept_in + kept_out
        dropped = len(baseline) - len(kept)
        write_baseline_entries(baseline_path, kept)
        print(f"tpulint: pruned {dropped} stale baseline entr"
              f"{'y' if dropped == 1 else 'ies'} "
              f"({len(baseline)} -> {len(kept)}) in {baseline_path}")
        return 0

    baseline = []
    if not args.no_baseline and baseline_path.exists():
        baseline = load_baseline(baseline_path)
        # when linting a subtree, baseline entries for files outside it
        # are out of scope — neither matchable nor stale
        linted = {relpath_for(p, root)
                  for p in iter_py_files(lint_targets)}
        baseline = [e for e in baseline if e["path"] in linted]
    new, matched, stale = split_by_baseline(findings, baseline)
    for f in new:
        if f.rule in stats:
            stats[f.rule]["new"] += 1
    for f in matched:
        if f.rule in stats:
            stats[f.rule]["baselined"] += 1

    fmt = args.format or ("json" if args.as_json else "text")
    if fmt == "sarif":
        print(json.dumps(_sarif_report(rules, new, matched), indent=1))
        return 1 if new else 0

    if fmt == "json":
        counts = {}
        for f in new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        report = {
            "version": 1,
            "rules": [r.id for r in rules],
            "total": len(findings),
            "new": len(new),
            "baselined": len(matched),
            "baseline_size": len(baseline),
            "baseline_stale": stale,
            "counts": counts,
            "findings": [f.as_dict(baselined=False) for f in new]
            + [f.as_dict(baselined=True) for f in matched],
        }
        if args.stats:
            report["stats"] = stats
        if args.changed is not None:
            report["changed_files"] = sorted(
                relpath_for(p, root) for p in iter_py_files(lint_targets))
        print(json.dumps(report, indent=1))
        return 1 if new else 0

    for f in new:
        print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule}: {f.message} "
              f"[{f.symbol}]")
    if stale:
        print(f"\ntpulint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed or moved — "
              "shrink the baseline with --prune-baseline):")
        for e in stale:
            print(f"  {e['rule']}: {e['path']} [{e['symbol']}] "
                  f"{e['line_text'][:60]}")
    if args.stats:
        print("\ntpulint per-rule stats "
              "(total/new/baselined/suppressed):")
        for rid in sorted(stats):
            s = stats[rid]
            print(f"  {rid:<26} {s['total']:>4} {s['new']:>4} "
                  f"{s['baselined']:>4} {s['suppressed']:>4}")
    if args.changed is not None:
        n = len(list(iter_py_files(lint_targets)))
        print(f"\ntpulint: incremental vs {args.changed}: {n} changed "
              f"file(s) linted (facts from the whole tree)")
    print(f"\ntpulint: {len(findings)} finding(s): {len(new)} new, "
          f"{len(matched)} baselined"
          + (f", {len(stale)} stale baseline" if stale else ""))
    return 1 if new else 0


def _sarif_report(rules, new: List[Finding],
                  matched: List[Finding]) -> Dict:
    """SARIF 2.1.0: one run, the rule catalog as reportingDescriptors,
    new findings at ``warning`` level, baselined ones downgraded to
    ``note`` with ``baselineState: unchanged`` so CI only annotates
    regressions."""
    results = []
    for f, baselined in [(f, False) for f in new] \
            + [(f, True) for f in matched]:
        results.append({
            "ruleId": f.rule,
            "level": "note" if baselined else "warning",
            "baselineState": "unchanged" if baselined else "new",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
                "logicalLocations": [{"fullyQualifiedName": f.symbol}],
            }],
            "partialFingerprints": {
                "tpulint/v1": "|".join(f.fingerprint())},
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "rules": [{"id": r.id,
                           "shortDescription": {"text": r.description}}
                          for r in rules],
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///./"}},
            "results": results,
        }],
    }


def _write_baseline(args, baseline_path: Path,
                    findings: List[Finding]) -> int:
    """--write-baseline with mandatory per-entry justification: carry
    existing justifications over by fingerprint, apply
    --justification to new entries, refuse anything still missing."""
    old = load_baseline(baseline_path) if baseline_path.exists() else []
    by_fp: Dict[tuple, List[str]] = {}
    for e in old:
        j = e.get("justification")
        if j:
            key = (e["rule"], e["path"], e["symbol"], e["line_text"])
            by_fp.setdefault(key, []).append(j)
    entries, unjustified = [], []
    for f in findings:
        e = baseline_entry(f)
        carried = by_fp.get(f.fingerprint())
        if carried:
            e["justification"] = carried.pop(0)
        elif args.justification:
            e["justification"] = args.justification
        else:
            unjustified.append(e)
            continue
        entries.append(e)
    if unjustified:
        print("tpulint: refusing to write baseline — entries lack a "
              "justification (pass --justification TEXT, or fix the "
              "finding instead):", file=sys.stderr)
        for e in unjustified[:20]:
            print(f"  {e['rule']}: {e['path']} [{e['symbol']}] "
                  f"{e['line_text'][:60]}", file=sys.stderr)
        if len(unjustified) > 20:
            print(f"  ... and {len(unjustified) - 20} more",
                  file=sys.stderr)
        return 2
    write_baseline_entries(baseline_path, entries)
    print(f"tpulint: wrote {len(entries)} baseline entries to "
          f"{baseline_path}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
