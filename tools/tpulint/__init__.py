"""tpulint — trace-safety, API-fidelity & concurrency-contract static
analyzer for paddle_tpu.

Run it:

    python -m tools.tpulint paddle_tpu/             # human output
    python -m tools.tpulint paddle_tpu/ --json      # machine-readable
    python -m tools.tpulint --format sarif          # CI annotations
    python -m tools.tpulint --changed origin/main   # incremental
    python -m tools.tpulint --list-rules

Thirteen rules ship (see README "Static analysis" for the catalog
with examples). Five are per-module trace-safety rules: unused-knob,
host-sync-in-jit, traced-bool, nonhashable-static, recompile-hazard.
Eight are package-wide interprocedural contract rules riding the
``Project`` pass (cross-module import/call graph, Thread-target
reachability, collective/donation taint, and the lock graph):
raw-collective, unregistered-metric, vjp-ledger-symmetry,
donation-reuse, unguarded-shared-mutation, lock-order-cycle,
blocking-under-lock, mesh-axis-contract.

Suppress a single site with ``# tpulint: disable=<rule>`` on (or on a
comment line directly above) the reported line; grandfathered
violations live in ``baseline.json`` next to this file, each with a
mandatory ``justification`` — the tier-1 gate (tests/test_tpulint.py)
fails on any NEW finding, so the baseline can only shrink.
"""
from .core import (Finding, ModuleInfo, Rule, baseline_entry, lint_paths,
                   lint_source, load_baseline, match_baseline_entries,
                   split_by_baseline, write_baseline)
from .project import Project, ProjectRule, lint_project
from .rules import ALL_RULES, RULES_BY_ID, select_rules

__all__ = [
    "Finding", "ModuleInfo", "Rule", "Project", "ProjectRule",
    "ALL_RULES", "RULES_BY_ID", "select_rules", "lint_source",
    "lint_paths", "lint_project", "load_baseline", "baseline_entry",
    "match_baseline_entries", "split_by_baseline", "write_baseline",
]
