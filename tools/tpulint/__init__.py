"""tpulint — trace-safety & API-fidelity static analyzer for paddle_tpu.

Run it:

    python -m tools.tpulint paddle_tpu/            # human output
    python -m tools.tpulint paddle_tpu/ --json     # machine-readable
    python -m tools.tpulint --list-rules

Five rules ship (see README "Static analysis" for the catalog with
examples): unused-knob, host-sync-in-jit, traced-bool,
nonhashable-static, recompile-hazard. Suppress a single site with
``# tpulint: disable=<rule>`` on (or on a comment line directly above)
the reported line; grandfathered violations live in ``baseline.json``
next to this file — the tier-1 gate (tests/test_tpulint.py) fails on
any NEW finding, so the baseline can only shrink.
"""
from .core import (Finding, ModuleInfo, Rule, baseline_entry, lint_paths,
                   lint_source, load_baseline, split_by_baseline,
                   write_baseline)
from .rules import ALL_RULES, RULES_BY_ID, select_rules

__all__ = [
    "Finding", "ModuleInfo", "Rule", "ALL_RULES", "RULES_BY_ID",
    "select_rules", "lint_source", "lint_paths", "load_baseline",
    "baseline_entry", "split_by_baseline", "write_baseline",
]
