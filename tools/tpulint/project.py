"""tpulint project model: the package-wide interprocedural pass.

``core.ModuleInfo`` answers questions about ONE file; a ``Project``
holds every parsed module of the lint tree and computes the cross-file
facts the contract rules need:

- *import resolution*: relative and absolute imports mapped onto the
  project's own modules, so ``from . import collective as C`` followed
  by ``C.t_psum(...)`` resolves to the actual shim def;
- *call resolution*: a best-effort map from call expressions to project
  function defs (local defs, imported symbols, module-alias attribute
  chains, ``self.method``), with a name-based method fallback used only
  where noted;
- *thread reachability*: the transitive closure of functions reachable
  from ``threading.Thread(target=...)`` entrypoints, across modules —
  the ckpt writer thread reaching ``GoodputLedger.record_overlapped``
  two modules away is the motivating case;
- *collective taint*: which canonical ledger op kinds (psum /
  all_gather / reduce_scatter / all_to_all / ppermute) a function
  transitively issues through the ``t_*`` shim — the fact the
  VJP-symmetry rule compares between a ``custom_vjp``'s fwd and bwd;
- *class concurrency facts*: per class, the lock attributes, the
  thread-safe attributes (queue.Queue / threading.Event / ...), every
  ``self.X`` mutation/read site with the set of locks lexically held,
  and a fixpoint "locks always held on entry" for private methods only
  ever called under a lock (``_close_interval`` in goodput.py);
- *donation facts*: attributes/stores/factory methods bound to
  ``jax.jit(..., donate_argnums=...)`` results, and forwarder wrappers
  (``def _run(self, site, fn, *args): ... fn(*args)``) so a donated
  buffer read after the dispatch is visible through one indirection;
- *lock facts* (:class:`LockFacts`): a whole-tree lock-ordering graph
  — every lock the concurrency facts know (class lock attrs, module-
  level ``threading.Lock()``/``Condition()`` globals) becomes a node,
  and an acquired-while-held edge is recorded whenever a lock is taken
  with another one held: directly (``with self.A: ... with self.B:``),
  through the entry-held fixpoint (a helper only ever called under the
  lock acquiring a second one), or through cross-module call
  resolution (a method holding ``A`` calling a function that
  transitively acquires ``B``). Each edge carries the thread
  entrypoint whose code exercises it (``<main>`` for code no Thread
  target reaches), which is what lets the lock-order-cycle rule demand
  two distinct entrypoints before calling a cycle a deadlock. The same
  pass records every call made with at least one lock held — the
  blocking-under-lock rule's input.

Everything is a heuristic tuned to this repo's idiom, like the core
taint pass: pragmas and the justified baseline absorb the residue.
"""
from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import (Any, Dict, FrozenSet, Iterable, Iterator, List,
                    Optional, Set, Tuple)

from .core import (Finding, ModuleInfo, Rule, func_root, func_simple_name,
                   iter_py_files, relpath_for)

# the traced-collective shim (distributed/collective.py) mapped onto the
# comm ledger's canonical op kinds (observability/commledger.py OPS)
COLLECTIVE_SHIMS = {
    "t_psum": "psum", "t_pmean": "psum", "t_pmax": "pmax",
    "t_pmin": "pmin", "t_all_gather": "all_gather",
    "t_psum_scatter": "reduce_scatter", "t_all_to_all": "all_to_all",
    "t_ppermute": "ppermute",
    # quant_comm wrappers (distributed/quant_comm.py): their int8
    # internals lower to shimmed a2a/all_gather pairs, but the
    # CONTRACT — and therefore the vjp-ledger-symmetry pairing — is
    # the logical reduce/gather op they implement. Mapping them here
    # (and stopping descent, like any shim) keeps psum/identity and
    # mirrored-ring pairings recognizable through the quantized
    # wrappers.
    "quantized_allreduce": "psum",
    "quantized_reduce_scatter": "reduce_scatter",
    "quantized_param_gather": "all_gather",
}

# raw lax collectives the shim wraps — using these directly anywhere
# else silently undercounts the comm ledger
RAW_COLLECTIVES = {
    "psum": "psum", "pmean": "psum", "pmax": "pmax", "pmin": "pmin",
    "all_gather": "all_gather", "psum_scatter": "reduce_scatter",
    "all_to_all": "all_to_all", "ppermute": "ppermute",
}

LOCK_CTORS = {"Lock", "RLock", "Condition"}
# attrs holding these never need an extra lock (internally synchronized
# or thread-local by construction)
THREADSAFE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
                    "Event", "Semaphore", "BoundedSemaphore", "Barrier",
                    "local"}
# finer classification the lock rules need: queues block on .get(),
# events/conditions block on .wait(), threads block on .join()
QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
EVENT_CTORS = {"Event"}
# container-method calls that mutate the receiver in place
MUTATING_METHODS = {"append", "appendleft", "extend", "extendleft",
                    "insert", "pop", "popleft", "popitem", "remove",
                    "clear", "discard", "setdefault"}
# names too generic for the name-based method fallback (they would
# resolve dict.get / file.write / Thread.start onto project classes)
_FALLBACK_BLOCKLIST = {
    "get", "set", "put", "add", "update", "pop", "append", "extend",
    "remove", "clear", "items", "keys", "values", "join", "start",
    "run", "close", "open", "wait", "check", "read", "write", "flush",
    "send",
    "recv", "acquire", "release", "notify", "notify_all", "copy",
    "sort", "split", "strip", "format", "encode", "decode", "match",
    "search", "group", "count", "index", "insert", "reshape", "astype",
}

FuncKey = Tuple[str, int]          # (module relpath, id(function node))


def _flatten_chain(expr: ast.expr) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None for anything with calls or
    subscripts in the chain."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return list(reversed(parts))


def module_name_for(relpath: str) -> str:
    """Dotted module name of a project-relative path
    (``pkg/sub/__init__.py`` -> ``pkg.sub``)."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") \
        else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ClassInfo:
    """Concurrency-relevant facts of one class definition."""

    def __init__(self, mod: ModuleInfo, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {}
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[child.name] = child
        self.is_threadlocal = any(
            (_flatten_chain(b) or [""])[-1] == "local"
            for b in node.bases)
        self.lock_attrs: Set[str] = set()
        self.threadsafe_attrs: Set[str] = set()
        # sub-classifications of the above (ctor-based, so an attr
        # only ever inferred from `with self.X:` lands in lock_attrs
        # but not cond_attrs — treated as a plain mutex)
        self.cond_attrs: Set[str] = set()
        self.queue_attrs: Set[str] = set()
        self.event_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        # attr -> [(node, method, is_mutation)]
        self.accesses: Dict[str, List[Tuple[ast.AST, ast.AST, bool]]] = {}
        self._entry_held: Optional[Dict[int, FrozenSet[str]]] = None
        self._init_only: Optional[Set[int]] = None
        self._collect()

    # -- fact collection -------------------------------------------------
    def _self_attr(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls"):
            return expr.attr
        return None

    def _note(self, attr: str, node: ast.AST, meth: ast.AST,
              mutation: bool) -> None:
        self.accesses.setdefault(attr, []).append((node, meth, mutation))

    def _collect(self) -> None:
        for meth in self.methods.values():
            for node in ast.walk(meth):
                # with self.X: => X is a lock-like attr
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        a = self._self_attr(item.context_expr)
                        if a is not None:
                            self.lock_attrs.add(a)
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    value = node.value
                    for tgt in targets:
                        for leaf in self._target_leaves(tgt):
                            a = self._self_attr(leaf)
                            sub = None
                            if a is None and isinstance(leaf, ast.Subscript):
                                sub = self._self_attr(leaf.value)
                            if a is not None:
                                self._note(a, node, meth, True)
                                self._classify_assign(a, value)
                            elif sub is not None:
                                self._note(sub, node, meth, True)
                elif isinstance(node, ast.Call):
                    # self.X.append(...) and friends mutate X in place
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr in MUTATING_METHODS:
                        a = self._self_attr(node.func.value)
                        if a is not None:
                            self._note(a, node, meth, True)
                elif isinstance(node, ast.Attribute) and \
                        isinstance(node.ctx, ast.Load):
                    a = self._self_attr(node)
                    if a is not None:
                        self._note(a, node, meth, False)

    def _target_leaves(self, tgt: ast.expr) -> Iterator[ast.expr]:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                yield from self._target_leaves(el)
        else:
            yield tgt

    def _classify_assign(self, attr: str, value: Optional[ast.expr]):
        if not isinstance(value, ast.Call):
            return
        name = func_simple_name(value.func)
        if name in LOCK_CTORS:
            self.lock_attrs.add(attr)
            if name == "Condition":
                self.cond_attrs.add(attr)
        elif name in THREADSAFE_CTORS:
            self.threadsafe_attrs.add(attr)
            if name in QUEUE_CTORS:
                self.queue_attrs.add(attr)
            elif name in EVENT_CTORS:
                self.event_attrs.add(attr)
        elif name == "Thread":
            self.thread_attrs.add(attr)

    # -- lock analysis ---------------------------------------------------
    def locks_held_at(self, node: ast.AST) -> FrozenSet[str]:
        """Lock attrs lexically held (enclosing ``with self.X:``)."""
        held: Set[str] = set()
        cur = self.mod.parent(node)
        while cur is not None and cur is not self.node:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    a = self._self_attr(item.context_expr)
                    if a is not None and a in self.lock_attrs:
                        held.add(a)
            cur = self.mod.parent(cur)
        return frozenset(held)

    def _in_class_call_sites(self) -> Dict[str, List[Tuple[ast.AST,
                                                           ast.AST]]]:
        """method name -> [(call node, calling method)] for
        self.m(...)/cls.m(...) calls inside this class."""
        out: Dict[str, List[Tuple[ast.AST, ast.AST]]] = {}
        for meth in self.methods.values():
            for node in ast.walk(meth):
                if isinstance(node, ast.Call):
                    a = self._self_attr(node.func)
                    if a is not None and a in self.methods:
                        out.setdefault(a, []).append((node, meth))
        return out

    def init_only_methods(self) -> Set[int]:
        """ids of methods only ever called (in-class) from __init__ —
        they run before any thread this class starts exists."""
        if self._init_only is not None:
            return self._init_only
        sites = self._in_class_call_sites()
        init = self.methods.get("__init__")
        init_only: Set[int] = set()
        changed = True
        while changed:
            changed = False
            for name, meth in self.methods.items():
                if name == "__init__" or id(meth) in init_only:
                    continue
                calls = sites.get(name)
                if not calls:
                    continue
                if all(c is init or id(c) in init_only
                       for _, c in calls):
                    init_only.add(id(meth))
                    changed = True
        self._init_only = init_only
        return init_only

    def entry_held(self) -> Dict[int, FrozenSet[str]]:
        """Fixpoint: locks guaranteed held whenever each method runs —
        the intersection over its non-__init__ in-class call sites of
        (locks lexically held at the site + the caller's own entry
        set). Methods with no in-class callers are entry points (no
        guarantee). This is what keeps a private helper like
        ``_close_interval`` (only ever called under ``self._lock``)
        from being a false positive."""
        if self._entry_held is not None:
            return self._entry_held
        sites = self._in_class_call_sites()
        init = self.methods.get("__init__")
        top = frozenset(self.lock_attrs)
        held = {id(m): (top if sites.get(name) else frozenset())
                for name, m in self.methods.items()}
        changed = True
        while changed:
            changed = False
            for name, meth in self.methods.items():
                calls = [(n, c) for n, c in sites.get(name, ())
                         if c is not init]
                if not calls:
                    new = frozenset()
                else:
                    new = top
                    for node, caller in calls:
                        new &= (self.locks_held_at(node)
                                | held.get(id(caller), frozenset()))
                if new != held[id(meth)]:
                    held[id(meth)] = new
                    changed = True
        self._entry_held = held
        return held


class Project:
    """Whole-tree analysis context shared by every project rule."""

    def __init__(self, modules: List[ModuleInfo],
                 root: Optional[Path] = None,
                 resources: Optional[Dict[str, Any]] = None):
        self.modules = modules
        self.root = root
        self.by_relpath: Dict[str, ModuleInfo] = {
            m.relpath: m for m in modules}
        self.by_modname: Dict[str, ModuleInfo] = {
            module_name_for(m.relpath): m for m in modules}
        self._resources = dict(resources or {})
        self._imports: Dict[str, Dict[str, Tuple]] = {}
        self._classes: Dict[str, List[ClassInfo]] = {}
        self._class_of_fn: Dict[FuncKey, ClassInfo] = {}
        self._method_index: Optional[Dict[str, List[Tuple[ModuleInfo,
                                                          ClassInfo,
                                                          ast.AST]]]] = None
        self._thread_reachable: Optional[Set[FuncKey]] = None
        self._thread_entries: Dict[FuncKey, str] = {}
        self._coll_cache: Dict[FuncKey, Set[str]] = {}
        self._lock_facts: Optional["LockFacts"] = None

    # -- construction ----------------------------------------------------
    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     resources: Optional[Dict[str, Any]] = None
                     ) -> "Project":
        """In-memory project (tests): {relpath: source}."""
        mods = [ModuleInfo(src, rel) for rel, src in sorted(
            sources.items())]
        return cls(mods, resources=resources)

    @classmethod
    def from_paths(cls, paths: Iterable[Path], root: Path
                   ) -> Tuple["Project", List[Finding]]:
        """Parse every .py under ``paths``; unparsable files become
        parse-error findings instead of members."""
        modules: List[ModuleInfo] = []
        errors: List[Finding] = []
        seen: Set[str] = set()
        for path in iter_py_files(paths):
            rel = relpath_for(path, root)
            if rel in seen:
                continue
            seen.add(rel)
            try:
                modules.append(ModuleInfo(
                    path.read_text(encoding="utf-8"), rel))
            except SyntaxError as e:
                errors.append(Finding(
                    rule="parse-error", path=rel, line=e.lineno or 1,
                    col=e.offset or 0, symbol="<module>",
                    message=str(e)))
        return cls(modules, root=root), errors

    # -- resources -------------------------------------------------------
    def resource(self, name: str) -> Optional[Any]:
        """Project-level data a rule needs beyond python sources.
        ``metric_schema``: the parsed observability schema.json, found
        next to any module named ``*/observability/catalog.py``."""
        if name in self._resources:
            return self._resources[name]
        value = None
        if name == "metric_schema" and self.root is not None:
            for rel in self.by_relpath:
                if rel.endswith("observability/catalog.py"):
                    p = Path(self.root) / rel.rsplit("/", 1)[0] / \
                        "schema.json"
                    if p.is_file():
                        try:
                            value = json.loads(
                                p.read_text(encoding="utf-8"))
                        except ValueError:
                            value = None
                        break
        self._resources[name] = value
        return value

    # -- imports ---------------------------------------------------------
    def imports(self, mod: ModuleInfo) -> Dict[str, Tuple]:
        """{bound name: ("module", dotted) | ("symbol", dotted, name)}
        restricted to targets that exist in this project."""
        cached = self._imports.get(mod.relpath)
        if cached is not None:
            return cached
        out: Dict[str, Tuple] = {}
        modname = module_name_for(mod.relpath)
        is_pkg = mod.relpath.endswith("__init__.py")
        pkg_parts = modname.split(".") if is_pkg \
            else modname.split(".")[:-1]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    if any(k == target or k.startswith(target + ".")
                           for k in self.by_modname):
                        out[bound] = ("module", target)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                        if node.level <= len(pkg_parts) + 1 else []
                else:
                    base = []
                base = base + (node.module.split(".")
                               if node.module else [])
                base_name = ".".join(base)
                for alias in node.names:
                    bound = alias.asname or alias.name
                    child = f"{base_name}.{alias.name}" if base_name \
                        else alias.name
                    if child in self.by_modname:
                        out[bound] = ("module", child)
                    elif base_name in self.by_modname:
                        out[bound] = ("symbol", base_name, alias.name)
        self._imports[mod.relpath] = out
        return out

    # -- function / class indexes ---------------------------------------
    def classes(self, mod: ModuleInfo) -> List[ClassInfo]:
        cached = self._classes.get(mod.relpath)
        if cached is not None:
            return cached
        out = [ClassInfo(mod, n) for n in ast.walk(mod.tree)
               if isinstance(n, ast.ClassDef)]
        for ci in out:
            for meth in ci.methods.values():
                self._class_of_fn[(mod.relpath, id(meth))] = ci
        self._classes[mod.relpath] = out
        return out

    def class_of(self, mod: ModuleInfo, fn: ast.AST) -> Optional[ClassInfo]:
        self.classes(mod)
        return self._class_of_fn.get((mod.relpath, id(fn)))

    def module_level_function(self, mod: ModuleInfo,
                              name: str) -> Optional[ast.AST]:
        for fn in mod.functions():
            if fn.name == name and isinstance(mod.parent(fn), ast.Module):
                return fn
        return None

    def _method_fallback(self, name: str):
        if self._method_index is None:
            idx: Dict[str, List] = {}
            for mod in self.modules:
                for ci in self.classes(mod):
                    for mname, meth in ci.methods.items():
                        idx.setdefault(mname, []).append((mod, ci, meth))
            self._method_index = idx
        if name.startswith("__") or name in _FALLBACK_BLOCKLIST:
            return []
        return self._method_index.get(name, [])

    # -- call resolution -------------------------------------------------
    def resolve_callable(self, mod: ModuleInfo, scope: Optional[ast.AST],
                         expr: ast.expr, name_fallback: bool = False
                         ) -> List[Tuple[ModuleInfo, ast.AST]]:
        """Project function defs a call/reference expression may hit.
        ``scope`` is the enclosing function (for nested defs / self).
        ``name_fallback`` additionally resolves ``<anything>.m(...)``
        to every project method named ``m`` (used by the thread-
        reachability closure only — coarse on purpose)."""
        chain = _flatten_chain(expr)
        if chain is None:
            return []
        root, rest = chain[0], chain[1:]
        # self.m / cls.m -> enclosing class method
        if root in ("self", "cls") and len(rest) == 1 and \
                scope is not None:
            ci = self.class_of(mod, scope)
            if ci is None:
                cur = mod.enclosing_function(scope)
                while cur is not None and ci is None:
                    ci = self.class_of(mod, cur)
                    cur = mod.enclosing_function(cur)
            if ci is not None and rest[0] in ci.methods:
                return [(mod, ci.methods[rest[0]])]
            return self._name_fallback_hits(rest[0]) if name_fallback \
                else []
        if not rest:
            # plain name: nested defs visible from scope, module level,
            # then imported symbol
            hits: List[Tuple[ModuleInfo, ast.AST]] = []
            cur = scope
            while cur is not None:
                for sub in ast.walk(cur):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and sub.name == root and sub is not cur:
                        hits.append((mod, sub))
                cur = mod.enclosing_function(cur)
            if hits:
                return hits[:1]
            fn = self.module_level_function(mod, root)
            if fn is not None:
                return [(mod, fn)]
            imp = self.imports(mod).get(root)
            if imp is not None and imp[0] == "symbol":
                m2 = self.by_modname.get(imp[1])
                if m2 is not None:
                    fn = self.module_level_function(m2, imp[2])
                    if fn is not None:
                        return [(m2, fn)]
            return []
        # dotted chain off an imported module alias
        imp = self.imports(mod).get(root)
        if imp is not None and imp[0] == "module":
            modname = imp[1]
            attrs = list(rest)
            while len(attrs) > 1 and f"{modname}.{attrs[0]}" \
                    in self.by_modname:
                modname = f"{modname}.{attrs[0]}"
                attrs = attrs[1:]
            if len(attrs) == 1:
                m2 = self.by_modname.get(modname)
                if m2 is not None:
                    fn = self.module_level_function(m2, attrs[0])
                    if fn is not None:
                        return [(m2, fn)]
            return []
        if name_fallback and len(rest) >= 1:
            return self._name_fallback_hits(rest[-1])
        return []

    def _name_fallback_hits(self, name: str):
        return [(mod, meth) for mod, _ci, meth
                in self._method_fallback(name)]

    # -- thread reachability ---------------------------------------------
    def thread_reachable(self) -> Set[FuncKey]:
        """ids of functions reachable from a Thread(target=...) —
        transitively, across modules, with the name-based method
        fallback for attribute calls on objects of unknown type."""
        if self._thread_reachable is not None:
            return self._thread_reachable
        work: List[Tuple[ModuleInfo, ast.AST]] = []
        reach: Set[FuncKey] = set()

        def push(mod, fn, entry):
            key = (mod.relpath, id(fn))
            if key not in reach:
                reach.add(key)
                self._thread_entries.setdefault(key, entry)
                work.append((mod, fn))

        for mod in self.modules:
            for call in ast.walk(mod.tree):
                if not isinstance(call, ast.Call) or \
                        func_simple_name(call.func) != "Thread":
                    continue
                targets = [kw.value for kw in call.keywords
                           if kw.arg == "target"]
                if not targets and len(call.args) >= 2:
                    targets = [call.args[1]]
                scope = mod.enclosing_function(call)
                for tgt in targets:
                    for m2, fn in self.resolve_callable(
                            mod, scope, tgt, name_fallback=True):
                        entry = f"{m2.relpath}:{m2.qualname_of(fn)}"
                        push(m2, fn, entry)
        while work:
            mod, fn = work.pop()
            entry = self._thread_entries[(mod.relpath, id(fn))]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                scope = mod.enclosing_function(node) or fn
                hits = self.resolve_callable(mod, scope, node.func)
                if not hits and isinstance(node.func, ast.Attribute):
                    hits = self.resolve_callable(
                        mod, scope, node.func, name_fallback=True)
                for m2, f2 in hits:
                    push(m2, f2, entry)
        self._thread_reachable = reach
        return reach

    def thread_entry_of(self, mod: ModuleInfo, fn: ast.AST
                        ) -> Optional[str]:
        """The Thread target this function is reachable from (its
        relpath:qualname), or None."""
        self.thread_reachable()
        return self._thread_entries.get((mod.relpath, id(fn)))

    def is_thread_reachable(self, mod: ModuleInfo, fn: ast.AST) -> bool:
        return (mod.relpath, id(fn)) in self.thread_reachable()

    # -- lock facts ------------------------------------------------------
    def lock_facts(self) -> "LockFacts":
        """The whole-tree lock graph + under-lock call sites (built
        once, shared by the lock-order-cycle and blocking-under-lock
        rules)."""
        if self._lock_facts is None:
            self._lock_facts = LockFacts(self)
        return self._lock_facts

    # -- collective taint ------------------------------------------------
    def collective_kinds(self, mod: ModuleInfo, fn: ast.AST
                         ) -> Set[str]:
        """Canonical ledger op kinds ``fn`` transitively issues through
        the t_* shim (cross-module; cycles truncate)."""
        visiting: Set[FuncKey] = set()

        def dfs(m: ModuleInfo, f: ast.AST) -> Set[str]:
            key = (m.relpath, id(f))
            cached = self._coll_cache.get(key)
            if cached is not None:
                return cached
            if key in visiting:
                return set()
            visiting.add(key)
            kinds: Set[str] = set()
            for node in ast.walk(f):
                if not isinstance(node, ast.Call):
                    continue
                name = func_simple_name(node.func)
                if name in COLLECTIVE_SHIMS:
                    kinds.add(COLLECTIVE_SHIMS[name])
                    continue
                scope = m.enclosing_function(node) or \
                    (f if isinstance(f, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) else None)
                for m2, f2 in self.resolve_callable(m, scope, node.func):
                    kinds |= dfs(m2, f2)
            visiting.discard(key)
            self._coll_cache[key] = kinds
            return kinds

        return dfs(mod, fn)


class LockFacts:
    """Whole-tree lock graph + under-lock call sites (see module
    docstring). Lock identity is conservative: one node per *declared*
    lock — ``relpath:Class.attr`` for instance locks (every instance of
    a class maps to the same node) and ``relpath:name`` for module-
    level lock globals. ``kinds`` remembers which nodes are Condition
    variables (their ``wait`` is protocol, not blocking-under-lock).

    ``edges``: ``(held, acquired) -> [(relpath, lineno, context,
    detail)]`` — every site where ``acquired`` is taken with ``held``
    already held. ``context`` is the Thread entrypoint whose code runs
    the site (``<main>`` when no Thread target reaches it).

    ``held_calls``: ``[(mod, fn, call, held_ids)]`` for every Call
    executed with at least one lock held (lexical ``with`` nesting plus
    the class entry-held fixpoint; nested defs/lambdas do not inherit).
    """

    def __init__(self, project: "Project"):
        self.project = project
        self.kinds: Dict[str, str] = {}     # lock id -> "lock" | "cond"
        self.edges: Dict[Tuple[str, str],
                         List[Tuple[str, int, str, str]]] = {}
        self.held_calls: List[Tuple[ModuleInfo, ast.AST, ast.Call,
                                    Tuple[str, ...]]] = []
        self._module_locks: Dict[str, Dict[str, str]] = {}
        self._acq_cache: Dict[FuncKey, FrozenSet[str]] = {}
        self._acq_visiting: Set[FuncKey] = set()
        self._build()

    # -- lock identity ---------------------------------------------------
    def module_locks(self, mod: ModuleInfo) -> Dict[str, str]:
        """Module-level lock globals: {bound name: "lock" | "cond"}."""
        cached = self._module_locks.get(mod.relpath)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            name = func_simple_name(node.value.func)
            if name not in LOCK_CTORS:
                continue
            kind = "cond" if name == "Condition" else "lock"
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = kind
        self._module_locks[mod.relpath] = out
        return out

    def resolve_lock(self, mod: ModuleInfo, scope: Optional[ast.AST],
                     expr: ast.expr) -> Optional[str]:
        """Lock node id of an acquisition expression (``self.X``, a
        module-level lock name, or ``alias.X`` through an import), or
        None for anything unresolvable."""
        # self.X / cls.X on the enclosing class
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls") and scope is not None:
            ci = self.project.class_of(mod, scope)
            cur = scope
            while ci is None and cur is not None:
                cur = mod.enclosing_function(cur)
                if cur is not None:
                    ci = self.project.class_of(mod, cur)
            if ci is not None and expr.attr in ci.lock_attrs:
                lid = f"{mod.relpath}:{ci.name}.{expr.attr}"
                self.kinds.setdefault(
                    lid, "cond" if expr.attr in ci.cond_attrs
                    else "lock")
                return lid
            return None
        if isinstance(expr, ast.Name):
            kind = self.module_locks(mod).get(expr.id)
            if kind is not None:
                lid = f"{mod.relpath}:{expr.id}"
                self.kinds.setdefault(lid, kind)
                return lid
            return None
        # alias.X where alias imports a project module
        chain = _flatten_chain(expr)
        if chain is not None and len(chain) == 2:
            imp = self.project.imports(mod).get(chain[0])
            if imp is not None and imp[0] == "module":
                m2 = self.project.by_modname.get(imp[1])
                if m2 is not None:
                    kind = self.module_locks(m2).get(chain[1])
                    if kind is not None:
                        lid = f"{m2.relpath}:{chain[1]}"
                        self.kinds.setdefault(lid, kind)
                        return lid
        return None

    # -- transitive "locks this function acquires" ----------------------
    def acquires(self, mod: ModuleInfo, fn: ast.AST) -> FrozenSet[str]:
        """Lock ids ``fn`` (or anything it calls, cross-module)
        acquires; cycles truncate, unresolvable calls contribute
        nothing (conservative toward silence)."""
        key = (mod.relpath, id(fn))
        cached = self._acq_cache.get(key)
        if cached is not None:
            return cached
        if key in self._acq_visiting:
            return frozenset()
        self._acq_visiting.add(key)
        out: Set[str] = set()
        for node in self._own_nodes(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self.resolve_lock(mod, fn, item.context_expr)
                    if lid is not None:
                        out.add(lid)
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "acquire":
                    lid = self.resolve_lock(mod, fn, node.func.value)
                    if lid is not None:
                        out.add(lid)
                else:
                    for m2, f2 in self.project.resolve_callable(
                            mod, fn, node.func):
                        out |= self.acquires(m2, f2)
        self._acq_visiting.discard(key)
        result = frozenset(out)
        self._acq_cache[key] = result
        return result

    @staticmethod
    def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
        """ast.walk(fn) minus the bodies of nested defs/lambdas (they
        run later, under whatever locks their CALLER holds)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- the walk --------------------------------------------------------
    def _build(self) -> None:
        for mod in self.project.modules:
            for fn in mod.functions():
                self._walk_fn(mod, fn)

    def _entry_held_ids(self, mod: ModuleInfo, fn: ast.AST
                        ) -> Tuple[str, ...]:
        ci = self.project.class_of(mod, fn)
        if ci is None:
            return ()
        held = ci.entry_held().get(id(fn), frozenset())
        out = []
        for attr in sorted(held):
            lid = f"{mod.relpath}:{ci.name}.{attr}"
            self.kinds.setdefault(
                lid, "cond" if attr in ci.cond_attrs else "lock")
            out.append(lid)
        return tuple(out)

    def _walk_fn(self, mod: ModuleInfo, fn: ast.AST) -> None:
        context = self.project.thread_entry_of(mod, fn) or "<main>"
        self._visit(mod, fn, fn.body, self._entry_held_ids(mod, fn),
                    context)

    def _edge(self, held: Tuple[str, ...], acquired: str,
              mod: ModuleInfo, node: ast.AST, context: str,
              detail: str) -> None:
        for h in held:
            if h == acquired:
                continue            # re-entry, not an ordering edge
            self.edges.setdefault((h, acquired), []).append(
                (mod.relpath, getattr(node, "lineno", 0), context,
                 detail))

    def _visit(self, mod: ModuleInfo, fn: ast.AST, body,
               held: Tuple[str, ...], context: str) -> None:
        for node in body if isinstance(body, list) else [body]:
            self._visit_node(mod, fn, node, held, context)

    def _visit_node(self, mod: ModuleInfo, fn: ast.AST, node: ast.AST,
                    held: Tuple[str, ...], context: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return                  # walked as its own entry
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                self._visit_node(mod, fn, item.context_expr,
                                 tuple(inner), context)
                lid = self.resolve_lock(mod, fn, item.context_expr)
                if lid is not None:
                    self._edge(tuple(inner), lid, mod, item.context_expr,
                               context, "with")
                    if lid not in inner:
                        inner.append(lid)
            self._visit(mod, fn, node.body, tuple(inner), context)
            return
        if isinstance(node, ast.Call):
            if held:
                self.held_calls.append((mod, fn, node, held))
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire":
                lid = self.resolve_lock(mod, fn, node.func.value)
                if lid is not None:
                    self._edge(held, lid, mod, node, context, "acquire")
            elif held:
                for m2, f2 in self.project.resolve_callable(
                        mod, fn, node.func):
                    for lid in sorted(self.acquires(m2, f2)):
                        if lid not in held:
                            self._edge(held, lid, mod, node, context,
                                       f"call {func_simple_name(node.func)}")
        for child in ast.iter_child_nodes(node):
            self._visit_node(mod, fn, child, held, context)


class ProjectRule(Rule):
    """A rule that needs the whole-tree Project, not one module."""

    project = True

    def check_project(self, project: Project) -> Iterator[Finding]:
        raise NotImplementedError

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        # project rules are driven via check_project
        return iter(())


def lint_project(project: Project, rules,
                 stats: Optional[Dict[str, Dict[str, int]]] = None
                 ) -> List[Finding]:
    """Run module rules over every member and project rules once;
    suppression pragmas applied per finding's home module. ``stats``
    (rule id -> counters) picks up per-rule suppression counts."""
    out: List[Finding] = []
    for rule in rules:
        if getattr(rule, "project", False):
            found = list(rule.check_project(project))
        else:
            found = [f for mod in project.modules
                     for f in rule.check(mod)]
        for f in found:
            mod = project.by_relpath.get(f.path)
            if mod is not None and mod.is_suppressed(f):
                if stats is not None:
                    stats.setdefault(rule.id, {}).setdefault(
                        "suppressed", 0)
                    stats[rule.id]["suppressed"] += 1
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
