"""lock-order-cycle: a static deadlock prover over the lock graph.

The Project's :class:`~tools.tpulint.project.LockFacts` pass records an
acquired-while-held edge every time one lock is taken with another
held — directly nested ``with`` blocks, helpers whose entry-held
fixpoint says a lock is always held when they run, and cross-module
calls that transitively acquire a lock. Each edge carries the Thread
entrypoint whose code exercises it (``<main>`` for the main thread).

The deadlock condition this rule proves: a CYCLE in that graph whose
edges are exercised from at least TWO distinct entrypoints. Two
threads walking the cycle from different edges can each hold one lock
of the cycle while waiting for the next — the classic AB/BA hang. A
cycle driven by a single entrypoint cannot interleave with itself (one
thread acquires sequentially), so it is not reported; neither is any
acyclic nesting, however deep — a consistent global order is exactly
what acyclicity certifies.

One finding per strongly connected component, anchored at the
earliest edge site, naming the locks on the cycle, a witness edge in
each direction, and the entrypoints that can interleave.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Set, Tuple

from ..core import Finding
from ..project import Project, ProjectRule


def _sccs(nodes: List[str],
          succ: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan SCCs (iterative), deterministic over sorted nodes."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(succ.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(succ.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(sorted(comp))
    return out


class LockOrderCycleRule(ProjectRule):
    id = "lock-order-cycle"
    description = ("cycle in the acquired-while-held lock graph "
                   "reachable from two thread entrypoints — deadlock")

    def check_project(self, project: Project) -> Iterator[Finding]:
        facts = project.lock_facts()
        succ: Dict[str, Set[str]] = {}
        nodes: Set[str] = set()
        for (a, b) in facts.edges:
            nodes.add(a)
            nodes.add(b)
            succ.setdefault(a, set()).add(b)
        for comp in _sccs(sorted(nodes), succ):
            if len(comp) < 2:
                continue            # self-edges are never recorded
            comp_set = set(comp)
            sites: List[Tuple[str, int, str, str, str, str]] = []
            contexts: Set[str] = set()
            for (a, b), elist in sorted(facts.edges.items()):
                if a in comp_set and b in comp_set:
                    for rel, line, ctx, detail in elist:
                        sites.append((rel, line, ctx, a, b, detail))
                        contexts.add(ctx)
            if len(contexts) < 2 or not sites:
                continue
            sites.sort(key=lambda s: (s[0], s[1]))
            rel, line, _ctx, a, b, _detail = sites[0]
            witness = {}
            for s in sites:
                witness.setdefault((s[3], s[4]), s)
            ways = "; ".join(
                f"{sa} -> {sb} at {srel}:{sline} [{sctx}]"
                for (srel, sline, sctx, sa, sb, _d)
                in list(witness.values())[:4])
            mod = project.by_relpath.get(rel)
            if mod is None:
                continue
            anchor = _Anchor(line)
            yield self.finding(
                mod, anchor,
                f"lock-order cycle over {{{', '.join(comp)}}} "
                f"exercised from entrypoints "
                f"{{{', '.join(sorted(contexts))}}} — two threads can "
                f"each hold one lock while waiting for the other "
                f"(deadlock); pick one global acquisition order "
                f"({ways})")


class _Anchor:
    """Minimal lineno/col carrier for Rule.finding anchoring."""

    def __init__(self, lineno: int, col_offset: int = 0):
        self.lineno = lineno
        self.col_offset = col_offset
