"""traced-bool: Python ``if``/``while`` on a traced value in jitted code.

Under ``jax.jit`` a Python branch on a traced array raises a
TracerBoolConversionError (or, with ``static_argnums`` misuse, silently
forks compilations). Control flow on traced values belongs in
``lax.cond`` / ``lax.while_loop`` / ``jnp.where`` — this repo wraps
those as ``static.nn.cond`` / ``static.nn.while_loop``.

Static conditions stay allowed: branches on Python knobs, ``x is None``
checks, ``isinstance``, and shape/ndim/dtype metadata are all resolved
at trace time and are idiomatic in kernels.
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import (Finding, ModuleInfo, Rule, STATIC_JAX_CALLS,
                    func_simple_name, is_jax_call)


class TracedBoolRule(Rule):
    id = "traced-bool"
    description = ("Python if/while on a traced value inside a jitted "
                   "region (use static.nn cond/while_loop or jnp.where)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in mod.functions():
            if not mod.is_traced(fn):
                continue
            tainted = mod.tainted_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                offender = self._offending(mod, node.test, tainted)
                if offender:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        mod, node,
                        f"`{kind}` on traced value {offender} inside "
                        f"jit-reachable '{mod.qualname_of(node)}' — "
                        "Python control flow forks at trace time; use "
                        "static.nn.cond/while_loop or jnp.where")

    def _offending(self, mod: ModuleInfo, test: ast.expr,
                   tainted: Set[str]) -> str:
        for node in ast.walk(test):
            if is_jax_call(node) and \
                    func_simple_name(node.func) not in STATIC_JAX_CALLS:
                return f"`{func_simple_name(node.func)}(...)`"
            if isinstance(node, ast.Name) and node.id in tainted \
                    and not self._static_use(mod, node):
                return f"'{node.id}'"
        return ""

    def _static_use(self, mod: ModuleInfo, name: ast.Name) -> bool:
        """The reference is static under tracing: shape/ndim/dtype
        access, len(), isinstance(), or an `is (not) None` operand."""
        if mod._under_static_access(name, name):
            return True
        parent = mod.parent(name)
        if isinstance(parent, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in parent.ops):
            return True
        return False
