"""unguarded-shared-mutation: a lightweight cross-thread race detector.

The host side of the framework is genuinely concurrent — the async
checkpoint writer, the watchdog monitor, the elastic heartbeat/watch
loops, the metrics exporter — and its locking discipline was, until
this rule, hand-audited convention. The contract it machine-checks:

    an instance attribute mutated from thread-target-reachable code
    and also accessed from other methods must have ONE lock held at
    every one of those sites.

Per class the rule uses the Project facts: thread reachability
(transitive from ``threading.Thread(target=...)``, cross-module),
lexically-held ``with self.<lock>:`` regions, and the entry-held
fixpoint (a private helper only ever called under the lock counts as
guarded). Exemptions: ``__init__`` and methods only reachable from it
(no thread exists yet), lock attributes themselves, attributes holding
internally-synchronized objects (queue.Queue, threading.Event, ...),
and ``threading.local`` subclasses.

One finding per (class, attribute), anchored at the first offending
thread-reachable mutation site, so fingerprints stay stable while the
fix lands.

Scope: only modules under the paths in ``SCOPE`` are *reported on*
(observability, checkpointing, serving, elastic, the watchdog) —
reachability is still computed over the whole tree, which is how the
ckpt writer thread is seen reaching the goodput ledger two modules
away.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from ..core import Finding, ModuleInfo
from ..project import ClassInfo, Project, ProjectRule

SCOPE = ("observability/", "distributed/checkpoint/",
         "distributed/watchdog.py", "inference/serving.py",
         "inference/router.py", "inference/disagg.py",
         "fleet/elastic/")


def _in_scope(relpath: str) -> bool:
    return any(s in relpath for s in SCOPE)


class SharedMutationRule(ProjectRule):
    id = "unguarded-shared-mutation"
    description = ("attribute mutated from a Thread-target-reachable "
                   "method and accessed elsewhere without a common lock")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not _in_scope(mod.relpath):
                continue
            for ci in project.classes(mod):
                if ci.is_threadlocal:
                    continue
                yield from self._check_class(project, mod, ci)

    def _check_class(self, project: Project, mod: ModuleInfo,
                     ci: ClassInfo) -> Iterator[Finding]:
        init = ci.methods.get("__init__")
        init_only = ci.init_only_methods()
        entry_held = ci.entry_held()

        def excluded(meth: ast.AST) -> bool:
            return meth is init or id(meth) in init_only

        skip_attrs = ci.lock_attrs | ci.threadsafe_attrs
        for attr, sites in sorted(ci.accesses.items()):
            if attr in skip_attrs or attr.startswith("__"):
                continue
            live = [(node, meth, mut) for node, meth, mut in sites
                    if not excluded(meth)]
            t_mut = [(node, meth) for node, meth, mut in live
                     if mut and project.is_thread_reachable(mod, meth)]
            other = [(node, meth) for node, meth, _mut in live
                     if not project.is_thread_reachable(mod, meth)]
            if not t_mut or not other:
                continue
            guards: List[FrozenSet[str]] = []
            for node, meth in t_mut + other:
                guards.append(ci.locks_held_at(node)
                              | entry_held.get(id(meth), frozenset()))
            common = frozenset(ci.lock_attrs)
            for g in guards:
                common &= g
            if common:
                continue
            anchor, anchor_meth = min(
                t_mut, key=lambda s: (getattr(s[0], "lineno", 0),
                                      getattr(s[0], "col_offset", 0)))
            entry = project.thread_entry_of(mod, anchor_meth) or "?"
            others = sorted({mod.qualname_of(m) for _n, m in other})
            locks = sorted(ci.lock_attrs)
            hint = (f"hold self.{locks[0]} at every site"
                    if locks else "add a lock attribute and hold it at "
                                  "every site")
            yield self.finding(
                mod, anchor,
                f"'self.{attr}' is mutated in "
                f"'{mod.qualname_of(anchor_meth)}' (reachable from "
                f"thread target {entry}) and accessed from "
                f"{', '.join(others[:4])} without a common lock — "
                f"data race; {hint}")
