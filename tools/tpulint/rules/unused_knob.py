"""unused-knob: a public API accepts a parameter and silently ignores it.

The round-5 findings class: masked_multihead_attention's ``src_mask``,
pool3d's ``ceil_mode``, matrix_nms's ``normalized`` — knobs a caller
sets expecting reference semantics while the body never reads them.
The repo convention (block_multihead_attention) is enforce-or-implement:
either serve the knob or ``enforce`` it at its default so divergence is
loud.

A parameter counts as read if its name is loaded anywhere in the body —
including inside an ``enforce(...)`` guard, which is exactly the
sanctioned fix.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from ..core import Finding, ModuleInfo, Rule

# accepted-everywhere compat knobs that are documented no-ops in the
# reference API itself (paddle's `name=` labels static-graph nodes)
IGNORED_PARAMS = {"self", "cls", "name"}


def _is_stub(fn: ast.AST) -> bool:
    body: List[ast.stmt] = list(fn.body)
    if body and isinstance(body[0], ast.Expr) and \
            isinstance(body[0].value, ast.Constant) and \
            isinstance(body[0].value.value, str):
        body = body[1:]
    if not body:
        return True
    return all(isinstance(s, (ast.Raise, ast.Pass)) or
               (isinstance(s, ast.Expr) and
                isinstance(s.value, ast.Constant))
               for s in body)


def _is_public(mod: ModuleInfo, fn: ast.AST) -> bool:
    name = fn.name
    if name.startswith("_") and not (name.startswith("__")
                                     and name.endswith("__")):
        return False
    parent = mod.parent(fn)
    if isinstance(parent, ast.ClassDef):
        return not parent.name.startswith("_")
    return isinstance(parent, ast.Module)


class UnusedKnobRule(Rule):
    id = "unused-knob"
    description = ("public function parameter never read in the body "
                   "(silent-ignore API divergence)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in mod.functions():
            if not _is_public(mod, fn) or _is_stub(fn):
                continue
            if any(isinstance(d, ast.Name) and d.id == "abstractmethod"
                   for d in fn.decorator_list):
                continue
            args = fn.args
            params = [a for a in (list(args.posonlyargs) + list(args.args)
                                  + list(args.kwonlyargs))
                      if a.arg not in IGNORED_PARAMS
                      and not a.arg.startswith("_")]
            if not params:
                continue
            loaded = set()
            for stmt in fn.body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Name) and \
                            isinstance(node.ctx, ast.Load):
                        loaded.add(node.id)
                    # nested defs capture params via their own args too
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.Lambda)):
                        loaded |= {a.arg for a in node.args.args}
            for p in params:
                if p.arg not in loaded:
                    # anchor at the parameter itself so the pragma /
                    # baseline pins the exact signature line
                    yield self.finding(
                        mod, p,
                        f"public parameter '{p.arg}' of {fn.name}() is "
                        "accepted but never read — enforce it at its "
                        "default or implement it (repo convention: "
                        "block_multihead_attention)")
