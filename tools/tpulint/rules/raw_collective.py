"""raw-collective: in-graph collectives bypassing the ledger shim.

Every in-graph collective must route through the ``t_*`` traced-
collective shim in ``distributed/collective.py`` so the comm ledger
(observability/commledger.py) sees it at trace time. A direct
``lax.psum`` / ``lax.all_gather`` / ``lax.psum_scatter`` /
``lax.all_to_all`` / ``lax.ppermute`` (or pmean/pmax/pmin — wire-
identical reduces) anywhere else moves bytes the ledger never counts:
``paddle_tpu_comm_bytes_total`` silently undercounts, and the exposed-
comm ablation replays the wrong program. This is the PR-7
``_ledger_a2a`` bug class (jax's default a2a transpose called lax
directly, leaving the MoE backward exchanges out of the ledger) turned
into a machine-checked contract.

Allowlisted: the shim module itself and the comm ledger's ablation /
replay lowering (``observability/commledger.py``) — the two places
that must touch lax by design.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, ModuleInfo, Rule, func_root, func_simple_name
from ..project import RAW_COLLECTIVES

ALLOWED_PATHS = ("distributed/collective.py",
                 "observability/commledger.py")

# call-target roots that mean "the jax collective, not some local fn"
_JAX_ROOTS = {"lax", "jax"}


class RawCollectiveRule(Rule):
    id = "raw-collective"
    description = ("raw lax collective outside distributed/collective.py"
                   " — bypasses the t_* shim, comm ledger undercounts")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.relpath.endswith(ALLOWED_PATHS):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = func_simple_name(node.func)
            if name not in RAW_COLLECTIVES:
                continue
            root = func_root(node.func)
            if root not in _JAX_ROOTS:
                continue
            shim = f"t_{name}"
            yield self.finding(
                mod, node,
                f"raw {root}.{name} outside the traced-collective shim "
                f"— the comm ledger never sees it (wire bytes "
                f"undercount, ablation replays diverge); route through "
                f"distributed.collective.{shim}")
