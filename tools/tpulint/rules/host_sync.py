"""host-sync-in-jit: device→host materialization inside traced code.

``.item()`` / ``.tolist()`` / ``.numpy()`` / ``np.asarray`` /
``jax.device_get`` on a value reachable from ``jax.jit`` / ``pjit`` /
``shard_map`` / ``lax.scan`` bodies (or ``def_op`` kernels, which trace
under vjp) either fails outright under tracing or — worse on the real
serving path — forces a blocking transfer per step. Fix by staying in
``jnp`` / ``lax``, or hoist the sync out of the compiled region.

``float()`` / ``int()`` / ``bool()`` are only flagged when applied to a
*tainted* expression (one holding a traced array per core taint
analysis) — casting static Python knobs inside kernels is fine.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, ModuleInfo, Rule, func_root, func_simple_name

SYNC_METHODS = {"item", "tolist", "numpy"}
NUMPY_ROOTS = {"np", "numpy", "_np", "onp"}
SYNC_BUILTINS = {"float", "int", "bool", "complex"}


class HostSyncInJitRule(Rule):
    id = "host-sync-in-jit"
    description = ("host-sync call (.item()/np.asarray/float()/...) on "
                   "a traced value inside jit-reachable code")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in mod.functions():
            if not mod.is_traced(fn):
                continue
            tainted = mod.tainted_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._classify(mod, node, tainted)
                if hit:
                    yield self.finding(
                        mod, node,
                        f"{hit} inside jit-reachable "
                        f"'{mod.qualname_of(node)}' forces a device→host "
                        "sync (or fails under tracing) — keep the value "
                        "in jnp/lax, or hoist it out of the compiled "
                        "region")

    def _classify(self, mod, call: ast.Call, tainted) -> str:
        fnode = call.func
        # x.item() / x.tolist() / x.numpy()
        if isinstance(fnode, ast.Attribute) and \
                fnode.attr in SYNC_METHODS and not call.args:
            return f".{fnode.attr}()"
        # np.asarray(x) / np.array(x) on a traced value
        if isinstance(fnode, ast.Attribute) and \
                fnode.attr in ("asarray", "array"):
            root = func_root(fnode)
            if root in NUMPY_ROOTS and call.args and \
                    self._arg_traced(mod, call.args[0], tainted):
                return f"{root}.{fnode.attr}(...)"
        # jax.device_get(x)
        if func_simple_name(fnode) == "device_get":
            return "jax.device_get(...)"
        # float(x)/int(x)/bool(x) on a tainted expression only
        if isinstance(fnode, ast.Name) and fnode.id in SYNC_BUILTINS \
                and call.args and \
                self._arg_traced(mod, call.args[0], tainted):
            return f"{fnode.id}(...)"
        return ""

    @staticmethod
    def _arg_traced(mod: ModuleInfo, arg: ast.expr, tainted) -> bool:
        return mod._expr_tainted(arg, tainted)
