"""donation-reuse: reading a buffer after donating it to a dispatch.

``jax.jit(..., donate_argnums=...)`` hands the argument's buffer to
XLA: after the call the python array is deleted (errors on CPU/GPU) or
— worse on TPU serving — silently aliases the output, so a read
observes torn data. The serving KV-cache contract this encodes: the
caller must REBIND the donated name from the call's results (``toks,
caches = fn(..., caches, ...)``) and never touch the old reference
again; the memledger already has to lower programs BEFORE the call for
the same reason.

Donation facts are interprocedural within a class/module:

- direct bindings: ``fn = jax.jit(f, donate_argnums=(2,))``;
- donating stores: ``self._step_fns[key] = jax.jit(...)`` marks the
  attribute, so ``fn = self._step_fns[key]; fn(...)`` is a donating
  call;
- factory methods: a method whose returns are jit-donating calls or
  reads of a donating store (``def _prefill_fn(...): ...; return
  self._prefill_fns[key]``) donates at its call sites;
- forwarder wrappers: ``def _run(self, site, fn, *args)`` whose body
  calls ``fn(*args)`` shifts the donated position by the payload
  offset (``self._run(site, fn, a, b, cache)``).

The finding lands on the first read of the donated name after the
dispatch (before any rebinding).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding, ModuleInfo, func_simple_name
from ..project import Project, ProjectRule

_JIT_NAMES = {"jit", "pjit"}


def _donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The donate_argnums of a jax.jit/pjit call, or None. A
    conditional ``(0, 1) if donate else ()`` counts with the donating
    branch (conservative)."""
    if func_simple_name(call.func) not in _JIT_NAMES:
        return None
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        out = _int_tuple(kw.value)
        if out:
            return out
        if isinstance(kw.value, ast.IfExp):
            for branch in (kw.value.body, kw.value.orelse):
                out = _int_tuple(branch)
                if out:
                    return out
    return None


def _int_tuple(expr: ast.expr) -> Optional[Tuple[int, ...]]:
    if isinstance(expr, (ast.Tuple, ast.List)):
        vals = []
        for el in expr.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                vals.append(el.value)
            else:
                return None
        return tuple(vals) or None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return (expr.value,)
    return None


def _self_attr_of(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id in ("self", "cls"):
        return expr.attr
    return None


class _ModuleFacts:
    """Donation facts of one module (classes + module level)."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        # attr name -> argnums (self._fns = jit(...) / self._fns[k] = ...)
        self.stores: Dict[str, Tuple[int, ...]] = {}
        # function name -> argnums (factory methods / functions)
        self.factories: Dict[str, Tuple[int, ...]] = {}
        # function name -> index of the forwarded-callable parameter
        # (positional, self excluded at call sites via naming)
        self.forwarders: Dict[str, int] = {}
        for _ in range(3):          # tiny fixpoint: store <-> factory
            before = (dict(self.stores), dict(self.factories))
            self._scan()
            if (self.stores, self.factories) == before:
                break
        self._find_forwarders()

    def _scan(self) -> None:
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Assign):
                nums = self._donating_value(node.value)
                if nums is None:
                    continue
                for tgt in node.targets:
                    attr = _self_attr_of(tgt)
                    if attr is None and isinstance(tgt, ast.Subscript):
                        attr = _self_attr_of(tgt.value)
                    if attr is not None:
                        self.stores[attr] = nums
            elif isinstance(node, ast.Return) and node.value is not None:
                nums = self._donating_value(node.value)
                if nums is not None:
                    fn = self.mod.enclosing_function(node)
                    if fn is not None:
                        self.factories[fn.name] = nums

    def _donating_value(self, expr: ast.expr) -> Optional[Tuple[int, ...]]:
        if isinstance(expr, ast.Call):
            nums = _donate_argnums(expr)
            if nums is not None:
                return nums
            # self._factory(...) returning a donating callable
            attr = _self_attr_of(expr.func)
            if attr is not None and attr in self.factories:
                return self.factories[attr]
            name = func_simple_name(expr.func)
            if name in self.factories:
                return self.factories[name]
            return None
        if isinstance(expr, ast.Subscript):
            attr = _self_attr_of(expr.value)
            if attr is not None and attr in self.stores:
                return self.stores[attr]
        attr = _self_attr_of(expr)
        if attr is not None and attr in self.stores:
            return self.stores[attr]
        return None

    def _find_forwarders(self) -> None:
        """``def w(self, a, f, *rest): ... f(*rest)`` — calling through
        ``w`` applies f's donation to the payload after f's position."""
        for fn in self.mod.functions():
            vararg = fn.args.vararg
            if vararg is None:
                continue
            pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id in pos and \
                        any(isinstance(a, ast.Starred) and
                            isinstance(a.value, ast.Name) and
                            a.value.id == vararg.arg
                            for a in node.args):
                    idx = pos.index(node.func.id)
                    if pos and pos[0] in ("self", "cls"):
                        idx -= 1
                    self.forwarders[fn.name] = idx


class DonationReuseRule(ProjectRule):
    id = "donation-reuse"
    description = ("value read after being donated (donate_argnums) "
                   "to a compiled dispatch — deleted/aliased buffer")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            facts = _ModuleFacts(mod)
            for fn in mod.functions():
                yield from self._check_fn(mod, facts, fn)

    def _check_fn(self, mod: ModuleInfo, facts: _ModuleFacts,
                  fn: ast.AST) -> Iterator[Finding]:
        # names bound to donating callables inside this function
        bound: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                nums = facts._donating_value(node.value)
                if nums is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            bound[tgt.id] = nums
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            donated = self._donated_args(mod, facts, bound, node)
            for pos, arg in donated:
                if not isinstance(arg, ast.Name):
                    continue
                read = self._read_after(mod, fn, node, arg.id)
                if read is not None:
                    yield self.finding(
                        mod, read,
                        f"'{arg.id}' is read after being donated "
                        f"(donate_argnums position {pos}) to a "
                        f"compiled dispatch — the buffer is deleted "
                        f"or aliases the output; rebind it from the "
                        f"call's results instead")

    def _donated_args(self, mod: ModuleInfo, facts: _ModuleFacts,
                      bound: Dict[str, Tuple[int, ...]],
                      call: ast.Call) -> List[Tuple[int, ast.expr]]:
        """(donated position, argument expr) pairs of one call."""
        func = call.func
        nums: Optional[Tuple[int, ...]] = None
        offset = 0
        # fn(...) with fn bound to a donating callable
        if isinstance(func, ast.Name) and func.id in bound:
            nums = bound[func.id]
        # self._fns[key](...) / self._factory(...)(...)
        if nums is None and isinstance(func, ast.Subscript):
            attr = _self_attr_of(func.value)
            if attr is not None:
                nums = facts.stores.get(attr)
        if nums is None and isinstance(func, ast.Call):
            nums = facts._donating_value(func)
        # forwarder: self._run(site, fn, *payload)
        if nums is None:
            fname = func_simple_name(func)
            if fname in facts.forwarders and call.args:
                fpos = facts.forwarders[fname]
                if fpos < len(call.args):
                    inner = call.args[fpos]
                    inner_nums = None
                    if isinstance(inner, ast.Name):
                        inner_nums = bound.get(inner.id)
                    if inner_nums is None:
                        inner_nums = facts._donating_value(inner)
                    if inner_nums is not None:
                        nums = inner_nums
                        offset = fpos + 1
        if nums is None:
            return []
        out = []
        for k in nums:
            idx = k + offset
            if idx < len(call.args):
                out.append((k, call.args[idx]))
        return out

    def _read_after(self, mod: ModuleInfo, fn: ast.AST, call: ast.Call,
                    name: str) -> Optional[ast.AST]:
        """First Load of ``name`` after the donating call's line, unless
        a Store to it happens first (rebinding — including the call's
        own assignment targets, which share its line)."""
        call_line = getattr(call, "lineno", 0)
        events: List[Tuple[int, int, str, ast.AST]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == name:
                line = getattr(node, "lineno", 0)
                if line < call_line:
                    continue
                kind = "store" if isinstance(
                    node.ctx, (ast.Store, ast.Del)) else "load"
                if kind == "load" and line == call_line:
                    continue        # the donating call's own argument
                events.append((line, getattr(node, "col_offset", 0),
                               kind, node))
        for line, _col, kind, node in sorted(
                events, key=lambda e: (e[0], 0 if e[2] == "store"
                                       else 1, e[1])):
            if kind == "store":
                return None
            return node
        return None
