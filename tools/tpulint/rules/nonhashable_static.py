"""nonhashable-static: ``static_argnums``/``static_argnames`` naming a
parameter whose default (or annotation) is a list/dict/set.

``jax.jit`` hashes static args into the compile-cache key; a list or
dict default means a guaranteed ``TypeError: unhashable type`` the
first time the default is actually exercised — typically long after the
code "worked" with explicit tuples in tests. Fix: make the default a
tuple / frozenset, or pass the structure as a traced pytree arg.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from ..core import Finding, ModuleInfo, Rule, func_simple_name

JIT_NAMES = {"jit", "pjit"}
NONHASHABLE_TYPES = {"list", "dict", "set", "List", "Dict", "Set",
                     "bytearray"}


def _nonhashable_default(node: Optional[ast.expr]) -> bool:
    if node is None:
        return False
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and \
            func_simple_name(node.func) in NONHASHABLE_TYPES:
        return True
    return False


def _nonhashable_annotation(node: Optional[ast.expr]) -> bool:
    if node is None:
        return False
    base = node.value if isinstance(node, ast.Subscript) else node
    name = base.id if isinstance(base, ast.Name) else \
        base.attr if isinstance(base, ast.Attribute) else None
    return name in NONHASHABLE_TYPES


def _params_with_defaults(fn: ast.AST) -> List[tuple]:
    """[(arg, default_or_None)] over posonly+positional (+kwonly)."""
    pos = list(fn.args.posonlyargs) + list(fn.args.args)
    defaults = list(fn.args.defaults)
    pad = [None] * (len(pos) - len(defaults))
    out = list(zip(pos, pad + defaults))
    out += list(zip(fn.args.kwonlyargs, fn.args.kw_defaults))
    return out


class NonhashableStaticRule(Rule):
    id = "nonhashable-static"
    description = ("static_argnums/static_argnames names a list/dict-"
                   "typed parameter (unhashable jit cache key)")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        fn_by_name = {}
        for fn in mod.functions():
            fn_by_name.setdefault(fn.name, fn)
        for fn in mod.functions():
            # decorator form: @jax.jit(...)/@partial(jax.jit, ...)
            for dec in fn.decorator_list:
                call = dec if isinstance(dec, ast.Call) else None
                if call is None:
                    continue
                target = call
                if func_simple_name(call.func) == "partial" and \
                        call.args and \
                        func_simple_name(call.args[0]) in JIT_NAMES:
                    pass
                elif func_simple_name(call.func) in JIT_NAMES:
                    pass
                else:
                    continue
                yield from self._check_call(mod, target, fn)
        # call form: jax.jit(f, static_argnums=...)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            names = None
            if func_simple_name(node.func) in JIT_NAMES and node.args \
                    and isinstance(node.args[0], ast.Name):
                names = node.args[0].id
            if names is None:
                continue
            target_fn = fn_by_name.get(names)
            if target_fn is not None:
                yield from self._check_call(mod, node, target_fn)

    def _check_call(self, mod: ModuleInfo, call: ast.Call,
                    fn: ast.AST) -> Iterator[Finding]:
        params = _params_with_defaults(fn)
        by_name = {a.arg: (a, d) for a, d in params}
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for name in self._const_strs(kw.value):
                    if name in by_name:
                        arg, default = by_name[name]
                        yield from self._flag(mod, call, fn, arg,
                                              default)
            elif kw.arg == "static_argnums":
                for idx in self._const_ints(kw.value):
                    if 0 <= idx < len(params):
                        arg, default = params[idx]
                        yield from self._flag(mod, call, fn, arg,
                                              default)

    def _flag(self, mod, call, fn, arg, default) -> Iterator[Finding]:
        if _nonhashable_default(default) or \
                _nonhashable_annotation(arg.annotation):
            yield self.finding(
                mod, call,
                f"static arg '{arg.arg}' of {fn.name}() has a "
                "list/dict/set default or annotation — jit hashes "
                "static args, so this raises 'unhashable type' at the "
                "first default call; use a tuple or pass it traced")

    @staticmethod
    def _const_strs(node: ast.expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value
        elif isinstance(node, (ast.Tuple, ast.List)):
            for el in node.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    yield el.value

    @staticmethod
    def _const_ints(node: ast.expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            yield node.value
        elif isinstance(node, (ast.Tuple, ast.List)):
            for el in node.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, int):
                    yield el.value
