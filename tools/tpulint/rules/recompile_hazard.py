"""recompile-hazard: raw shape-derived Python ints at a jit boundary.

This repo compiles serving programs through shape-keyed *factories*
(``_prefill_fn`` / ``_decode_fn`` / ``_step_fn`` — the ``*_fn`` naming
convention): every int argument to a factory becomes part of a compile
cache key, so an int derived from a runtime length — ``len(prompt)``,
``ids.shape[1]``, page counts — silently compiles one XLA program *per
distinct value*. That is the exact hazard PR 1's ``_bucket()`` lattice
exists to kill (cf. the recompile-sensitivity lessons in the Ragged
Paged Attention paper).

Scope and sanitization:
- boundary = a call whose callee name ends in ``_fn`` (the factory
  convention). Calls to the *returned* jitted function are not
  boundaries: there, Python ints become weak-typed traced scalars and
  do not fork compilations.
- a value is sanitized once it flows through a ``*bucket*`` call or a
  module-local function that itself buckets (``_max_len``).
- shape-taint propagates through arithmetic and through
  len/int/min/max/abs/sum/round only; any other call is a barrier (its
  result is an arbitrary object, usually an array, not a key int).
- array-producing arguments (``jnp.asarray(...)``, ``x.reshape(...)``
  method calls) and subscript indices (``a[:n]``) are exempt.
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import Finding, ModuleInfo, Rule, func_simple_name

# calls whose int result stays "the same int" for taint purposes
PROP_FUNCS = {"len", "int", "min", "max", "abs", "sum", "round",
              "float", "divmod", "bool"}
ASARRAY_WRAPPERS = {"asarray", "array", "int32", "int64", "full",
                    "arange", "zeros", "ones", "Tensor", "to_tensor"}


def _shape_refs(node: ast.expr, shape_derived: Set[str],
                sanitizers: Set[str]):
    """Yield offending references in ``node``: shape metadata reads and
    shape-derived names — honoring call barriers, bucket sanitizers and
    subscript-index exemption."""
    if isinstance(node, ast.Call):
        name = func_simple_name(node.func) or ""
        if "bucket" in name or name in sanitizers:
            return                          # sanitized subtree
        if name == "len":
            yield "len(...)"
            return
        if name not in PROP_FUNCS:
            return                          # barrier: opaque result
        for arg in node.args:
            yield from _shape_refs(arg, shape_derived, sanitizers)
        return
    if isinstance(node, ast.Attribute):
        if node.attr in ("shape", "ndim", "size"):
            yield f".{node.attr}"
            return
        yield from _shape_refs(node.value, shape_derived, sanitizers)
        return
    if isinstance(node, ast.Subscript):
        # a[:n] / a[i] passes a's elements, not the index int
        yield from _shape_refs(node.value, shape_derived, sanitizers)
        return
    if isinstance(node, ast.Name):
        if node.id in shape_derived:
            yield node.id
        return
    for child in ast.iter_child_nodes(node):
        yield from _shape_refs(child, shape_derived, sanitizers)


class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    description = ("shape/length-derived Python int reaches a *_fn jit "
                   "factory without _bucket()-style quantization")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        sanitizers = mod.sanitizer_names()
        for fn in mod.functions():
            yield from self._check_function(mod, fn, sanitizers)

    def _check_function(self, mod: ModuleInfo, fn: ast.AST,
                        sanitizers: Set[str]) -> Iterator[Finding]:
        shape_derived: Set[str] = set()
        changed = True
        passes = 0
        while changed and passes < 10:
            changed = False
            passes += 1
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign) and \
                        isinstance(node.target, ast.Name):
                    targets, value = [node.target], node.value
                else:
                    continue
                if not any(_shape_refs(value, shape_derived, sanitizers)):
                    continue
                names = []
                for t in targets:
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        names += [e.id for e in t.elts
                                  if isinstance(e, ast.Name)]
                for n in names:
                    if n not in shape_derived:
                        shape_derived.add(n)
                        changed = True
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            callee = func_simple_name(call.func) or ""
            if not callee.endswith("_fn"):
                continue
            for arg in list(call.args) + \
                    [k.value for k in call.keywords]:
                if self._arg_is_array(arg):
                    continue
                bad = next(_shape_refs(arg, shape_derived, sanitizers),
                           None)
                if bad:
                    yield self.finding(
                        mod, call,
                        f"shape-derived int '{bad}' reaches jit factory "
                        f"'{callee}(...)' unquantized — every distinct "
                        "value compiles a new XLA program; round it "
                        "onto the _bucket() lattice first")

    @staticmethod
    def _arg_is_array(arg: ast.expr) -> bool:
        """jnp.asarray(...) / x.reshape(...)-style args are traced
        operands whose SHAPE is already fixed by upstream bucketing —
        their values don't key the factory cache."""
        if not isinstance(arg, ast.Call):
            return False
        return func_simple_name(arg.func) in (
            ASARRAY_WRAPPERS | {"reshape", "astype", "ravel", "flatten"})