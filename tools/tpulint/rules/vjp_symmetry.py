"""vjp-ledger-symmetry: custom_vjp fwd/bwd collective pairing.

A ``jax.custom_vjp`` whose forward issues ledger-shimmed (``t_*``)
collectives owns its backward's communication too: jax's default
transposes call ``lax`` directly (the PR-7 ``_ledger_a2a`` bug class),
so a bwd that issues NO ``t_*`` collective usually means the backward
exchanges run outside the comm ledger — or do not run at all.

Accepted pairings (the ones the tree documents):

- *mirrored ring* (collective_matmul.py): each non-reduce op kind the
  fwd issues has its transpose kind in the bwd — ``all_gather`` ↔
  ``reduce_scatter``, ``all_to_all`` ↔ ``all_to_all``, ``ppermute`` ↔
  ``ppermute``;
- *psum/identity* (Megatron mp_ops pairing): a fwd issuing only
  reduce-family ops (psum/pmean/pmax/pmin) pairs with an identity bwd
  — the cotangent is replicated, no backward comm is correct;
- *gather/slice* (the _c_concat pairing): a fwd issuing only
  ``all_gather`` pairs with a bwd that takes a local slice
  (``dynamic_slice_in_dim`` et al.) of the replicated cotangent.

Anything else — fwd collectives with an empty bwd, or a bwd whose op
kinds are not the mirrors — is flagged at the ``defvjp`` call.
Collective facts are transitive and cross-module (the ring impl
helpers live behind two layers of delegation).
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from ..core import Finding, ModuleInfo, func_simple_name
from ..project import Project, ProjectRule

REDUCE_KINDS = {"psum", "pmax", "pmin"}
MIRROR = {
    "all_gather": {"reduce_scatter"},
    "reduce_scatter": {"all_gather"},
    "all_to_all": {"all_to_all"},
    "ppermute": {"ppermute"},
}
_SLICE_CALLS = {"dynamic_slice_in_dim", "slice_in_dim", "dynamic_slice",
                "slice", "take_along_axis"}


def _is_custom_vjp_def(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = func_simple_name(target)
        if name == "custom_vjp":
            return True
        if name == "partial" and isinstance(dec, ast.Call) and dec.args \
                and func_simple_name(dec.args[0]) == "custom_vjp":
            return True
    return False


class VjpSymmetryRule(ProjectRule):
    id = "vjp-ledger-symmetry"
    description = ("custom_vjp fwd issues t_* collectives but bwd is "
                   "not the mirrored/documented pairing")

    def check_project(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute) or \
                        node.func.attr != "defvjp" or \
                        len(node.args) < 2:
                    continue
                primal = self._resolve_primal(project, mod, node.func.value)
                if primal is None or not _is_custom_vjp_def(primal):
                    continue
                fwd_kinds = self._kinds_of(project, mod, node, node.args[0])
                bwd_kinds = self._kinds_of(project, mod, node, node.args[1])
                if fwd_kinds is None or bwd_kinds is None or \
                        not fwd_kinds:
                    continue
                msg = self._verdict(project, mod, node, primal,
                                    fwd_kinds, bwd_kinds)
                if msg:
                    yield self.finding(mod, node, msg)

    # -- resolution ------------------------------------------------------
    def _resolve_primal(self, project: Project, mod: ModuleInfo,
                        expr: ast.expr) -> Optional[ast.AST]:
        scope = mod.enclosing_function(expr)
        hits = project.resolve_callable(mod, scope, expr)
        return hits[0][1] if hits else None

    def _fn_nodes(self, project: Project, mod: ModuleInfo,
                  at: ast.AST, expr: ast.expr
                  ) -> Optional[List[Tuple[ModuleInfo, ast.AST]]]:
        """The function bodies an fwd/bwd argument denotes: a lambda is
        itself; a name resolves through the project. None = opaque."""
        if isinstance(expr, ast.Lambda):
            return [(mod, expr)]
        scope = mod.enclosing_function(at)
        hits = project.resolve_callable(mod, scope, expr)
        return hits or None

    def _kinds_of(self, project: Project, mod: ModuleInfo, at: ast.AST,
                  expr: ast.expr) -> Optional[Set[str]]:
        fns = self._fn_nodes(project, mod, at, expr)
        if fns is None:
            return None
        kinds: Set[str] = set()
        for m, fn in fns:
            kinds |= project.collective_kinds(m, fn)
        return kinds

    def _bwd_has_slice(self, project: Project, mod: ModuleInfo,
                       at: ast.AST, expr: ast.expr) -> bool:
        fns = self._fn_nodes(project, mod, at, expr) or []
        for m, fn in fns:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        func_simple_name(node.func) in _SLICE_CALLS:
                    return True
        return False

    # -- the pairing table -----------------------------------------------
    def _verdict(self, project, mod, node, primal, fwd_kinds,
                 bwd_kinds) -> Optional[str]:
        name = getattr(primal, "name", "<custom_vjp>")
        ring = sorted(fwd_kinds - REDUCE_KINDS)
        if not bwd_kinds:
            if not ring:
                return None           # psum/identity (Megatron) pairing
            if set(ring) == {"all_gather"} and self._bwd_has_slice(
                    project, mod, node, node.args[1]):
                return None           # gather/slice (_c_concat) pairing
            return (f"custom_vjp '{name}': fwd issues ledger-shimmed "
                    f"{sorted(fwd_kinds)} but bwd issues no t_* "
                    f"collective — the backward exchange either runs "
                    f"outside the comm ledger (raw lax transpose) or "
                    f"is missing; mirror the ring in the bwd "
                    f"(collective_matmul.py pairing table)")
        missing = [k for k in ring
                   if not (MIRROR.get(k, {k}) & bwd_kinds)]
        if missing:
            return (f"custom_vjp '{name}': bwd {sorted(bwd_kinds)} is "
                    f"not the mirrored pairing of fwd "
                    f"{sorted(fwd_kinds)} — missing the transpose of "
                    f"{missing} (all_gather↔reduce_scatter, "
                    f"a2a↔a2a, ppermute↔ppermute)")
        return None
