"""mesh-axis-contract: axis literals must name a real mesh axis.

A wrong axis string in a ``t_*`` collective or a ``PartitionSpec``
compiles clean — jax resolves axes at trace time against whatever mesh
is current, and an unknown name either errors deep inside a jit trace
or, worse, silently reshards. The rule resolves the project's mesh-axis
vocabulary statically and flags any literal that falls outside it.

Vocabulary = the canonical fleet axes (``dp``/``pp``/``sharding``/
``sep``/``ep``/``mp`` from the topology order plus the flat ``world``
mesh) unioned with every axis the tree *declares*: string literals in
``Mesh(devices, (...))`` second-positional or ``axis_names=`` tuples
(``shard_map``/``new_group`` sites included), and module-level
``*_ORDER``/``*_AXES`` string-tuple constants. Declaring an axis
anywhere puts it in scope everywhere — the checker proves "this name
exists on some mesh", not placement, which keeps it zero-false-positive
on multi-mesh trees.

Checked sites: the ``t_*`` collective shims and their quantized
wrappers (axes is the second positional or the ``axes=`` kwarg;
literal strings and tuples/lists of strings resolve, anything dynamic
is skipped), and ``P(...)``/``PartitionSpec(...)`` entries including
nested tuple entries, in modules that import PartitionSpec.

One extra contract where it is statically resolvable: within a
function, literal ``P`` specs pin which dims an axis shards; a
``t_psum_scatter(..., axes, scatter_dimension=k)`` with a literal axis
that those specs place on *different* dims is flagged — the classic
``_ZeroPlan`` drift where the spec moves and the scatter dim does not.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding, ModuleInfo, func_simple_name
from ..project import Project, ProjectRule

CANONICAL_AXES = {"dp", "pp", "sharding", "sep", "ep", "mp", "world"}

# shims whose signature is (value, axes, ...)
AXES_ARG1 = {"t_psum", "t_all_gather", "t_psum_scatter", "t_ppermute",
             "t_all_to_all", "maybe_quantized_psum",
             "quantized_reduce_scatter", "quantized_allreduce",
             "quantized_param_gather"}
_VOCAB_NAME_RE = re.compile(r"(axis|axes|order)", re.IGNORECASE)


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _str_seq(node: ast.AST) -> Optional[List[str]]:
    """All-string literal tuple/list, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for elt in node.elts:
        s = _str_const(elt)
        if s is None:
            return None
        out.append(s)
    return out


def _axis_literals(node: ast.AST) -> Optional[List[Tuple[str, ast.AST]]]:
    """(axis, anchor) pairs for a literal axes argument; None when the
    argument is dynamic (a variable, an attribute, ...)."""
    s = _str_const(node)
    if s is not None:
        return [(s, node)]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            s = _str_const(elt)
            if s is None:
                return None
            out.append((s, elt))
        return out
    return None


class MeshAxisContractRule(ProjectRule):
    id = "mesh-axis-contract"
    description = ("collective axis literal or PartitionSpec entry "
                   "naming an axis no mesh declares, or a scatter dim "
                   "contradicting the specs")

    def check_project(self, project: Project) -> Iterator[Finding]:
        vocab = self._vocabulary(project)
        for mod in project.modules:
            p_names = self._partition_spec_names(mod)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = func_simple_name(node.func)
                if name in AXES_ARG1:
                    yield from self._check_collective(
                        mod, node, vocab)
                elif name in p_names:
                    yield from self._check_spec(mod, node, vocab)
            yield from self._check_scatter_dims(mod, p_names)

    # -- vocabulary -------------------------------------------------------
    def _vocabulary(self, project: Project) -> Set[str]:
        vocab = set(CANONICAL_AXES)
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    if func_simple_name(node.func) == "Mesh" and \
                            len(node.args) >= 2:
                        vocab |= set(_str_seq(node.args[1]) or ())
                    for kw in node.keywords:
                        if kw.arg == "axis_names":
                            vocab |= set(_str_seq(kw.value) or ())
                            s = _str_const(kw.value)
                            if s is not None:
                                vocab.add(s)
                elif isinstance(node, ast.Assign) and \
                        mod.enclosing_function(node) is None:
                    seq = _str_seq(node.value)
                    if seq:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name) and \
                                    _VOCAB_NAME_RE.search(tgt.id):
                                vocab |= set(seq)
        return vocab

    @staticmethod
    def _partition_spec_names(mod: ModuleInfo) -> Set[str]:
        """Local names PartitionSpec is bound to (P, PartitionSpec, an
        as-alias); empty when the module never imports it."""
        out: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "PartitionSpec":
                        out.add(alias.asname or alias.name)
        return out

    # -- checks -----------------------------------------------------------
    @staticmethod
    def _axes_arg(node: ast.Call) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == "axes":
                return kw.value
        if len(node.args) >= 2:
            return node.args[1]
        return None

    def _check_collective(self, mod: ModuleInfo, node: ast.Call,
                          vocab: Set[str]) -> Iterator[Finding]:
        arg = self._axes_arg(node)
        if arg is None:
            return
        lits = _axis_literals(arg)
        if lits is None:
            return
        fname = func_simple_name(node.func)
        for axis, anchor in lits:
            if axis not in vocab:
                yield self.finding(
                    mod, anchor,
                    f"{fname} over unknown mesh axis '{axis}' — no "
                    f"Mesh/shard_map in the tree declares it "
                    f"(known: {', '.join(sorted(vocab))}); a typo "
                    f"here errors at trace time or silently reshards")

    def _check_spec(self, mod: ModuleInfo, node: ast.Call,
                    vocab: Set[str]) -> Iterator[Finding]:
        for arg in node.args:
            entries = [arg]
            if isinstance(arg, (ast.Tuple, ast.List)):
                entries = list(arg.elts)
            for entry in entries:
                s = _str_const(entry)
                if s is not None and s not in vocab:
                    yield self.finding(
                        mod, entry,
                        f"PartitionSpec names unknown mesh axis "
                        f"'{s}' — no Mesh/shard_map in the tree "
                        f"declares it (known: "
                        f"{', '.join(sorted(vocab))})")

    def _check_scatter_dims(self, mod: ModuleInfo,
                            p_names: Set[str]) -> Iterator[Finding]:
        if not p_names:
            return
        for fn in mod.functions():
            spec_dims: Dict[str, Set[int]] = {}
            scatters: List[Tuple[ast.Call, str, int]] = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = func_simple_name(node.func)
                if name in p_names:
                    for dim, arg in enumerate(node.args):
                        entries = list(arg.elts) if isinstance(
                            arg, (ast.Tuple, ast.List)) else [arg]
                        for entry in entries:
                            s = _str_const(entry)
                            if s is not None:
                                spec_dims.setdefault(s, set()).add(dim)
                elif name == "t_psum_scatter":
                    arg = self._axes_arg(node)
                    axis = _str_const(arg) if arg is not None else None
                    if axis is None:
                        continue
                    dim_node = None
                    for kw in node.keywords:
                        if kw.arg == "scatter_dimension":
                            dim_node = kw.value
                    if dim_node is None and len(node.args) >= 3:
                        dim_node = node.args[2]
                    if dim_node is None:
                        dim = 0
                    elif isinstance(dim_node, ast.Constant) and \
                            isinstance(dim_node.value, int):
                        dim = dim_node.value
                    else:
                        continue    # dynamic dim: not resolvable
                    scatters.append((node, axis, dim))
            for node, axis, dim in scatters:
                dims = spec_dims.get(axis)
                if dims and dim not in dims:
                    yield self.finding(
                        mod, node,
                        f"t_psum_scatter over '{axis}' with "
                        f"scatter_dimension={dim}, but the specs in "
                        f"this function shard '{axis}' on dim"
                        f"{'s' if len(dims) > 1 else ''} "
                        f"{sorted(dims)} — the scatter dim and the "
                        f"spec drifted apart")
