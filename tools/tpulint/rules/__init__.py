"""tpulint rule registry. A rule is a ``core.Rule`` subclass; adding a
module here (and instantiating it in ALL_RULES) is the whole plugin
surface — the CLI, baseline, suppression and JSON layers are generic.
"""
from __future__ import annotations

from typing import Dict, List

from ..core import Rule
from .host_sync import HostSyncInJitRule
from .nonhashable_static import NonhashableStaticRule
from .recompile_hazard import RecompileHazardRule
from .traced_bool import TracedBoolRule
from .unused_knob import UnusedKnobRule

ALL_RULES: List[Rule] = [
    UnusedKnobRule(),
    HostSyncInJitRule(),
    TracedBoolRule(),
    NonhashableStaticRule(),
    RecompileHazardRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}


def select_rules(ids=None) -> List[Rule]:
    if not ids:
        return list(ALL_RULES)
    unknown = [i for i in ids if i not in RULES_BY_ID]
    if unknown:
        raise KeyError(f"unknown rule id(s): {', '.join(unknown)} "
                       f"(known: {', '.join(sorted(RULES_BY_ID))})")
    return [RULES_BY_ID[i] for i in ids]
