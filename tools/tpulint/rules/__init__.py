"""tpulint rule registry. A rule is a ``core.Rule`` subclass (or a
``project.ProjectRule`` when it needs the whole-tree interprocedural
pass); adding a module here (and instantiating it in ALL_RULES) is the
whole plugin surface — the CLI, baseline, suppression and JSON layers
are generic.
"""
from __future__ import annotations

from typing import Dict, List

from ..core import Rule
from .blocking_under_lock import BlockingUnderLockRule
from .donation_reuse import DonationReuseRule
from .host_sync import HostSyncInJitRule
from .lock_order import LockOrderCycleRule
from .mesh_axis import MeshAxisContractRule
from .nonhashable_static import NonhashableStaticRule
from .raw_collective import RawCollectiveRule
from .recompile_hazard import RecompileHazardRule
from .shared_mutation import SharedMutationRule
from .traced_bool import TracedBoolRule
from .unregistered_metric import UnregisteredMetricRule
from .unused_knob import UnusedKnobRule
from .vjp_symmetry import VjpSymmetryRule

ALL_RULES: List[Rule] = [
    UnusedKnobRule(),
    HostSyncInJitRule(),
    TracedBoolRule(),
    NonhashableStaticRule(),
    RecompileHazardRule(),
    # the interprocedural contract rules (tools/tpulint/project.py)
    RawCollectiveRule(),
    UnregisteredMetricRule(),
    VjpSymmetryRule(),
    DonationReuseRule(),
    SharedMutationRule(),
    # the lock-graph rules (Project.lock_facts) + the mesh-axis contract
    LockOrderCycleRule(),
    BlockingUnderLockRule(),
    MeshAxisContractRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}


def select_rules(ids=None) -> List[Rule]:
    if not ids:
        return list(ALL_RULES)
    unknown = [i for i in ids if i not in RULES_BY_ID]
    if unknown:
        raise KeyError(f"unknown rule id(s): {', '.join(unknown)} "
                       f"(known: {', '.join(sorted(RULES_BY_ID))})")
    return [RULES_BY_ID[i] for i in ids]
