"""unregistered-metric: metric names vs the schema, both directions.

Metric names are API: dashboards and the scrape config key on them,
``observability/catalog.py`` declares them, ``schema.json`` pins them,
and the tier-1 schema gate compares a LIVE registry against the file.
That gate only sees metrics that were actually registered during the
test run — a registration on a path the tests never execute drifts
silently. This rule closes the gap statically:

- direction 1: every ``<registry>.counter("name", ...)`` / ``gauge`` /
  ``histogram`` call whose name is a string literal, anywhere in the
  tree, must name a metric present in ``schema.json``;
- direction 2: every ``schema.json`` entry must be registered by SOME
  call in the tree — an unpublished catalog entry is stale and gets
  flagged (anchored at the catalog module).

``jnp.histogram`` and friends never match: only calls whose first
argument is a string literal and whose receiver is not a jax-family
alias count as registrations.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ..core import (JAX_ROOT_RE, Finding, ModuleInfo, Rule, func_root,
                    func_simple_name)
from ..project import Project, ProjectRule

_REGISTER_METHODS = {"counter", "gauge", "histogram"}


def collect_registrations(project: Project
                          ) -> List[Tuple[ModuleInfo, ast.Call, str]]:
    """Every (module, call, metric-name) registration site with a
    string-literal name in the project."""
    out: List[Tuple[ModuleInfo, ast.Call, str]] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute) or \
                    node.func.attr not in _REGISTER_METHODS:
                continue
            if not node.args or \
                    not isinstance(node.args[0], ast.Constant) or \
                    not isinstance(node.args[0].value, str):
                continue
            root = func_root(node.func)
            if root is not None and JAX_ROOT_RE.match(root):
                continue            # jnp.histogram(x, ...) etc.
            out.append((mod, node, node.args[0].value))
    return out


def registered_names(project: Project) -> Set[str]:
    """The full statically-visible metric set (the single source of
    truth the hardened schema gate compares schema.json against)."""
    return {name for _, _, name in collect_registrations(project)}


class UnregisteredMetricRule(ProjectRule):
    id = "unregistered-metric"
    description = ("metric registered outside schema.json, or a "
                   "schema.json entry no code registers (stale)")

    def check_project(self, project: Project) -> Iterator[Finding]:
        schema = project.resource("metric_schema")
        if not isinstance(schema, dict) or not schema:
            return                  # no schema in this tree: nothing to pin
        regs = collect_registrations(project)
        seen: Set[str] = set()
        catalog_mod = None
        counts: Dict[str, int] = {}
        for mod, node, name in regs:
            seen.add(name)
            counts[mod.relpath] = counts.get(mod.relpath, 0) + 1
            if name not in schema:
                yield self.finding(
                    mod, node,
                    f"metric {name!r} is registered here but missing "
                    f"from schema.json — dashboards/scrape configs key "
                    f"on the schema; declare it in observability/"
                    f"catalog.py and regenerate schema.json")
        if counts:
            catalog_mod = project.by_relpath[
                max(counts, key=lambda k: counts[k])]
        if catalog_mod is None:
            return
        for name in sorted(set(schema) - seen):
            yield Finding(
                rule=self.id, path=catalog_mod.relpath, line=1, col=0,
                symbol="<schema>",
                message=(f"schema.json declares {name!r} but no code "
                         f"registers it — stale catalog entry; drop it "
                         f"from the schema or restore the registration"),
                line_text=f"<schema:{name}>")
