"""blocking-under-lock: no slow or unbounded work while a lock is held.

The serving admission RLock and the checkpoint condition variable sit
on every hot path; a jitted dispatch (compile + device execute), a
``block_until_ready``/``device_get`` sync, a timeout-less
``Queue.get``/``Thread.join``/``Event.wait``, file I/O, HTTP, or a
bare ``time.sleep`` executed inside one of those critical sections
serializes every other thread behind device or kernel time. The rule
consumes the Project lock graph's under-lock call sites (lexical
``with`` nesting plus the class entry-held fixpoint, so a private
helper only ever called under the lock is still "under the lock") and
convicts the blocking categories above.

Condition variables get protocol treatment instead of a blanket ban:
``cv.wait()`` with the *same* cv held is the correct idiom and is
exempt — unless a *different* lock is also held across the wait (that
lock is then pinned for an unbounded sleep). Two protocol sub-checks
ride along: ``cv.wait()`` outside a predicate loop (spurious wakeups
make the bare ``if``/``wait`` form wrong; ``wait_for`` encodes the
loop) and ``notify``/``notify_all`` without the condition held.

A liveness sub-check covers teardown: a timeout-less ``Queue.get()``
or ``Thread.join()`` in Thread-target-reachable code can never
observe shutdown — ``close()`` hangs behind it even with no lock held,
so those are flagged lock or no lock.

Scope mirrors unguarded-shared-mutation: the concurrent host-side
surfaces (serving, checkpointing, observability, elastic, watchdog).
Rebinding a jitted callable under the lock and dispatching after
release is the sanctioned pattern and is not flagged.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple

from ..core import Finding, ModuleInfo, func_simple_name
from ..project import Project, ProjectRule, _flatten_chain
from .shared_mutation import _in_scope

# os.* entry points that hit the filesystem (os.path.* is pure string
# manipulation and stays exempt via the chain-length check).
OS_IO = {"listdir", "makedirs", "mkdir", "rename", "replace", "remove",
         "unlink", "rmdir", "stat", "scandir", "walk", "fsync", "open"}
SYNC_NAMES = {"block_until_ready", "device_get"}


def _self_attr(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id in ("self", "cls"):
        return expr.attr
    return None


def _is_jit_value(value: ast.AST) -> bool:
    return isinstance(value, ast.Call) and \
        func_simple_name(value.func) in ("jit", "pjit")


def _timeoutless(call: ast.Call) -> bool:
    """No positional timeout and no timeout=/block= kwarg: the call
    can block forever."""
    if call.args:
        return False
    return not any(kw.arg in ("timeout", "block") for kw in call.keywords)


class BlockingUnderLockRule(ProjectRule):
    id = "blocking-under-lock"
    description = ("jitted dispatch, device sync, unbounded wait, or "
                   "I/O while a lock is held (or in teardown-critical "
                   "thread code)")

    def check_project(self, project: Project) -> Iterator[Finding]:
        facts = project.lock_facts()
        self._jit_cache: Dict[str, Tuple[Set[str], Dict[str, Set[str]]]] = {}
        self._local_cache: Dict[Tuple[str, int], Set[str]] = {}
        held_map: Dict[int, Tuple[str, ...]] = {
            id(call): held for _m, _f, call, held in facts.held_calls}
        seen: Set[Tuple[str, int, str]] = set()

        def emit(mod: ModuleInfo, node: ast.AST, kind: str,
                 message: str) -> Iterator[Finding]:
            key = (mod.relpath, getattr(node, "lineno", 0), kind)
            if key not in seen:
                seen.add(key)
                yield self.finding(mod, node, message)

        for mod, fn, call, held in facts.held_calls:
            if not _in_scope(mod.relpath):
                continue
            yield from self._check_held_call(
                project, facts, mod, fn, call, held, emit)
        for mod in project.modules:
            if not _in_scope(mod.relpath):
                continue
            yield from self._check_cv_protocol(
                project, facts, mod, held_map, emit)
            yield from self._check_teardown_liveness(
                project, facts, mod, held_map, emit)

    # -- under-lock categories -------------------------------------------
    def _check_held_call(self, project: Project, facts, mod: ModuleInfo,
                         fn: ast.AST, call: ast.Call,
                         held: Tuple[str, ...], emit) -> Iterator[Finding]:
        func = call.func
        locks = ", ".join(held)
        ci = project.class_of(mod, fn)

        if self._is_jit_dispatch(project, mod, fn, func):
            yield from emit(
                mod, call, "jit",
                f"jitted dispatch while holding {locks} — compile + "
                f"device execution serialize every other thread on the "
                f"lock; bind the callable under the lock, dispatch "
                f"after release")
            return
        name = func_simple_name(func)
        if name in SYNC_NAMES:
            yield from emit(
                mod, call, "sync",
                f"device sync ({name}) while holding {locks} — blocks "
                f"for full device latency; copy out after releasing")
            return

        attr = _self_attr(func.value) if isinstance(func, ast.Attribute) \
            else None
        if ci is not None and attr is not None:
            if name == "get" and attr in ci.queue_attrs and \
                    _timeoutless(call):
                yield from emit(
                    mod, call, "queue-get",
                    f"timeout-less self.{attr}.get() while holding "
                    f"{locks} — unbounded block with the lock pinned; "
                    f"use get(timeout=...) or move the get outside")
                return
            if name == "join" and attr in ci.thread_attrs and \
                    _timeoutless(call):
                yield from emit(
                    mod, call, "join",
                    f"timeout-less self.{attr}.join() while holding "
                    f"{locks} — the joined thread may need that very "
                    f"lock to exit; join(timeout=...) outside the lock")
                return
            if name == "wait" and attr in ci.event_attrs and \
                    _timeoutless(call):
                yield from emit(
                    mod, call, "event-wait",
                    f"timeout-less self.{attr}.wait() while holding "
                    f"{locks} — the setter may need the lock; wait "
                    f"with a timeout outside the critical section")
                return
        if isinstance(func, ast.Attribute) and \
                name in ("wait", "wait_for"):
            lid = facts.resolve_lock(mod, fn, func.value)
            if lid is not None and facts.kinds.get(lid) == "cond":
                others = [h for h in held if h != lid]
                if lid in held and others:
                    yield from emit(
                        mod, call, "cv-cross-lock",
                        f"condition wait on {lid} while ALSO holding "
                        f"{', '.join(others)} — the extra lock stays "
                        f"pinned for the whole (unbounded) wait; "
                        f"release it before waiting")
                elif lid not in held:
                    yield from emit(
                        mod, call, "cv-unheld",
                        f"condition wait on {lid} without holding it "
                        f"(while holding {locks}) — wait() requires "
                        f"the condition's own lock")
                return

        chain = _flatten_chain(func)
        if isinstance(func, ast.Name) and func.id == "open":
            yield from emit(
                mod, call, "io",
                f"file I/O (open) while holding {locks} — disk "
                f"latency serializes the lock; stage data out first")
        elif chain is not None and len(chain) >= 2:
            if chain[0] == "os" and len(chain) == 2 and chain[1] in OS_IO:
                yield from emit(
                    mod, call, "io",
                    f"file I/O (os.{chain[1]}) while holding {locks} — "
                    f"move filesystem work outside the critical section")
            elif chain[0] == "shutil":
                yield from emit(
                    mod, call, "io",
                    f"file I/O (shutil.{chain[1]}) while holding "
                    f"{locks} — move filesystem work outside the "
                    f"critical section")
            elif chain[0] == "requests" or chain[-1] == "urlopen":
                yield from emit(
                    mod, call, "http",
                    f"HTTP call while holding {locks} — network "
                    f"latency is unbounded; never under a lock")
            elif chain == ["time", "sleep"]:
                yield from emit(
                    mod, call, "sleep",
                    f"time.sleep while holding {locks} — sleeping "
                    f"with a lock held starves every waiter")
        elif name == "urlopen":
            yield from emit(
                mod, call, "http",
                f"HTTP call (urlopen) while holding {locks} — network "
                f"latency is unbounded; never under a lock")

    # -- jit-binding facts -----------------------------------------------
    def _jit_bindings(self, project: Project, mod: ModuleInfo
                      ) -> Tuple[Set[str], Dict[str, Set[str]]]:
        cached = self._jit_cache.get(mod.relpath)
        if cached is not None:
            return cached
        globs: Set[str] = set()
        attrs: Dict[str, Set[str]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or \
                    not _is_jit_value(node.value):
                continue
            encl = mod.enclosing_function(node)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and encl is None:
                    globs.add(tgt.id)
                    continue
                base = tgt.value if isinstance(tgt, ast.Subscript) \
                    else tgt
                attr = _self_attr(base)
                if attr is not None and encl is not None:
                    ci = project.class_of(mod, encl)
                    if ci is not None:
                        attrs.setdefault(ci.name, set()).add(attr)
        result = (globs, attrs)
        self._jit_cache[mod.relpath] = result
        return result

    def _local_jit_names(self, project: Project, mod: ModuleInfo,
                         fn: ast.AST) -> Set[str]:
        key = (mod.relpath, id(fn))
        cached = self._local_cache.get(key)
        if cached is not None:
            return cached
        _globs, attrs = self._jit_bindings(project, mod)
        ci = project.class_of(mod, fn)
        cls_attrs = attrs.get(ci.name, set()) if ci is not None else set()
        out: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            is_jit = _is_jit_value(value) or \
                (_self_attr(value) in cls_attrs
                 if isinstance(value, ast.Attribute) else False)
            if is_jit:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        self._local_cache[key] = out
        return out

    def _is_jit_dispatch(self, project: Project, mod: ModuleInfo,
                         fn: ast.AST, func: ast.expr) -> bool:
        if isinstance(func, ast.Call):
            return _is_jit_value(func)        # jax.jit(f)(x) inline
        globs, attrs = self._jit_bindings(project, mod)
        if isinstance(func, ast.Name):
            return func.id in globs or \
                func.id in self._local_jit_names(project, mod, fn)
        base = func.value if isinstance(func, ast.Subscript) else func
        attr = _self_attr(base)
        if attr is not None:
            ci = project.class_of(mod, fn)
            if ci is not None and attr in attrs.get(ci.name, set()):
                return True
        return False

    # -- CV protocol ------------------------------------------------------
    def _check_cv_protocol(self, project: Project, facts,
                           mod: ModuleInfo,
                           held_map: Dict[int, Tuple[str, ...]],
                           emit) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            name = node.func.attr
            if name not in ("wait", "notify", "notify_all"):
                continue
            fn = mod.enclosing_function(node)
            lid = facts.resolve_lock(mod, fn, node.func.value)
            if lid is None or facts.kinds.get(lid) != "cond":
                continue
            if name == "wait":
                if not self._in_predicate_loop(mod, node):
                    yield from emit(
                        mod, node, "cv-no-loop",
                        f"condition wait on {lid} outside a predicate "
                        f"loop — spurious wakeups and stolen wakeups "
                        f"make bare wait() wrong; use `while not "
                        f"pred: cv.wait()` or cv.wait_for(pred)")
            else:
                held = held_map.get(id(node), ())
                if lid not in held:
                    yield from emit(
                        mod, node, "cv-notify-unheld",
                        f"{name}() on {lid} without holding it — "
                        f"notify outside the condition's lock races "
                        f"the waiter's predicate check")

    @staticmethod
    def _in_predicate_loop(mod: ModuleInfo, node: ast.AST) -> bool:
        cur = mod.parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(cur, ast.While):
                return True
            cur = mod.parent(cur)
        return False

    # -- teardown liveness ------------------------------------------------
    def _check_teardown_liveness(self, project: Project, facts,
                                 mod: ModuleInfo,
                                 held_map: Dict[int, Tuple[str, ...]],
                                 emit) -> Iterator[Finding]:
        for fn in mod.functions():
            if not project.is_thread_reachable(mod, fn):
                continue
            ci = project.class_of(mod, fn)
            if ci is None:
                continue
            for node in facts._own_nodes(fn):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                if id(node) in held_map:
                    continue        # the under-lock pass already owns it
                attr = _self_attr(node.func.value)
                if attr is None or not _timeoutless(node):
                    continue
                name = node.func.attr
                if name == "get" and attr in ci.queue_attrs:
                    yield from emit(
                        mod, node, "teardown-get",
                        f"timeout-less self.{attr}.get() in Thread-"
                        f"reachable code — the loop can never observe "
                        f"shutdown and close()/join() hangs behind "
                        f"it; use get(timeout=...) and poll a stop "
                        f"Event")
                elif name == "join" and attr in ci.thread_attrs:
                    yield from emit(
                        mod, node, "teardown-join",
                        f"timeout-less self.{attr}.join() in Thread-"
                        f"reachable code — a wedged peer blocks this "
                        f"thread forever; join(timeout=...) and "
                        f"escalate")
