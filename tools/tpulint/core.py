"""tpulint core: the per-file analysis model shared by all rules.

A ``ModuleInfo`` wraps one parsed source file and lazily computes the
two module-wide analyses every trace-safety rule needs:

- *traced reachability*: which function defs execute under a jax trace
  (decorated with ``jax.jit``/``def_op``/..., passed to ``jax.jit`` /
  ``lax.scan`` / ``pallas_call`` / ..., nested inside such a function,
  or called from one — a transitive closure over same-module calls by
  simple name);
- *value taint*: per function, which local names hold traced array
  values (assigned from ``jnp.``/``jax.``/``lax.``-rooted expressions,
  or parameters that are passed straight into such calls). Shape-like
  accesses (``.shape``/``.ndim``/``.dtype``/``len()``) never taint —
  those are static under tracing.

Both are heuristics tuned for this repo's idiom (name-based, no cross-
file resolution); the baseline file and ``# tpulint: disable=<rule>``
pragmas absorb the residue, which is the design point of the tool.
"""
from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Optional, Set, Tuple

PRAGMA_RE = re.compile(
    r"tpulint:\s*(disable|disable-file)\s*=\s*([\w, \-]+)")

# leftmost roots of attribute chains that produce traced values
JAX_ROOT_RE = re.compile(r"^_?(jnp|jax|lax|pl|pltpu)\d?$")

# wrappers whose function-valued arguments run under a jax trace
TRACE_WRAPPERS = {
    "jit", "pjit", "shard_map", "scan", "while_loop", "fori_loop",
    "cond", "switch", "vmap", "pmap", "grad", "value_and_grad",
    "checkpoint", "remat", "pallas_call", "custom_jvp", "custom_vjp",
}

# decorators that make the decorated body run under a jax trace.
# def_op: this repo's dispatch — kernel bodies re-execute under vjp
# tracing even on the eager path (core/dispatch.py).
TRACED_DECORATORS = {"jit", "pjit", "def_op", "vmap", "custom_jvp",
                     "custom_vjp", "checkpoint", "remat"}

# attribute accesses that are static under tracing (never taint)
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "name"}

# jnp calls whose first argument is a static scalar/extent being
# PROMOTED to an array (not evidence that the argument was an array)
PROMOTING_JAX_CALLS = {"asarray", "array", "arange", "full", "zeros",
                       "ones", "PRNGKey", "float32", "int32", "int64",
                       "bfloat16"}

# jnp/jax calls whose results are static metadata, not traced values
# (dtype predicates, mesh/topology queries, backend introspection)
STATIC_JAX_CALLS = {"issubdtype", "isdtype", "result_type", "dtype",
                    "iinfo", "finfo", "broadcast_shapes",
                    "iscomplexobj", "isrealobj", "isscalar",
                    "default_backend", "devices", "device_count",
                    "local_device_count", "process_index",
                    "axis_size", "axis_index"}


def func_root(node: ast.expr) -> Optional[str]:
    """Leftmost Name id of an attribute chain (``jax.nn.softmax`` →
    ``jax``); None for anything else."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def func_simple_name(node: ast.expr) -> Optional[str]:
    """Rightmost component of a call target (``jax.jit`` → ``jit``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_jax_call(node: ast.AST) -> bool:
    """Call whose target chains off a jax-family module alias and is
    not a static metadata helper."""
    if not isinstance(node, ast.Call):
        return False
    root = func_root(node.func)
    if root is None or not JAX_ROOT_RE.match(root):
        return False
    return func_simple_name(node.func) not in STATIC_JAX_CALLS


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str                 # posix path relative to the lint root
    line: int
    col: int
    symbol: str               # enclosing def qualname or "<module>"
    message: str
    line_text: str = ""

    def fingerprint(self) -> Tuple[str, str, str, str]:
        """Line-number-free identity used for baseline matching (stable
        across unrelated edits that shift lines)."""
        return (self.rule, self.path, self.symbol, self.line_text.strip())

    def as_dict(self, baselined: bool) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "symbol": self.symbol,
                "message": self.message, "baselined": baselined}


class Rule:
    """Base class for tpulint rules. Subclasses set ``id`` /
    ``description`` and yield Findings from ``check``."""

    id: str = ""
    description: str = ""

    def check(self, mod: "ModuleInfo") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: "ModuleInfo", node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id, path=mod.relpath, line=line,
            col=getattr(node, "col_offset", 0),
            symbol=mod.qualname_of(node), message=message,
            line_text=mod.line(line))


class ModuleInfo:
    def __init__(self, source: str, relpath: str):
        self.source = source
        self.relpath = relpath
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        self._funcs: List[ast.AST] = [
            n for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        self._qualnames: Dict[int, str] = {}
        for fn in self._funcs:
            self._qualnames[id(fn)] = self._compute_qualname(fn)
        self._comments = self._collect_comments(source)
        self._file_disabled = self._collect_file_pragmas()
        self._traced_ids: Optional[Set[int]] = None
        self._taint_cache: Dict[int, Set[str]] = {}
        self._sanitizers: Optional[Set[str]] = None

    # -- plumbing --------------------------------------------------------
    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def functions(self) -> List[ast.AST]:
        return list(self._funcs)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parent(cur)
        return None

    def _compute_qualname(self, fn: ast.AST) -> str:
        parts = [fn.name]
        cur = self.parent(fn)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(parts))

    def qualname_of(self, node: ast.AST) -> str:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return self._qualnames[id(node)]
        fn = self.enclosing_function(node)
        return self._qualnames[id(fn)] if fn is not None else "<module>"

    # -- suppressions ----------------------------------------------------
    @staticmethod
    def _collect_comments(source: str) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            toks = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in toks:
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            pass
        return out

    def _pragma_rules(self, lineno: int, kind: str) -> Set[str]:
        text = self._comments.get(lineno, "")
        m = PRAGMA_RE.search(text)
        if not m or m.group(1) != kind:
            return set()
        return {r.strip() for r in m.group(2).split(",") if r.strip()}

    def _collect_file_pragmas(self) -> Set[str]:
        out: Set[str] = set()
        for ln in self._comments:
            out |= self._pragma_rules(ln, "disable-file")
        return out

    def _is_comment_only_line(self, lineno: int) -> bool:
        text = self.line(lineno).strip()
        return text.startswith("#")

    def is_suppressed(self, finding: Finding) -> bool:
        if finding.rule in self._file_disabled or \
                "all" in self._file_disabled:
            return True

        def hit(ln: int) -> bool:
            rules = self._pragma_rules(ln, "disable")
            return finding.rule in rules or "all" in rules

        if hit(finding.line):
            return True
        # pylint-style standalone pragma on the line(s) just above
        ln = finding.line - 1
        while ln >= 1 and self._is_comment_only_line(ln):
            if hit(ln):
                return True
            ln -= 1
        return False

    # -- traced reachability ---------------------------------------------
    def traced_functions(self) -> Set[int]:
        """ids of function nodes whose bodies run under a jax trace."""
        if self._traced_ids is not None:
            return self._traced_ids
        traced: Set[int] = set()
        for fn in self._funcs:
            for dec in fn.decorator_list:
                name = func_simple_name(
                    dec.func if isinstance(dec, ast.Call) else dec)
                if name in TRACED_DECORATORS:
                    traced.add(id(fn))
                elif name == "partial" and isinstance(dec, ast.Call) \
                        and dec.args:
                    inner = func_simple_name(dec.args[0])
                    if inner in TRACED_DECORATORS:
                        traced.add(id(fn))
        # functions handed to jit/scan/... — resolved LEXICALLY: a bare
        # Name only reaches defs visible from the call site (module
        # level, or nested in one of the call's enclosing functions);
        # self.<name> args reach same-named methods. This is what keeps
        # an unrelated public method named `step` out of the traced set
        # when some closure `step` is jitted elsewhere in the file.
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call) or \
                    func_simple_name(call.func) not in TRACE_WRAPPERS:
                continue
            ancestors = set()
            cur = self.enclosing_function(call)
            while cur is not None:
                ancestors.add(id(cur))
                cur = self.enclosing_function(cur)
            for arg in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(arg, ast.Name):
                    for fn in self._funcs:
                        if fn.name != arg.id:
                            continue
                        owner = self.enclosing_function(fn)
                        at_module = isinstance(self.parent(fn), ast.Module)
                        if at_module or (owner is not None
                                         and id(owner) in ancestors):
                            traced.add(id(fn))
                elif isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id in ("self", "cls"):
                    for fn in self._funcs:
                        if fn.name == arg.attr and \
                                isinstance(self.parent(fn), ast.ClassDef):
                            traced.add(id(fn))
        # closure: nested defs + same-module callees of traced functions
        fn_by_name: Dict[str, List[ast.AST]] = {}
        for fn in self._funcs:
            fn_by_name.setdefault(fn.name, []).append(fn)
        changed = True
        while changed:
            changed = False
            for fn in self._funcs:
                if id(fn) not in traced:
                    continue
                for sub in ast.walk(fn):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and id(sub) not in traced:
                        traced.add(id(sub))
                        changed = True
                    if isinstance(sub, ast.Call):
                        callee = None
                        if isinstance(sub.func, ast.Name):
                            callee = sub.func.id
                        elif isinstance(sub.func, ast.Attribute) and \
                                isinstance(sub.func.value, ast.Name) and \
                                sub.func.value.id in ("self", "cls"):
                            callee = sub.func.attr
                        for target in fn_by_name.get(callee, []):
                            if id(target) not in traced:
                                traced.add(id(target))
                                changed = True
        self._traced_ids = traced
        return traced

    def is_traced(self, fn: ast.AST) -> bool:
        return id(fn) in self.traced_functions()

    # -- value taint -----------------------------------------------------
    def tainted_names(self, fn: ast.AST) -> Set[str]:
        """Local names of ``fn`` holding traced array values (see module
        docstring for what counts)."""
        if id(fn) in self._taint_cache:
            return self._taint_cache[id(fn)]
        params = {a.arg for a in
                  list(fn.args.posonlyargs) + list(fn.args.args)
                  + list(fn.args.kwonlyargs)} - {"self", "cls"}
        tainted: Set[str] = set()
        # parameters with direct tensor evidence: passed bare as the
        # FIRST positional argument of a jax-family call (the array
        # slot). Later positions / kwargs are overwhelmingly static
        # knobs (axis=, shape tuples, pad modes) — not evidence.
        for call in ast.walk(fn):
            if is_jax_call(call) and call.args and \
                    func_simple_name(call.func) not in PROMOTING_JAX_CALLS:
                arg = call.args[0]
                if isinstance(arg, ast.Name) and arg.id in params:
                    tainted.add(arg.id)
        changed = True
        passes = 0
        while changed and passes < 10:
            changed = False
            passes += 1
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                else:
                    continue
                if not self._expr_tainted(value, tainted):
                    continue
                for tgt in targets:
                    for name in self._target_names(tgt):
                        if name not in tainted:
                            tainted.add(name)
                            changed = True
        self._taint_cache[id(fn)] = tainted
        return tainted

    def _target_names(self, tgt: ast.expr) -> Iterator[str]:
        if isinstance(tgt, ast.Name):
            yield tgt.id
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                yield from self._target_names(el)

    def _expr_tainted(self, expr: ast.expr, tainted: Set[str]) -> bool:
        for node in ast.walk(expr):
            if is_jax_call(node):
                return True
            if isinstance(node, ast.Name) and node.id in tainted \
                    and not self._under_static_access(node, expr):
                return True
        return False

    def _under_static_access(self, name: ast.Name,
                             within: ast.expr) -> bool:
        """True when ``name``'s value only feeds a static accessor in
        this expression (``x.shape``, ``len(x)``, ``x.ndim``...)."""
        parent = self.parent(name)
        if isinstance(parent, ast.Attribute) and \
                parent.attr in STATIC_ATTRS:
            return True
        if isinstance(parent, ast.Call) and parent.func is not name and \
                func_simple_name(parent.func) in (
                    {"len", "isinstance", "hasattr", "getattr", "type"}
                    | STATIC_JAX_CALLS):
            return True
        return False

    # -- recompile-hazard helpers ---------------------------------------
    def sanitizer_names(self) -> Set[str]:
        """Module-local functions that quantize shape-derived ints onto
        a bucket lattice: any def whose body calls a ``*bucket*``
        function (e.g. ``_max_len`` calling ``_bucket``)."""
        if self._sanitizers is not None:
            return self._sanitizers
        out: Set[str] = set()
        for fn in self._funcs:
            for call in ast.walk(fn):
                if isinstance(call, ast.Call):
                    name = func_simple_name(call.func) or ""
                    if "bucket" in name:
                        out.add(fn.name)
                        break
        self._sanitizers = out
        return out


# ---------------------------------------------------------------------------
# driving
# ---------------------------------------------------------------------------
def iter_py_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def lint_source(source: str, relpath: str, rules,
                resources=None) -> List[Finding]:
    """Lint one source string as a single-module project; suppression
    pragmas applied, no baseline."""
    from .project import Project, lint_project

    try:
        mod = ModuleInfo(source, relpath)
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=relpath,
                        line=e.lineno or 1, col=e.offset or 0,
                        symbol="<module>", message=str(e))]
    return lint_project(Project([mod], resources=resources), rules)


def relpath_for(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root).as_posix()
    except ValueError:
        return path.as_posix()


def lint_paths(paths: Iterable[Path], rules,
               root: Optional[Path] = None,
               project_paths: Optional[Iterable[Path]] = None,
               stats: Optional[Dict[str, Dict[str, int]]] = None
               ) -> List[Finding]:
    """Lint ``paths``. Interprocedural facts are built from
    ``project_paths`` when given (the ``--changed`` incremental mode:
    facts whole-tree, findings only for the changed files)."""
    from .project import Project, lint_project

    root = (root or Path.cwd()).resolve()
    fact_paths = list(project_paths) if project_paths is not None \
        else list(paths)
    project, findings = Project.from_paths(fact_paths, root)
    findings = list(findings)
    findings.extend(lint_project(project, rules, stats=stats))
    if project_paths is not None:
        linted = {relpath_for(p, root) for p in iter_py_files(paths)}
        findings = [f for f in findings if f.path in linted]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def load_baseline(path: Path) -> List[Dict[str, str]]:
    data = json.loads(path.read_text(encoding="utf-8"))
    return list(data.get("findings", []))


def baseline_entry(f: Finding) -> Dict[str, str]:
    return {"rule": f.rule, "path": f.path, "symbol": f.symbol,
            "line_text": f.line_text.strip()}


def write_baseline(path: Path, findings: List[Finding]) -> None:
    write_baseline_entries(path, [baseline_entry(f) for f in findings])


def write_baseline_entries(path: Path,
                           entries: List[Dict[str, str]]) -> None:
    path.write_text(json.dumps(
        {"comment": "tpulint grandfathered violations — shrink me, "
                    "never grow me (see README 'Static analysis')",
         "findings": entries}, indent=1) + "\n", encoding="utf-8")


def match_baseline_entries(findings: List[Finding],
                           baseline: List[Dict[str, str]]
                           ) -> List[Dict[str, str]]:
    """The subset of baseline entries a current finding still matches
    (multiset semantics; the ORIGINAL dicts are returned so extra keys
    like ``justification`` survive a prune)."""
    pool: Dict[Tuple[str, str, str, str], List[Dict[str, str]]] = {}
    for e in baseline:
        key = (e["rule"], e["path"], e["symbol"], e["line_text"])
        pool.setdefault(key, []).append(e)
    kept: List[Dict[str, str]] = []
    for f in findings:
        entries = pool.get(f.fingerprint())
        if entries:
            kept.append(entries.pop(0))
    return kept


def split_by_baseline(findings: List[Finding],
                      baseline: List[Dict[str, str]]):
    """Partition findings into (new, baselined) against the baseline
    multiset; returns (new, baselined, stale_entries)."""
    pool: Dict[Tuple[str, str, str, str], int] = {}
    for e in baseline:
        key = (e["rule"], e["path"], e["symbol"], e["line_text"])
        pool[key] = pool.get(key, 0) + 1
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        key = f.fingerprint()
        if pool.get(key, 0) > 0:
            pool[key] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = [{"rule": k[0], "path": k[1], "symbol": k[2],
              "line_text": k[3]}
             for k, n in pool.items() for _ in range(n)]
    return new, matched, stale
