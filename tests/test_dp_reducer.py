"""Bucketed DataParallel Reducer (reference fluid/imperative/reducer.h:
129 — bucket partitioning, fused per-bucket allreduce, no_sync)."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.parallel import DataParallel, Reducer


def _model(seed=0):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def test_bucket_partitioning_respects_budget():
    m = _model()
    # tiny budget: every param gets its own bucket
    r1 = Reducer(m.parameters(), comm_buffer_size_mb=1e-9)
    assert r1.num_buckets == len([p for p in m.parameters()
                                  if p.trainable])
    # huge budget: one bucket
    r2 = Reducer(m.parameters(), comm_buffer_size_mb=1e3)
    assert r2.num_buckets == 1


def test_fused_reduce_grads_match_plain_backward(monkeypatch):
    """Grads routed through the fused bucket path equal plain backward
    (single process: allreduce is identity), and exactly num_buckets
    fused reductions fire."""
    r = np.random.RandomState(0)
    x = paddle.to_tensor(r.randn(4, 8).astype("float32"))
    y = paddle.to_tensor(r.randn(4, 4).astype("float32"))

    plain = _model(3)
    loss = paddle.mean((plain(x) - y) ** 2)
    loss.backward()
    ref = {n: np.asarray(p.grad._value)
           for n, p in plain.named_parameters()}

    wrapped = _model(3)
    dp = DataParallel(wrapped, comm_buffer_size=1e-9)  # per-param buckets
    loss = paddle.mean((dp(x) - y) ** 2)
    loss.backward()
    assert dp._reducer.fused_reduce_count == dp._reducer.num_buckets
    for n, p in wrapped.named_parameters():
        np.testing.assert_allclose(np.asarray(p.grad._value), ref[n],
                                   rtol=1e-5, atol=1e-6, err_msg=n)

    # one big bucket: same grads, ONE fused reduce
    wrapped2 = _model(3)
    dp2 = DataParallel(wrapped2, comm_buffer_size=1000)
    loss = paddle.mean((dp2(x) - y) ** 2)
    loss.backward()
    assert dp2._reducer.num_buckets == 1
    assert dp2._reducer.fused_reduce_count == 1
    for n, p in wrapped2.named_parameters():
        np.testing.assert_allclose(np.asarray(p.grad._value), ref[n],
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_no_sync_skips_reduction():
    r = np.random.RandomState(1)
    x = paddle.to_tensor(r.randn(4, 8).astype("float32"))
    y = paddle.to_tensor(r.randn(4, 4).astype("float32"))
    m = _model(4)
    dp = DataParallel(m, comm_buffer_size=1000)
    with dp.no_sync():
        loss = paddle.mean((dp(x) - y) ** 2)
        loss.backward()
    assert dp._reducer.fused_reduce_count == 0  # sync skipped
    assert all(p.grad is not None for p in m.parameters()
               if p.trainable)


def test_reducer_preserves_accumulated_grads():
    """no_sync accumulate + synced backward: the bucket fire must swap
    only the provisional part, keeping prior accumulation (review
    finding: q.grad was overwritten wholesale)."""
    r = np.random.RandomState(2)
    x1 = paddle.to_tensor(r.randn(4, 8).astype("float32"))
    x2 = paddle.to_tensor(r.randn(4, 8).astype("float32"))
    y = paddle.to_tensor(r.randn(4, 4).astype("float32"))

    plain = _model(5)
    for xb in (x1, x2):
        loss = paddle.mean((plain(xb) - y) ** 2)
        loss.backward()
    ref = {n: np.asarray(p.grad._value)
           for n, p in plain.named_parameters()}

    m = _model(5)
    dp = DataParallel(m, comm_buffer_size=1000)  # one bucket
    with dp.no_sync():
        loss = paddle.mean((dp(x1) - y) ** 2)
        loss.backward()
    loss = paddle.mean((dp(x2) - y) ** 2)
    loss.backward()
    for n, p in m.named_parameters():
        np.testing.assert_allclose(np.asarray(p.grad._value), ref[n],
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_find_unused_parameters_degrades_to_per_param():
    m = _model(6)
    from paddle_tpu.distributed.parallel import Reducer

    r = Reducer(m.parameters(), find_unused_parameters=True)
    assert r.num_buckets == len([p for p in m.parameters()
                                 if p.trainable])
