"""Elastic manager (TCPStore heartbeats) + collective watchdog
(reference: fleet/elastic/manager.py + CommTaskManager timeout)."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import native
from paddle_tpu.distributed.watchdog import (CommTaskManager,
                                             TimeoutError_, watch)

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def test_watchdog_passes_fast_steps():
    mgr = CommTaskManager(timeout=5.0, poll_interval=0.05)
    for _ in range(3):
        with mgr.track("step"):
            time.sleep(0.01)
    mgr.check()
    mgr.shutdown()


def test_watchdog_detects_hang():
    fired = []
    mgr = CommTaskManager(timeout=0.2, poll_interval=0.05,
                          on_timeout=lambda name: fired.append(name))
    with pytest.raises(TimeoutError_):
        with mgr.track("hung_allreduce"):
            time.sleep(0.6)
    assert fired == ["hung_allreduce"]
    mgr.shutdown()


def test_watch_wrapper_blocks_until_ready():
    import jax.numpy as jnp

    def step(x):
        return paddle.to_tensor(np.asarray(x) * 2)

    wrapped = watch(step, timeout=5.0, poll_interval=0.05)
    out = wrapped(np.ones(4, "float32"))
    np.testing.assert_array_equal(np.asarray(out._value), 2 * np.ones(4))
    wrapped._watchdog.shutdown()


@pytest.mark.skipif(native.load() is None, reason="native lib unavailable")
def test_elastic_heartbeats_and_scale_in():
    from paddle_tpu.distributed import TCPStore
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)

    master = TCPStore("127.0.0.1", 0, is_master=True, timeout=10)
    stores = [TCPStore("127.0.0.1", master.port) for _ in range(3)]
    changes = []
    mgrs = [ElasticManager(s, job_id="j1", rank=i, np_=3,
                           heartbeat_interval=0.1, node_timeout=0.5,
                           on_world_change=lambda w, i=i:
                           changes.append((i, tuple(w))))
            for i, s in enumerate(stores)]
    for m in mgrs:
        m.register()
    assert mgrs[0].wait_world(3, timeout=5)
    assert sorted(mgrs[0].alive_ranks()) == [0, 1, 2]

    # rank 2 dies: its heartbeat stops → peers see scale-in
    mgrs[2]._stop.set()
    time.sleep(0.3)  # let an in-flight heartbeat write drain
    master.delete_key("/elastic/j1/nodes/2")
    deadline = time.time() + 5
    while time.time() < deadline and not changes:
        time.sleep(0.1)
    assert changes and all(2 not in w for _, w in changes)
    assert mgrs[0].status == ElasticStatus.RESTART

    for m in mgrs[:2]:
        m.exit()
    for s in stores:
        s.close()
    master.close()
