"""Group-sharded (ZeRO 1/2/3), sequence-parallel, and recompute parity
tests over the 8-device CPU mesh (the reference's loss-parity strategy:
test/collective/fleet/dygraph_group_sharded_stage{2,3}.py,
hybrid_parallel_mp_model_with_sequence_parallel.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.distributed.fleet.layers import mpu
from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu


def _mlp(parallel_cls=None, d=16, h=32):
    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(d, h)
            self.fc2 = paddle.nn.Linear(h, d)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    return MLP()


def _loss_fn(model, batch):
    out = model(batch["x"])
    return paddle.mean((out - batch["y"]) ** 2)


def _golden_steps(model, x, y, steps=3, lr=0.1):
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    losses = []
    for _ in range(steps):
        out = model(paddle.to_tensor(x))
        loss = paddle.mean((out - paddle.to_tensor(y)) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_group_sharded_parity(level):
    """dp=2 x sharding=4 ZeRO training matches single-device numerics."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sharding_degree": 4,
                               "mp_degree": 1, "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(3)
    model = _mlp()
    golden = _mlp()
    golden.set_state_dict(model.state_dict())

    np.random.seed(0)
    x = np.random.randn(8, 16).astype("float32")
    y = np.random.randn(8, 16).astype("float32")
    g_losses = _golden_steps(golden, x, y)

    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    model, opt, _ = dist.group_sharded_parallel(model, opt, level)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(_loss_fn)

    for i in range(3):
        loss = step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)})
        np.testing.assert_allclose(float(loss), g_losses[i], rtol=1e-4,
                                   atol=1e-6, err_msg=f"step {i} {level}")

    for (n, pd), (_, pg) in zip(model.named_parameters(),
                                golden.named_parameters()):
        np.testing.assert_allclose(np.asarray(pd._value),
                                   np.asarray(pg._value), rtol=1e-4,
                                   atol=1e-5, err_msg=f"{level}:{n}")

    # optimizer moments must be physically sharded over the sharding axis
    specs = [str(eng._zero.state_spec(p)) for p in eng.trainable
             if eng._zero.entry(p) is not None]
    assert specs and all("sharding" in s for s in specs)
    if level == "p_g_os":
        pspecs = [str(eng._zero.storage_spec(p)) for p in eng.trainable
                  if eng._zero.entry(p) is not None]
        assert pspecs and all("sharding" in s for s in pspecs)


class SPBlock(paddle.nn.Layer):
    """Column/Row sequence-parallel pair on [b, s, d] activations."""

    def __init__(self, d=16, h=32, seq_axis=1):
        super().__init__()
        self._ax = seq_axis
        self.norm = paddle.nn.LayerNorm(d)
        self.fc1 = spu.ColumnSequenceParallelLinear(
            d, h, gather_output=False, seq_axis=seq_axis)
        self.fc2 = spu.RowSequenceParallelLinear(
            h, d, input_is_parallel=True, seq_axis=seq_axis)
        for p in self.norm.parameters():
            spu.mark_as_sequence_parallel_parameter(p)

    def forward(self, x):
        x = spu.scatter(x, axis=self._ax)
        x = self.norm(x)
        x = paddle.nn.functional.relu(self.fc1(x))
        x = self.fc2(x)
        return spu.gather(x, axis=self._ax)


class DenseBlock(paddle.nn.Layer):
    def __init__(self, d=16, h=32):
        super().__init__()
        self.norm = paddle.nn.LayerNorm(d)
        self.fc1 = paddle.nn.Linear(d, h)
        self.fc2 = paddle.nn.Linear(h, d)

    def forward(self, x):
        x = self.norm(x)
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def test_sequence_parallel_parity():
    """SP (allgather/reduce-scatter pairing) matches plain execution."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(5)
    model = SPBlock()
    golden = DenseBlock()
    golden.set_state_dict(model.state_dict())
    assert spu.register_sequence_parallel_allreduce_hooks(model)

    np.random.seed(1)
    x = np.random.randn(4, 8, 16).astype("float32")
    y = np.random.randn(4, 8, 16).astype("float32")
    g_losses = _golden_steps(golden, x, y)

    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(_loss_fn)
    for i in range(3):
        loss = step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)})
        np.testing.assert_allclose(float(loss), g_losses[i], rtol=1e-4,
                                   atol=1e-6, err_msg=f"step {i}")

    for (n, pd), (_, pg) in zip(model.named_parameters(),
                                golden.named_parameters()):
        np.testing.assert_allclose(np.asarray(pd._value),
                                   np.asarray(pg._value), rtol=1e-4,
                                   atol=1e-5, err_msg=n)


def test_sp_ops_roundtrip_eager():
    """Outside an SPMD region all SP primitives are identities."""
    x = paddle.to_tensor(np.random.randn(4, 6).astype("float32"))
    for f in (spu.scatter, spu.gather, spu.all_gather, spu.reduce_scatter):
        out = f(x, axis=0)
        np.testing.assert_array_equal(np.asarray(out._value),
                                      np.asarray(x._value))
    out = spu.ScatterOp.apply(x)
    np.testing.assert_array_equal(np.asarray(out._value),
                                  np.asarray(x._value))


def test_recompute_matches_plain():
    """recompute() gives identical loss and grads to the plain forward."""
    paddle.seed(9)
    model = _mlp()
    ref = _mlp()
    ref.set_state_dict(model.state_dict())

    x = np.random.RandomState(2).randn(4, 16).astype("float32")

    out = ref(paddle.to_tensor(x))
    loss_ref = paddle.mean(out ** 2)
    loss_ref.backward()

    from paddle_tpu.distributed.fleet import recompute

    xin = paddle.to_tensor(x)
    out2 = recompute(model, xin)
    loss = paddle.mean(out2 ** 2)
    loss.backward()

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-6)
    for (n, pd), (_, pg) in zip(model.named_parameters(),
                                ref.named_parameters()):
        assert pd.grad is not None, n
        np.testing.assert_allclose(np.asarray(pd.grad._value),
                                   np.asarray(pg.grad._value), rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_recompute_closure_gets_param_grads():
    """The reference idiom recompute(lambda h: self.mlp(h), h) must still
    deliver grads to the closed-over layer's params."""
    paddle.seed(4)
    model = _mlp()
    x = paddle.to_tensor(np.random.RandomState(3).randn(4, 16)
                         .astype("float32"))

    from paddle_tpu.distributed.fleet import recompute

    out = recompute(lambda h: model(h), x)
    loss = paddle.mean(out ** 2)
    loss.backward()
    for n, p in model.named_parameters():
        assert p.grad is not None, n
        assert float(paddle.mean(paddle.abs(p.grad))) > 0, n


def test_recompute_inside_engine():
    """recompute works under the compiled SPMD step (remat in XLA)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    class RematMLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.block = _mlp()

        def forward(self, x):
            from paddle_tpu.distributed.fleet import recompute

            return recompute(self.block, x)

    paddle.seed(3)
    model = RematMLP()
    golden = _mlp()
    golden.set_state_dict(model.block.state_dict())

    np.random.seed(0)
    x = np.random.randn(8, 16).astype("float32")
    y = np.random.randn(8, 16).astype("float32")
    g_losses = _golden_steps(golden, x, y, steps=2)

    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(_loss_fn)
    for i in range(2):
        loss = step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)})
        np.testing.assert_allclose(float(loss), g_losses[i], rtol=1e-4,
                                   atol=1e-6)
