"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py) — torch
parity across modes/layers/directions; the scan kernels share cuDNN
gate order so weights port directly."""
import numpy as np
import torch

import jax
import paddle_tpu as paddle
from paddle_tpu import nn

rng = np.random.RandomState(0)
B, T, IN, H = 3, 7, 5, 6


def _cells(pl_rnn):
    cells = []
    for layer in pl_rnn:
        if hasattr(layer, "cell"):
            cells.append(layer.cell)
        else:
            cells.append(layer.rnn_fw.cell)
            cells.append(layer.rnn_bw.cell)
    return cells


def _copy_weights(pl_rnn, th_rnn, D):
    for i, cell in enumerate(_cells(pl_rnn)):
        layer, d = divmod(i, D)
        sfx = f"_l{layer}" + ("_reverse" if d else "")
        for ours, theirs in [("weight_ih", "weight_ih"),
                             ("weight_hh", "weight_hh"),
                             ("bias_ih", "bias_ih"),
                             ("bias_hh", "bias_hh")]:
            getattr(cell, ours)._value = jax.numpy.asarray(
                getattr(th_rnn, f"{theirs}{sfx}").detach().numpy())


def _check(mode, pl_cls, th_cls, num_layers, direction):
    D = 2 if direction != "forward" else 1
    paddle.seed(0)
    pl = pl_cls(IN, H, num_layers=num_layers, direction=direction)
    th = th_cls(IN, H, num_layers=num_layers, batch_first=True,
                bidirectional=(D == 2))
    _copy_weights(pl, th, D)
    x = rng.randn(B, T, IN).astype("float32")
    out_p, st_p = pl(paddle.to_tensor(x))
    out_t, st_t = th(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(out_p._value),
                               out_t.detach().numpy(), atol=1e-5)
    if mode == "LSTM":
        np.testing.assert_allclose(np.asarray(st_p[0]._value),
                                   st_t[0].detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(st_p[1]._value),
                                   st_t[1].detach().numpy(), atol=1e-5)
    else:
        np.testing.assert_allclose(np.asarray(st_p._value),
                                   st_t.detach().numpy(), atol=1e-5)


def test_lstm_matches_torch():
    for L in (1, 2):
        for d in ("forward", "bidirect"):
            _check("LSTM", nn.LSTM, torch.nn.LSTM, L, d)


def test_gru_matches_torch():
    for L in (1, 2):
        for d in ("forward", "bidirect"):
            _check("GRU", nn.GRU, torch.nn.GRU, L, d)


def test_simple_rnn_matches_torch():
    for L in (1, 2):
        for d in ("forward", "bidirect"):
            _check("RNN", nn.SimpleRNN, torch.nn.RNN, L, d)


def test_initial_states_roundtrip():
    paddle.seed(1)
    lstm = nn.LSTM(IN, H, num_layers=2)
    x = paddle.to_tensor(rng.randn(B, T, IN).astype("float32"))
    out1, (h1, c1) = lstm(x)
    # feeding the final states back continues the sequence exactly
    out2, _ = lstm(x, (h1, c1))
    full, _ = lstm(paddle.to_tensor(np.concatenate(
        [np.asarray(x._value)] * 2, axis=1)))
    np.testing.assert_allclose(np.asarray(out2._value),
                               np.asarray(full._value)[:, T:], atol=1e-5)


def test_time_major():
    paddle.seed(2)
    gru_bm = nn.GRU(IN, H)
    gru_tm = nn.GRU(IN, H, time_major=True)
    for c_dst, c_src in zip(_cells(gru_tm), _cells(gru_bm)):
        for w in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
            getattr(c_dst, w)._value = getattr(c_src, w)._value
    x = rng.randn(B, T, IN).astype("float32")
    o1, _ = gru_bm(paddle.to_tensor(x))
    o2, _ = gru_tm(paddle.to_tensor(x.transpose(1, 0, 2)))
    np.testing.assert_allclose(np.asarray(o1._value),
                               np.asarray(o2._value).transpose(1, 0, 2),
                               atol=1e-6)


def test_gradients_flow_and_train():
    paddle.seed(3)
    lstm = nn.LSTM(IN, H, num_layers=1)
    head = nn.Linear(H, 2)
    params = list(lstm.parameters()) + list(head.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=5e-3, parameters=params)
    x = paddle.to_tensor(rng.randn(8, T, IN).astype("float32"))
    y = paddle.to_tensor(np.arange(8) % 2)
    first = None
    for _ in range(15):
        out, (h, _) = lstm(x)
        loss = nn.functional.cross_entropy(head(h[-1]), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.8


def test_cells_single_step():
    paddle.seed(4)
    cell = nn.LSTMCell(IN, H)
    x = paddle.to_tensor(rng.randn(B, IN).astype("float32"))
    h, (h2, c2) = cell(x)
    assert h.shape == [B, H] and c2.shape == [B, H]
    cell_g = nn.GRUCell(IN, H)
    h, hn = cell_g(x)
    assert h.shape == [B, H]
    cell_s = nn.SimpleRNNCell(IN, H, activation="relu")
    h, hn = cell_s(x)
    assert (np.asarray(h._value) >= 0).all()


def test_custom_cell_through_rnn():
    class Doubler(nn.Layer):
        def forward(self, x, states=None):
            s = states if states is not None else x * 0
            out = x + s
            return out, out

    runner = nn.RNN(Doubler())
    x = paddle.to_tensor(np.ones((2, 4, 3), "float32"))
    out, st = runner(x)
    # cumulative sum over time: 1, 2, 3, 4
    np.testing.assert_allclose(np.asarray(out._value)[0, :, 0],
                               [1, 2, 3, 4])


def test_birnn_wrapper():
    paddle.seed(5)
    bi = nn.BiRNN(nn.GRUCell(IN, H), nn.GRUCell(IN, H))
    x = paddle.to_tensor(rng.randn(B, T, IN).astype("float32"))
    out, (st_f, st_b) = bi(x)
    assert out.shape == [B, T, 2 * H]


def test_dropout_between_layers_trains_only():
    paddle.seed(6)
    lstm = nn.LSTM(IN, H, num_layers=2, dropout=0.5)
    x = paddle.to_tensor(rng.randn(B, T, IN).astype("float32"))
    lstm.eval()
    o1, _ = lstm(x)
    o2, _ = lstm(x)
    np.testing.assert_allclose(np.asarray(o1._value),
                               np.asarray(o2._value))  # eval: no dropout


def test_sequence_length_matches_torch_packed():
    paddle.seed(7)
    D = 2
    pl = nn.LSTM(IN, H, direction="bidirect")
    th = torch.nn.LSTM(IN, H, batch_first=True, bidirectional=True)
    _copy_weights(pl, th, D)
    x = rng.randn(B, T, IN).astype("float32")
    lens = np.array([7, 4, 2])
    out_p, (h_p, c_p) = pl(paddle.to_tensor(x),
                           sequence_length=paddle.to_tensor(lens))
    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.tensor(x), torch.tensor(lens), batch_first=True,
        enforce_sorted=False)
    out_t, (h_t, c_t) = th(packed)
    out_t, _ = torch.nn.utils.rnn.pad_packed_sequence(out_t,
                                                      batch_first=True)
    np.testing.assert_allclose(np.asarray(out_p._value),
                               out_t.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_p._value),
                               h_t.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_p._value),
                               c_t.detach().numpy(), atol=1e-5)


def test_bias_attr_false():
    cell = nn.GRUCell(IN, H, bias_ih_attr=False, bias_hh_attr=False)
    assert cell.bias_ih is None and cell.bias_hh is None
    x = paddle.to_tensor(rng.randn(B, IN).astype("float32"))
    h, _ = cell(x)
    assert h.shape == [B, H]


def test_subclassed_cell_uses_custom_forward():
    class ConstCell(nn.GRUCell):
        def forward(self, x, states=None):
            out = (x[:, :1] * 0 + 5.0).expand([x.shape[0],
                                               self.hidden_size])
            return out, out

    runner = nn.RNN(ConstCell(IN, H))
    x = paddle.to_tensor(rng.randn(2, 3, IN).astype("float32"))
    out, _ = runner(x)
    np.testing.assert_allclose(np.asarray(out._value),
                               np.full((2, 3, H), 5.0, "float32"))
