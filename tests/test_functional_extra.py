"""Functional tail (reference: python/paddle/nn/functional/*) — brute
force / torch oracles for the new math; smoke for delegations."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

rng = np.random.RandomState(0)


def _t(x):
    return paddle.to_tensor(x)


def _np(t):
    return np.asarray(t._value)


class TestRNNT:
    def test_t1_u0(self):
        logits = rng.randn(1, 1, 1, 4).astype("float32")
        ll = F.rnnt_loss(_t(logits), _t(np.zeros((1, 0), "int64")),
                         _t(np.array([1])), _t(np.array([0])),
                         reduction="none")
        ref = -np.log(np.exp(logits[0, 0, 0, 0])
                      / np.exp(logits[0, 0, 0]).sum())
        assert abs(float(_np(ll)[0]) - ref) < 1e-5

    def test_t2_u1_bruteforce(self):
        T, U, V = 2, 1, 3
        lg = rng.randn(1, T, U + 1, V).astype("float32")
        lp = np.log(np.exp(lg) / np.exp(lg).sum(-1, keepdims=True))
        lab = np.array([[1]])
        p1 = lp[0, 0, 0, 1] + lp[0, 0, 1, 0] + lp[0, 1, 1, 0]
        p2 = lp[0, 0, 0, 0] + lp[0, 1, 0, 1] + lp[0, 1, 1, 0]
        ref = -np.logaddexp(p1, p2)
        ours = float(_np(F.rnnt_loss(
            _t(lg), _t(lab), _t(np.array([T])), _t(np.array([U])),
            reduction="none"))[0])
        assert abs(ours - ref) < 1e-4

    def test_t3_u2_bruteforce(self):
        T, U, V = 3, 2, 4
        lg = rng.randn(1, T, U + 1, V).astype("float32")
        lp = np.log(np.exp(lg) / np.exp(lg).sum(-1, keepdims=True))
        lab = np.array([[2, 1]])

        # enumerate all monotone paths from (0,0) to (T-1, U) + final blank
        import itertools
        total = -np.inf
        # a path is a sequence of moves: T-1 blanks (t+1) and U emits (u+1)
        for moves in set(itertools.permutations(
                "b" * (T - 1) + "e" * U)):
            t = u = 0
            s = 0.0
            ok = True
            for m in moves:
                if m == "b":
                    s += lp[0, t, u, 0]
                    t += 1
                else:
                    s += lp[0, t, u, lab[0, u]]
                    u += 1
            s += lp[0, T - 1, U, 0]  # final blank
            total = np.logaddexp(total, s)
        ours = float(_np(F.rnnt_loss(
            _t(lg), _t(lab), _t(np.array([T])), _t(np.array([U])),
            reduction="none"))[0])
        assert abs(ours - (-total)) < 1e-4

    def test_batched_lengths_and_grad(self):
        B, T, U, V = 2, 4, 2, 5
        lg = _t(rng.randn(B, T, U + 1, V).astype("float32"))
        lg.stop_gradient = False
        loss = F.rnnt_loss(lg, _t(rng.randint(1, V, (B, U))),
                           _t(np.array([4, 3])), _t(np.array([2, 1])))
        loss.backward()
        g = _np(lg.grad)
        assert np.isfinite(g).all() and np.abs(g).max() > 0


class TestNewMath:
    def test_sigmoid_focal_loss(self):
        lgt = rng.randn(6).astype("float32")
        lab = (rng.rand(6) > 0.5).astype("float32")
        ours = float(F.sigmoid_focal_loss(_t(lgt), _t(lab),
                                          reduction="sum"))
        p = 1 / (1 + np.exp(-lgt))
        ce = -(lab * np.log(p) + (1 - lab) * np.log(1 - p))
        pt = p * lab + (1 - p) * (1 - lab)
        ref = ((0.25 * lab + 0.75 * (1 - lab)) * ce * (1 - pt) ** 2).sum()
        assert abs(ours - ref) < 1e-4

    def test_margin_ranking_loss(self):
        a = rng.randn(5).astype("float32")
        b = rng.randn(5).astype("float32")
        y = np.sign(rng.randn(5)).astype("float32")
        ours = float(F.margin_ranking_loss(_t(a), _t(b), _t(y),
                                           margin=0.3))
        ref = float(torch.nn.functional.margin_ranking_loss(
            torch.tensor(a), torch.tensor(b), torch.tensor(y),
            margin=0.3))
        assert abs(ours - ref) < 1e-5

    def test_dice_loss_perfect_prediction(self):
        lab = rng.randint(0, 3, (4, 6, 1))
        onehot = np.eye(3, dtype="float32")[lab[..., 0]]
        loss = float(_np(F.dice_loss(_t(onehot), _t(lab))))
        assert loss < 1e-3

    def test_gumbel_softmax(self):
        paddle.seed(0)
        x = _t(rng.randn(4, 6).astype("float32"))
        soft = _np(F.gumbel_softmax(x))
        np.testing.assert_allclose(soft.sum(-1), np.ones(4), rtol=1e-5)
        hard = _np(F.gumbel_softmax(x, hard=True))
        assert ((hard == 0) | (hard == 1)).all()
        assert (hard.sum(-1) == 1).all()

    def test_gumbel_hard_straight_through_grad(self):
        paddle.seed(1)
        x = _t(rng.randn(3, 5).astype("float32"))
        x.stop_gradient = False
        F.gumbel_softmax(x, hard=True).sum().backward()
        assert np.isfinite(_np(x.grad)).all()

    def test_pdist(self):
        x = rng.randn(5, 3).astype("float32")
        ours = _np(F.pdist(_t(x)))
        ref = torch.nn.functional.pdist(torch.tensor(x)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_npair_loss_finite(self):
        a = rng.randn(6, 4).astype("float32")
        p = rng.randn(6, 4).astype("float32")
        lab = rng.randint(0, 3, 6)
        assert np.isfinite(float(_np(F.npair_loss(_t(a), _t(p),
                                                  _t(lab)))))

    def test_fractional_max_pool(self):
        x = rng.randn(1, 2, 9, 9).astype("float32")
        out = F.fractional_max_pool2d(_t(x), 4, random_u=0.3)
        assert out.shape == [1, 2, 4, 4]
        # every output equals the max of SOME input window
        assert np.isin(_np(out), x).all()
        out3 = F.fractional_max_pool3d(
            _t(rng.randn(1, 1, 6, 6, 6).astype("float32")), 2,
            random_u=0.7)
        assert out3.shape == [1, 1, 2, 2, 2]

    def test_class_center_sample(self):
        paddle.seed(2)
        lab = _t(np.array([1, 5, 5, 9]))
        remapped, sampled = F.class_center_sample(lab, 20, 8)
        s = _np(sampled)
        assert len(s) == 8 and {1, 5, 9} <= set(s.tolist())
        r = _np(remapped)
        assert (s[r] == np.array([1, 5, 5, 9])).all()


class TestDelegationsAndInplace:
    def test_functional_pooling(self):
        x = rng.randn(1, 2, 8).astype("float32")
        assert F.avg_pool1d(_t(x), 2, 2).shape == [1, 2, 4]
        assert F.max_pool1d(_t(x), 2, 2).shape == [1, 2, 4]
        assert F.adaptive_avg_pool1d(_t(x), 3).shape == [1, 2, 3]
        x3 = rng.randn(1, 2, 4, 4, 4).astype("float32")
        assert F.adaptive_max_pool3d(_t(x3), 2).shape == [1, 2, 2, 2, 2]

    def test_functional_losses_smoke(self):
        a = rng.randn(4, 6).astype("float32")
        b = rng.randn(4, 6).astype("float32")
        assert np.isfinite(float(F.cosine_embedding_loss(
            _t(a), _t(b), _t(np.array([1, -1, 1, -1])))))
        assert np.isfinite(float(F.soft_margin_loss(
            _t(a), _t(np.sign(b)))))
        assert np.isfinite(float(F.triplet_margin_loss(
            _t(a), _t(b), _t(b[::-1].copy()))))

    def test_hsigmoid_functional(self):
        out = F.hsigmoid_loss(_t(rng.randn(3, 8).astype("float32")),
                              _t(rng.randint(0, 10, 3)), 10,
                              _t(rng.randn(9, 8).astype("float32")))
        assert out.shape == [3, 1]

    def test_inplace_variants(self):
        x = _t(np.array([-1.0, 2.0], "float32"))
        y = F.relu_(x)
        assert y is x and _np(x).tolist() == [0.0, 2.0]
        x2 = _t(np.array([0.0, 100.0], "float32"))
        F.tanh_(x2)
        assert abs(_np(x2)[1] - 1.0) < 1e-6
        x3 = _t(np.array([1.0, 3.0], "float32"))
        F.softmax_(x3)
        assert abs(_np(x3).sum() - 1.0) < 1e-5

    def test_upsample_and_zeropad(self):
        x = rng.randn(1, 2, 3, 3).astype("float32")
        assert F.upsample(_t(x), scale_factor=2).shape == [1, 2, 6, 6]
        assert F.zeropad2d(_t(x), [1, 1, 2, 2]).shape == [1, 2, 7, 5]
