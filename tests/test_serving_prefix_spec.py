"""Prefix-cache sharing + speculative decoding on the serving engine.

Under test (inference/serving.py, the two PR-16 serving optimizations):
- shared-prefix admission maps cached pages into the new slot's block
  table and the chunk planner starts at the first COLD chunk (the
  chunk plan is asserted through the per-request prefill_chunk spans)
- copy-on-write on divergence: a full-prefix-hit refeed copies the
  final shared page first, and the DONOR's output stays bit-identical
- greedy speculative decoding commits exactly the plain-decode token
  stream (bit-gated), with tokens/step > 1 at nonzero acceptance
- preempting a slot that holds shared pages leaves the sharer intact
- idle cached pages are reclaimed (LRU) under pool pressure
- the ref-counted free-list accounting invariant holds across
  admit/evict/preempt/shed/finish (debug_invariants mode)
- ZERO recompiles after warmup with both features on (the compile
  lattice gains no data-dependent shapes)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.enforce import PreconditionNotMetError
from paddle_tpu.inference import Config, ServingEngine, create_predictor
from paddle_tpu.models.llama import (LlamaForCausalLM, llama_tiny,
                                     llama_tiny_draft)

PAGE = 8


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(11)
    return LlamaForCausalLM(llama_tiny())


@pytest.fixture(scope="module")
def draft_model():
    paddle.seed(13)
    return LlamaForCausalLM(llama_tiny_draft())


@pytest.fixture()
def paged_pred(tiny_model):
    return create_predictor(
        Config().set_model(tiny_model).enable_paged_kv(page_size=PAGE))


@pytest.fixture()
def draft_pred(draft_model):
    return create_predictor(
        Config().set_model(draft_model).enable_paged_kv(page_size=PAGE))


_SOLO_CACHE = {}


def _solo(tiny_model, prompt, n_new):
    """One-request-at-a-time Predictor reference output. One module-
    wide predictor (its bucketed programs reuse across prompt shapes)
    and memoized outputs keep the 20+ reference decodes cheap."""
    if "pred" not in _SOLO_CACHE:
        _SOLO_CACHE["pred"] = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(
                page_size=PAGE))
    key = (prompt.tobytes(), n_new)
    if key not in _SOLO_CACHE:
        pred = _SOLO_CACHE["pred"]
        _SOLO_CACHE[key] = np.asarray(
            pred.generate(paddle.to_tensor(prompt[None]),
                          max_new_tokens=n_new)._value)[0]
    return _SOLO_CACHE[key]


def _sys_prompt(pages, seed=5):
    r = np.random.RandomState(seed)
    return r.randint(1, 256, (pages * PAGE,))


def _with_tail(sysp, tail, seed):
    r = np.random.RandomState(seed)
    return np.concatenate([sysp, r.randint(1, 256, (tail,))])


def _chunk_spans(eng, rid):
    for tr in eng.request_traces():
        if tr["rid"] == rid:
            return [s for s in tr["spans"]
                    if s["name"] == "prefill_chunk"]
    return []


class TestPrefixCache:
    def test_shared_prefix_skips_prefill_chunks(self, tiny_model,
                                                paged_pred):
        """A request sharing a 4-page prefix with an earlier one feeds
        ONE chunk starting at the cached frontier instead of three —
        asserted on the chunk plan (prefill_chunk spans) — and both
        outputs match the sequential reference exactly."""
        sysp = _sys_prompt(4)                       # 32 tokens, Sc = 16
        eng = ServingEngine(paged_pred, max_batch=2, prefill_chunk=16,
                            prefix_cache=True, debug_invariants=True)
        donor = _with_tail(sysp, 0, 1)              # exactly the prefix
        sharer = _with_tail(sysp, 8, 2)             # prefix + 1 cold page
        rid0 = eng.submit(donor, max_new_tokens=4)
        eng.run()                                   # donor registers pages
        rid1 = eng.submit(sharer, max_new_tokens=4)
        done = eng.run()
        s = eng.prefix_cache_stats()
        assert s["hits"] == 4 and s["skipped_tokens"] >= 32
        spans = _chunk_spans(eng, rid1)
        assert len(spans) == 1                      # 3 chunks skipped
        assert spans[0]["meta"]["start"] == 32      # first COLD token
        assert spans[0]["meta"]["tokens"] == 8
        # ledger-exact reuse accounting: fed + skipped == prompt tokens
        assert s["fed_tokens"] + s["skipped_tokens"] == \
            len(donor) + len(sharer)
        np.testing.assert_array_equal(
            done[rid0].output_ids, _solo(tiny_model, donor, 4))
        np.testing.assert_array_equal(
            done[rid1].output_ids, _solo(tiny_model, sharer, 4))

    def test_cow_divergence_keeps_donor_bit_identical(self, tiny_model,
                                                      paged_pred):
        """A full-prompt hit refeeds its last token into a shared page
        — the copy-on-write must leave the mid-decode donor's pages
        untouched: both requests equal the sequential reference."""
        sysp = _sys_prompt(3)
        eng = ServingEngine(paged_pred, max_batch=2, prefill_chunk=16,
                            prefix_cache=True, debug_invariants=True)
        rid0 = eng.submit(sysp, max_new_tokens=10)
        for _ in range(4):                  # donor reaches mid-decode
            eng.step()
        rid1 = eng.submit(sysp.copy(), max_new_tokens=10)
        done = eng.run()
        assert eng.prefix_cache_stats()["cow"] >= 1
        ref = _solo(tiny_model, sysp, 10)
        np.testing.assert_array_equal(done[rid0].output_ids, ref)
        np.testing.assert_array_equal(done[rid1].output_ids, ref)

    def test_preempting_sharer_leaves_other_sharer_intact(
            self, tiny_model, paged_pred):
        """Two admitted requests share the donor's cached pages; page
        starvation preempts the YOUNGER one mid-prefill. The elder
        sharer (refcount drops 2 -> 1) must keep decoding on the
        still-live pages, and the preempted request restarts exactly."""
        sysp = _sys_prompt(3)                        # 3 cached pages
        eng = ServingEngine(paged_pred, max_batch=3, pool_pages=8,
                            prefill_chunk=16, prefix_cache=True,
                            debug_invariants=True)
        rid_d = eng.submit(sysp, max_new_tokens=4)
        eng.run()                                    # donor -> 3 idle pages
        cold = np.random.RandomState(9).randint(1, 256, (40,))
        rid_x = eng.submit(cold, max_new_tokens=4)       # elder, cold
        rid_1 = eng.submit(_with_tail(sysp, 8, 3), max_new_tokens=4)
        rid_2 = eng.submit(_with_tail(sysp, 8, 4), max_new_tokens=4)
        done = eng.run()
        preempts = [s for tr in eng.request_traces()
                    for s in tr["spans"] if s["name"] == "preempt"]
        assert preempts, "scenario must starve pages into a preemption"
        for rid, p in [(rid_d, sysp), (rid_x, cold),
                       (rid_1, _with_tail(sysp, 8, 3)),
                       (rid_2, _with_tail(sysp, 8, 4))]:
            np.testing.assert_array_equal(
                done[rid].output_ids, _solo(tiny_model, p, 4))

    def test_lru_reclaim_under_pool_pressure(self, tiny_model,
                                             paged_pred):
        """Distinct prompts fill the cache with idle registered pages;
        later admissions must reclaim them (oldest first) instead of
        stalling — and every output stays exact."""
        eng = ServingEngine(paged_pred, max_batch=2, pool_pages=8,
                            prefill_chunk=16, prefix_cache=True,
                            debug_invariants=True)
        prompts = [_sys_prompt(3, seed=20 + i) for i in range(4)]
        done = {}
        for p in prompts:                   # sequential: cache fills up
            eng.submit(p, max_new_tokens=4)
            done.update(eng.run())
        s = eng.prefix_cache_stats()
        assert s["reclaimed"] >= 1
        rids = sorted(done)
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(
                done[rid].output_ids, _solo(tiny_model, p, 4))

    def test_requires_chunked_mode(self, paged_pred):
        with pytest.raises(PreconditionNotMetError):
            ServingEngine(paged_pred, max_batch=2, prefix_cache=True)


class TestPoolInvariant:
    def test_invariant_holds_across_lifecycle(self, paged_pred):
        """admit / finish / preempt / shed / reclaim sequences keep
        free + idle + refcounted-live an exact partition of the pool
        (debug mode checks after every transition; one more explicit
        check after the drain)."""
        sysp = _sys_prompt(3)
        eng = ServingEngine(paged_pred, max_batch=2, pool_pages=8,
                            prefill_chunk=16, prefix_cache=True,
                            max_queue=3, debug_invariants=True)
        for i in range(6):                  # overflows max_queue: sheds
            eng.submit(_with_tail(sysp, 2 + i, 30 + i),
                       max_new_tokens=3)
        eng.run()
        shed = [r for r in eng.finished.values() if r.shed]
        assert shed, "queue bound must shed"
        eng.check_invariants()
        free = len(eng._free_pages) + len(eng._lru)
        live = sum(1 for pg in range(eng.P - 1) if eng._refcount[pg])
        assert free + live == eng.P - 1

    def test_invariant_catches_double_free(self, paged_pred):
        """The checker is not a tautology: corrupting the free list
        (a simulated double free) must raise."""
        eng = ServingEngine(paged_pred, max_batch=2, prefill_chunk=16,
                            prefix_cache=True)
        eng.check_invariants()
        eng._free_pages.append(eng._free_pages[0])
        with pytest.raises(PreconditionNotMetError, match="invariant"):
            eng.check_invariants()


class TestSpeculativeDecoding:
    def _outputs(self, pred, prompts, n_new, **kw):
        eng = ServingEngine(pred, max_batch=3, prefill_chunk=16,
                            debug_invariants=True, **kw)
        rids = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        done = eng.run()
        return eng, [done[r].output_ids for r in rids]

    def test_greedy_spec_bit_identical_to_plain(self, tiny_model,
                                                paged_pred, draft_pred):
        """The acceptance gate: with a REAL (distinct) draft model the
        committed ids equal plain greedy decode token-for-token."""
        r = np.random.RandomState(3)
        prompts = [r.randint(1, 256, (L,)) for L in [7, 12, 21, 5, 9]]
        _, plain = self._outputs(paged_pred, prompts, 10)
        eng, spec = self._outputs(paged_pred, prompts, 10,
                                  draft_predictor=draft_pred,
                                  spec_tokens=3)
        for a, b in zip(plain, spec):
            np.testing.assert_array_equal(a, b)
        s = eng.spec_stats()
        assert s["rounds"] > 0 and s["tokens_per_step"] >= 1.0

    def test_self_speculation_tokens_per_step(self, tiny_model,
                                              paged_pred):
        """Target-as-its-own-draft: every proposal matches the target
        argmax chain, so acceptance is 1.0 and each verify round
        commits k+1 tokens (minus budget-capped tails) — tokens/step
        must clear 1 by a wide margin, outputs still exact."""
        r = np.random.RandomState(4)
        prompts = [r.randint(1, 256, (L,)) for L in [7, 12, 9]]
        _, plain = self._outputs(paged_pred, prompts, 12)
        eng, spec = self._outputs(paged_pred, prompts, 12,
                                  draft_predictor=paged_pred,
                                  spec_tokens=3)
        for a, b in zip(plain, spec):
            np.testing.assert_array_equal(a, b)
        s = eng.spec_stats()
        assert s["accept_rate"] > 0.9
        assert s["tokens_per_step"] > 2.0

    def test_spec_requires_greedy_and_chunked(self, tiny_model,
                                              paged_pred):
        with pytest.raises(PreconditionNotMetError):
            ServingEngine(paged_pred, max_batch=2,
                          draft_predictor=paged_pred, spec_tokens=2)
        cfg = Config().set_model(tiny_model).enable_paged_kv(
            page_size=PAGE)
        cfg.generation.temperature = 0.7
        hot = create_predictor(cfg)
        with pytest.raises(PreconditionNotMetError):
            ServingEngine(hot, max_batch=2, prefill_chunk=16,
                          draft_predictor=hot, spec_tokens=2)
        with pytest.raises(PreconditionNotMetError):
            ServingEngine(paged_pred, max_batch=2, prefill_chunk=16,
                          spec_tokens=2)    # draft missing


class TestComposedCompileStability:
    def test_zero_recompiles_after_warmup_both_features(
            self, tiny_model, paged_pred, draft_pred):
        """Prefix cache + spec decode together: after one warmup mix
        (cold prompt, shared prefix, full hit with CoW, decode), a
        varied stream triggers ZERO additional XLA compiles."""
        sysp = _sys_prompt(2)
        eng = ServingEngine(paged_pred, max_batch=3, prefill_chunk=16,
                            prefix_cache=True, debug_invariants=True,
                            draft_predictor=draft_pred, spec_tokens=3)
        for p, n in [(_with_tail(sysp, 5, 1), 6),
                     (_with_tail(sysp, 9, 2), 6), (sysp.copy(), 4)]:
            eng.submit(p, max_new_tokens=n)
        eng.run()
        warm = eng.stats.compiles
        for i in range(6):
            eng.submit(_with_tail(sysp, 3 + i, 40 + i),
                       max_new_tokens=4 + (i % 3))
        eng.submit(sysp.copy(), max_new_tokens=3)
        done = eng.run()
        assert eng.stats.compiles == warm, "recompiled after warmup"
        assert eng.prefix_cache_stats()["hits"] > 0
        for req in done.values():
            ref = _solo(tiny_model, req.prompt, req.max_new_tokens)
            np.testing.assert_array_equal(req.output_ids, ref)
