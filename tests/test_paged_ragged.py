"""Paged (block-table) KV cache + ragged-batch decode.

Reference parity targets:
- phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu +
  block_attn.h (paged cache attention kernel)
- python/paddle/incubate/nn/functional/block_multihead_attention.py:19
  (python surface / semantics: per-seq block tables, ragged lengths)

TPU redesign under test: the physical page id comes from a
scalar-prefetched block table inside the Pallas BlockSpec index map
(ops/pallas/decode_attention.py), and the Predictor allocates pages per
row with a trash page absorbing right-pad writes.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.decode_attention import (
    _dense_ragged, decode_attention, paged_attention_dense,
    paged_decode_attention)


def _rand(r, *shape):
    return jnp.asarray(r.randn(*shape), jnp.float32)


class TestPagedKernel:
    def test_ragged_vector_offset_matches_dense(self):
        r = np.random.RandomState(0)
        B, H, KV, D, M = 3, 8, 2, 128, 512
        q = _rand(r, B, 1, H, D)
        kc, vc = _rand(r, B, KV, M, D), _rand(r, B, KV, M, D)
        lens = jnp.asarray([100, 37, 411], jnp.int32)
        out = decode_attention(q, kc, vc, lens, interpret=True)
        ref = _dense_ragged(q, kc, vc, lens)
        assert float(jnp.abs(out - ref).max()) < 1e-4

    @pytest.mark.parametrize("Sq", [1, 8])
    def test_paged_matches_gathered_dense(self, Sq):
        r = np.random.RandomState(1)
        B, H, KV, D, M, page = 3, 8, 2, 128, 512, 64
        npages = M // page
        P = B * npages + 5
        q = _rand(r, B, Sq, H, D)
        kp, vp = _rand(r, P, KV, page, D), _rand(r, P, KV, page, D)
        # scrambled physical page order: proves the table indirection
        tbl = jnp.asarray(
            r.permutation(P)[:B * npages].reshape(B, npages), jnp.int32)
        lens = jnp.asarray([100, 37, 411], jnp.int32)
        out = paged_decode_attention(q, kp, vp, tbl, lens,
                                     interpret=True)
        ref = paged_attention_dense(q, kp, vp, tbl, lens)
        assert float(jnp.abs(out - ref).max()) < 1e-4

    def test_paged_vs_contiguous_cache(self):
        """Pages laid out to mirror a contiguous cache must reproduce
        the contiguous kernel's output exactly."""
        r = np.random.RandomState(2)
        B, H, KV, D, M, page = 2, 4, 4, 128, 256, 64
        npages = M // page
        q = _rand(r, B, 1, H, D)
        kc, vc = _rand(r, B, KV, M, D), _rand(r, B, KV, M, D)
        # pool[b*npages + j] = cache[b][:, j*page:(j+1)*page]
        kp = jnp.swapaxes(kc.reshape(B, KV, npages, page, D), 1, 2) \
            .reshape(B * npages, KV, page, D)
        vp = jnp.swapaxes(vc.reshape(B, KV, npages, page, D), 1, 2) \
            .reshape(B * npages, KV, page, D)
        tbl = jnp.arange(B * npages, dtype=jnp.int32).reshape(B, npages)
        lens = jnp.asarray([200, 129], jnp.int32)
        paged = paged_decode_attention(q, kp, vp, tbl, lens,
                                       interpret=True)
        dense = decode_attention(q, kc, vc, lens, interpret=True)
        assert float(jnp.abs(paged - dense).max()) < 1e-4


class TestRaggedGenerate:
    @classmethod
    def setup_class(cls):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        paddle.seed(0)
        cls.cfg = llama_tiny()
        cls.model = LlamaForCausalLM(cls.cfg)
        r = np.random.RandomState(0)
        cls.lens = [11, 24, 17]
        cls.S0 = max(cls.lens)
        cls.ids = np.zeros((3, cls.S0), np.int64)
        for b, L in enumerate(cls.lens):
            cls.ids[b, :L] = r.randint(1, cls.cfg.vocab_size, (L,))

    def _pred(self, **cfg_calls):
        from paddle_tpu.inference import Config, create_predictor

        conf = Config().set_model(self.model)
        if cfg_calls.get("paged"):
            conf.enable_paged_kv(page_size=8)
        return create_predictor(conf)

    def test_ragged_equals_per_row_solo(self):
        """Each ragged row must produce exactly the tokens it would
        produce decoded alone (no lockstep, no pad contamination)."""
        pred = self._pred()
        out = np.asarray(pred.generate(
            paddle.to_tensor(self.ids), max_new_tokens=6,
            lengths=np.array(self.lens))._value)
        for b, L in enumerate(self.lens):
            solo = np.asarray(pred.generate(
                paddle.to_tensor(self.ids[b:b + 1, :L]),
                max_new_tokens=6)._value)[0, L:]
            assert (out[b, self.S0:] == solo).all(), (b, out[b], solo)

    def test_paged_equals_dense(self):
        out = np.asarray(self._pred().generate(
            paddle.to_tensor(self.ids), max_new_tokens=6,
            lengths=np.array(self.lens))._value)
        out_p = np.asarray(self._pred(paged=True).generate(
            paddle.to_tensor(self.ids), max_new_tokens=6,
            lengths=np.array(self.lens))._value)
        assert (out == out_p).all()

    def test_eos_freezes_row(self):
        pred = self._pred()
        base = np.asarray(pred.generate(
            paddle.to_tensor(self.ids), max_new_tokens=6,
            lengths=np.array(self.lens))._value)
        eos = int(base[0, self.S0 + 1])  # row 0's 2nd new token
        out = np.asarray(pred.generate(
            paddle.to_tensor(self.ids), max_new_tokens=6,
            lengths=np.array(self.lens), eos_token_id=eos)._value)
        row = out[0, self.S0:]
        assert row[1] == eos and (row[2:] == eos).all()
        # rows that never hit eos are unchanged
        for b in (1, 2):
            if eos not in base[b, self.S0:]:
                assert (out[b] == base[b]).all()

    def test_paged_pool_is_smaller_than_dense(self):
        """The point of paging: sum-of-lengths pages, not B*max_len."""
        from paddle_tpu.inference import Config, create_predictor

        pred = create_predictor(
            Config().set_model(self.model).enable_paged_kv(page_size=8))
        n_new = 4
        caches, P = pred._paged_caches(self.lens, n_new, 64, 8,
                                       jnp.float32)
        dense_rows = 3 * 64
        assert P * 8 < dense_rows
        # every owned page id is unique; unowned entries hit the trash
        tables = np.asarray(caches[0][2])
        owned = [t for b, L in enumerate(self.lens)
                 for t in tables[b, :-(-(L + n_new) // 8)]]
        assert len(owned) == len(set(owned))
        assert (tables.max() == P - 1)  # trash page referenced


def test_block_multihead_attention_reference_surface():
    """The reference's exact python API name over the paged kernel
    (reference: incubate/nn/functional/block_multihead_attention.py:19)
    — decode phase: per-row write at seq_lens_decoder, ragged attend."""
    import paddle_tpu.incubate.nn.functional as IF
    from paddle_tpu.ops.pallas.decode_attention import \
        paged_attention_dense

    r = np.random.RandomState(0)
    B, H, D, page, npages = 2, 4, 8, 8, 4
    P = B * npages + 1
    kp = jnp.asarray(r.randn(P, H, page, D), jnp.float32)
    vp = jnp.asarray(r.randn(P, H, page, D), jnp.float32)
    tbl = jnp.asarray(r.permutation(P - 1)[:B * npages]
                      .reshape(B, npages), jnp.int32)
    lens = np.array([[5], [13]], np.int32)
    qkv = r.randn(B, 3 * H * D).astype("float32")
    z = paddle.to_tensor(np.zeros((B, 1), "int32"))
    out, _, kc, vc = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kp), paddle.to_tensor(vp),
        z, paddle.to_tensor(lens),
        paddle.to_tensor(np.ones((B, 1), "int32")),
        None, None, None, None, paddle.to_tensor(tbl), block_size=page)
    q = qkv.reshape(B, 3, H, D)[:, 0]
    kn, vn = np.asarray(kc._value), np.asarray(vc._value)
    ref = paged_attention_dense(jnp.asarray(q)[:, None], jnp.asarray(kn),
                                jnp.asarray(vn), tbl,
                                jnp.asarray(lens.reshape(-1)))
    assert np.abs(np.asarray(out._value).reshape(B, 1, H, D)
                  - np.asarray(ref)).max() < 1e-5
    for b, L in enumerate([5, 13]):
        p_id, s = int(tbl[b, L // page]), L % page
        assert np.allclose(kn[p_id, :, s, :],
                           qkv.reshape(B, 3, H, D)[b, 1])


def test_block_multihead_attention_gqa_layout():
    """Reference GQA qkv layout: (H + 2*KV)*D consecutive head planes;
    kv heads land in the KV-head cache and q attends grouped."""
    import paddle_tpu.incubate.nn.functional as IF
    from paddle_tpu.ops.pallas.decode_attention import \
        paged_attention_dense

    r = np.random.RandomState(1)
    B, H, KV, D, page, npages = 2, 8, 2, 8, 8, 4
    P = B * npages + 1
    kp = jnp.asarray(r.randn(P, KV, page, D), jnp.float32)
    vp = jnp.asarray(r.randn(P, KV, page, D), jnp.float32)
    tbl = jnp.asarray(r.permutation(P - 1)[:B * npages]
                      .reshape(B, npages), jnp.int32)
    lens = np.array([[5], [13]], np.int32)
    qkv = r.randn(B, (H + 2 * KV) * D).astype("float32")
    z = paddle.to_tensor(np.zeros((B, 1), "int32"))
    out, _, kc, vc = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kp), paddle.to_tensor(vp),
        z, paddle.to_tensor(lens),
        paddle.to_tensor(np.ones((B, 1), "int32")),
        None, None, None, None, paddle.to_tensor(tbl), block_size=page)
    heads = qkv.reshape(B, H + 2 * KV, D)
    ref = paged_attention_dense(
        jnp.asarray(heads[:, :H])[:, None], jnp.asarray(kc._value),
        jnp.asarray(vc._value), tbl, jnp.asarray(lens.reshape(-1)))
    assert np.abs(np.asarray(out._value).reshape(B, 1, H, D)
                  - np.asarray(ref)).max() < 1e-5
    kn = np.asarray(kc._value)
    p_id, s = int(tbl[0, 5 // page]), 5 % page
    assert np.allclose(kn[p_id, :, s, :], heads[0, H:H + KV])
    # seq_lens_decoder beyond the table must refuse loudly
    with pytest.raises(Exception, match="block table"):
        IF.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kp),
            paddle.to_tensor(vp), z,
            paddle.to_tensor(np.array([[32], [1]], "int32")),
            paddle.to_tensor(np.ones((B, 1), "int32")),
            None, None, None, None, paddle.to_tensor(tbl),
            block_size=page)
