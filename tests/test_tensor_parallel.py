"""TP layer parity: mp-sharded execution over an 8-device mesh must match
the same model run unsharded (the reference's loss-parity strategy,
test/collective/fleet/hybrid_parallel_mp_layers.py analog)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.distributed.fleet.layers import mpu


@pytest.fixture(scope="module")
def hcg():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    return fleet.init(is_collective=True, strategy=strategy)


def _loss_fn(model, batch):
    out = model(batch["x"])
    return paddle.mean((out - batch["y"]) ** 2)


class MLP(paddle.nn.Layer):
    def __init__(self, d=16, h=32, parallel=True):
        super().__init__()
        if parallel:
            self.fc1 = mpu.ColumnParallelLinear(d, h, gather_output=False)
            self.fc2 = mpu.RowParallelLinear(h, d, input_is_parallel=True)
        else:
            self.fc1 = paddle.nn.Linear(d, h)
            self.fc2 = paddle.nn.Linear(h, d)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _copy_params(src, dst):
    sd = src.state_dict()
    dst.set_state_dict({k: v for k, v in sd.items()})


def test_column_row_parallel_forward_backward_parity(hcg):
    paddle.seed(7)
    model = MLP(parallel=True)
    golden = MLP(parallel=False)
    _copy_params(model, golden)

    np.random.seed(0)
    x = np.random.randn(8, 16).astype("float32")
    y = np.random.randn(8, 16).astype("float32")

    # golden single-device step
    g_opt = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=golden.parameters())
    out = golden(paddle.to_tensor(x))
    loss_g = paddle.mean((out - paddle.to_tensor(y)) ** 2)
    loss_g.backward()
    g_opt.step()

    # distributed step over dp=2 x mp=4
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(_loss_fn)
    loss_d = step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)})

    np.testing.assert_allclose(float(loss_d), float(loss_g), rtol=1e-5)
    for (n, pd), (_, pg) in zip(model.named_parameters(),
                                golden.named_parameters()):
        np.testing.assert_allclose(np.asarray(pd._value),
                                   np.asarray(pg._value), rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_vocab_parallel_embedding_parity(hcg):
    paddle.seed(11)
    vocab, dim = 64, 16
    emb_p = mpu.VocabParallelEmbedding(vocab, dim)
    emb_s = paddle.nn.Embedding(vocab, dim)
    emb_s.set_state_dict(emb_p.state_dict())

    ids = np.random.RandomState(1).randint(0, vocab, (8, 5))

    golden = emb_s(paddle.to_tensor(ids))

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = emb_p

        def forward(self, x):
            return self.emb(x)

    model = M()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    fwd = eng.eval_step(lambda m, b: m(b["ids"]))
    out = fwd({"ids": paddle.to_tensor(ids)})
    np.testing.assert_allclose(np.asarray(out._value),
                               np.asarray(golden._value), rtol=1e-5)


def test_parallel_cross_entropy_parity(hcg):
    paddle.seed(13)
    B, V = 8, 32
    logits_np = np.random.RandomState(2).randn(B, V).astype("float32")
    labels_np = np.random.RandomState(3).randint(0, V, (B,))

    golden = paddle.nn.functional.cross_entropy(
        paddle.to_tensor(logits_np), paddle.to_tensor(labels_np),
        reduction="none")

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.w = self.create_parameter((V, V))

        def forward(self, logits, labels):
            from paddle_tpu.distributed.fleet.layers.mpu import mp_ops

            local = mp_ops._c_split(logits)  # shard vocab dim over mp
            return mpu.parallel_cross_entropy(local, labels)

    model = M()
    opt = paddle.optimizer.SGD(parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    fwd = eng.eval_step(lambda m, b: m(b["logits"], b["labels"]))
    out = fwd({"logits": paddle.to_tensor(logits_np),
               "labels": paddle.to_tensor(labels_np)})
    got = np.asarray(out._value).reshape(B)
    np.testing.assert_allclose(got, np.asarray(golden._value).reshape(B),
                               rtol=1e-5, atol=1e-6)
