"""Meta-optimizer family + ASP structured sparsity + sparse tensors
(reference: fleet/meta_optimizers/{gradient_merge,lars,dgc,localsgd}_
optimizer.py, incubate/asp/, python/paddle/sparse/ — semantics tests)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _model_and_data(seed=0):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    r = np.random.RandomState(0)
    x = paddle.to_tensor(r.randn(16, 8).astype("float32"))
    y = paddle.to_tensor(r.randn(16, 4).astype("float32"))
    return m, x, y


def _loss(m, x, y):
    return paddle.mean((m(x) - y) ** 2)


def test_gradient_merge_equals_big_batch():
    """k accumulated micro-steps == one step on the k-x batch."""
    from paddle_tpu.distributed.fleet.meta_optimizers import \
        GradientMergeOptimizer

    m1, x, y = _model_and_data(7)
    snap = [np.asarray(p._value) for p in m1.parameters()]
    opt = optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
    gm = GradientMergeOptimizer(opt, k_steps=4)
    for i in range(4):
        loss = _loss(m1, x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
        loss.backward()
        gm.step()
        gm.clear_grad()
    merged = [np.asarray(p._value) for p in m1.parameters()]

    m2, _, _ = _model_and_data(7)
    for p, v in zip(m2.parameters(), snap):
        p._value = jnp.asarray(v)
    opt2 = optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())
    # mean over the 4 quarter-batches == mean of the 4 losses
    loss = sum(_loss(m2, x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
               for i in range(4)) / 4
    loss.backward()
    opt2.step()
    for a, p in zip(merged, m2.parameters()):
        np.testing.assert_allclose(a, np.asarray(p._value), rtol=1e-5,
                                   atol=1e-6)


def test_lars_momentum_trains_and_scales():
    m, x, y = _model_and_data(8)
    opt = optimizer.LarsMomentum(learning_rate=0.1, momentum=0.9,
                                 parameters=m.parameters())
    first = float(_loss(m, x, y))
    for _ in range(20):
        loss = _loss(m, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < first


def test_dgc_sparsifies_with_error_feedback():
    from paddle_tpu.distributed.fleet.meta_optimizers import \
        DGCMomentumOptimizer

    m, x, y = _model_and_data(9)
    opt = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                               parameters=m.parameters(), sparsity=0.75)
    first = float(_loss(m, x, y))
    for _ in range(30):
        loss = _loss(m, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < first  # converges despite 75% dropped entries
    # error feedback buffers hold the dropped mass
    assert opt._err and all(np.isfinite(np.asarray(v)).all()
                            for v in opt._err.values())


def test_localsgd_steps():
    from paddle_tpu.distributed.fleet.meta_optimizers import \
        LocalSGDOptimizer

    m, x, y = _model_and_data(10)
    opt = LocalSGDOptimizer(optimizer.SGD(learning_rate=0.1,
                                          parameters=m.parameters()),
                            k_steps=2)
    first = float(_loss(m, x, y))
    for _ in range(6):
        loss = _loss(m, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < first


def test_asp_prune_and_guarantee():
    from paddle_tpu.incubate import asp

    m, x, y = _model_and_data(11)
    asp.reset_excluded_layers()
    asp.prune_model(m, n=2, m=4)
    for name, p in m.named_parameters():
        if p._value.ndim == 2:
            assert asp.check_sparsity(p, n=2, m=4), name
            assert asp.calculate_density(p) <= 0.55
    opt = asp.decorate(optimizer.SGD(learning_rate=0.05,
                                     parameters=m.parameters()))
    first = float(_loss(m, x, y))
    for _ in range(10):
        loss = _loss(m, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < first
    # the 2:4 pattern SURVIVED the optimizer steps (the decorate
    # contract: masks re-applied after every update)
    for name, p in m.named_parameters():
        if p._value.ndim == 2:
            assert asp.check_sparsity(p, n=2, m=4), name


def test_sparse_coo_roundtrip_and_ops():
    import paddle_tpu.sparse as sp

    dense = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
    idx = np.array([[0, 1, 1], [1, 0, 2]])
    s = sp.sparse_coo_tensor(idx, np.array([1, 2, 3], np.float32),
                             shape=(2, 3))
    assert s.nnz == 3 and sp.is_sparse(s)
    np.testing.assert_array_equal(np.asarray(s.to_dense()._value), dense)

    # csr construction converges to the same layout
    c = sp.sparse_csr_tensor([0, 1, 3], [1, 0, 2],
                             np.array([1, 2, 3], np.float32), (2, 3))
    np.testing.assert_array_equal(np.asarray(c.to_dense()._value), dense)

    # sparse + sparse, sparse @ dense, relu, transpose
    two = sp.add(s, s)
    np.testing.assert_array_equal(np.asarray(two.to_dense()._value),
                                  2 * dense)
    d = np.random.RandomState(0).randn(3, 4).astype("float32")
    mm = sp.matmul(s, paddle.to_tensor(d))
    np.testing.assert_allclose(np.asarray(mm._value), dense @ d,
                               rtol=1e-5)
    neg = sp.sparse_coo_tensor(idx, np.array([-1, 2, -3], np.float32),
                               shape=(2, 3))
    np.testing.assert_array_equal(
        np.asarray(sp.relu(neg).to_dense()._value),
        np.maximum(np.asarray(neg.to_dense()._value), 0))
    t = sp.transpose(s, [1, 0])
    np.testing.assert_array_equal(np.asarray(t.to_dense()._value),
                                  dense.T)


def test_sparse_masked_matmul():
    import paddle_tpu.sparse as sp

    r = np.random.RandomState(1)
    a = r.randn(4, 6).astype("float32")
    b = r.randn(6, 5).astype("float32")
    idx = np.array([[0, 1, 3], [2, 4, 0]])
    mask = sp.sparse_coo_tensor(idx, np.ones(3, np.float32), (4, 5))
    out = sp.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), mask)
    full = a @ b
    got = np.asarray(out.to_dense()._value)
    for i, j in zip(*idx):
        np.testing.assert_allclose(got[i, j], full[i, j], rtol=1e-5)
    assert out.nnz == 3


def test_lars_exclude_from_weight_decay():
    """Excluded params (by name fragment) get plain momentum — no LARS
    scaling, no weight decay."""
    paddle.seed(12)
    m = nn.Linear(4, 4)
    m.weight.name = "linear.weight"
    m.bias.name = "linear.bias"
    lars = optimizer.LarsMomentum(
        learning_rate=0.1, momentum=0.0, parameters=m.parameters(),
        lars_weight_decay=0.5, exclude_from_weight_decay=["bias"])
    ref = optimizer.Momentum(learning_rate=0.1, momentum=0.0,
                             parameters=[])
    b0 = np.asarray(m.bias._value).copy()
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    loss = paddle.mean(m(x))
    loss.backward()
    g_bias = np.asarray(m.bias.grad._value).copy()
    lars.step()
    # excluded bias: plain SGD update (local lr 1, no decay)
    np.testing.assert_allclose(np.asarray(m.bias._value),
                               b0 - 0.1 * g_bias, rtol=1e-5, atol=1e-6)
