"""Unified telemetry (paddle_tpu/observability/).

Under test:
- metrics primitives: Counter/Gauge/Histogram with labels, thread
  safety, fixed-bucket percentiles, conflicting re-registration
- exports: Prometheus text exposition round-trip, JSONL sink
  round-trip, in-process snapshots
- training instrumentation: a ParallelEngine loop fills the step
  histogram / loss / grad-norm / token counters with correct counts,
  and the engine compile counter stays FLAT with telemetry enabled
- serving instrumentation: ServingEngine emits TTFT/TPOT histograms,
  occupancy gauges, admission/eviction/backfill counters — zero
  recompiles after warmup
- traces: annotate() named regions survive jit tracing and surface in
  current_regions(); the watchdog dumps a flight record on timeout
- the metric schema gate: names/labels/types in a live snapshot must
  match the checked-in schema.json (dashboards don't silently break)
- tpulint: the observability package lints clean with ZERO baseline
  entries
"""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import catalog
from paddle_tpu.observability.metrics import MetricsRegistry


@pytest.fixture()
def reg():
    """A fresh registry per test, detached from the global one."""
    return MetricsRegistry()


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------
class TestPrimitives:
    def test_counter_labels(self, reg):
        c = reg.counter("reqs_total", "requests", labelnames=("event",))
        c.inc(event="submitted")
        c.inc(2, event="submitted")
        c.inc(event="evicted")
        assert c.value(event="submitted") == 3
        assert c.value(event="evicted") == 1
        with pytest.raises(ValueError):
            c.inc(-1, event="submitted")
        with pytest.raises(ValueError):
            c.inc(event="submitted", extra="nope")

    def test_gauge(self, reg):
        g = reg.gauge("depth")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value() == 3

    def test_histogram_percentiles(self, reg):
        h = reg.histogram("lat", buckets=(0.001, 0.01, 0.1, 1.0))
        for v in (0.005,) * 98 + (0.5,) * 2:
            h.observe(v)
        assert h.count() == 100
        # p50 lands in the (0.001, 0.01] bucket, p99 in (0.1, 1.0]
        assert 0.001 <= h.percentile(50) <= 0.01
        assert 0.1 <= h.percentile(99) <= 0.5
        assert h.percentile(100) == 0.5

    def test_histogram_empty_and_overflow(self, reg):
        h = reg.histogram("lat", buckets=(1.0,))
        assert h.percentile(99) == 0.0
        h.observe(5.0)              # +Inf bucket
        assert h.percentile(99) == 5.0

    def test_reregistration_same_spec_returns_same_object(self, reg):
        a = reg.counter("c", "x", labelnames=("k",))
        b = reg.counter("c", "x", labelnames=("k",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("c")          # type conflict
        with pytest.raises(ValueError):
            reg.counter("c", labelnames=("other",))   # label conflict

    def test_thread_safety(self, reg):
        c = reg.counter("n")
        h = reg.histogram("h", buckets=(0.5, 1.0))

        def work():
            for _ in range(500):
                c.inc()
                h.observe(0.25)

        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value() == 4000
        assert h.count() == 4000


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------
class TestExports:
    def _populate(self, reg):
        c = reg.counter("tokens_total", "tokens", labelnames=("phase",))
        c.inc(7, phase="decode")
        c.inc(2, phase="prefill")
        g = reg.gauge("depth", "queue depth")
        g.set(3)
        h = reg.histogram("ttft_seconds", "ttft",
                          buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5):
            h.observe(v)
        return reg

    def test_prometheus_round_trip(self, reg):
        self._populate(reg)
        text = reg.prometheus_text()
        parsed = obs.parse_prometheus_text(text)
        assert parsed["tokens_total"][(("phase", "decode"),)] == 7
        assert parsed["tokens_total"][(("phase", "prefill"),)] == 2
        assert parsed["depth"][()] == 3
        assert parsed["ttft_seconds_count"][()] == 3
        assert parsed["ttft_seconds_sum"][()] == pytest.approx(0.555)
        # cumulative bucket counts
        assert parsed["ttft_seconds_bucket"][(("le", "0.01"),)] == 1
        assert parsed["ttft_seconds_bucket"][(("le", "+Inf"),)] == 3

    def test_jsonl_round_trip(self, reg, tmp_path):
        self._populate(reg)
        snap = reg.snapshot()
        sink = obs.JsonlSink(tmp_path / "m.jsonl")
        sink.write(snap)
        sink.write(reg.snapshot())
        back = obs.JsonlSink.read(tmp_path / "m.jsonl")
        assert len(back) == 2
        assert back[0]["metrics"]["ttft_seconds"]["series"][0]["count"] \
            == 3
        assert back[0]["metrics"]["tokens_total"]["series"][0]["labels"]

    def test_snapshot_percentiles(self, reg):
        self._populate(reg)
        row = reg.snapshot()["metrics"]["ttft_seconds"]["series"][0]
        assert row["count"] == 3 and "p50" in row and "p99" in row


# ---------------------------------------------------------------------------
# training instrumentation
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def trained_engine():
    """One tiny GPT train loop; its registry snapshot is shared by the
    train-side assertions (module-scoped: compile once)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    obs.reset_registry()
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    r = np.random.RandomState(0)
    ids = r.randint(0, 128, (4, 17))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    losses = [float(step(batch)) for _ in range(4)]
    return eng, losses, eng.metrics_snapshot()["metrics"]


class TestTrainingInstrumentation:
    def test_step_histogram_counts(self, trained_engine):
        _, losses, m = trained_engine
        row = m["paddle_tpu_train_step_seconds"]["series"][0]
        assert row["count"] == 4
        assert row["sum"] > 0
        assert m["paddle_tpu_train_steps_total"]["series"][0]["value"] \
            == 4
        # 4 steps x B4 x S16 token ids
        assert m["paddle_tpu_train_tokens_total"]["series"][0]["value"] \
            == 4 * 4 * 16

    def test_loss_and_grad_norm_gauges(self, trained_engine):
        _, losses, m = trained_engine
        # one-step lag: the snapshot (taken after the loop) flushed the
        # LAST step's scalars
        assert m["paddle_tpu_train_loss"]["series"][0]["value"] \
            == pytest.approx(losses[-1], rel=1e-5)
        assert m["paddle_tpu_train_grad_norm"]["series"][0]["value"] > 0

    def test_throughput_and_mfu_gauges(self, trained_engine):
        _, _, m = trained_engine
        assert m["paddle_tpu_train_tokens_per_sec"]["series"][0][
            "value"] > 0
        # CPU: peak FLOPs unknown -> MFU pinned to 0, not garbage
        assert m["paddle_tpu_train_mfu"]["series"][0]["value"] == 0.0

    def test_compile_counters_flat_in_steady_state(self, trained_engine):
        eng, _, m = trained_engine
        rows = {tuple(sorted(s["labels"].items())): s["value"]
                for s in m["paddle_tpu_compiles_total"]["series"]}
        assert rows[(("site", "train_engine"),)] == 1   # one signature
        assert eng.stats.compiles == 1
        assert eng.stats.cache_hits == 3

    def test_pod_throughput_single_process(self, trained_engine):
        eng, _, _ = trained_engine
        rep = eng.pod_throughput()
        assert rep["processes"] == 1.0
        assert rep["pod_tokens_per_sec"] == pytest.approx(
            rep["local_tokens_per_sec"])

    def test_pod_throughput_aggregates_across_hosts(self, trained_engine,
                                                    monkeypatch):
        """pod_tokens_per_sec must be the cross-host SUM of the local
        gauges (simulated 3-host pod: every host reports the same local
        rate, the pod gauge carries 3x it)."""
        eng, _, _ = trained_engine
        import paddle_tpu.observability as obs_mod

        monkeypatch.setattr(obs_mod, "cross_host_sum",
                            lambda v: 3.0 * float(v))
        local = eng._metrics["tokens_per_sec"].value()
        assert local > 0
        rep = eng.pod_throughput()
        assert rep["pod_tokens_per_sec"] == pytest.approx(3.0 * local)
        assert eng._metrics["pod_tokens_per_sec"].value() == \
            pytest.approx(3.0 * local)
        # the local gauge itself is untouched by aggregation
        assert rep["local_tokens_per_sec"] == pytest.approx(local)


class TestFirstStepLag:
    """The one-step-lag scalar fetch on the very FIRST step: before any
    step the gauges hold their zero-init; after one step but before the
    next flush they still do (the lag contract: the fetch happens at
    the NEXT step's entry / at metrics_snapshot, never on the hot
    path); the first flush then lands exactly that step's values."""

    @pytest.fixture()
    def fresh_engine(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.engine import ParallelEngine
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        obs.reset_registry()
        paddle.seed(3)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                        num_heads=2, max_position_embeddings=16)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        eng = ParallelEngine(model, opt, hcg.mesh)
        step = eng.train_step(
            lambda m, b: crit(m(b["x"]), b["y"]))
        r = np.random.RandomState(0)
        ids = r.randint(0, 64, (2, 9))
        batch = {"x": paddle.to_tensor(ids[:, :-1]),
                 "y": paddle.to_tensor(ids[:, 1:])}
        return eng, step, batch

    def test_gauges_zero_before_any_step(self, fresh_engine):
        eng, _, _ = fresh_engine
        assert eng._metrics["grad_norm"].value() == 0.0
        assert eng._metrics["loss"].value() == 0.0
        eng._flush_pending_scalars()          # no pending: a no-op
        assert eng._metrics["grad_norm"].value() == 0.0

    def test_first_step_lags_then_flushes(self, fresh_engine):
        eng, step, batch = fresh_engine
        loss = float(step(batch))
        # one-step lag: the first step's scalars are PENDING, the
        # gauges still hold zero until something flushes
        assert eng._pending_scalars is not None
        assert eng._metrics["grad_norm"].value() == 0.0
        assert eng._metrics["loss"].value() == 0.0
        m = eng.metrics_snapshot()["metrics"]   # flushes the lag
        assert m["paddle_tpu_train_loss"]["series"][0]["value"] == \
            pytest.approx(loss, rel=1e-5)
        assert m["paddle_tpu_train_grad_norm"]["series"][0]["value"] > 0
        assert eng._pending_scalars is None
        # flushing twice is idempotent (nothing new pending)
        before = eng._metrics["grad_norm"].value()
        eng._flush_pending_scalars()
        assert eng._metrics["grad_norm"].value() == before


# ---------------------------------------------------------------------------
# serving instrumentation
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def served_engine():
    from paddle_tpu.inference import (Config, ServingEngine,
                                      create_predictor)
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    obs.reset_registry()
    paddle.seed(11)
    model = LlamaForCausalLM(llama_tiny())
    pred = create_predictor(
        Config().set_model(model).enable_paged_kv(page_size=8))
    eng = ServingEngine(pred, max_batch=2, decode_chunk=2)
    r = np.random.RandomState(0)
    V = model.config.vocab_size
    # warmup mix, then a longer mixed stream (arrivals backfill)
    for L in (7, 12):
        eng.submit(r.randint(1, V, (L,)), max_new_tokens=6)
    eng.run()
    warm_compiles = eng.stats.compiles
    lens = [24, 17, 11, 9, 5]
    rids = [eng.submit(r.randint(1, V, (L,)), max_new_tokens=6)
            for L in lens]
    done = eng.run()
    n_requests = 2 + len(lens)
    return (eng, warm_compiles, n_requests,
            {rid: done[rid] for rid in rids},
            eng.metrics_snapshot()["metrics"])


class TestServingInstrumentation:
    def test_ttft_histogram_counts(self, served_engine):
        _, _, n_requests, _, m = served_engine
        assert m["paddle_tpu_serving_ttft_seconds"]["series"][0][
            "count"] == n_requests

    def test_tpot_histogram_counts(self, served_engine):
        _, _, n_requests, done, m = served_engine
        # every request decodes > 1 token, so each contributes one TPOT
        assert all(len(r.new_tokens) > 1 for r in done.values())
        row = m["paddle_tpu_serving_tpot_seconds"]["series"][0]
        assert row["count"] == n_requests
        assert row["p99"] >= row["p50"] > 0

    def test_lifecycle_counters(self, served_engine):
        _, _, n_requests, _, m = served_engine
        ev = {s["labels"]["event"]: s["value"]
              for s in m["paddle_tpu_serving_requests_total"]["series"]}
        assert ev["submitted"] == n_requests
        assert ev["admitted"] == n_requests
        assert ev["evicted"] == n_requests
        assert 0 < ev["backfilled"] <= n_requests

    def test_token_counters(self, served_engine):
        _, _, n_requests, done, m = served_engine
        tok = {s["labels"]["phase"]: s["value"]
               for s in m["paddle_tpu_serving_tokens_total"]["series"]}
        assert tok["prefill"] == n_requests   # one sampled token each
        assert tok["decode"] > 0

    def test_occupancy_gauges_drain_to_zero(self, served_engine):
        eng, _, _, _, m = served_engine
        assert m["paddle_tpu_serving_queue_depth"]["series"][0][
            "value"] == 0
        assert m["paddle_tpu_serving_active_slots"]["series"][0][
            "value"] == 0
        assert m["paddle_tpu_serving_free_pages"]["series"][0][
            "value"] == eng.P - 1
        assert m["paddle_tpu_serving_page_occupancy"]["series"][0][
            "value"] == 0.0

    def test_no_recompiles_after_warmup_with_telemetry(self,
                                                       served_engine):
        eng, warm_compiles, _, _, _ = served_engine
        # the acceptance gate: instrumentation must not perturb the
        # compiled (B, Sb, P) program lattice
        assert eng.stats.compiles == warm_compiles


# ---------------------------------------------------------------------------
# schema gate
# ---------------------------------------------------------------------------
class TestSchemaGate:
    def test_checked_in_schema_matches_catalog(self):
        """schema.json IS the catalog: regenerating it must be a no-op
        (renaming a metric or changing a label set fails here first)."""
        r = MetricsRegistry()
        catalog.train_metrics(r)
        catalog.serving_metrics(r)
        catalog.fleet_metrics(r)
        with open(catalog.SCHEMA_PATH) as f:
            checked_in = json.load(f)
        assert r.schema() == checked_in

    def test_live_snapshots_stay_inside_schema(self, trained_engine,
                                               served_engine):
        """Every metric either engine emitted must exist in schema.json
        with the exact declared label set."""
        with open(catalog.SCHEMA_PATH) as f:
            schema = json.load(f)
        for m in (trained_engine[2], served_engine[4]):
            for name, entry in m.items():
                assert name in schema, f"undeclared metric {name}"
                assert sorted(entry["labels"]) == schema[name]["labels"]
                assert entry["type"] == schema[name]["type"]
                for row in entry["series"]:
                    assert sorted(row["labels"]) == schema[name]["labels"]

    def test_core_metrics_present(self, trained_engine, served_engine):
        assert "paddle_tpu_train_step_seconds" in trained_engine[2]
        assert "paddle_tpu_serving_ttft_seconds" in served_engine[4]
        assert "paddle_tpu_serving_tpot_seconds" in served_engine[4]

    def test_tpulint_and_schema_agree_on_the_metric_set(self):
        """Single source of truth: the STATIC metric set tpulint's
        unregistered-metric rule collects from the whole tree must
        equal schema.json's key set exactly. The live-registry check
        above only sees metrics the test run registers; this one pins
        every registration site in the code — the two checkers can
        never drift apart, and a registration on an untested code path
        still fails CI."""
        import sys
        from pathlib import Path

        repo = Path(__file__).resolve().parent.parent
        if str(repo) not in sys.path:
            sys.path.insert(0, str(repo))
        from tools.tpulint import Project
        from tools.tpulint.rules.unregistered_metric import \
            registered_names

        project, errors = Project.from_paths(
            [repo / "paddle_tpu"], repo)
        assert errors == []
        with open(catalog.SCHEMA_PATH) as f:
            schema = json.load(f)
        static = registered_names(project)
        assert static == set(schema), (
            f"schema.json and the tree's metric registrations drifted: "
            f"only-in-code={sorted(static - set(schema))} "
            f"only-in-schema={sorted(set(schema) - static)}")


# ---------------------------------------------------------------------------
# traces + flight records
# ---------------------------------------------------------------------------
class TestTracesAndFlight:
    def test_annotate_inside_jit_and_region_stack(self):
        import jax
        import jax.numpy as jnp

        seen = {}

        def f(x):
            with obs.annotate("outer"):
                with obs.annotate("inner"):
                    seen.update(obs.current_regions())
                    return x * 2

        out = jax.jit(f)(jnp.ones((2,)))
        assert float(out[0]) == 2.0
        (stack,) = [v for k, v in seen.items() if "MainThread" in k]
        assert stack == ["outer", "inner"]
        assert not any("MainThread" in k
                       for k in obs.current_regions())   # popped

    def test_flight_dump_contents(self, tmp_path):
        reg = obs.reset_registry()
        reg.counter("paddle_tpu_train_steps_total").inc(3)
        reg.snapshot()                      # feeds the ring
        reg.snapshot()
        path = obs.dump_flight_record(
            str(tmp_path / "f.json"), reason="unit test")
        rec = json.load(open(path))
        assert rec["reason"] == "unit test"
        assert len(rec["snapshots"]) >= 2
        assert rec["snapshots"][-1]["metrics"][
            "paddle_tpu_train_steps_total"]["series"][0]["value"] == 3
        assert any("MainThread" in k for k in rec["thread_stacks"])

    def test_watchdog_timeout_dumps_flight_record(self, tmp_path,
                                                  monkeypatch):
        from paddle_tpu.distributed.watchdog import (CommTaskManager,
                                                     TimeoutError_)

        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        mgr = CommTaskManager(timeout=0.15, poll_interval=0.03)
        try:
            with pytest.raises(TimeoutError_) as ei:
                with mgr.track("hung_collective"):
                    time.sleep(0.5)
            assert "flight record" in str(ei.value)
            assert mgr.last_flight_record
            rec = json.load(open(mgr.last_flight_record))
            assert "hung_collective" in rec["reason"]
            # the tracked region was in flight on the main thread
            regions = [r for k, rs in rec["inflight_regions"].items()
                       for r in rs if "MainThread" in k]
            assert "watchdog:hung_collective" in regions
            # the monitor thread itself shows up in the stacks
            assert any("watchdog-monitor" in k
                       for k in rec["thread_stacks"])
        finally:
            mgr.shutdown()

    def test_flight_ring_is_bounded(self):
        rec = obs.FlightRecorder(maxlen=4)
        for i in range(10):
            rec.push({"i": i})
        snaps = rec.snapshots()
        assert len(snaps) == 4 and snaps[-1]["i"] == 9


# ---------------------------------------------------------------------------
# flop accountant
# ---------------------------------------------------------------------------
class TestFlops:
    def test_params_from_config(self):
        from paddle_tpu.models.llama import llama_tiny

        cfg = llama_tiny()
        assert obs.flops.params_from_config(cfg) == cfg.num_params()
        assert obs.flops.params_from_config(object()) is None

    def test_mfu_math(self):
        # 1e9 params at 1000 tok/s vs 6e12 peak: 6e12/6e12 = 1.0
        assert obs.flops.mfu(int(1e9), 1000.0, 1, 6e12) \
            == pytest.approx(1.0)
        assert obs.flops.mfu(int(1e9), 1000.0, 1, 0.0) == 0.0

    def test_attention_term_additive(self):
        from paddle_tpu.models.gpt import GPTConfig

        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=2, max_position_embeddings=32)
        n = cfg.num_params()
        base = obs.flops.train_flops_per_token(n, config=None)
        with_attn = obs.flops.train_flops_per_token(n, config=cfg)
        assert with_attn == base + 12.0 * 2 * 32 * 32


# ---------------------------------------------------------------------------
# tpulint gate: the new package must be clean with ZERO baseline entries
# ---------------------------------------------------------------------------
def test_tpulint_observability_package_zero_baseline():
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths([repo / "paddle_tpu" / "observability"],
                              ALL_RULES, root=repo)
    finally:
        sys.path.remove(str(repo))
    assert findings == [], [str(f) for f in findings]
