"""Host-memory offload tier (distributed/host_offload.py).

The contract under test, end to end:
- HostState round trips are BIT-exact (bytes copied, never re-derived)
  at the original sharding — which is why every parity assertion below
  is ``==``, not allclose.
- The engine knob (``sharding_configs["offload"]``) moves optimizer
  moments / AMP masters / quant-comm EF residuals (optionally stored
  param shards) to host between steps and prefetches them per-bucket
  just in time: loss trajectories offload-on vs offload-off are
  identical, with ZERO recompiles after warmup (the tier lives outside
  the compiled step).
- Every transfer is booked at the closed form (per-device addressable-
  shard bytes per slot) into the ``paddle_tpu_offload_*`` gauges, with
  conservation: cumulative d2h - h2d == bytes currently host-resident.
- memledger's measured accounting books the offloaded split under a
  ``host_state`` component that the analytic closed form matches
  byte-for-byte, and the auto_tuner prices the tier (cheaper HBM,
  dearer step time) so over-HBM configs surface only with offload.
- The serving engine reuses the tier for cold prefix-cache KV pages:
  LRU-evicted pages spill to host and fault back through the normal
  admission accounting on a prefix hit, outputs bit-exact.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import host_offload as ho
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.observability import memledger as ml


def _reset_fleet():
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)


# ---------------------------------------------------------------------------
# HostState: the round-trip primitive
# ---------------------------------------------------------------------------
class TestHostState:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int32"])
    def test_round_trip_bit_exact(self, dtype):
        import jax.numpy as jnp

        r = np.random.RandomState(0)
        arr = jnp.asarray(r.randn(6, 10).astype("float32")).astype(dtype)
        hs = ho.page_out(arr)
        assert ho.is_host(hs)
        assert hs.shape == (6, 10) and hs.dtype == np.dtype(arr.dtype)
        assert hs.nbytes == arr.nbytes
        back = ho.place(hs)
        assert back.dtype == arr.dtype
        np.testing.assert_array_equal(
            np.asarray(back, dtype=np.float32),
            np.asarray(arr, dtype=np.float32))
        assert back.sharding == arr.sharding

    def test_sharded_round_trip_preserves_layout(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = jax.make_mesh((4, 2), ("x", "y"))
        arr = jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8),
            NamedSharding(mesh, P("x", "y")))
        hs = ho.page_out(arr)
        # memledger prices a HostState like the live array it replaces
        assert ml.shard_bytes(hs) == ml.shard_bytes(arr)
        back = ho.place(hs)
        assert back.sharding == arr.sharding
        np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))

    def test_make_config_normalization(self):
        assert ho.make_config(None) is None
        assert ho.make_config({}) is None
        assert ho.make_config(
            {"optimizer": False, "params": False}) is None
        cfg = ho.make_config(True)
        assert cfg.optimizer and not cfg.params
        cfg = ho.make_config({"params": True, "optimizer": False,
                              "prefetch_buckets": 3})
        assert cfg.params and not cfg.optimizer
        assert cfg.prefetch_buckets == 3
        assert ho.make_config(cfg) is cfg


# ---------------------------------------------------------------------------
# engine integration: parity, residency, ledger, recompiles
# ---------------------------------------------------------------------------
def _mlp():
    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(16, 32)
            self.fc2 = paddle.nn.Linear(32, 16)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    return MLP()


def _loss_fn(model, batch):
    return paddle.mean((model(batch["x"]) - batch["y"]) ** 2)


def _flat_engine(offload, quant="none", amp=False, stage=3):
    """dp2 x sharding4 ZeRO engine; offload rides the strategy knob
    (sharding_configs["offload"]) exactly like the reference dict."""
    strategy = fleet.DistributedStrategy()
    sc = {"comm_overlap": True, "comm_buffer_size_MB": 0.0005,
          "sharding_stage": stage}
    if offload is not None:
        sc["offload"] = offload
    strategy.hybrid_configs = {
        "dp_degree": 2, "sharding_degree": 4,
        "sharding_configs": sc,
        "quant_comm": {"dtype": quant, "chunk": 32}}
    _reset_fleet()
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(3)
    model = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10) \
        if amp else None
    step = eng.train_step(_loss_fn, scaler=scaler)
    r = np.random.RandomState(0)
    batch = {"x": paddle.to_tensor(r.randn(8, 16).astype("float32")),
             "y": paddle.to_tensor(r.randn(8, 16).astype("float32"))}
    return eng, step, batch


class TestEngineOffload:
    def test_loss_parity_and_residency(self):
        _, step0, b0 = _flat_engine(None)
        gold = [float(step0(b0)) for _ in range(4)]
        eng, step, b = _flat_engine({"optimizer": True,
                                     "prefetch_buckets": 1})
        got = [float(step(b)) for _ in range(4)]
        assert got == gold  # bit-exact: the tier only copies bytes

        # between steps every moment leaf lives on the host tier
        tier = eng._offload
        assert tier is not None
        hosted = sum(
            1 for p in eng.trainable
            for v in (eng.optimizer._states.get(id(p)) or {}).values()
            if ho.is_host(v))
        assert hosted > 0
        assert tier.host_resident_bytes("optimizer_state") > 0

    def test_transfer_ledger_closed_form_and_gauges(self):
        from paddle_tpu.observability import get_registry

        eng, step, b = _flat_engine({"optimizer": True})
        float(step(b))
        # steady-state window: each step is one h2d prefetch + one d2h
        # page-out of every offloaded slot at shard_bytes granularity
        slot_closed = sum(
            ho.host_shard_bytes(tier_get)
            for tier_get in (eng._offload._get(eng, key) for key, _c, _b
                             in eng._offload._iter_slots(eng)))
        t0 = eng._offload.transfer_bytes()
        steps = 3
        for _ in range(steps):
            float(step(b))
        tier = eng._offload
        assert tier.transfer_bytes() - t0 == 2 * steps * slot_closed
        # conservation: everything sent down minus everything brought
        # back is exactly what the host currently holds
        resident = tier.host_resident_bytes()
        assert (tier.transfer_bytes(direction="d2h")
                - tier.transfer_bytes(direction="h2d")) == resident
        assert resident == slot_closed
        # the gauges carry the same cumulative closed forms
        snap = get_registry().snapshot()["metrics"]
        series = snap["paddle_tpu_offload_transfer_bytes"]["series"]
        vals = {(dict(s["labels"])["component"],
                 dict(s["labels"])["direction"]): s["value"]
                for s in series}
        for (c, d), v in tier._bytes.items():
            assert vals[(c, d)] == float(v)
        host = snap["paddle_tpu_offload_host_bytes"]["series"]
        assert sum(s["value"] for s in host
                   if dict(s["labels"])["component"]
                   != "kv_page") == float(resident)

    def test_zero_recompiles_after_warmup(self):
        eng, step, b = _flat_engine({"optimizer": True,
                                     "prefetch_buckets": 2})
        float(step(b))
        n = eng.stats.compiles
        for _ in range(3):
            float(step(b))
        assert eng.stats.compiles == n

    def test_amp_quant_params_offload_parity(self):
        """The full state surface at once: AMP scaler + int8 EF
        residuals + stored param shards all host-resident between
        steps — trajectory still bit-exact, eval still served."""
        _, step0, b0 = _flat_engine(None, quant="int8", amp=True)
        gold = [float(step0(b0)) for _ in range(5)]
        eng, step, b = _flat_engine(
            {"optimizer": True, "params": True, "prefetch_buckets": 2},
            quant="int8", amp=True)
        got = [float(step(b)) for _ in range(5)]
        assert got == gold
        tier = eng._offload
        assert tier.host_resident_bytes("quant_residual") > 0
        assert tier.host_resident_bytes("params") > 0
        # eval with params offloaded: restore_params pages them in
        ev = eng.eval_step(lambda mdl, bt: mdl(bt["x"]))
        v1 = np.asarray(ev(b))
        v2 = np.asarray(ev(b))
        np.testing.assert_array_equal(v1, v2)
        # and training resumes cleanly after the eval window
        float(step(b))

    def test_memledger_host_state_cross_check(self):
        eng, step, b = _flat_engine({"optimizer": True, "params": True},
                                    quant="int8", amp=True)
        for _ in range(2):
            float(step(b))
        acct = ml.account_engine(eng)
        closed = ml.closed_form_state_bytes(eng)
        assert "host_state" in acct.components
        for k, v in closed.items():
            assert acct.components.get(k) == v, (k, acct.components, closed)
        # host_state is exactly what the tier reports resident, and
        # device_bytes excludes it
        assert acct.components["host_state"] == \
            eng._offload.host_resident_bytes()
        assert acct.device_bytes == \
            acct.measured_bytes - acct.components["host_state"]

    def test_checkpoint_round_trip_under_offload(self, tmp_path):
        eng, step, b = _flat_engine({"optimizer": True,
                                     "prefetch_buckets": 1})
        for _ in range(2):
            float(step(b))
        ck = str(tmp_path / "ck")
        eng.save_checkpoint(ck)
        la = [float(step(b)) for _ in range(2)]
        eng.restore_checkpoint(ck)
        lb = [float(step(b)) for _ in range(2)]
        assert la == lb  # restore rebuilt the host tier bit-exactly
        # state is back on the host tier after the restore window
        assert eng._offload.host_resident_bytes() > 0


# ---------------------------------------------------------------------------
# the gpt13b smoke topology: mp2 x pp2 x sharding2, vpp2, AMP + int8
# ---------------------------------------------------------------------------
def _build_gpt_hybrid(offload):
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    _reset_fleet()
    paddle.seed(0)
    strategy = fleet.DistributedStrategy()
    sc = {"comm_overlap": True, "comm_buffer_size_MB": 0.001,
          "sharding_stage": 3}
    if offload is not None:
        sc["offload"] = offload
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
        "pp_configs": {"num_virtual_pipeline_stages": 2},
        "sharding_configs": sc,
        "quant_comm": {"dtype": "int8", "chunk": 64,
                       "error_feedback": True}}
    strategy.sharding_configs = {"stage": 3}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_position_embeddings=32)
    model = GPTForCausalLMPipe(cfg)
    dm = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters()))
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10)
    r = np.random.RandomState(0)
    ids = r.randint(0, 128, (8, 17))
    batch = [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])]
    return dm, opt, scaler, batch


class TestGpt13bSmokeParity:
    def test_hybrid_offload_bit_exact_and_recompile_free(self):
        dm0, opt0, sc0, b0 = _build_gpt_hybrid(None)
        gold = [float(dm0.train_batch(b0, opt0, scaler=sc0))
                for _ in range(3)]
        dm, opt, sc, b = _build_gpt_hybrid(
            {"optimizer": True, "prefetch_buckets": 2})
        got = [float(dm.train_batch(b, opt, scaler=sc))
               for _ in range(3)]
        assert got == gold  # bit-exact across mp x pp x sharding + vpp
        eng = dm._engine
        n = eng.stats.compiles
        float(dm.train_batch(b, opt, scaler=sc))
        assert eng.stats.compiles == n
        tier = eng._offload
        assert tier.host_resident_bytes("optimizer_state") > 0
        assert tier.host_resident_bytes("quant_residual") > 0
        # ledger == closed form on the hybrid mesh too
        slot_closed = sum(
            ho.host_shard_bytes(tier._get(eng, key))
            for key, _c, _b in tier._iter_slots(eng))
        assert tier.host_resident_bytes() == slot_closed
        assert (tier.transfer_bytes(direction="d2h")
                - tier.transfer_bytes(direction="h2d")) == slot_closed


# ---------------------------------------------------------------------------
# auto_tuner: the tier is priced, gated, and surfaces when needed
# ---------------------------------------------------------------------------
class TestTunerPricing:
    MODEL = {"hidden_size": 5120, "num_layers": 40,
             "vocab_size": 50304, "num_heads": 40}

    def test_memory_and_time_ordering(self):
        from paddle_tpu.distributed.auto_tuner.cost_model import (
            estimate_memory_gb, estimate_step_time)

        cfg = {"dp_degree": 1, "mp_degree": 4, "pp_degree": 2,
               "sharding_degree": 1, "sharding_stage": 3,
               "micro_batch_size": 1}
        off = dict(cfg, offload={"optimizer": True,
                                 "prefetch_buckets": 2})
        m_s3 = estimate_memory_gb(self.MODEL, cfg, 8, 1024,
                                  recompute=True)
        m_off = estimate_memory_gb(self.MODEL, off, 8, 1024,
                                   recompute=True)
        t_s3 = estimate_step_time(self.MODEL, cfg, 8, 1024)
        t_off = estimate_step_time(self.MODEL, off, 8, 1024)
        # cheaper HBM, dearer step time — never a free lunch
        assert m_off < m_s3
        assert t_off > t_s3
        # prefetch overlap halves the DMA tax vs the blocking tier
        t_block = estimate_step_time(
            self.MODEL, dict(cfg, offload={"optimizer": True,
                                           "prefetch_buckets": 0}),
            8, 1024)
        assert t_s3 < t_off < t_block

    def test_candidates_gated_on_knob(self):
        from paddle_tpu.distributed.auto_tuner.tuner import (
            default_candidates)

        base = default_candidates(8, self.MODEL, 16)
        assert not any("offload" in c for c in base)
        cands = default_candidates(8, self.MODEL, 16, tune_offload=True)
        offs = [c for c in cands if "offload" in c]
        assert offs
        # offload rides stage 3, never replaces it
        assert all(c.get("sharding_stage") == 3
                   and c["sharding_degree"] > 1 for c in offs)

    def test_over_hbm_trainable_only_with_offload(self):
        from paddle_tpu.distributed.auto_tuner.tuner import AutoTuner

        # the flagship 8-chip slice: sharding_degree 1 leaves no axis
        # to shave the fp32 optimizer image — over a 16 GB chip without
        # the host tier, comfortably under it with the tier on
        cfg = {"dp_degree": 1, "mp_degree": 4, "pp_degree": 2,
               "sharding_degree": 1, "sharding_stage": 3,
               "micro_batch_size": 1}
        off = dict(cfg, offload={"optimizer": True,
                                 "prefetch_buckets": 2})
        kw = dict(num_devices=8, global_batch=8, seq_len=1024,
                  hbm_gb=16.0, recompute=True)
        bare = AutoTuner(self.MODEL, candidates=[dict(cfg)], **kw)
        assert bare.pruned() == []
        with pytest.raises(RuntimeError, match="no config fits"):
            bare.best_by_model()
        tuned = AutoTuner(self.MODEL, candidates=[dict(cfg), off], **kw)
        best = tuned.best_by_model()
        assert best.get("offload", {}).get("optimizer") is True
        assert best["sharding_stage"] == 3
        assert best["_pred_mem_gb"] <= 16.0


# ---------------------------------------------------------------------------
# serving: cold KV pages spill to host, fault back on a prefix hit
# ---------------------------------------------------------------------------
class TestServingSpill:
    PAGE = 8

    @pytest.fixture(scope="class")
    def tiny_model(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        _reset_fleet()
        paddle.seed(11)
        return LlamaForCausalLM(llama_tiny())

    def _engine(self, model, **kw):
        from paddle_tpu.inference import (Config, ServingEngine,
                                          create_predictor)

        pred = create_predictor(
            Config().set_model(model).enable_paged_kv(
                page_size=self.PAGE))
        return ServingEngine(pred, max_batch=2, pool_pages=8,
                             prefill_chunk=16, prefix_cache=True,
                             debug_invariants=True, **kw)

    def _solo(self, model, prompt, n):
        from paddle_tpu.inference import Config, create_predictor

        pred = create_predictor(
            Config().set_model(model).enable_paged_kv(
                page_size=self.PAGE))
        return np.asarray(pred.generate(
            paddle.to_tensor(prompt[None]), max_new_tokens=n)._value)[0]

    def test_spill_fault_parity_and_ledger(self, tiny_model):
        eng = self._engine(tiny_model, host_spill_pages=8)
        prompts = [np.random.RandomState(20 + i).randint(
            1, 256, (3 * self.PAGE,)) for i in range(4)]
        done = {}
        for p in prompts:     # 4 x 3 pages through an 8-page pool
            eng.submit(p, max_new_tokens=4)
            done.update(eng.run())
        sp = eng.spill_stats()
        assert sp["spilled"] >= 1      # LRU evictions went to host
        assert sp["host_pages"] >= 1
        # payload closed form: page rows across every pool and layer
        k0 = eng.pools[0][0]
        item = np.dtype(k0.dtype).itemsize
        page_bytes = (2 * len(eng.pools) * k0.shape[1] * self.PAGE
                      * k0.shape[3] * item)
        assert sp["transfer_bytes"]["d2h"] == page_bytes * sp["spilled"]

        # resubmit the first prompt: its spilled pages fault back and
        # serve as ordinary prefix hits
        hits0 = eng.prefix_cache_stats()["hits"]
        eng.submit(prompts[0], max_new_tokens=4)
        done2 = eng.run()
        sp2 = eng.spill_stats()
        assert sp2["faulted"] >= 1
        assert sp2["transfer_bytes"]["h2d"] == \
            page_bytes * sp2["faulted"]
        assert eng.prefix_cache_stats()["hits"] > hits0

        # every output (through spill, fault, reuse) bit-matches a
        # fresh single-request predictor
        for rid, p in zip(sorted(done), prompts):
            np.testing.assert_array_equal(
                done[rid].output_ids, self._solo(tiny_model, p, 4))
        rid2 = sorted(done2)[-1]
        np.testing.assert_array_equal(
            done2[rid2].output_ids, self._solo(tiny_model, prompts[0], 4))
        eng.check_invariants()

    def test_spill_capacity_trims_oldest(self, tiny_model):
        eng = self._engine(tiny_model, host_spill_pages=2)
        for i in range(4):
            p = np.random.RandomState(40 + i).randint(
                1, 256, (3 * self.PAGE,))
            eng.submit(p, max_new_tokens=2)
            eng.run()
        sp = eng.spill_stats()
        assert sp["host_pages"] <= 2   # cap enforced
        assert sp["dropped"] >= 1      # overflow counted, not hoarded
        eng.check_invariants()

    def test_spill_requires_prefix_cache(self, tiny_model):
        from paddle_tpu.core.enforce import EnforceNotMet
        from paddle_tpu.inference import Config, ServingEngine, \
            create_predictor

        pred = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(
                page_size=self.PAGE))
        with pytest.raises(EnforceNotMet, match="prefix"):
            ServingEngine(pred, pool_pages=8, host_spill_pages=4)


# ---------------------------------------------------------------------------
# tpulint: the new host-tier paths stay clean, zero baseline
# ---------------------------------------------------------------------------
def test_tpulint_offload_surface_zero_baseline():
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths(
            [repo / "paddle_tpu" / "distributed" / "host_offload.py",
             repo / "paddle_tpu" / "inference" / "serving.py"],
            ALL_RULES, root=repo)
    finally:
        sys.path.remove(str(repo))
    assert findings == [], [str(f) for f in findings]
