"""paddle.vision.ops (reference: python/paddle/vision/ops.py) —
numpy/brute-force parity for the detection operator set."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def _iou(a, b):
    ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
    ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / max(ua, 1e-10)


def test_nms_vs_bruteforce():
    rng = np.random.RandomState(0)
    xy = rng.rand(30, 2) * 10
    wh = rng.rand(30, 2) * 4 + 0.5
    boxes = np.concatenate([xy, xy + wh], 1).astype("float32")
    scores = rng.rand(30).astype("float32")
    keep = np.asarray(V.nms(paddle.to_tensor(boxes), 0.4,
                            paddle.to_tensor(scores))._value)
    # greedy reference
    order = np.argsort(-scores, kind="stable")
    ref, alive = [], np.ones(30, bool)
    for i in order:
        if not alive[i]:
            continue
        ref.append(i)
        for j in range(30):
            if alive[j] and _iou(boxes[i], boxes[j]) > 0.4:
                alive[j] = False
    assert keep.tolist() == ref


def test_nms_categories_and_topk():
    boxes = np.array([[0, 0, 2, 2], [0.1, 0, 2, 2], [5, 5, 7, 7],
                      [5.1, 5, 7, 7]], "float32")
    scores = np.array([0.9, 0.8, 0.95, 0.1], "float32")
    cats = np.array([0, 1, 0, 1])
    keep = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                            paddle.to_tensor(scores),
                            paddle.to_tensor(cats), [0, 1], top_k=3)._value)
    # per-category nms keeps all 4 (overlaps are cross-category), sorted
    # by score -> [2, 0, 1] after top_k=3
    assert keep.tolist() == [2, 0, 1]


def test_roi_align_whole_image_box():
    """aligned=True with a full-image box and sampling_ratio=1 samples
    each bin at the exact pixel center, recovering the map."""
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = V.roi_align(paddle.to_tensor(x),
                      paddle.to_tensor(np.array([[0, 0, 4, 4]], "float32")),
                      paddle.to_tensor(np.array([1], "int32")),
                      output_size=4, sampling_ratio=1, aligned=True)
    got = np.asarray(out._value)[0, 0]
    np.testing.assert_allclose(got, x[0, 0], atol=1e-5)
    # aligned=False shifts samples by +0.5: first bin of the first row
    # averages cells (0,0),(0,1),(1,0),(1,1)
    out2 = V.roi_align(paddle.to_tensor(x),
                       paddle.to_tensor(np.array([[0, 0, 4, 4]], "float32")),
                       paddle.to_tensor(np.array([1], "int32")),
                       output_size=4, sampling_ratio=1, aligned=False)
    assert abs(float(np.asarray(out2._value)[0, 0, 0, 0]) - 2.5) < 1e-5


def test_roi_align_grad_flows():
    x = paddle.to_tensor(np.random.RandomState(0).rand(1, 2, 8, 8)
                         .astype("float32"))
    x.stop_gradient = False
    out = V.roi_align(x, paddle.to_tensor(
        np.array([[1, 1, 6, 6]], "float32")),
        paddle.to_tensor(np.array([1], "int32")), 2)
    out.sum().backward()
    g = np.asarray(x.grad._value)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_roi_pool_exact_bins():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = V.roi_pool(paddle.to_tensor(x),
                     paddle.to_tensor(np.array([[0, 0, 3, 3]], "float32")),
                     paddle.to_tensor(np.array([1], "int32")),
                     output_size=2)
    got = np.asarray(out._value)[0, 0]
    # roi spans cells 0..3 in both dims -> 2x2 max pool
    np.testing.assert_allclose(got, [[5, 7], [13, 15]])


def test_psroi_pool_channel_mapping():
    # C=4, output 2x2 -> out_c=1; channel (i*2+j) feeds bin (i, j)
    x = np.stack([np.full((4, 4), c, np.float32) for c in range(4)])[None]
    out = V.psroi_pool(paddle.to_tensor(x),
                       paddle.to_tensor(np.array([[0, 0, 4, 4]], "float32")),
                       paddle.to_tensor(np.array([1], "int32")), 2)
    got = np.asarray(out._value)[0, 0]
    np.testing.assert_allclose(got, [[0, 1], [2, 3]])


def test_box_coder_roundtrip():
    rng = np.random.RandomState(1)
    priors = np.array([[0, 0, 4, 4], [2, 2, 8, 10]], "float32")
    gt = np.array([[1, 1, 5, 6]], "float32")
    enc = V.box_coder(paddle.to_tensor(priors), None,
                      paddle.to_tensor(gt), "encode_center_size")
    assert enc.shape == [1, 2, 4]
    dec = V.box_coder(paddle.to_tensor(priors), None,
                      paddle.to_tensor(np.asarray(enc._value)[0]),
                      "decode_center_size", axis=0)
    np.testing.assert_allclose(np.asarray(dec._value),
                               np.tile(gt, (2, 1)), atol=1e-4)


def test_yolo_box_shapes_and_ranges():
    N, na, cls, H, W = 2, 3, 5, 4, 4
    x = paddle.to_tensor(np.random.RandomState(2).randn(
        N, na * (5 + cls), H, W).astype("float32"))
    img = paddle.to_tensor(np.full((N, 2), 32, "int32"))
    boxes, scores = V.yolo_box(x, img, [10, 13, 16, 30, 33, 23], cls,
                               0.01, 8)
    assert boxes.shape == [N, H * W * na, 4]
    assert scores.shape == [N, H * W * na, cls]
    b = np.asarray(boxes._value)
    assert (b >= 0).all() and (b <= 32).all()  # clipped to image


def test_prior_box():
    inp = paddle.to_tensor(np.zeros((1, 8, 2, 2), "float32"))
    img = paddle.to_tensor(np.zeros((1, 3, 16, 16), "float32"))
    boxes, var = V.prior_box(inp, img, min_sizes=[4.0], max_sizes=[8.0],
                             aspect_ratios=[2.0], clip=True)
    assert boxes.shape == [2, 2, 3, 4]  # 2 ars(+flip off)=2? min+ar+max=3
    bv = np.asarray(boxes._value)
    assert (bv >= 0).all() and (bv <= 1).all()
    assert var.shape == [2, 2, 3, 4]


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],      # small -> low level
                     [0, 0, 200, 200],    # large -> high level
                     [0, 0, 24, 24]], "float32")
    multi, restore, per_lvl = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224,
        rois_num=paddle.to_tensor(np.array([3], "int32")))
    assert len(multi) == 4
    total = sum(m.shape[0] for m in multi)
    assert total == 3
    r = np.asarray(restore._value)[:, 0]
    cat = np.concatenate([np.asarray(m._value) for m in multi])
    np.testing.assert_allclose(cat[r], rois)
    counts = np.stack([np.asarray(p._value) for p in per_lvl]).sum()
    assert counts == 3


def test_deform_conv2d_zero_offset_equals_conv():
    """With zero offsets and no mask, deformable conv IS a regular
    conv — the strongest correctness anchor for the sampler."""
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, 9, 9).astype("float32")
    w = rng.randn(6, 4, 3, 3).astype("float32")
    off = np.zeros((2, 2 * 3 * 3, 9, 9), "float32")
    out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                          paddle.to_tensor(w), stride=1, padding=1)
    import jax

    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_deform_conv2d_layer_with_mask():
    paddle.seed(0)
    layer = V.DeformConv2D(3, 8, 3, padding=1)
    x = paddle.to_tensor(np.random.RandomState(4).rand(1, 3, 6, 6)
                         .astype("float32"))
    off = paddle.to_tensor(np.random.RandomState(5).randn(1, 18, 6, 6)
                           .astype("float32") * 0.1)
    mask = paddle.to_tensor(np.ones((1, 9, 6, 6), "float32"))
    out = layer(x, off, mask)
    assert out.shape == [1, 8, 6, 6]
    assert np.isfinite(np.asarray(out._value)).all()


def test_conv_norm_activation():
    block = V.ConvNormActivation(3, 16, 3, stride=2)
    x = paddle.to_tensor(np.random.rand(1, 3, 8, 8).astype("float32"))
    block.eval()
    assert block(x).shape == [1, 16, 4, 4]


def test_read_file_decode_jpeg(tmp_path):
    try:
        from PIL import Image
    except ImportError:
        import pytest
        pytest.skip("no PIL")
    import numpy as _np
    p = str(tmp_path / "t.jpg")
    Image.fromarray(_np.zeros((8, 8, 3), _np.uint8)).save(p)
    raw = V.read_file(p)
    assert "uint8" in str(raw.dtype) and raw.shape[0] > 0
    img = V.decode_jpeg(raw)
    assert img.shape == [3, 8, 8]


def test_yolo_box_score_alignment():
    """Boxes and scores must flatten in the same (h, w, anchor) order:
    plant a single hot cell and check its box and score land on the
    same row."""
    N, na, cls, H, W = 1, 2, 3, 2, 2
    x = np.full((N, na * (5 + cls), H, W), -10.0, "float32")
    # anchor 1, cell (h=1, w=0): strong conf, class 2 hot, dx=+large
    a = 1
    base = a * (5 + cls)
    x[0, base + 4, 1, 0] = 10.0          # conf ~ 1
    x[0, base + 5 + 2, 1, 0] = 10.0      # class 2 ~ 1
    img = paddle.to_tensor(np.full((N, 2), 16, "int32"))
    boxes, scores = V.yolo_box(paddle.to_tensor(x), img,
                               [2, 2, 4, 4], cls, 0.5, 4)
    s = np.asarray(scores._value)[0]
    b = np.asarray(boxes._value)[0]
    row = int(s.max(axis=1).argmax())
    # anchor-major (anchor, h, w) flattening — the reference kernel's
    # box_idx = ((i*box_num + j)*stride + k*w + l) row order
    assert row == (a * H + 1) * W + 0
    assert s[row].argmax() == 2
    assert np.abs(b[row]).sum() > 0      # the box row is the live one
    dead = np.delete(np.arange(H * W * na), row)
    assert np.abs(b[dead]).sum() == 0    # all other rows suppressed


def test_yolo_box_iou_aware():
    N, na, cls, H, W = 1, 2, 3, 2, 2
    rng = np.random.RandomState(6)
    body = rng.randn(N, na * (5 + cls), H, W).astype("float32")
    iou_head = np.full((N, na, H, W), 5.0, "float32")  # sigmoid ~ 1
    x = np.concatenate([iou_head, body], axis=1)
    img = paddle.to_tensor(np.full((N, 2), 16, "int32"))
    b1, s1 = V.yolo_box(paddle.to_tensor(x), img, [2, 2, 4, 4], cls,
                        0.0, 4, iou_aware=True, iou_aware_factor=0.5)
    b0, s0 = V.yolo_box(paddle.to_tensor(body), img, [2, 2, 4, 4], cls,
                        0.0, 4)
    # iou ~= 1 -> conf^(0.5) * 1: scores are the sqrt-conf version
    s0v, s1v = np.asarray(s0._value), np.asarray(s1._value)
    np.testing.assert_allclose(np.asarray(b1._value),
                               np.asarray(b0._value), rtol=1e-4, atol=1e-5)
    assert (s1v >= s0v - 1e-5).all()     # sqrt raises sub-1 confidences


def test_prior_box_min_max_order():
    inp = paddle.to_tensor(np.zeros((1, 8, 1, 1), "float32"))
    img = paddle.to_tensor(np.zeros((1, 3, 16, 16), "float32"))
    b_def, _ = V.prior_box(inp, img, min_sizes=[4.0], max_sizes=[8.0],
                           aspect_ratios=[2.0])
    b_caffe, _ = V.prior_box(inp, img, min_sizes=[4.0], max_sizes=[8.0],
                             aspect_ratios=[2.0],
                             min_max_aspect_ratios_order=True)
    d = np.asarray(b_def._value)[0, 0]
    c = np.asarray(b_caffe._value)[0, 0]
    # default: [min, ar2, max]; caffe: [min, max, ar2]
    np.testing.assert_allclose(d[0], c[0])
    np.testing.assert_allclose(d[2], c[1])  # max moved to slot 1
    np.testing.assert_allclose(d[1], c[2])


def test_matrix_nms():
    # two overlapping high-score boxes + one isolated: the overlapped
    # second box decays below post_threshold, the isolated one survives
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0, 10, 10],
                       [50, 50, 60, 60]]], "float32")
    scores = np.zeros((1, 2, 3), "float32")
    scores[0, 1] = [0.9, 0.85, 0.8]
    out, idx, num = V.matrix_nms(paddle.to_tensor(boxes),
                                 paddle.to_tensor(scores),
                                 score_threshold=0.1, post_threshold=0.5,
                                 nms_top_k=-1, keep_top_k=-1,
                                 return_index=True)
    o = np.asarray(out._value)
    assert int(np.asarray(num._value)[0]) == o.shape[0]
    kept_scores = o[:, 1]
    assert 0.9 in np.round(kept_scores, 4)        # top box undecayed
    assert (kept_scores > 0.5).all()
    # the heavily-overlapped 0.85 box must have decayed away
    assert not np.isclose(kept_scores, 0.85).any()


def test_generate_proposals():
    N, A, H, W = 1, 2, 4, 4
    rng2 = np.random.RandomState(0)
    scores = rng2.rand(N, A, H, W).astype("float32")
    deltas = (rng2.rand(N, 4 * A, H, W).astype("float32") - 0.5) * 0.1
    # simple anchor grid
    anchors = np.zeros((H, W, A, 4), "float32")
    for y in range(H):
        for x in range(W):
            anchors[y, x, 0] = [x * 8, y * 8, x * 8 + 16, y * 8 + 16]
            anchors[y, x, 1] = [x * 8, y * 8, x * 8 + 24, y * 8 + 24]
    variances = np.ones_like(anchors)
    rois, roi_scores, num = V.generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas),
        paddle.to_tensor(np.array([[32, 32]], "float32")),
        paddle.to_tensor(anchors), paddle.to_tensor(variances),
        pre_nms_top_n=20, post_nms_top_n=5, nms_thresh=0.5,
        min_size=1.0, return_rois_num=True)
    r = np.asarray(rois._value)
    assert r.shape[1] == 4 and r.shape[0] <= 5
    assert int(np.asarray(num._value)[0]) == r.shape[0]
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 32).all()


def test_matrix_nms_gaussian_matches_reference_formula():
    # duplicate box under gaussian decay must suppress per the
    # kernel's exp((comp^2 - iou^2) * sigma) (MULTIPLIED by sigma)
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 10.5]]], "float32")
    scores = np.zeros((1, 2, 2), "float32")
    scores[0, 1] = [0.9, 0.85]
    out = V.matrix_nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                       0.1, post_threshold=0.0, nms_top_k=-1,
                       keep_top_k=-1, use_gaussian=True,
                       gaussian_sigma=2.0, return_rois_num=False)
    o = np.asarray(out._value)
    iou = 10.0 / 10.5
    expect = 0.85 * np.exp(-(iou ** 2) * 2.0)
    assert np.isclose(o[:, 1], expect, rtol=1e-3).any()


def test_generate_proposals_returns_real_scores():
    N, A, H, W = 1, 1, 2, 2
    scores = np.array([[[[0.9, 0.1], [0.2, 0.8]]]], "float32")
    deltas = np.zeros((N, 4, H, W), "float32")
    anchors = np.zeros((H, W, A, 4), "float32")
    for y in range(H):
        for x in range(W):
            anchors[y, x, 0] = [x * 16, y * 16, x * 16 + 15, y * 16 + 15]
    rois, roi_scores = V.generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas),
        paddle.to_tensor(np.array([[32, 32]], "float32")),
        paddle.to_tensor(anchors),
        paddle.to_tensor(np.ones_like(anchors)),
        nms_thresh=0.5, min_size=1.0)
    rs = np.asarray(roi_scores._value)
    assert rs.max() > 0.89  # real scores, not zeros
    assert (np.sort(rs)[::-1] == rs).all()  # sorted by NMS order
