"""paddle.distribution (reference: test/distribution/ — moment checks on
large samples + closed-form log_prob/entropy/KL)."""
import numpy as np
import pytest
from scipy import stats as sps

import paddle_tpu as paddle
from paddle_tpu import distribution as D

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def setup_function(_):
    paddle.seed(0)


def test_normal():
    n = D.Normal(1.0, 2.0)
    s = n.sample((20000,))
    arr = np.asarray(s._value)
    assert abs(arr.mean() - 1.0) < 0.1 and abs(arr.std() - 2.0) < 0.1
    lp = float(n.log_prob(paddle.to_tensor(0.5))._value)
    np.testing.assert_allclose(lp, sps.norm(1.0, 2.0).logpdf(0.5),
                               rtol=1e-5)
    np.testing.assert_allclose(float(n.entropy()._value),
                               sps.norm(1.0, 2.0).entropy(), rtol=1e-5)


def test_uniform_categorical_bernoulli():
    u = D.Uniform(-1.0, 3.0)
    arr = np.asarray(u.sample((10000,))._value)
    assert arr.min() >= -1 and arr.max() < 3
    np.testing.assert_allclose(float(u.log_prob(
        paddle.to_tensor(0.0))._value), -np.log(4.0), rtol=1e-6)
    assert np.isneginf(float(u.log_prob(paddle.to_tensor(5.0))._value))

    c = D.Categorical(probs=paddle.to_tensor(
        np.array([0.2, 0.3, 0.5], "float32")))
    samples = np.asarray(c.sample((20000,))._value)
    freq = np.bincount(samples, minlength=3) / 20000
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)
    np.testing.assert_allclose(float(c.entropy()._value),
                               sps.entropy([0.2, 0.3, 0.5]), rtol=1e-5)

    b = D.Bernoulli(probs=0.3)
    arr = np.asarray(b.sample((20000,))._value)
    assert abs(arr.mean() - 0.3) < 0.02
    np.testing.assert_allclose(
        float(b.log_prob(paddle.to_tensor(1.0))._value), np.log(0.3),
        rtol=1e-5)


def test_beta_dirichlet_multinomial():
    be = D.Beta(2.0, 3.0)
    arr = np.asarray(be.sample((20000,))._value)
    np.testing.assert_allclose(arr.mean(), 2 / 5, atol=0.02)
    np.testing.assert_allclose(
        float(be.log_prob(paddle.to_tensor(0.4))._value),
        sps.beta(2, 3).logpdf(0.4), rtol=1e-4)

    d = D.Dirichlet(paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32")))
    s = np.asarray(d.sample((5000,))._value)
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-4)
    np.testing.assert_allclose(s.mean(0), [1 / 6, 2 / 6, 3 / 6], atol=0.02)

    m = D.Multinomial(10, paddle.to_tensor(
        np.array([0.25, 0.75], "float32")))
    s = np.asarray(m.sample((2000,))._value)
    assert s.shape == (2000, 2) and np.all(s.sum(-1) == 10)
    np.testing.assert_allclose(s.mean(0), [2.5, 7.5], atol=0.15)
    np.testing.assert_allclose(
        float(m.log_prob(paddle.to_tensor(
            np.array([2.0, 8.0], "float32")))._value),
        sps.multinomial(10, [0.25, 0.75]).logpmf([2, 8]), rtol=1e-4)


def test_more_families_and_kl():
    e = D.Exponential(2.0)
    arr = np.asarray(e.sample((20000,))._value)
    np.testing.assert_allclose(arr.mean(), 0.5, atol=0.02)

    g = D.Gumbel(0.0, 1.0)
    assert np.isfinite(float(g.log_prob(paddle.to_tensor(0.3))._value))

    l = D.Laplace(0.0, 1.0)
    np.testing.assert_allclose(
        float(l.log_prob(paddle.to_tensor(0.5))._value),
        sps.laplace.logpdf(0.5), rtol=1e-5)

    kl = D.kl_divergence(D.Normal(0.0, 1.0), D.Normal(1.0, 2.0))
    ref = (np.log(2.0) + (1 + 1) / 8 - 0.5)
    np.testing.assert_allclose(float(kl._value), ref, rtol=1e-5)

    klc = D.kl_divergence(
        D.Categorical(probs=paddle.to_tensor(
            np.array([0.5, 0.5], "float32"))),
        D.Categorical(probs=paddle.to_tensor(
            np.array([0.9, 0.1], "float32"))))
    ref = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
    np.testing.assert_allclose(float(klc._value), ref, rtol=1e-5)

    with pytest.raises(NotImplementedError):
        D.kl_divergence(D.Normal(0.0, 1.0), D.Uniform(0.0, 1.0))
