"""paddle.signal + paddle.audio (reference: python/paddle/signal.py,
python/paddle/audio/) — stft/istft roundtrip, scipy window parity,
feature pipeline shapes."""
import numpy as np
import pytest
import scipy.signal.windows as sw

import paddle_tpu as paddle


def test_frame_overlap_add_inverse():
    x = paddle.to_tensor(np.arange(32, dtype=np.float32))
    f = paddle.signal.frame(x, frame_length=8, hop_length=8)
    assert f.shape == [8, 4]
    back = paddle.signal.overlap_add(f, hop_length=8)
    np.testing.assert_allclose(np.asarray(back._value),
                               np.arange(32, dtype=np.float32))


def test_frame_first_axis():
    x = paddle.to_tensor(np.random.rand(20, 3).astype("float32"))
    f = paddle.signal.frame(x, frame_length=4, hop_length=2, axis=0)
    assert f.shape == [9, 4, 3]


def test_stft_istft_roundtrip():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(2, 800).astype("float32"))
    S = paddle.signal.stft(x, n_fft=128, hop_length=32)
    assert S.shape == [2, 65, 26]  # centered: 1 + (800+128-128)//32
    assert "complex" in str(S.dtype)
    y = paddle.signal.istft(S, n_fft=128, hop_length=32, length=800)
    np.testing.assert_allclose(np.asarray(y._value),
                               np.asarray(x._value), atol=1e-4)


def test_stft_windowed_roundtrip_and_1d():
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.rand(600).astype("float32"))
    w = paddle.audio.functional.get_window("hann", 100)
    S = paddle.signal.stft(x, n_fft=100, hop_length=25, window=w)
    assert S.shape[0] == 51
    y = paddle.signal.istft(S, n_fft=100, hop_length=25, window=w,
                            length=600)
    np.testing.assert_allclose(np.asarray(y._value),
                               np.asarray(x._value), atol=1e-4)


def test_stft_not_onesided_normalized():
    x = paddle.to_tensor(np.random.RandomState(2).rand(1, 256)
                         .astype("float32"))
    S = paddle.signal.stft(x, n_fft=64, hop_length=16, onesided=False,
                           normalized=True)
    assert S.shape == [1, 64, 17]


@pytest.mark.parametrize("name", ["hann", "hamming", "blackman", "cosine",
                                  "bohman", "triang", "bartlett"])
def test_windows_match_scipy(name):
    ours = np.asarray(paddle.audio.functional.get_window(name, 64)._value)
    ref = sw.get_window(name, 64, fftbins=True).astype("float32")
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_gaussian_tukey_windows():
    ours = np.asarray(paddle.audio.functional.get_window(
        ("gaussian", 7.0), 33, fftbins=False)._value)
    ref = sw.gaussian(33, 7.0).astype("float32")
    np.testing.assert_allclose(ours, ref, atol=1e-5)
    ours = np.asarray(paddle.audio.functional.get_window(
        ("tukey", 0.5), 32)._value)
    ref = sw.get_window(("tukey", 0.5), 32, fftbins=True).astype("float32")
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_mel_conversions():
    hz = paddle.to_tensor(np.array([0.0, 440.0, 4000.0], dtype=np.float32))
    mel = paddle.audio.functional.hz_to_mel(hz)
    back = paddle.audio.functional.mel_to_hz(mel)
    np.testing.assert_allclose(np.asarray(back._value),
                               np.asarray(hz._value), rtol=1e-4, atol=1e-2)


def test_fbank_matrix_properties():
    fb = np.asarray(paddle.audio.functional.compute_fbank_matrix(
        16000, 512, n_mels=40)._value)
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # every mel filter has some support
    assert (fb.sum(axis=1) > 0).all()


def test_power_to_db():
    x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], dtype=np.float32))
    db = np.asarray(paddle.audio.functional.power_to_db(x)._value)
    np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-4)


def test_dct_orthonormal():
    d = np.asarray(paddle.audio.functional.create_dct(13, 40)._value)
    # ortho-normalized DCT-II columns are orthonormal
    gram = d.T @ d
    np.testing.assert_allclose(gram, np.eye(13), atol=1e-4)


def test_feature_layers_pipeline():
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 2048)
                         .astype("float32"))
    spec = paddle.audio.features.Spectrogram(n_fft=256, hop_length=128)
    s = spec(x)
    assert s.shape == [2, 129, 17]
    assert (np.asarray(s._value) >= 0).all()
    mel = paddle.audio.features.MelSpectrogram(sr=16000, n_fft=256,
                                               hop_length=128, n_mels=32)
    m = mel(x)
    assert m.shape == [2, 32, 17]
    logmel = paddle.audio.features.LogMelSpectrogram(
        sr=16000, n_fft=256, hop_length=128, n_mels=32)
    assert logmel(x).shape == [2, 32, 17]
    mfcc = paddle.audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=256,
                                      hop_length=128, n_mels=32)
    assert mfcc(x).shape == [2, 13, 17]


def test_frame_1d_axis0():
    # paddle semantics: 1-D input with axis=0 -> [num_frames, frame_length]
    f = paddle.signal.frame(
        paddle.to_tensor(np.arange(32, dtype=np.float32)), 8, 8, axis=0)
    assert f.shape == [4, 8]


def test_stft_complex_onesided_raises():
    x = paddle.to_tensor((np.random.rand(256)
                          + 1j * np.random.rand(256)).astype("complex64"))
    with pytest.raises(Exception, match="onesided"):
        paddle.signal.stft(x, n_fft=64)
    S = paddle.signal.stft(x, n_fft=64, onesided=False)  # full spectrum ok
    assert S.shape == [64, 17]


def test_istft_window_shape_validated():
    S = paddle.signal.stft(
        paddle.to_tensor(np.random.rand(512).astype("float32")), n_fft=64)
    bad = paddle.audio.functional.get_window("hann", 100)
    with pytest.raises(Exception, match="window"):
        paddle.signal.istft(S, n_fft=64, window=bad)


def test_mfcc_grad_flows():
    x = paddle.to_tensor(np.random.RandomState(0).rand(1, 1024)
                         .astype("float32"))
    x.stop_gradient = False
    m = paddle.audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=256,
                                   hop_length=128, n_mels=32)(x)
    m.sum().backward()
    g = np.asarray(x.grad._value)
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_stft_grad_flows():
    x = paddle.to_tensor(np.random.RandomState(3).rand(300)
                         .astype("float32"))
    x.stop_gradient = False
    S = paddle.signal.stft(x, n_fft=64, hop_length=16)
    loss = S.abs().sum()
    loss.backward()
    g = np.asarray(x.grad._value)
    assert g.shape == (300,) and np.isfinite(g).all() and np.abs(g).max() > 0


def test_stft_complex_window_onesided_raises():
    x = paddle.to_tensor(np.random.rand(256).astype("float32"))
    w = (np.ones(64) + 1j * np.ones(64)).astype("complex64")
    with pytest.raises(Exception, match="onesided"):
        paddle.signal.stft(x, n_fft=64, window=paddle.to_tensor(w))
