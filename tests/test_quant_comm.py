"""Quantized collectives (distributed/quant_comm.py) — codec, wire
exactness, error feedback, engine integration, checkpoint, and lint.

Under test:
- the int8/fp8 per-chunk codec: round-trip error bounds, zero chunks,
  nonfinite propagation (AMP found_inf must survive compression),
  stochastic rounding unbiasedness, the fixed chunk lattice
- quantized reduce-scatter / allreduce vs the full-precision
  collectives on the 8-vdev mesh, with ledger wire bytes pinned to the
  closed form (int8 payload + bf16 scale sidecar) EXACTLY
- knob-off byte-identity: quant_comm "none" leaves the engine's comm
  ledger byte-for-byte as before
- engine e2e (flat + pp seam scan): loss tracks fp32, zero steady-state
  recompiles, residual state carried, gauges published
- the convergence-parity gate: 200 deterministic steps int8+EF vs
  fp32 within a pinned tolerance AND the same test detects the
  divergence when error feedback is off (a harness that cannot see the
  failure it guards is no gate)
- crash/restore: the EF residual joins the checkpoint commit unit —
  save+restore+continue == straight run bit-exactly with the knob on
- collective-matmul rings: quantized ag_matmul/matmul_rs/
  matmul_allreduce fwd+bwd parity within quantization tolerance, int8
  ppermute payloads on the ledger
- auto_tuner: quant_comm in the search space, residual HBM in the
  analytic memory model
- tpulint: quant_comm pinned at zero baseline entries and
  vjp-ledger-symmetry green over the quantized rings
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import collective as C
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import quant_comm as qc
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.observability import commledger as cl

try:
    from jax.experimental.shard_map import shard_map as _sm

    def _shard_map(f, mesh, in_specs, out_specs):
        return _sm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)
except Exception:  # pragma: no cover - newer jax
    def _shard_map(f, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)


INT8 = qc.make_config({"dtype": "int8", "chunk": 16})
FP8 = qc.make_config({"dtype": "fp8", "chunk": 16})


def _reset_fleet():
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------
class TestCodec:
    def test_roundtrip_error_bound(self):
        rng = np.random.RandomState(0)
        # wide per-chunk dynamic range: scales must adapt per chunk
        x = rng.randn(4, 64).astype(np.float32) * \
            np.array([1e3, 1.0, 1e-2, 0.0])[:, None]
        q, s = qc.encode(jnp.asarray(x), INT8)
        assert q.dtype == jnp.int8 and s.dtype == jnp.bfloat16
        assert q.shape == x.shape and s.shape == (4, 64 // 16)
        d = np.asarray(qc.decode(q, s, INT8))
        # error per element <= half a quantization step of ITS chunk
        # (bf16 scale rounding adds ~2^-8 relative slop)
        amax = np.abs(x).reshape(4, 4, 16).max(-1)
        bound = (amax / 127.0) * 0.5 * 1.02 + 1e-12
        err = np.abs(d - x).reshape(4, 4, 16).max(-1)
        assert (err <= bound + amax * 2 ** -7).all()

    def test_zero_chunk_exact_and_fp8(self):
        x = jnp.zeros((32,), jnp.float32)
        q, s = qc.encode(x, INT8)
        assert np.asarray(qc.decode(q, s, INT8)).max() == 0.0
        xr = jnp.asarray(np.random.RandomState(1).randn(64)
                         .astype(np.float32))
        q8, s8 = qc.encode(xr, FP8)
        assert q8.dtype == jnp.float8_e4m3fn
        d8 = np.asarray(qc.decode(q8, s8, FP8))
        assert np.abs(d8 - np.asarray(xr)).max() < 0.1

    def test_nonfinite_propagates(self):
        """A chunk holding inf must decode nonfinite — AMP's found_inf
        check runs on the SYNCED grads, so compression that silently
        finite-ized an overflow would break the scaler protocol."""
        for bad in (np.inf, np.nan):
            x = np.ones(16, np.float32)
            x[3] = bad
            q, s = qc.encode(jnp.asarray(x), INT8)
            d = np.asarray(qc.decode(q, s, INT8))
            assert not np.isfinite(d).all()

    def test_stochastic_rounding_unbiased(self):
        x = jnp.full((2048,), 0.3, jnp.float32)
        cfg = qc.make_config({"dtype": "int8", "chunk": 2048,
                              "stochastic_rounding": True})
        key = jax.random.key(0)
        q, s = qc.encode(x, cfg, key)
        d = np.asarray(qc.decode(q, s, cfg))
        vals = set(np.unique(np.asarray(q)).tolist())
        assert len(vals) == 2          # floor and floor+1 both hit
        assert abs(d.mean() - 0.3) < 0.005   # unbiased in expectation
        # same key -> same rounding (compile-stable determinism)
        q2, _ = qc.encode(x, cfg, key)
        assert (np.asarray(q) == np.asarray(q2)).all()

    def test_padding_lattice(self):
        assert qc.padded_len(40, 16) == 48
        assert qc.payload_wire_bytes(40, INT8) == 48 + 3 * 2
        cfg = qc.make_config({"dtype": "int8", "chunk": 64})
        assert qc.reduce_scatter_wire_bytes(4 * 40, 4, cfg) == \
            3 * (64 + 1 * 2)

    def test_make_config_validates(self):
        with pytest.raises(Exception):
            qc.make_config({"dtype": "int4"})
        with pytest.raises(Exception):
            qc.make_config({"nope": 1})
        with pytest.raises(Exception):
            qc.make_config({"chunk": 0})
        assert not qc.make_config(None).enabled
        assert qc.make_config({"dtype": "fp8"}).qmax == 448.0


# ---------------------------------------------------------------------------
# quantized collectives: parity + exact ledger bytes
# ---------------------------------------------------------------------------
class TestQuantizedCollectives:
    def _mesh(self, n=4):
        return Mesh(np.array(jax.devices()[:n]), ("s",))

    def test_reduce_scatter_parity_and_bytes(self):
        mesh = self._mesh(4)
        N = 4 * 40                      # L=40 pads to 48 on chunk 16
        v = np.random.RandomState(0).randn(4 * N).astype(np.float32)

        def f(x):
            with C.spmd_region():
                sh, deq = qc.quantized_reduce_scatter(
                    x.reshape(-1), ("s",), INT8)
                return sh, x.reshape(-1) - deq

        fn = jax.jit(_shard_map(f, mesh, P("s"), (P("s"), P("s"))))
        with cl.capture() as led:
            sh, resid = fn(jnp.asarray(v))
        ref = v.reshape(4, N).sum(0)
        scale_bound = np.abs(v).max() / 127.0 * 4 * 1.1
        assert np.abs(np.asarray(sh) - ref).max() <= scale_bound
        # wire bytes == the closed form EXACTLY (int8 + bf16 scales)
        assert led.bytes_for(op="all_to_all") == \
            qc.reduce_scatter_wire_bytes(N, 4, INT8)
        # records carry the quant stamps
        recs = [r for r in led.records if r.payload_ratio != 1.0]
        assert recs and {r.wire_dtype for r in recs} == \
            {"int8", "bfloat16"}
        # residual == v - decode(encode(v)) locally: adding it back to
        # the dequantized image reconstructs v exactly
        assert np.asarray(resid).shape == (4 * N,)

    def test_allreduce_parity_bytes_and_mean(self):
        mesh = self._mesh(4)
        N = 100                         # not divisible by p: pads
        v = np.random.RandomState(1).randn(4 * N).astype(np.float32)

        def f(x):
            with C.spmd_region():
                full, _ = qc.quantized_allreduce(
                    x.reshape(-1), ("s",), INT8, mean=True)
                return full

        fn = jax.jit(_shard_map(f, mesh, P("s"), P("s")))
        with cl.capture() as led:
            out = fn(jnp.asarray(v))
        ref = v.reshape(4, N).mean(0)
        got = np.asarray(out).reshape(4, N)
        bound = np.abs(v).max() / 127.0 * 2.2
        for r in range(4):              # every rank converged near ref
            assert np.abs(got[r] - ref).max() <= bound
        assert led.bytes_for() == qc.allreduce_wire_bytes(N, 4, INT8)
        ratios = led.quant_ratios()
        assert set(ratios) == {"s"} and 0 < ratios["s"] < 0.5

    def test_quant_ratio_math(self):
        """quant_ratios folds compressed records back to their
        uncompressed-equivalent bytes through the payload_ratio
        stamp."""
        led = cl.CommLedger()
        led.add(cl.CommRecord(op="all_to_all", axes=("s",), axis="s",
                              shape=(4, 64), dtype="int8", p=4,
                              payload_bytes=256, wire_bytes=192.0,
                              wire_dtype="int8", payload_ratio=0.25))
        led.add(cl.CommRecord(op="psum", axes=("s",), axis="s",
                              shape=(8,), dtype="float32", p=4,
                              payload_bytes=32, wire_bytes=48.0))
        r = led.quant_ratios()["s"]
        assert abs(r - (192.0 + 48.0) / (768.0 + 48.0)) < 1e-9

    def test_param_gather_own_shard_exact(self):
        mesh = self._mesh(4)
        shard = np.random.RandomState(2).randn(4, 8, 3) \
            .astype(np.float32)

        def f(x):
            with C.spmd_region():
                full = qc.quantized_param_gather(x, ("s",), 0, INT8)
                idx = jax.lax.axis_index("s")
                own = jax.lax.dynamic_slice_in_dim(full, idx * 8, 8,
                                                   axis=0)
                return own[None]

        fn = jax.jit(_shard_map(f, mesh, P("s"), P("s")))
        own = np.asarray(fn(jnp.asarray(shard.reshape(32, 3))))
        # every rank's own block survives the quantized gather EXACTLY
        assert (own == shard).all()


# ---------------------------------------------------------------------------
# engine integration (flat ZeRO-2 + knob-off byte identity)
# ---------------------------------------------------------------------------
def _mlp():
    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(16, 32)
            self.fc2 = paddle.nn.Linear(32, 16)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    return MLP()


def _flat_engine(quant_dtype="none", steps=6, error_feedback=True,
                 chunk=32, lr=0.01, seed=3, stochastic=False):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 2, "sharding_degree": 4,
        "sharding_configs": {"comm_overlap": True,
                             "comm_buffer_size_MB": 0.0005},
        "quant_comm": {"dtype": quant_dtype, "chunk": chunk,
                       "error_feedback": error_feedback,
                       "stochastic_rounding": stochastic}}
    _reset_fleet()
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    model = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    model, opt, _ = dist.group_sharded_parallel(model, opt, "os_g")
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: paddle.mean(
        (m(b["x"]) - b["y"]) ** 2))
    np.random.seed(0)
    x = np.random.randn(8, 16).astype("float32")
    y = np.random.randn(8, 16).astype("float32")
    batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}
    losses = [float(step(batch)) for _ in range(steps)]
    eng._flush_pending_scalars()
    return eng, losses, batch, step


class TestEngineFlat:
    def test_quant_tracks_fp32_zero_recompiles(self):
        eng_off, l_off, _, _ = _flat_engine("none")
        eng_on, l_on, _, _ = _flat_engine("int8")
        assert eng_on.stats.compiles == 1
        assert eng_on.stats.cache_hits == len(l_on) - 1
        gap = max(abs(a - b) for a, b in zip(l_off, l_on))
        assert gap < 5e-3
        # residual state exists and is finite
        assert eng_on._quant_residuals
        for v in eng_on._quant_residuals.values():
            assert np.isfinite(np.asarray(v)).all()

    def test_knob_off_ledger_byte_identical(self):
        """dtype "none" must leave the wire byte-for-byte as today."""
        eng, _, _, _ = _flat_engine("none")
        led = eng.comm_ledger()
        assert not led.quant_ratios()
        for r in led.records:
            assert r.payload_ratio == 1.0
            assert "int8" not in r.dtype
        # the exact closed forms the PR-8 tests pin still hold: every
        # record's wire bytes match the op's ring formula
        for r in led.records:
            assert r.wire_bytes == cl.wire_bytes(r.op, r.payload_bytes,
                                                 r.p)

    def test_quant_rs_bytes_closed_form(self):
        """The bucketed quantized reduce-scatter's a2a bytes on the
        sharding axis equal ceil(int8 payload + bf16 scales) exactly,
        summed over buckets (trips included)."""
        eng, _, _, _ = _flat_engine("int8")
        led = eng.comm_ledger()
        plan = eng._bucket_plan
        cfg = eng._quant_cfg
        expect = 0.0
        for g in plan.groups:
            if g.kind != "rs":
                continue
            for b in g.buckets:
                n = sum(int(np.prod(e.shape)) for e in b)
                expect += qc.reduce_scatter_wire_bytes(n, g.n, cfg)
        assert led.bytes_for(axis="sharding", op="all_to_all") == expect

    def test_gauges_published(self):
        _flat_engine("int8")
        from paddle_tpu.observability import get_registry

        snap = get_registry().snapshot()["metrics"]
        qr = snap["paddle_tpu_comm_quant_ratio"]["series"]
        assert any(s["labels"].get("axis") == "sharding" and
                   0 < s["value"] < 1 for s in qr)
        qn = snap["paddle_tpu_train_quant_residual_norm"]["series"]
        assert qn and qn[0]["value"] >= 0.0

    def test_stochastic_rounding_runs_compile_stable(self):
        eng, losses, _, _ = _flat_engine("int8", stochastic=True)
        assert eng.stats.compiles == 1
        assert all(np.isfinite(losses))

    def test_fp8_path(self):
        eng, losses, _, _ = _flat_engine("fp8")
        assert all(np.isfinite(losses))
        led = eng.comm_ledger()
        assert any("float8" in r.wire_dtype for r in led.records)


# ---------------------------------------------------------------------------
# convergence-parity gate (deterministic horizon)
# ---------------------------------------------------------------------------
class TestConvergenceGate:
    """int8 + error feedback must track the fp32 sync over a 300-step
    deterministic horizon; the SAME harness with error feedback off
    must show measurable divergence — proving the gate can detect the
    failure it guards.

    The task plants a ~200x dynamic-range spread inside ONE scale
    chunk (chunk >= bucket payload): two loud-but-irrelevant input
    features pin the int8 scale, so the target-relevant quiet
    gradients sit below one quantization step. Without error feedback
    they round to zero most steps and the model visibly stalls; with
    the residual carrying what each step failed to transmit, the
    quiet coordinates still receive their time-averaged gradient and
    the loss tracks the fp32 run. Everything is deterministic: fixed
    seeds, fixed batch, single XLA CPU backend — the tolerances are
    pins, not statistics."""

    def _run(self, dtype, error_feedback=True, steps=300):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 2, "sharding_degree": 4,
            "sharding_configs": {"comm_overlap": True,
                                 "comm_buffer_size_MB": 0.0005},
            # one scale chunk per bucket — the worst-case lattice
            "quant_comm": {"dtype": dtype, "chunk": 65536,
                           "error_feedback": error_feedback}}
        _reset_fleet()
        hcg = fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(7)
        model = _mlp()
        opt = paddle.optimizer.Adam(learning_rate=0.03,
                                    parameters=model.parameters())
        model, opt, _ = dist.group_sharded_parallel(model, opt, "os_g")
        eng = ParallelEngine(model, opt, hcg.mesh)
        step = eng.train_step(lambda m, b: paddle.mean(
            (m(b["x"]) - b["y"]) ** 2))
        rng = np.random.RandomState(0)
        x = rng.randn(64, 16).astype("float32")
        x[:, :2] *= 200.0           # loud, target-irrelevant
        W = rng.randn(14, 16).astype("float32")
        y = (x[:, 2:] @ W * 0.1).astype("float32")
        batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}
        losses = [float(step(batch)) for _ in range(steps)]
        return float(np.mean(losses[-20:]))

    @pytest.mark.slow  # ~25s 300-step convergence horizon; 1-cpu tier-1 budget
    def test_int8_ef_matches_fp32_and_no_ef_diverges(self):
        ref = self._run("none")
        ef = self._run("int8", True)
        no_ef = self._run("int8", False)
        # pinned tolerance: EF lands within 4x of the fp32 tail loss
        # (observed ~2.3x; deterministic, so this is a pin with margin)
        assert ef <= 4.0 * ref, (ref, ef, no_ef)
        # and the harness DETECTS the EF-off failure: the no-EF tail
        # is at least 2x the EF tail (observed ~3.6x) — the quiet
        # coordinates demonstrably stop training
        assert no_ef >= 2.0 * ef, (ref, ef, no_ef)


# ---------------------------------------------------------------------------
# checkpoint: the EF residual is part of the commit unit
# ---------------------------------------------------------------------------
class TestCheckpointResidual:
    def test_save_restore_continue_bit_exact(self, tmp_path):
        # straight run: 6 steps
        _, straight, _, _ = _flat_engine("int8", steps=6)
        # interrupted run: 3 steps, save, restore into a FRESH engine,
        # 3 more — must equal the straight run bit-exactly, which
        # requires the residual to round-trip
        eng, first, batch, step = _flat_engine("int8", steps=3)
        path = str(tmp_path / "ck")
        eng.save_checkpoint(path)
        saved_res = {k: np.asarray(v)
                     for k, v in eng._quant_residuals.items()}
        assert saved_res
        eng2, _, batch2, step2 = _flat_engine("int8", steps=1)
        meta = eng2.restore_checkpoint(path)
        assert sorted(meta["quant_residual_keys"]) == \
            sorted(saved_res)
        for k, v in eng2._quant_residuals.items():
            assert (np.asarray(v) == saved_res[k]).all()
        rest = [float(step2(batch2)) for _ in range(3)]
        assert rest == straight[3:]

    def test_dropping_residual_changes_trajectory(self, tmp_path):
        """The negative control: a resume that zeroes the residual is
        NOT bit-exact — i.e. the state actually matters and the test
        above could catch a loader that silently dropped it."""
        _, straight, _, _ = _flat_engine("int8", steps=6)
        eng, _, _, _ = _flat_engine("int8", steps=3)
        path = str(tmp_path / "ck")
        eng.save_checkpoint(path)
        eng2, _, batch2, step2 = _flat_engine("int8", steps=1)
        eng2.restore_checkpoint(path)
        # sabotage: zero the residuals post-restore
        eng2._quant_residuals = {
            k: jnp.zeros_like(v)
            for k, v in eng2._quant_residuals.items()}
        rest = [float(step2(batch2)) for _ in range(3)]
        assert rest != straight[3:]


def _gpt_pipe(quant_dtype="int8", chunk=64):
    """The gpt13b smoke topology (mp2 x pp2 x sharding2, stage 2,
    comm_overlap, rings on) with quant_comm — the bench flagship
    shape, tiny."""
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_position_embeddings=32)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
        "mp_configs": {"mp_async_allreduce": True},
        "sharding_configs": {"comm_overlap": True,
                             "comm_buffer_size_MB": 0.001},
        "quant_comm": {"dtype": quant_dtype, "chunk": chunk}}
    strategy.sharding_configs = {"stage": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    _reset_fleet()
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    model = GPTForCausalLMPipe(cfg)
    dm = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(
        paddle.optimizer.AdamW(learning_rate=1e-3,
                               parameters=model.parameters()))
    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (8, 17))
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])
    return dm, opt, x, y


class TestCheckpointResidualGptTopology:
    @pytest.mark.slow
    def test_5_crash_5_equals_10_straight(self, tmp_path):
        """The flagship-topology acceptance: 5 steps + save + restore
        into a fresh engine + 5 more == 10 straight, bit-exactly, with
        quant_comm on — which holds ONLY if the seam-scan EF residuals
        (and the sharded stage-2 param shards the quantized gather
        stores) round-trip through the checkpoint."""
        dm, opt, x, y = _gpt_pipe()
        straight = [float(dm.train_batch([x, y], opt))
                    for _ in range(10)]
        dm1, opt1, x1, y1 = _gpt_pipe()
        first = [float(dm1.train_batch([x1, y1], opt1))
                 for _ in range(5)]
        assert first == straight[:5]
        path = str(tmp_path / "ck")
        dm1.save_checkpoint(path)
        assert dm1._engine._quant_residuals     # seam residuals exist
        dm2, opt2, x2, y2 = _gpt_pipe()
        dm2.restore_checkpoint(path, optimizer=opt2)
        rest = [float(dm2.train_batch([x2, y2], opt2))
                for _ in range(5)]
        assert rest == straight[5:]


# ---------------------------------------------------------------------------
# pp seam scan (pipelined stacked params)
# ---------------------------------------------------------------------------
class TestSeamScan:
    @pytest.mark.slow
    def test_pipelined_quant_seam(self):
        from paddle_tpu.models import GPTForCausalLMPipe
        from paddle_tpu.models.gpt import GPTConfig

        def run(dtype):
            cfg = GPTConfig(vocab_size=128, hidden_size=32,
                            num_layers=4, num_heads=4,
                            max_position_embeddings=32)
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {
                "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                "sharding_degree": 2,
                "mp_configs": {"mp_async_allreduce": True},
                "sharding_configs": {"comm_overlap": True,
                                     "comm_buffer_size_MB": 0.001},
                "quant_comm": {"dtype": dtype, "chunk": 64}}
            strategy.sharding_configs = {"stage": 2}
            strategy.pipeline_configs = {"accumulate_steps": 2,
                                         "micro_batch_size": 2}
            _reset_fleet()
            fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            model = GPTForCausalLMPipe(cfg)
            dm = fleet.distributed_model(model)
            opt = fleet.distributed_optimizer(
                paddle.optimizer.AdamW(learning_rate=1e-4,
                                       parameters=model.parameters()))
            r = np.random.RandomState(0)
            ids = r.randint(0, cfg.vocab_size, (8, 17))
            x = paddle.to_tensor(ids[:, :-1])
            y = paddle.to_tensor(ids[:, 1:])
            losses = [float(dm.train_batch([x, y], opt))]
            cw = dm._engine.stats.compiles
            for _ in range(2):
                losses.append(float(dm.train_batch([x, y], opt)))
            return (losses, dm._engine,
                    dm._engine.stats.compiles - cw)

        l_off, _, _ = run("none")
        l_on, eng, rc = run("int8")
        assert rc == 0
        assert max(abs(a - b) for a, b in zip(l_off, l_on)) < 5e-2
        # seam residuals ride the scan: [nb, tick elems] buffers exist
        assert any(v.ndim == 2 for v in eng._quant_residuals.values())
        led = eng.comm_ledger()
        # scan-tick a2a records carry trips=nb
        assert any(r.trips > 1 and r.payload_ratio != 1.0
                   for r in led.records)


# ---------------------------------------------------------------------------
# quantized collective-matmul rings
# ---------------------------------------------------------------------------
class TestQuantRings:
    def _mesh(self, n=4):
        return Mesh(np.array(jax.devices()[:n]), ("mp",))

    def _with_ring_quant(self):
        return qc.override({"dtype": "int8", "chunk": 32,
                            "mp_rings": True})

    def test_ag_matmul_fwd_bwd_parity(self):
        from paddle_tpu.distributed import collective_matmul as cm

        mesh = self._mesh(4)
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype(np.float32)    # 4 ranks x 4 rows
        w = rng.randn(8, 8).astype(np.float32)

        def gold(xs, ws):
            def f(xl, wl):
                with C.spmd_region():
                    full = jax.lax.all_gather(xl, "mp", axis=0,
                                              tiled=True)
                    return jnp.sum(full @ wl)
            return jax.jit(_shard_map(f, mesh, (P("mp"), P()), P()))(
                xs, ws)

        def fused(xs, ws):
            def f(xl, wl):
                with C.spmd_region():
                    return jnp.sum(cm.ag_matmul(xl, wl, ("mp",), 0))
            return jax.jit(_shard_map(f, mesh, (P("mp"), P()), P()))(
                xs, ws)

        ref, (rgx, rgw) = jax.value_and_grad(gold, (0, 1))(
            jnp.asarray(x), jnp.asarray(w))
        with self._with_ring_quant():
            with cl.capture() as led:
                got, (ggx, ggw) = jax.value_and_grad(fused, (0, 1))(
                    jnp.asarray(x), jnp.asarray(w))
        scale = max(np.abs(np.asarray(ref)), 1.0)
        assert abs(float(got) - float(ref)) / scale < 0.05
        assert np.abs(np.asarray(ggx) - np.asarray(rgx)).max() / \
            max(np.abs(np.asarray(rgx)).max(), 1.0) < 0.1
        assert np.abs(np.asarray(ggw) - np.asarray(rgw)).max() / \
            max(np.abs(np.asarray(rgw)).max(), 1.0) < 0.1
        # the wire carried int8 + bf16 ppermutes, stamped
        pp = [r for r in led.records if r.op == "ppermute"]
        assert pp and all(r.payload_ratio != 1.0 for r in pp)
        assert {r.wire_dtype for r in pp} == {"int8", "bfloat16"}

    def test_matmul_allreduce_parity_and_gather_bytes(self):
        from paddle_tpu.distributed import collective_matmul as cm

        _reset_fleet()
        mesh = self._mesh(4)
        rng = np.random.RandomState(1)
        x = rng.randn(8, 16).astype(np.float32)    # k sharded: [8, 4]
        w = rng.randn(16, 8).astype(np.float32)    # [k_local 4, 8] x 4

        def gold(xl, wl):
            with C.spmd_region():
                return C.t_psum(xl @ wl, ("mp",))

        def fused(xl, wl):
            with C.spmd_region():
                return cm.matmul_allreduce(xl, wl, ("mp",), 0)

        gf = jax.jit(_shard_map(gold, mesh, (P(None, "mp"), P("mp")),
                                P()))
        ref = np.asarray(gf(jnp.asarray(x), jnp.asarray(w)))
        with self._with_ring_quant():
            ff = jax.jit(_shard_map(fused, mesh,
                                    (P(None, "mp"), P("mp")), P()))
            with cl.capture() as led:
                got = np.asarray(ff(jnp.asarray(x), jnp.asarray(w)))
        assert np.abs(got - ref).max() / max(np.abs(ref).max(), 1.0) \
            < 0.05
        ag = [r for r in led.records if r.op == "all_gather"]
        assert ag and all(r.payload_ratio != 1.0 for r in ag)

    def test_knob_off_rings_untouched(self):
        from paddle_tpu.distributed import collective_matmul as cm

        _reset_fleet()
        mesh = self._mesh(4)
        x = np.random.RandomState(2).randn(16, 8).astype(np.float32)
        w = np.random.RandomState(3).randn(8, 8).astype(np.float32)

        def f(xl, wl):
            with C.spmd_region():
                return cm.ag_matmul(xl, wl, ("mp",), 0)

        fn = jax.jit(_shard_map(f, mesh, (P("mp"), P()), P("mp")))
        with cl.capture() as led:
            fn(jnp.asarray(x), jnp.asarray(w))
        assert all(r.payload_ratio == 1.0 for r in led.records)
        assert all(r.dtype == "float32" for r in led.records
                   if r.op == "ppermute")


# ---------------------------------------------------------------------------
# auto_tuner + memory model
# ---------------------------------------------------------------------------
class TestTunerAndMemory:
    def test_search_space_grows_quant_variants(self):
        from paddle_tpu.distributed.auto_tuner import default_candidates

        model = {"hidden_size": 64, "num_layers": 4, "num_heads": 4,
                 "vocab_size": 128}
        base = default_candidates(8, model, 32)
        quant = default_candidates(8, model, 32, tune_quant_comm=True)
        q_cfgs = [c for c in quant if "quant_comm" in c]
        assert len(quant) > len(base) and q_cfgs
        assert all(c["quant_comm"]["dtype"] == "int8" for c in q_cfgs)

    def test_memory_model_prices_residual(self):
        from paddle_tpu.distributed.auto_tuner import estimate_memory_gb

        model = {"hidden_size": 512, "num_layers": 8,
                 "vocab_size": 1024}
        cfg = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
               "sharding_degree": 2}
        off = estimate_memory_gb(model, cfg, 32, 128)
        on = estimate_memory_gb(
            model, dict(cfg, quant_comm={"dtype": "int8"}), 32, 128)
        # the delta is exactly one local fp32 grad image
        P_local = (1024 * 512 + 8 * (4 * 512 * 512 + 2 * 512 * 2048)
                   + 2 * 512) / 2
        assert abs((on - off) * 1e9 - P_local * 4) < 1e3

    def test_step_time_model_discounts_quant_comm(self):
        from paddle_tpu.distributed.auto_tuner import estimate_step_time

        model = {"hidden_size": 512, "num_layers": 8,
                 "vocab_size": 1024}
        cfg = {"dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
               "sharding_degree": 1}
        off = estimate_step_time(model, cfg, 32, 128)
        on = estimate_step_time(
            model, dict(cfg, quant_comm={"dtype": "int8"}), 32, 128)
        assert on < off

    def test_measured_accounting_reports_residual(self):
        from paddle_tpu.observability import memledger as ml

        eng, _, _, _ = _flat_engine("int8")
        acct = ml.account_engine(eng, batch_tokens=8)
        expect = sum(
            int(np.prod(v.shape)) * 4 // 8    # 8 vdevs share dim 0
            for v in eng._quant_residuals.values())
        assert acct.components.get("quant_residual") == expect


# ---------------------------------------------------------------------------
# static analysis
# ---------------------------------------------------------------------------
class TestLint:
    def test_quant_comm_zero_baseline(self):
        """quant_comm.py ships lint-clean: zero baseline entries."""
        base = json.loads(
            (Path(__file__).parent.parent / "tools" / "tpulint" /
             "baseline.json").read_text())
        for e in base.get("findings", []):
            assert "quant_comm" not in str(e), e

    def test_tree_clean_incl_vjp_symmetry(self):
        """Whole-tree tpulint exit 0 — in particular the quantized
        rings keep the mirrored-ring / psum-identity pairings
        recognizable (the quant_comm wrappers map to their LOGICAL
        collective kinds in the shim table)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.tpulint", "paddle_tpu/",
             "--select", "vjp-ledger-symmetry,raw-collective"],
            cwd=str(Path(__file__).parent.parent),
            capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_quantized_wrapper_kinds_resolve(self):
        """Fixture: a custom_vjp whose fwd psums through
        quantized_allreduce still reads as the Megatron psum/identity
        pairing."""
        from tools.tpulint.project import COLLECTIVE_SHIMS

        assert COLLECTIVE_SHIMS["quantized_allreduce"] == "psum"
        assert COLLECTIVE_SHIMS["quantized_reduce_scatter"] == \
            "reduce_scatter"
        assert COLLECTIVE_SHIMS["quantized_param_gather"] == \
            "all_gather"
