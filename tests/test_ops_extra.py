"""Long-tail op surface + grid_sample/affine_grid/ctc_loss (reference:
paddle/phi/api/yaml ops without previous counterparts; torch CPU used
as the numeric oracle where available)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

rng = np.random.RandomState(0)


def _t(x):
    return paddle.to_tensor(x)


def _np(t):
    return np.asarray(t._value)


class TestIndexing:
    def test_index_add_put(self):
        x = np.zeros((5, 3), "float32")
        v = np.ones((2, 3), "float32")
        out = _np(paddle.index_add(_t(x), _t(np.array([1, 3])), 0, _t(v)))
        assert out[1].sum() == 3 and out[3].sum() == 3 and out[0].sum() == 0
        out = _np(paddle.index_put(_t(x), (_t(np.array([0, 2])),),
                                   _t(np.full((2, 3), 7.0, "float32"))))
        assert (out[0] == 7).all() and (out[2] == 7).all()

    def test_masked_select(self):
        x = np.arange(6, dtype="float32").reshape(2, 3)
        out = _np(paddle.masked_select(_t(x), _t(x > 2)))
        np.testing.assert_allclose(out, [3, 4, 5])

    def test_fill_diagonal(self):
        x = np.zeros((3, 4), "float32")
        out = _np(paddle.fill_diagonal(_t(x), 5.0))
        np.testing.assert_allclose(np.diag(out), [5, 5, 5])
        y = np.array([1.0, 2.0, 3.0], "float32")
        out = _np(paddle.fill_diagonal_tensor(_t(x), _t(y)))
        np.testing.assert_allclose(np.diag(out), y)

    def test_renorm_matches_torch(self):
        x = rng.randn(4, 5, 6).astype("float32")
        out = _np(paddle.renorm(_t(x), 2.0, 1, 1.0))
        ref = torch.renorm(torch.tensor(x).transpose(0, 1), 2, 0, 1.0) \
            .transpose(0, 1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_misc_small(self):
        x = rng.randn(3, 4).astype("float32")
        np.testing.assert_allclose(_np(paddle.mv(_t(x), _t(x[0]))),
                                   x @ x[0], rtol=1e-5)
        assert int(_np(paddle.numel(_t(x)))) == 12
        np.testing.assert_allclose(_np(paddle.ops.extra.shape(_t(x))),
                                   [3, 4])
        assert abs(float(paddle.dist(_t(x), _t(x * 0), 2))
                   - np.linalg.norm(x)) < 1e-4
        out = paddle.unbind(_t(x), axis=0)
        assert len(out) == 3
        a, b = paddle.broadcast_tensors([_t(np.ones((1, 4), "float32")),
                                         _t(np.ones((3, 1), "float32"))])
        assert a.shape == [3, 4] and b.shape == [3, 4]

    def test_complex_views(self):
        x = rng.randn(3, 2).astype("float32")
        c = paddle.as_complex(_t(x))
        assert "complex" in str(c.dtype)
        back = _np(paddle.as_real(c))
        np.testing.assert_allclose(back, x, rtol=1e-6)
        c2 = paddle.ops.extra.complex(_t(x[:, 0]), _t(x[:, 1]))
        np.testing.assert_allclose(_np(c2), x[:, 0] + 1j * x[:, 1])

    def test_tri_indices_logspace(self):
        r, c = _np(paddle.tril_indices(4, 4))
        rr, cc = np.tril_indices(4)
        np.testing.assert_allclose(r, rr)
        np.testing.assert_allclose(c, cc)
        ls = _np(paddle.logspace(0, 3, 4))
        np.testing.assert_allclose(ls, [1, 10, 100, 1000], rtol=1e-4)

    def test_unique_consecutive(self):
        x = np.array([1, 1, 2, 2, 2, 3, 1, 1])
        out, inv, cnt = paddle.unique_consecutive(
            _t(x), return_inverse=True, return_counts=True)
        np.testing.assert_allclose(_np(out), [1, 2, 3, 1])
        np.testing.assert_allclose(_np(cnt), [2, 3, 1, 2])
        np.testing.assert_allclose(_np(out)[_np(inv)], x)

    def test_bit_shifts(self):
        x = np.array([1, 2, 4], "int32")
        np.testing.assert_allclose(
            _np(paddle.bitwise_left_shift(_t(x), _t(np.array([1, 1, 1],
                                                            "int32")))),
            [2, 4, 8])
        np.testing.assert_allclose(
            _np(paddle.bitwise_right_shift(_t(x), _t(np.array([1, 1, 1],
                                                             "int32")))),
            [0, 1, 2])

    def test_cummin(self):
        x = np.array([3.0, 1.0, 2.0, 0.5], "float32")
        np.testing.assert_allclose(_np(paddle.cummin(_t(x))),
                                   [3, 1, 1, 0.5])


class TestLayoutOps:
    def test_channel_shuffle_roundtrip(self):
        x = rng.randn(2, 6, 4, 4).astype("float32")
        s = paddle.channel_shuffle(_t(x), 2)
        back = _np(paddle.channel_shuffle(s, 3))
        np.testing.assert_allclose(back, x)

    def test_pixel_unshuffle_inverts_shuffle(self):
        x = rng.randn(2, 4, 3, 3).astype("float32")
        up = F.pixel_shuffle(_t(x), 2)
        back = _np(paddle.pixel_unshuffle(up, 2))
        np.testing.assert_allclose(back, x)

    def test_fold_unfold_inverse(self):
        # non-overlapping patches: fold(unfold(x)) == x
        x = rng.randn(1, 2, 4, 6).astype("float32")
        cols = F.unfold(_t(x), kernel_sizes=2, strides=2)
        back = _np(paddle.fold(cols, output_sizes=(4, 6), kernel_sizes=2,
                               strides=2))
        np.testing.assert_allclose(back, x, rtol=1e-6)

    def test_max_pool_with_index_and_unpool(self):
        x = rng.randn(1, 1, 4, 4).astype("float32")
        out, idx = paddle.max_pool2d_with_index(_t(x), 2, 2)
        ref = torch.nn.functional.max_pool2d(torch.tensor(x), 2, 2).numpy()
        np.testing.assert_allclose(_np(out), ref, rtol=1e-6)
        restored = _np(paddle.max_unpool2d(out, idx, 2, 2))
        # unpool scatters each max back to its argmax position
        assert restored.shape == (1, 1, 4, 4)
        np.testing.assert_allclose(np.sort(restored[restored != 0]),
                                   np.sort(ref.reshape(-1)))


class TestRandomAndSpecial:
    def test_distributions_shapes_and_ranges(self):
        paddle.seed(0)
        lam = _t(np.full((1000,), 4.0, "float32"))
        p = _np(paddle.poisson(lam))
        assert abs(p.mean() - 4.0) < 0.5
        g = _np(paddle.standard_gamma(_t(np.full((1000,), 2.0,
                                                 "float32"))))
        assert abs(g.mean() - 2.0) < 0.3
        d = _np(paddle.dirichlet(_t(np.ones((100, 3), "float32"))))
        np.testing.assert_allclose(d.sum(-1), np.ones(100), rtol=1e-5)
        b = _np(paddle.binomial(_t(np.full((1000,), 10)),
                                _t(np.full((1000,), 0.3, "float32"))))
        assert abs(b.mean() - 3.0) < 0.4
        t = paddle.to_tensor(np.zeros((500,), "float32"))
        paddle.exponential_(t)
        assert abs(_np(t).mean() - 1.0) < 0.25

    def test_special_functions(self):
        import scipy.special as sp

        x = np.linspace(0.1, 5, 20).astype("float32")
        np.testing.assert_allclose(_np(paddle.i0e(_t(x))), sp.i0e(x),
                                   rtol=1e-4)
        np.testing.assert_allclose(_np(paddle.i1e(_t(x))), sp.i1e(x),
                                   rtol=1e-4)
        np.testing.assert_allclose(_np(paddle.gammaln(_t(x))),
                                   sp.gammaln(x), rtol=1e-4)
        np.testing.assert_allclose(
            _np(paddle.gammaincc(_t(x), _t(x))), sp.gammaincc(x, x),
            rtol=1e-3)

    def test_top_p_sampling(self):
        paddle.seed(0)
        logits = np.full((4, 10), -10.0, "float32")
        logits[:, 3] = 10.0  # all mass on token 3
        scores, ids = paddle.top_p_sampling(_t(logits), 0.9)
        assert _np(ids).reshape(-1).tolist() == [3, 3, 3, 3]


class TestConvPool3D:
    def test_conv3d_matches_torch(self):
        x = rng.randn(1, 2, 5, 6, 7).astype("float32")
        w = rng.randn(4, 2, 3, 3, 3).astype("float32")
        out = _np(paddle.ops.extra.conv3d(_t(x), _t(w), stride=1,
                                          padding=1))
        ref = torch.nn.functional.conv3d(torch.tensor(x),
                                         torch.tensor(w), padding=1)
        np.testing.assert_allclose(out, ref.numpy(), rtol=1e-3,
                                   atol=1e-4)

    def test_pool3d_matches_torch(self):
        x = rng.randn(1, 2, 6, 6, 6).astype("float32")
        out = _np(paddle.ops.extra.max_pool3d(_t(x), 2, 2))
        ref = torch.nn.functional.max_pool3d(torch.tensor(x), 2, 2)
        np.testing.assert_allclose(out, ref.numpy(), rtol=1e-6)
        out = _np(paddle.ops.extra.avg_pool3d(_t(x), 2, 2))
        ref = torch.nn.functional.avg_pool3d(torch.tensor(x), 2, 2)
        np.testing.assert_allclose(out, ref.numpy(), rtol=1e-5)

    def test_pool3d_ceil_mode_matches_torch(self):
        """ceil_mode via asymmetric right-padding in the reduce_window
        pads (max: -inf pad; avg exclusive: real-element divisor)."""
        x = rng.randn(2, 3, 5, 7, 6).astype("float32")
        t = torch.tensor(x)
        for k, s, p in [(2, 2, 0), (3, 2, 1), (2, 3, 0)]:
            out = _np(paddle.ops.extra.max_pool3d(
                _t(x), k, s, p, ceil_mode=True))
            ref = torch.nn.functional.max_pool3d(
                t, k, s, p, ceil_mode=True).numpy()
            assert out.shape == ref.shape, (k, s, p)
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
            out = _np(paddle.ops.extra.avg_pool3d(
                _t(x), k, s, p, ceil_mode=True))
            ref = torch.nn.functional.avg_pool3d(
                t, k, s, p, ceil_mode=True,
                count_include_pad=False).numpy()
            assert out.shape == ref.shape, (k, s, p)
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        # return_mask: ceil-mode indices match torch's
        out, mask = paddle.ops.extra.max_pool3d(_t(x), 2, 2, 0,
                                                ceil_mode=True,
                                                return_mask=True)
        ro, ri = torch.nn.functional.max_pool3d(t, 2, 2, 0,
                                                ceil_mode=True,
                                                return_indices=True)
        np.testing.assert_allclose(_np(out), ro.numpy(), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_array_equal(_np(mask), ri.numpy())


class TestActivationsLosses:
    def test_activations(self):
        x = rng.randn(50).astype("float32")
        np.testing.assert_allclose(_np(paddle.stanh(_t(x))),
                                   1.7159 * np.tanh(0.67 * x), rtol=1e-5)
        np.testing.assert_allclose(
            _np(paddle.thresholded_relu(_t(x), 0.5)),
            np.where(x > 0.5, x, 0), rtol=1e-6)
        np.testing.assert_allclose(
            _np(paddle.log_sigmoid(_t(x))),
            torch.nn.functional.logsigmoid(torch.tensor(x)).numpy(),
            rtol=1e-5)
        m = rng.randn(2, 6, 3).astype("float32")
        out = _np(paddle.maxout(_t(m), 2, axis=1))
        assert out.shape == (2, 3, 3)
        paddle.seed(1)
        r = _np(paddle.rrelu(_t(x), training=False))
        a = (1 / 8 + 1 / 3) / 2
        np.testing.assert_allclose(r, np.where(x >= 0, x, a * x),
                                   rtol=1e-5)

    def test_huber_loss_matches_torch(self):
        x = rng.randn(20).astype("float32")
        y = rng.randn(20).astype("float32")
        ours = float(paddle.ops.extra.huber_loss(_t(x), _t(y), 1.0))
        ref = float(torch.nn.functional.huber_loss(
            torch.tensor(x), torch.tensor(y), delta=1.0))
        assert abs(ours - ref) < 1e-5

    def test_clip_by_norm_and_squared_l2(self):
        x = np.array([3.0, 4.0], "float32")
        np.testing.assert_allclose(_np(paddle.clip_by_norm(_t(x), 1.0)),
                                   [0.6, 0.8], rtol=1e-5)
        assert float(paddle.squared_l2_norm(_t(x))) == 25.0

    def test_shard_index(self):
        x = np.array([1, 6, 12, 19], "int64")
        out = _np(paddle.shard_index(_t(x), 20, 2, 0))
        np.testing.assert_allclose(out, [1, 6, -1, -1])
        out = _np(paddle.shard_index(_t(x), 20, 2, 1))
        np.testing.assert_allclose(out, [-1, -1, 2, 9])


class TestGridSampleCTC:
    def test_grid_sample_matches_torch(self):
        x = rng.randn(2, 3, 5, 7).astype("float32")
        grid = (rng.rand(2, 4, 6, 2).astype("float32") * 2.2 - 1.1)
        for mode in ("bilinear", "nearest"):
            for pad in ("zeros", "border", "reflection"):
                ours = _np(F.grid_sample(_t(x), _t(grid), mode, pad,
                                         True))
                ref = torch.nn.functional.grid_sample(
                    torch.tensor(x), torch.tensor(grid), mode=mode,
                    padding_mode=pad, align_corners=True).numpy()
                np.testing.assert_allclose(ours, ref, atol=2e-5,
                                           err_msg=f"{mode}/{pad}")

    def test_grid_sample_grad(self):
        x = _t(rng.rand(1, 1, 4, 4).astype("float32"))
        x.stop_gradient = False
        grid = _t((rng.rand(1, 3, 3, 2).astype("float32") - 0.5))
        F.grid_sample(x, grid).sum().backward()
        g = _np(x.grad)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_affine_grid_matches_torch(self):
        theta = rng.randn(2, 2, 3).astype("float32")
        for ac in (True, False):
            ours = _np(F.affine_grid(_t(theta), [2, 3, 4, 5], ac))
            ref = torch.nn.functional.affine_grid(
                torch.tensor(theta), [2, 3, 4, 5],
                align_corners=ac).numpy()
            np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_ctc_loss_matches_torch(self):
        T, B, C, L = 12, 3, 6, 4
        logits = rng.randn(T, B, C).astype("float32")
        logp = torch.log_softmax(torch.tensor(logits), -1)
        labels = rng.randint(1, C, (B, L))
        in_lens = np.array([12, 10, 7])
        lab_lens = np.array([4, 3, 2])
        ref = torch.nn.functional.ctc_loss(
            logp, torch.tensor(labels), torch.tensor(in_lens),
            torch.tensor(lab_lens), blank=0, reduction="none").numpy()
        ours = _np(F.ctc_loss(_t(logp.numpy()), _t(labels), _t(in_lens),
                              _t(lab_lens), reduction="none"))
        np.testing.assert_allclose(ours, ref, atol=1e-3)

    def test_ctc_loss_grad_matches_torch_through_logsoftmax(self):
        # torch's raw log_probs-grad has logits semantics (documented
        # quirk); through log_softmax both frameworks agree exactly
        T, B, C, L = 10, 2, 5, 3
        logits_np = rng.randn(T, B, C).astype("float32")
        labels = rng.randint(1, C, (B, L))
        in_lens = np.array([10, 8])
        lab_lens = np.array([3, 2])
        tl = torch.tensor(logits_np, requires_grad=True)
        torch.nn.functional.ctc_loss(
            torch.log_softmax(tl, -1), torch.tensor(labels),
            torch.tensor(in_lens), torch.tensor(lab_lens),
            reduction="sum").backward()
        pl = _t(logits_np)
        pl.stop_gradient = False
        F.ctc_loss(F.log_softmax(pl, axis=-1), _t(labels), _t(in_lens),
                   _t(lab_lens), reduction="sum").backward()
        np.testing.assert_allclose(_np(pl.grad), tl.grad.numpy(),
                                   atol=1e-4)

    def test_gather_tree(self):
        # 2 steps, 1 batch, 2 beams: final beam 0 came from step-0 beam 1
        ids = np.array([[[5, 6]], [[7, 8]]])
        parents = np.array([[[0, 0]], [[1, 0]]])
        out = _np(paddle.gather_tree(_t(ids), _t(parents)))
        assert out[1, 0].tolist() == [7, 8]
        assert out[0, 0].tolist() == [6, 5]  # backtraced parents

    def test_edit_distance(self):
        d = _np(paddle.edit_distance(
            [_t(np.array([1, 2, 3]))], [_t(np.array([1, 3, 3, 4]))],
            normalized=False))
        assert d[0] == 2.0  # substitute + insert


class TestReviewRegressions:
    def test_max_unpool2d_with_padding_shape(self):
        x = rng.randn(1, 1, 4, 4).astype("float32")
        out, idx = paddle.max_pool2d_with_index(_t(x), 2, 2, padding=1)
        restored = paddle.max_unpool2d(out, idx, 2, 2, padding=1)
        assert restored.shape == [1, 1, 4, 4]

    def test_rrelu_grad_flows(self):
        x = _t(rng.randn(10).astype("float32"))
        x.stop_gradient = False
        out = paddle.rrelu(x, training=False)
        assert not out.stop_gradient
        out.sum().backward()
        assert np.isfinite(_np(x.grad)).all()

    def test_top_p_per_row_and_seed(self):
        logits = np.zeros((2, 5), "float32")
        logits[0, 1] = 10.0   # row 0: all mass on token 1
        logits[1] = np.array([2.0, 1.9, 1.8, -10, -10])
        _, ids1 = paddle.top_p_sampling(_t(logits),
                                        _t(np.array([0.5, 0.99],
                                                    "float32")), seed=3)
        _, ids2 = paddle.top_p_sampling(_t(logits),
                                        _t(np.array([0.5, 0.99],
                                                    "float32")), seed=3)
        assert _np(ids1)[0, 0] == 1           # row-0 nucleus is {1}
        assert _np(ids1).tolist() == _np(ids2).tolist()  # seeded

    def test_ctc_norm_by_times(self):
        T, B, C, L = 8, 2, 5, 3
        logits = rng.randn(T, B, C).astype("float32")
        lp = torch.log_softmax(torch.tensor(logits), -1).numpy()
        labels = rng.randint(1, C, (B, L))
        il, ll = np.array([8, 6]), np.array([3, 2])
        raw = _np(F.ctc_loss(_t(lp), _t(labels), _t(il), _t(ll),
                             reduction="none"))
        nbt = _np(F.ctc_loss(_t(lp), _t(labels), _t(il), _t(ll),
                             reduction="none", norm_by_times=True))
        np.testing.assert_allclose(nbt, raw / il, rtol=1e-6)

    def test_clip_by_norm_zero_grad(self):
        x = _t(np.zeros(3, "float32"))
        x.stop_gradient = False
        paddle.clip_by_norm(x, 1.0).sum().backward()
        assert np.isfinite(_np(x.grad)).all()

    def test_no_duplicate_ops(self):
        # mv/numel/unbind live in math/manipulation only
        from paddle_tpu.ops import extra
        assert not hasattr(extra, "mv")
        assert not hasattr(extra, "numel")
        assert not hasattr(extra, "unbind")
        assert callable(paddle.mv) and callable(paddle.numel)
        assert callable(paddle.unbind)
