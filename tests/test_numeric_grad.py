"""OpTest-style numeric-gradient checks for families that previously
had forward-only coverage (round-4 verdict item 10): vision ops
(roi_align, deform_conv2d, grid_sample, affine_grid) and distribution
transforms (log-prob / log-det-jacobian gradients).

Method mirrors the reference's OpTest.check_grad (test/legacy_test/
op_test.py): central finite differences on a scalar projection of the
op output vs the autograd gradient.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.tensor import Tensor

EPS = 1e-3


def _num_grad(fn, x, eps=EPS):
    """Central-difference gradient of scalar fn at numpy point x."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        old = flat[i]
        flat[i] = old + eps
        fp = fn(x)
        flat[i] = old - eps
        fm = fn(x)
        flat[i] = old
        gf[i] = (fp - fm) / (2 * eps)
    return g


def _auto_grad(op, x_np, *rest):
    x = Tensor(paddle.to_tensor(x_np)._value, stop_gradient=False)
    out = op(x, *rest)
    (out.sum()).backward()
    return np.asarray(x.grad._value)


def _check(op, x_np, *rest, rtol=5e-2, atol=5e-3):
    def scalar(v):
        with paddle.no_grad():
            return float(np.asarray(
                op(paddle.to_tensor(v.astype("float32")),
                   *rest).sum()._value))

    num = _num_grad(scalar, x_np.astype(np.float64).copy())
    auto = _auto_grad(op, x_np.astype("float32"), *rest)
    np.testing.assert_allclose(auto, num, rtol=rtol, atol=atol)


class TestVisionOpGrads:
    def test_roi_align_input_grad(self):
        from paddle_tpu.vision.ops import roi_align

        r = np.random.RandomState(0)
        x = r.randn(1, 2, 8, 8).astype(np.float64)
        boxes = paddle.to_tensor(
            np.array([[1.0, 1.0, 6.0, 6.0]], "float32"))
        bn = paddle.to_tensor(np.array([1], "int32"))
        _check(lambda t: roi_align(t, boxes, bn, 2, spatial_scale=1.0),
               x)

    def test_deform_conv2d_grads(self):
        from paddle_tpu.vision.ops import deform_conv2d

        r = np.random.RandomState(1)
        x = r.randn(1, 2, 5, 5).astype(np.float64) * 0.5
        # 3x3 kernel -> offset channels 2*3*3
        off = paddle.to_tensor(
            (r.randn(1, 18, 3, 3) * 0.1).astype("float32"))
        w = paddle.to_tensor(r.randn(3, 2, 3, 3).astype("float32") * 0.3)
        _check(lambda t: deform_conv2d(t, off, w), x)

    def test_grid_sample_grads_wrt_input_and_grid(self):
        r = np.random.RandomState(2)
        x = r.randn(1, 2, 4, 4).astype(np.float64)
        grid_np = (r.rand(1, 3, 3, 2) * 1.6 - 0.8).astype(np.float64)
        grid_t = paddle.to_tensor(grid_np.astype("float32"))
        _check(lambda t: F.grid_sample(t, grid_t, align_corners=True), x)

        # grad w.r.t. the GRID (the bilinear sampling positions)
        x_t = paddle.to_tensor(x.astype("float32"))

        def scalar(gv):
            with paddle.no_grad():
                return float(np.asarray(F.grid_sample(
                    x_t, paddle.to_tensor(gv.astype("float32")),
                    align_corners=True).sum()._value))

        num = _num_grad(scalar, grid_np.copy())
        g = Tensor(paddle.to_tensor(grid_np.astype("float32"))._value,
                   stop_gradient=False)
        F.grid_sample(x_t, g, align_corners=True).sum().backward()
        np.testing.assert_allclose(np.asarray(g.grad._value), num,
                                   rtol=5e-2, atol=5e-3)

    def test_affine_grid_grad(self):
        r = np.random.RandomState(3)
        theta = r.randn(1, 2, 3).astype(np.float64) * 0.5
        _check(lambda t: F.affine_grid(t, [1, 1, 3, 3],
                                       align_corners=True), theta)


class TestDistributionGrads:
    def test_normal_log_prob_grad_wrt_value(self):
        from paddle_tpu.distribution import Normal

        d = Normal(loc=0.5, scale=1.3)
        x = np.array([0.1, -0.4, 1.2], np.float64)
        _check(lambda t: d.log_prob(t), x)

    def test_transformed_log_det_jacobian_grads(self):
        from paddle_tpu.distribution.extra import (AffineTransform,
                                                   SigmoidTransform)

        r = np.random.RandomState(4)
        x = r.randn(5).astype(np.float64)
        aff = AffineTransform(paddle.to_tensor(np.float32(0.3)),
                              paddle.to_tensor(np.float32(1.7)))
        _check(lambda t: aff.forward_log_det_jacobian(t) + aff.forward(t),
               x)
        sig = SigmoidTransform()
        _check(lambda t: sig.forward_log_det_jacobian(t) + sig.forward(t),
               x)

    def test_gamma_log_prob_grad(self):
        from paddle_tpu.distribution import Gamma

        d = Gamma(paddle.to_tensor(np.float32(2.0)),
                  paddle.to_tensor(np.float32(1.5)))
        x = np.array([0.4, 1.1, 2.5], np.float64)
        _check(lambda t: d.log_prob(t), x)
