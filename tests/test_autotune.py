"""Pallas block autotune + algorithm cache (reference:
phi/kernels/autotune/cache.h AlgorithmsCache, switch_autotune.cc)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.autotune import AlgoCache, autotune


def test_autotune_picks_argmin_and_caches(tmp_path):
    path = str(tmp_path / "algo.json")
    cache = AlgoCache(path)
    times = {(128, 128): 3.0, (256, 256): 1.0, (512, 512): 2.0}
    calls = []

    def measure(c):
        calls.append(c)
        return times[c]

    best = autotune("k1", list(times), measure, cache)
    assert best == (256, 256)
    assert len(calls) == 3
    # cache hit: no more measurements
    again = autotune("k1", list(times), measure, cache)
    assert again == (256, 256) and len(calls) == 3
    # persisted: a NEW cache over the same file skips the search too
    cache2 = AlgoCache(path)
    assert autotune("k1", list(times), measure, cache2) == (256, 256)
    assert len(calls) == 3
    with open(path) as f:
        assert "k1" in json.load(f)


def test_autotune_skips_infeasible():
    cache = AlgoCache(None)

    def measure(c):
        if c == "bad":
            raise ValueError("no compile")
        return {"a": 2.0, "b": 1.0}[c]

    assert autotune("k", ["bad", "a", "b"], measure, cache) == "b"
    with pytest.raises(RuntimeError):
        autotune("none", ["bad"],
                 lambda c: (_ for _ in ()).throw(ValueError()), cache)


def test_flash_autotune_flag_consults_cache(monkeypatch, tmp_path):
    """With FLAGS_use_autotune on, flash block selection goes through
    the cache (measurements mocked — no TPU in CI)."""
    import jax.numpy as jnp

    import paddle_tpu.ops.pallas.autotune as AT
    from paddle_tpu.ops.pallas import flash_attention as FA

    cache = AT.AlgoCache(None)
    cache.put("flash:1x256x2x128:256:float32:True", (128, 256))
    monkeypatch.setattr(AT, "get_cache", lambda: cache)
    paddle.set_flags({"FLAGS_use_autotune": True})
    try:
        q = jnp.zeros((1, 256, 2, 128), jnp.float32)
        # interpret=False path consults the cache before any pallas call
        scale, interp, qs, ks, bq, bkv = FA._prep(
            q, q, True, None, False, None, None)
        assert (bq, bkv) == (128, 256)
    finally:
        paddle.set_flags({"FLAGS_use_autotune": False})
