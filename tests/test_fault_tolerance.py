"""Crash-consistent training: atomic async checkpointing, exact resume,
deterministic failpoints, and survivor-driven auto-recovery.

Under test (the fault-tolerance stack this PR composes):
- distributed/failpoints.py — the deterministic fault-injection
  substrate (raise/hang/corrupt/kill at named sites)
- distributed/checkpoint/ — the atomic commit protocol (tmp + fsync +
  per-shard crc32 + COMMIT + rename), the loader that refuses
  uncommitted/corrupt dirs, the rolling async CheckpointManager
- ParallelEngine.save_checkpoint/restore_checkpoint — full-state
  (params, ZeRO-2 moments, AMP masters + GradScaler, counters, RNG)
  exact resume: 5 + crash + 5 == 10 straight, bit-identical, with 0
  recompiles after restore
- fleet/elastic — heartbeat-failure ERROR surfacing, reusable manager,
  resume_latest newest-committed fallback, the train_with_recovery loop
- watchdog — log-mode actually logs, context-manager/shutdown wiring
- ServingEngine — bounded-queue + deadline load shedding, /healthz
  degraded
"""
import json
import logging
import os
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import failpoints as fp
from paddle_tpu.distributed.checkpoint import (CheckpointCorruptError,
                                               CheckpointManager,
                                               is_committed,
                                               latest_committed,
                                               load_state_dict,
                                               resolve_committed,
                                               save_state_dict,
                                               wait_async_saves)
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  ElasticStatus,
                                                  train_with_recovery)
from paddle_tpu.distributed.watchdog import CommTaskManager, watch

# every failpoint on the checkpoint WRITE path — the crash matrix
CKPT_FAILPOINTS = ("ckpt.write_shard", "ckpt.write_metadata",
                   "ckpt.commit", "ckpt.rename")


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


def _mlp(seed=0, d=8, h=16):
    paddle.seed(seed)
    return paddle.nn.Sequential(paddle.nn.Linear(d, h), paddle.nn.ReLU(),
                                paddle.nn.Linear(h, d))


def _params(m):
    return {n: np.asarray(p._value) for n, p in m.named_parameters()}


# ---------------------------------------------------------------------------
# failpoints: the substrate itself
# ---------------------------------------------------------------------------
class TestFailpoints:
    def test_parse_and_raise(self):
        fp.configure("a.site=raise")
        with pytest.raises(fp.FailpointError):
            fp.hit("a.site")

    def test_nth_hit_trigger(self):
        fp.configure("a.site=raise@3")
        fp.hit("a.site")
        fp.hit("a.site")
        with pytest.raises(fp.FailpointError):
            fp.hit("a.site")
        assert fp.hit_count("a.site") == 3

    def test_corrupt_mangles_payload(self):
        fp.configure("a.site=corrupt")
        data = b"0123456789"
        out = fp.hit("a.site", data)
        assert out != data and len(out) == len(data)

    def test_unarmed_is_passthrough(self):
        data = b"xyz"
        assert fp.hit("nobody.home", data) is data

    def test_scoped_restores(self):
        with fp.scoped("x=raise"):
            assert fp.active("x")
        assert not fp.active("x")

    def test_bad_specs_rejected(self):
        for spec in ("novalue", "a=explode", "a=raise@0"):
            with pytest.raises(ValueError):
                fp.configure(spec)
        fp.clear()

    def test_hang_with_duration(self):
        fp.configure("a.site=hang:0.05")
        t0 = time.perf_counter()
        fp.hit("a.site")
        assert time.perf_counter() - t0 >= 0.05


# ---------------------------------------------------------------------------
# atomic commit protocol
# ---------------------------------------------------------------------------
class TestAtomicCheckpoint:
    def test_commit_layout_npz_not_pickle(self, tmp_path):
        m = _mlp()
        p = str(tmp_path / "ck")
        save_state_dict(m.state_dict(), p)
        assert is_committed(p)
        with open(os.path.join(p, "0_0.distcp"), "rb") as f:
            magic = f.read(2)
        assert magic == b"PK", "shards must be npz (zip), not pickle"
        with open(os.path.join(p, "0.metadata")) as f:
            md = json.load(f)
        assert md["checksums"], "per-shard crc32 missing from metadata"
        commit = json.load(open(os.path.join(p, "COMMIT")))
        assert commit["shard_files"] == ["0_0.distcp"]

    @pytest.mark.parametrize("site", CKPT_FAILPOINTS)
    def test_crash_matrix_preserves_previous(self, tmp_path, site):
        """A save that dies at ANY write failpoint leaves the previous
        committed checkpoint loadable and bit-exact."""
        p = str(tmp_path / "ck")
        a = _mlp(seed=1)
        save_state_dict(a.state_dict(), p)
        want = _params(a)

        b = _mlp(seed=2)            # different weights, same shapes
        with fp.scoped(f"{site}=raise"):
            with pytest.raises(fp.FailpointError):
                save_state_dict(b.state_dict(), p)

        tgt = _mlp(seed=3)
        load_state_dict(tgt.state_dict(), p)
        got = _params(tgt)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)

    def test_uncommitted_dir_refused(self, tmp_path):
        p = str(tmp_path / "ck")
        m = _mlp()
        with fp.scoped("ckpt.commit=raise"):
            with pytest.raises(fp.FailpointError):
                save_state_dict(m.state_dict(), p)
        assert resolve_committed(p) is None
        with pytest.raises(Exception, match="no committed checkpoint"):
            load_state_dict(_mlp().state_dict(), p)

    def test_committed_tmp_is_recovered(self, tmp_path):
        """Crash between COMMIT and rename: the committed .tmp is
        durable, and the loader falls back to it."""
        p = str(tmp_path / "ck")
        m = _mlp(seed=4)
        with fp.scoped("ckpt.rename=raise"):
            with pytest.raises(fp.FailpointError):
                save_state_dict(m.state_dict(), p)
        assert not os.path.isdir(p)
        assert resolve_committed(p) == p + ".tmp"
        tgt = _mlp(seed=5)
        load_state_dict(tgt.state_dict(), p)
        got, want = _params(tgt), _params(m)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)

    def test_corrupt_shard_refused(self, tmp_path):
        p = str(tmp_path / "ck")
        m = _mlp()
        with fp.scoped("ckpt.write_shard=corrupt"):
            save_state_dict(m.state_dict(), p)
        assert is_committed(p)      # commit happened; bytes are bad
        with pytest.raises(CheckpointCorruptError):
            load_state_dict(_mlp().state_dict(), p)

    def test_on_disk_bitflip_caught_by_checksum(self, tmp_path):
        """Bit rot after a clean commit: the crc32 the metadata carries
        refuses the shard."""
        p = str(tmp_path / "ck")
        m = _mlp()
        save_state_dict(m.state_dict(), p)
        shard = os.path.join(p, "0_0.distcp")
        blob = bytearray(open(shard, "rb").read())
        blob[len(blob) // 2] ^= 0x01   # flip one payload bit
        open(shard, "wb").write(bytes(blob))
        with pytest.raises(CheckpointCorruptError):
            load_state_dict(_mlp().state_dict(), p)

    def test_bfloat16_roundtrip(self, tmp_path):
        """npz void-records round-trip back to ml_dtypes via the
        metadata dtype string."""
        import jax.numpy as jnp

        p = str(tmp_path / "ck")
        w = paddle.to_tensor(
            jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
            .astype(jnp.bfloat16))
        save_state_dict({"w": w}, p)
        tgt = paddle.to_tensor(jnp.zeros((3, 4), jnp.bfloat16))
        load_state_dict({"w": tgt}, p)
        np.testing.assert_array_equal(np.asarray(tgt._value),
                                      np.asarray(w._value))

    def test_async_save_matches_sync(self, tmp_path):
        m = _mlp(seed=6)
        ps, pa = str(tmp_path / "sync"), str(tmp_path / "async")
        save_state_dict(m.state_dict(), ps)
        save_state_dict(m.state_dict(), pa, async_save=True)
        wait_async_saves()
        assert is_committed(pa)
        t1, t2 = _mlp(seed=7), _mlp(seed=8)
        load_state_dict(t1.state_dict(), ps)
        load_state_dict(t2.state_dict(), pa)
        a, b = _params(t1), _params(t2)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


# ---------------------------------------------------------------------------
# CheckpointManager: rolling retention, async, fallback, gauges
# ---------------------------------------------------------------------------
class TestCheckpointManager:
    def test_retention_keeps_last_k(self, tmp_path):
        m = _mlp()
        mgr = CheckpointManager(str(tmp_path), keep_last_k=2)
        for s in (1, 2, 3, 4):
            mgr.save(m.state_dict(), step=s)
        # the base also carries the run's goodput journal (PR 11) —
        # retention is about the step_* checkpoint dirs
        names = sorted(n for n in os.listdir(str(tmp_path))
                       if n.startswith("step_"))
        assert names == ["step_00000003", "step_00000004"]
        assert sorted(os.listdir(str(tmp_path))) == \
            ["goodput.jsonl"] + names
        assert mgr.latest_step() == 4

    def test_newest_committed_fallback_after_crash(self, tmp_path):
        """Kill (raise) during save N: latest_committed returns N-1 and
        its content is the state saved at N-1."""
        a, b = _mlp(seed=1), _mlp(seed=2)
        mgr = CheckpointManager(str(tmp_path), keep_last_k=3)
        mgr.save(a.state_dict(), step=2, extra_meta={"step": 2})
        with fp.scoped("ckpt.commit=raise"):
            with pytest.raises(fp.FailpointError):
                mgr.save(b.state_dict(), step=4)
        latest = latest_committed(str(tmp_path))
        assert latest is not None and latest.endswith("step_00000002")
        tgt = _mlp(seed=3)
        load_state_dict(tgt.state_dict(), latest)
        got, want = _params(tgt), _params(a)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)

    def test_corrupt_latest_skipped_after_delete(self, tmp_path):
        """A committed-but-corrupt newest checkpoint raises on load;
        deleting it falls back one save (the documented recovery)."""
        import shutil

        m = _mlp()
        mgr = CheckpointManager(str(tmp_path), keep_last_k=3)
        mgr.save(m.state_dict(), step=2)
        with fp.scoped("ckpt.write_shard=corrupt"):
            mgr.save(m.state_dict(), step=4)
        latest = latest_committed(str(tmp_path))
        assert latest.endswith("step_00000004")
        with pytest.raises(CheckpointCorruptError):
            load_state_dict(_mlp().state_dict(), latest)
        shutil.rmtree(latest)
        assert latest_committed(str(tmp_path)).endswith("step_00000002")

    def test_async_mode_and_gauges(self, tmp_path):
        from paddle_tpu.observability import get_registry

        m = _mlp()
        with CheckpointManager(str(tmp_path), keep_last_k=2,
                               async_save=True) as mgr:
            mgr.save(m.state_dict(), step=10, extra_meta={"step": 10})
            mgr.wait()
            assert mgr.latest_step() == 10
        snap = get_registry().snapshot()["metrics"]
        for name in ("paddle_tpu_ckpt_last_save_age_seconds",
                     "paddle_tpu_ckpt_save_seconds",
                     "paddle_tpu_ckpt_save_bytes",
                     "paddle_tpu_ckpt_last_committed_step",
                     "paddle_tpu_ckpt_async_pending",
                     "paddle_tpu_ckpt_saves_total"):
            assert name in snap, name
        assert snap["paddle_tpu_ckpt_last_committed_step"][
            "series"][0]["value"] == 10.0

    def test_async_background_failure_surfaces(self, tmp_path):
        m = _mlp()
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        fp.configure("ckpt.write_metadata=raise")
        mgr.save(m.state_dict(), step=2)
        with pytest.raises(fp.FailpointError):
            mgr.wait()
        fp.clear()
        mgr.close()

    # -- teardown liveness (ISSUE 17 blocking-under-lock conviction) ----
    def test_close_terminates_writer_promptly(self, tmp_path):
        """Regression: the writer loop used a timeout-less
        Queue.get(), so it could only ever exit via the None sentinel
        — a writer wedged on anything else made close() hang its full
        30s join. The loop now polls with a bounded get and a stop
        Event; close() must return fast and leave the thread dead."""
        import threading

        m = _mlp()
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(m.state_dict(), step=1)
        mgr.wait()
        writer = mgr._writer
        assert writer is not None and writer.is_alive()
        t0 = time.monotonic()
        mgr.close()
        assert time.monotonic() - t0 < 5.0
        writer.join(timeout=5)
        assert not writer.is_alive()
        assert mgr._writer is None

    def test_close_idempotent_and_save_restarts_writer(self, tmp_path):
        m = _mlp()
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(m.state_dict(), step=1)
        mgr.close()
        mgr.close()                       # second close is a no-op
        # a save after close restarts a fresh writer (stop cleared)
        mgr.save(m.state_dict(), step=2)
        mgr.wait()
        assert mgr.latest_step() == 2
        assert mgr._writer is not None and mgr._writer.is_alive()
        mgr.close()

    def test_stale_sentinel_does_not_kill_live_writer(self, tmp_path):
        """A close() racing a save() used to leave a None sentinel in
        the queue that the NEXT writer consumed as its own shutdown
        order, silently dropping every queued checkpoint behind it.
        A sentinel with the stop Event clear is now ignored."""
        m = _mlp()
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(m.state_dict(), step=1)
        mgr.wait()
        mgr._queue.put(None)              # stale sentinel, stop NOT set
        mgr.save(m.state_dict(), step=2)
        mgr.wait(timeout=30)
        assert mgr.latest_step() == 2
        assert mgr._writer is not None and mgr._writer.is_alive()
        mgr.close()


# ---------------------------------------------------------------------------
# engine exact resume: the headline parity property on the gpt13b smoke
# topology (mp2 x pp2 x sharding2, vpp2, AMP GradScaler)
# ---------------------------------------------------------------------------
def _build_hybrid():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    paddle.seed(0)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
        "pp_configs": {"num_virtual_pipeline_stages": 2}}
    strategy.sharding_configs = {"stage": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_position_embeddings=32)
    model = GPTForCausalLMPipe(cfg)
    dm = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters()))
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10)
    return cfg, model, dm, opt, scaler


def _hbatch(step, cfg, B=8, S=16):
    r = np.random.RandomState(100 + step)
    ids = r.randint(0, cfg.vocab_size, (B, S + 1))
    return [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])]


class TestExactResumeHybrid:
    @pytest.mark.slow  # ~30s 10-step x2 hybrid horizon; 1-cpu tier-1 budget
    def test_five_crash_five_equals_ten_straight(self, tmp_path):
        """10 straight steps vs 5 + 'crash' (fresh model/opt/engine,
        i.e. a restarted process) + restore + 5: losses AND params
        bit-identical, 0 recompiles after restore."""
        ck = str(tmp_path / "ck")
        cfg, gmodel, gdm, gopt, gscaler = _build_hybrid()
        gold = [float(gdm.train_batch(_hbatch(s, cfg), gopt,
                                      scaler=gscaler))
                for s in range(10)]
        gold_params = _params(gmodel)

        cfg, model, dm, opt, scaler = _build_hybrid()
        first = [float(dm.train_batch(_hbatch(s, cfg), opt,
                                      scaler=scaler))
                 for s in range(5)]
        assert first == gold[:5]
        dm.save_checkpoint(ck, step=5, scaler=scaler)

        # the crash: everything rebuilt from scratch (fresh random
        # init), only the checkpoint survives
        cfg, model2, dm2, opt2, scaler2 = _build_hybrid()
        meta = dm2.restore_checkpoint(ck, optimizer=opt2,
                                      scaler=scaler2)
        assert meta["step"] == 5
        second = [float(dm2.train_batch(_hbatch(s, cfg), opt2,
                                        scaler=scaler2))
                  for s in range(5, 10)]
        assert second == gold[5:], (second, gold[5:])
        got = _params(model2)
        for k, v in gold_params.items():
            np.testing.assert_array_equal(got[k], v, err_msg=k)

        # restore into the ALREADY-compiled engine: zero recompiles
        c0 = dm2._engine.stats.compiles
        dm2.restore_checkpoint(ck, scaler=scaler2)
        float(dm2.train_batch(_hbatch(5, cfg), opt2, scaler=scaler2))
        assert dm2._engine.stats.compiles == c0

    def test_engine_crash_matrix_falls_back(self, tmp_path):
        """The full crash matrix at engine level: a save dying at any
        checkpoint failpoint leaves a bit-exact checkpoint restorable
        (newest-committed fallback through the manager). A save that
        died at the rename — AFTER its COMMIT hit disk — legitimately
        IS the newest committed state (the .tmp fallback); every
        earlier failpoint falls back to the previous save."""
        import re

        base = str(tmp_path / "run")
        cfg, model, dm, opt, scaler = _build_hybrid()
        mgr = CheckpointManager(base, keep_last_k=len(CKPT_FAILPOINTS)
                                + 2)
        float(dm.train_batch(_hbatch(0, cfg), opt, scaler=scaler))
        dm.save_checkpoint(manager=mgr, step=1, scaler=scaler)
        snaps = {1: _params(model)}     # state at each attempted save
        for i, site in enumerate(CKPT_FAILPOINTS):
            float(dm.train_batch(_hbatch(1 + i, cfg), opt,
                                 scaler=scaler))
            snaps[2 + i] = _params(model)
            with fp.scoped(f"{site}=raise"):
                with pytest.raises(fp.FailpointError):
                    dm.save_checkpoint(manager=mgr, step=2 + i,
                                       scaler=scaler)
        latest = latest_committed(base)
        assert latest is not None
        step = int(re.search(r"step_(\d+)", latest).group(1))
        # pre-COMMIT failpoints never advance the newest checkpoint;
        # only the post-COMMIT rename crash may (as a committed .tmp)
        assert step == 1 or latest.endswith(".tmp")
        cfg, model2, dm2, opt2, scaler2 = _build_hybrid()
        meta = dm2.restore_checkpoint(latest, optimizer=opt2,
                                      scaler=scaler2)
        assert meta["step"] == step
        got, want = _params(model2), snaps[step]
        for k, v in want.items():
            np.testing.assert_array_equal(got[k], v, err_msg=k)


class TestReshardOnLoad:
    def test_save_dp2_mp2_resume_mp4(self, tmp_path):
        """Save under dp2 x mp2, resume under mp4: the metadata's
        global offsets reassemble every tensor (optimizer moments
        included) into the new layout."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.layers import mpu

        class TP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = mpu.ColumnParallelLinear(16, 32,
                                                    gather_output=False)
                self.fc2 = mpu.RowParallelLinear(32, 16,
                                                 input_is_parallel=True)

            def forward(self, x):
                return self.fc2(
                    paddle.nn.functional.relu(self.fc1(x)))

        def build(dp, mp):
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                                       "pp_degree": 1}
            hcg = fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(3 + dp)
            model = TP()
            opt = paddle.optimizer.AdamW(
                parameters=model.parameters())
            eng = ParallelEngine(model, opt, hcg.mesh)
            return model, opt, eng

        ck = str(tmp_path / "ck")
        model, opt, eng = build(2, 2)
        step = eng.train_step(
            lambda m, b: paddle.mean(m(b["x"]) ** 2))
        r = np.random.RandomState(0)
        for s in range(3):
            step({"x": paddle.to_tensor(
                r.randn(8, 16).astype("float32"))})
        eng.save_checkpoint(ck, step=3)
        want = _params(model)
        want_m1 = {i: np.asarray(opt._states[id(p)]["moment1"])
                   for i, p in enumerate(eng.trainable)}

        model2, opt2, eng2 = build(1, 4)
        meta = eng2.restore_checkpoint(ck)
        assert meta["step"] == 3
        got = _params(model2)
        for k, v in want.items():
            np.testing.assert_array_equal(got[k], v, err_msg=k)
        for i, p in enumerate(eng2.trainable):
            np.testing.assert_array_equal(
                np.asarray(opt2._states[id(p)]["moment1"]),
                want_m1[i], err_msg=f"moment1[{i}]")
        assert opt2._step_count == opt._step_count
        # the resumed layout is genuinely mp4-sharded
        assert not model2.fc1.weight._value.sharding.is_fully_replicated


# ---------------------------------------------------------------------------
# recovery loop + watchdog + elastic satellites
# ---------------------------------------------------------------------------
class _StubElastic:
    def __init__(self):
        self.status = ElasticStatus.HOLD

    @property
    def restart_needed(self):
        return self.status in (ElasticStatus.RESTART,
                               ElasticStatus.ERROR)


class _StubStore:
    """In-memory store standing in for TCPStore (same surface)."""

    def __init__(self):
        self.kv = {}
        self.fail_set = False

    def set(self, key, value):
        if self.fail_set:
            raise ConnectionError("store down")
        self.kv[key] = str(value)

    def get(self, key, timeout=None):
        return self.kv[key]

    def check(self, key):
        return key in self.kv

    def delete_key(self, key):
        self.kv.pop(key, None)


class TestRecoveryLoop:
    def test_elastic_restart_stops_loop_and_dumps_flight(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        el = _StubElastic()
        ran = []

        def step_fn(s):
            ran.append(s)
            if s == 2:
                el.status = ElasticStatus.RESTART
            return s

        verdict, at = train_with_recovery(step_fn, 10, elastic=el)
        assert (verdict, at) == ("restart", 3)
        assert ran == [0, 1, 2]
        assert any(f.startswith("flight_") for f in os.listdir(tmp_path))

    def test_watchdog_timeout_stops_loop(self):
        with CommTaskManager(timeout=0.2, poll_interval=0.05) as wd:
            def step_fn(s):
                if s == 1:
                    time.sleep(0.6)     # the hung collective
                return s

            verdict, at = train_with_recovery(step_fn, 5, watchdog=wd)
        assert (verdict, at) == ("restart", 1)

    def test_completion_and_periodic_saves(self):
        saves = []
        verdict, at = train_with_recovery(
            lambda s: s, 6, save_fn=saves.append, save_every=2)
        assert (verdict, at) == ("completed", 6)
        assert saves == [2, 4, 6]

    def test_resume_latest_cold_start(self, tmp_path):
        m = _mlp()
        from paddle_tpu.distributed.fleet.elastic import resume_latest

        assert resume_latest(str(tmp_path / "none"), m) is None

    def test_resume_latest_roundtrip(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (
            resume_latest, save_train_state)

        m = _mlp(seed=1)
        opt = paddle.optimizer.AdamW(parameters=m.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype("float32"))
        loss = paddle.mean(m(x) ** 2)
        loss.backward()
        opt.step()
        mgr = CheckpointManager(str(tmp_path), keep_last_k=2)
        save_train_state(mgr.step_dir(7), m, opt, step=7)
        m2 = _mlp(seed=9)
        opt2 = paddle.optimizer.AdamW(parameters=m2.parameters())
        meta = resume_latest(str(tmp_path), m2, opt2)
        assert meta["step"] == 7
        got, want = _params(m2), _params(m)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)
        assert opt2._step_count == opt._step_count


class TestWatchdogSatellites:
    def test_log_mode_logs_with_flight_path(self, caplog, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        with caplog.at_level(logging.ERROR, "paddle_tpu.watchdog"):
            with CommTaskManager(timeout=0.1, poll_interval=0.02,
                                 error_handling="log") as mgr:
                with mgr.track("hung_thing"):
                    time.sleep(0.4)
                mgr.check()     # log mode: never raises
        msgs = [r.getMessage() for r in caplog.records]
        assert any("hung_thing" in m and "flight" in m for m in msgs)
        assert any(str(tmp_path) in m for m in msgs)

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="error_handling"):
            CommTaskManager(error_handling="explode")

    def test_lazy_thread_and_shutdown(self):
        mgr = CommTaskManager(timeout=5.0, poll_interval=0.05)
        assert mgr._thread is None      # no leak before first track
        with mgr.track("s"):
            pass
        assert mgr._thread is not None and mgr._thread.is_alive()
        mgr.shutdown()
        assert mgr._thread is None

    def test_watch_context_manager_stops_monitor(self):
        with watch(lambda x: paddle.to_tensor(np.asarray(x) * 2),
                   timeout=5.0, poll_interval=0.05) as w:
            out = w(np.ones(4, "float32"))
            np.testing.assert_array_equal(np.asarray(out._value),
                                          2 * np.ones(4))
            t = w._watchdog._thread
            assert t is not None and t.is_alive()
        assert w._watchdog._thread is None


class TestElasticSatellites:
    def test_heartbeat_failure_flags_error(self, caplog):
        store = _StubStore()
        mgr = ElasticManager(store, job_id="j", rank=0, np_=1,
                             heartbeat_interval=0.05, node_timeout=0.5)
        with caplog.at_level(logging.ERROR, "paddle_tpu.elastic"):
            mgr.register()
            store.fail_set = True
            deadline = time.time() + 5
            while time.time() < deadline and \
                    mgr.status is not ElasticStatus.ERROR:
                time.sleep(0.02)
        assert mgr.status is ElasticStatus.ERROR
        assert mgr.restart_needed        # ERROR surfaces as restart
        assert any("heartbeat" in r.getMessage()
                   for r in caplog.records)
        mgr._stop.set()

    def test_ack_world_change_makes_manager_reusable(self):
        store = _StubStore()
        mgr = ElasticManager(store, job_id="j2", rank=0, np_=2,
                             heartbeat_interval=0.05, node_timeout=0.2)
        mgr.register()
        store.set("/elastic/j2/nodes/1", str(time.time()))
        assert mgr.wait_world(2, timeout=5)
        # let the watcher RECORD the 2-rank world before killing rank 1
        # (rank 1 has no heartbeat thread, so keep its key fresh)
        deadline = time.time() + 5
        while time.time() < deadline and mgr._last_world != (0, 1):
            store.set("/elastic/j2/nodes/1", str(time.time()))
            time.sleep(0.02)
        assert mgr._last_world == (0, 1)
        # rank 1 dies
        deadline = time.time() + 5
        store.delete_key("/elastic/j2/nodes/1")
        while time.time() < deadline and not mgr.restart_needed:
            time.sleep(0.02)
        assert mgr.status is ElasticStatus.RESTART
        mgr.ack_world_change()
        assert mgr.status is ElasticStatus.HOLD
        assert not mgr.restart_needed
        # a NEW world change re-arms it
        store.set("/elastic/j2/nodes/1", str(time.time()))
        deadline = time.time() + 5
        while time.time() < deadline and not mgr.restart_needed:
            time.sleep(0.02)
        assert mgr.status is ElasticStatus.RESTART
        mgr._stop.set()
        # ERROR is sticky: ack must not clear it
        mgr.status = ElasticStatus.ERROR
        mgr.ack_world_change()
        assert mgr.status is ElasticStatus.ERROR


# ---------------------------------------------------------------------------
# serving graceful degradation
# ---------------------------------------------------------------------------
class TestServingDegradation:
    @pytest.fixture(scope="class")
    def tiny_model(self):
        from paddle_tpu.distributed import fleet as _fleet
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        # the hybrid-resume classes above initialized a multi-axis
        # fleet; serving here is single-device
        _fleet._fleet_state.update(initialized=False, hcg=None,
                                   strategy=None)
        paddle.seed(11)
        return LlamaForCausalLM(llama_tiny())

    def _engine(self, tiny_model, **kw):
        from paddle_tpu.inference import (Config, ServingEngine,
                                          create_predictor)

        pred = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(page_size=8))
        return ServingEngine(pred, max_batch=2, **kw)

    def test_queue_full_sheds_at_submit(self, tiny_model):
        from paddle_tpu.observability import get_registry

        eng = self._engine(tiny_model, max_queue=2)
        V = tiny_model.config.vocab_size
        r = np.random.RandomState(0)
        rids = [eng.submit(r.randint(1, V, (5,)), max_new_tokens=4)
                for _ in range(6)]
        # 2 queued (+0 active yet) -> the rest shed immediately
        shed = [rid for rid in rids if rid in eng.finished
                and eng.finished[rid].shed]
        assert len(shed) == 4
        assert all(eng.finished[rid].shed_reason == "queue_full"
                   for rid in shed)
        assert eng.health() == "degraded"
        done = eng.run()
        served = [rid for rid in rids if rid not in shed]
        for rid in served:
            assert not done[rid].shed and done[rid].new_tokens
        snap = get_registry().snapshot()["metrics"]
        series = snap["paddle_tpu_serving_shed_total"]["series"]
        vals = {tuple(s["labels"].items()): s["value"] for s in series}
        assert vals[(("reason", "queue_full"),)] >= 4

    def test_deadline_sheds_before_prefill_not_in_ttft(self, tiny_model):
        eng = self._engine(tiny_model, admission_deadline_s=0.0)
        V = tiny_model.config.vocab_size
        r = np.random.RandomState(1)
        ttft_before = eng._metrics["ttft"].count()
        rid = eng.submit(r.randint(1, V, (5,)), max_new_tokens=4)
        time.sleep(0.01)
        eng.step()
        assert eng.finished[rid].shed_reason == "deadline"
        assert not eng.finished[rid].new_tokens   # never prefillled
        # shed latency excluded from TTFT
        assert eng._metrics["ttft"].count() == ttft_before

    def test_healthz_reports_degraded(self, tiny_model):
        from paddle_tpu.observability.exporter import serve_metrics

        eng = self._engine(tiny_model, max_queue=1)
        V = tiny_model.config.vocab_size
        r = np.random.RandomState(2)
        for _ in range(3):
            eng.submit(r.randint(1, V, (4,)), max_new_tokens=2)
        assert eng.health() == "degraded"
        with serve_metrics(0) as srv:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz") as resp:
                doc = json.loads(resp.read())
        assert doc["status"] == "degraded"
        comps = {c["component"]: c["status"]
                 for c in doc.get("components", [])}
        assert comps.get("serving") == "degraded"

    def test_unbounded_engine_stays_ok(self, tiny_model):
        eng = self._engine(tiny_model)
        assert eng.health() == "ok"
        assert eng.max_queue is None


# ---------------------------------------------------------------------------
# SIGKILL crash matrix (subprocess; the real preemption)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestSigkillMatrix:
    REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    WORKER = os.path.join(REPO, "tests", "workers",
                          "ckpt_crash_worker.py")

    def _run(self, extra_env, timeout=600):
        import subprocess
        import sys

        env = dict(os.environ)
        for k in list(env):
            if k.startswith(("PADDLE_", "JAX_", "XLA_")):
                del env[k]
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["JAX_PLATFORMS"] = "cpu"
        env["OMP_NUM_THREADS"] = "1"
        env.update({k: str(v) for k, v in extra_env.items()})
        p = subprocess.run(
            [sys.executable, self.WORKER], env=env, cwd=self.REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=timeout)
        return p.returncode, p.stdout.decode(errors="replace")[-3000:]

    def _losses(self, path):
        with open(path) as f:
            return [float(l) for l in f.read().split()]

    TOTAL, SAVE_EVERY = 8, 2

    @pytest.fixture(scope="class")
    def golden(self, tmp_path_factory):
        """One uninterrupted run shared by the whole matrix."""
        gold_base = str(tmp_path_factory.mktemp("gold"))
        rc, log = self._run({"CKPT_BASE": gold_base + "/ck",
                             "TOTAL_STEPS": self.TOTAL,
                             "SAVE_EVERY": 100,
                             "TEST_OUT": gold_base + "/out"})
        assert rc == 0, log
        return self._losses(gold_base + "/out.log")

    @pytest.mark.parametrize("site", CKPT_FAILPOINTS)
    def test_sigkill_then_resume_bit_exact(self, tmp_path, site, golden):
        """SIGKILL delivered inside the failpoint during the SECOND
        save: the relaunch restores the newest COMMITTED checkpoint
        (checksums verified) and the loss curve continues the
        uninterrupted golden bit-exactly."""
        total, save_every = self.TOTAL, self.SAVE_EVERY
        gold = golden

        base = str(tmp_path / f"run_{site.replace('.', '_')}")
        rc, log = self._run({
            "CKPT_BASE": base + "/ck", "TOTAL_STEPS": total,
            "SAVE_EVERY": save_every, "TEST_OUT": base + "/p1",
            "PADDLE_TPU_FAILPOINTS": f"{site}=kill@2"})
        assert rc == -9, (site, rc, log)   # SIGKILLed mid-save

        rc, log = self._run({"CKPT_BASE": base + "/ck",
                             "TOTAL_STEPS": total,
                             "SAVE_EVERY": save_every,
                             "TEST_OUT": base + "/p2"})
        assert rc == 0, (site, log)
        with open(base + "/p2.json") as f:
            start = json.load(f)["start"]
        # first save (step 2) certainly committed; a committed .tmp of
        # the second may legitimately be newer
        assert start in (2, 4), (site, start)
        resumed = self._losses(base + "/p2.log")
        assert resumed == gold[start:], (site, resumed, gold[start:])

    def test_sigkill_mid_prefetch_resume_bit_exact(self, tmp_path,
                                                   golden):
        """SIGKILL inside the ``offload.prefetch`` failpoint — between
        one step's host page-out and the next dispatch, the window
        where ALL optimizer state exists only as host buffers of a dead
        process. The relaunch rebuilds the host tier from the committed
        checkpoint and continues the OFFLOAD-OFF golden bit-exactly:
        crash safety and the offload on/off parity property in one run.
        The worker's single flat bucket makes hit N fire right before
        step N-1's dispatch (states page out at train_step build), so
        kill@3 dies entering step 2: steps 0-1 ran, the step-2 save
        committed."""
        total, save_every = self.TOTAL, self.SAVE_EVERY
        base = str(tmp_path / "run_offload_prefetch")
        rc, log = self._run({
            "CKPT_BASE": base + "/ck", "TOTAL_STEPS": total,
            "SAVE_EVERY": save_every, "TEST_OUT": base + "/p1",
            "OFFLOAD": 1,
            "PADDLE_TPU_FAILPOINTS": "offload.prefetch=kill@3"})
        assert rc == -9, (rc, log)
        assert self._losses(base + "/p1.log") == golden[:2]

        rc, log = self._run({"CKPT_BASE": base + "/ck",
                             "TOTAL_STEPS": total,
                             "SAVE_EVERY": save_every,
                             "TEST_OUT": base + "/p2",
                             "OFFLOAD": 1})
        assert rc == 0, log
        with open(base + "/p2.json") as f:
            start = json.load(f)["start"]
        assert start == 2, (start, log)
        assert self._losses(base + "/p2.log") == golden[start:]
