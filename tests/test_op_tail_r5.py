"""Round-5 op-surface tail: 3-D pooling, rrelu, margin_cross_entropy,
Adadelta/Adamax/ASGD/Rprop optimizers, functional
fused_multi_transformer / masked_multihead_attention.

Reference parity targets: phi pool3d/unpool3d kernels (torch as the
numeric oracle), nn/functional/activation.py rrelu, functional/common
margin_cross_entropy, python/paddle/optimizer/{adadelta,adamax,asgd,
rprop}.py, incubate/nn/functional/fused_transformer.py:964 +
masked_multihead_attention.py:19.
"""
import numpy as np
import pytest
import torch

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn


def _np(t):
    return np.asarray(t._value)


class TestPool3D:
    def setup_method(self, _):
        self.x = np.random.RandomState(0).randn(2, 3, 8, 10, 12) \
            .astype("float32")

    def test_max_pool3d(self):
        o = F.max_pool3d(paddle.to_tensor(self.x), 2, 2)
        t = torch.nn.functional.max_pool3d(torch.tensor(self.x), 2, 2)
        assert np.allclose(_np(o), t.numpy(), atol=1e-6)

    def test_avg_pool3d_exclusive(self):
        o = F.avg_pool3d(paddle.to_tensor(self.x), 3, 2, 1)
        t = torch.nn.functional.avg_pool3d(torch.tensor(self.x), 3, 2, 1,
                                           count_include_pad=False)
        assert np.allclose(_np(o), t.numpy(), atol=1e-5)

    def test_unpool3d_roundtrip(self):
        o, idx = F.max_pool3d(paddle.to_tensor(self.x), 2, 2,
                              return_mask=True)
        u = F.max_unpool3d(o, idx, 2, 2)
        tt, tidx = torch.nn.functional.max_pool3d(
            torch.tensor(self.x), 2, 2, return_indices=True)
        tu = torch.nn.functional.max_unpool3d(tt, tidx, 2, 2)
        assert np.allclose(_np(u), tu.numpy())


class TestActivationsLosses:
    def test_rrelu(self):
        x = np.random.RandomState(1).randn(64).astype("float32")
        o = _np(F.rrelu(paddle.to_tensor(x), training=False))
        assert np.allclose(o, np.where(x >= 0, x, x * (1 / 8 + 1 / 3) / 2),
                           atol=1e-6)
        ot = _np(F.rrelu(paddle.to_tensor(x), training=True))
        neg = x < 0
        assert (ot[~neg] == x[~neg]).all()
        ratio = ot[neg] / x[neg]
        assert (ratio >= 1 / 8 - 1e-6).all() and (ratio <= 1 / 3 + 1e-6).all()

    def test_margin_cross_entropy_reduces_to_softmax_ce(self):
        r = np.random.RandomState(2)
        cos = np.clip(r.randn(4, 10) / 3, -1, 1).astype("float32")
        lab = r.randint(0, 10, (4,))
        ours = float(_np(F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(lab),
            margin1=1.0, margin2=0.0, margin3=0.0, scale=10.0)))
        ref = torch.nn.functional.cross_entropy(
            torch.tensor(cos) * 10.0, torch.tensor(lab))
        assert abs(ours - float(ref)) < 1e-5

    def test_margin_cross_entropy_arcface_margin_raises_loss(self):
        r = np.random.RandomState(3)
        cos = np.clip(r.randn(4, 10) / 3, -1, 1).astype("float32")
        lab = r.randint(0, 10, (4,))
        plain = float(_np(F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(lab),
            margin1=1.0, margin2=0.0, margin3=0.0)))
        arc = float(_np(F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(lab),
            margin1=1.0, margin2=0.5, margin3=0.0)))
        assert arc > plain  # margin makes the target harder


@pytest.mark.parametrize("cls", ["Adadelta", "Adamax", "ASGD", "Rprop"])
def test_optimizer_tail_converges(cls):
    paddle.seed(0)
    r = np.random.RandomState(4)
    m = nn.Linear(4, 2)
    opt = getattr(paddle.optimizer, cls)(learning_rate=0.05,
                                         parameters=m.parameters())
    X = paddle.to_tensor(r.randn(16, 4).astype("float32"))
    y = paddle.to_tensor(r.randint(0, 2, (16,)))
    l0 = None
    for _ in range(30):
        loss = nn.functional.cross_entropy(m(X), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        l0 = l0 or float(loss.numpy())
    assert float(loss.numpy()) < l0


class TestIncubateFunctional:
    def test_fused_multi_transformer_matches_layer(self):
        import paddle_tpu.incubate.nn.functional as IF
        from paddle_tpu.incubate.nn import FusedMultiTransformer

        paddle.seed(0)
        E, H, FF, L = 32, 4, 64, 2
        layer = FusedMultiTransformer(E, H, FF, num_layers=L)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 6, E).astype("float32"))
        ref = layer(x)
        out = IF.fused_multi_transformer(
            x, layer.ln_scales, layer.ln_biases,
            layer.qkv_weights, layer.qkv_biases,
            layer.linear_weights, layer.linear_biases,
            layer.ffn_ln_scales, layer.ffn_ln_biases,
            layer.ffn1_weights, layer.ffn1_biases,
            layer.ffn2_weights, layer.ffn2_biases,
            trans_qkvw=False, num_heads=H)
        assert np.abs(_np(out) - _np(ref)).max() < 1e-5

    def test_masked_multihead_attention_step(self):
        import paddle_tpu.incubate.nn.functional as IF
        from paddle_tpu.ops.pallas.decode_attention import _dense_ragged

        r = np.random.RandomState(1)
        B, H, M, D = 2, 4, 16, 8
        lens = np.array([[3], [5]], np.int32)
        ckv = jnp.stack([jnp.asarray(r.randn(B, H, M, D), jnp.float32),
                         jnp.asarray(r.randn(B, H, M, D), jnp.float32)])
        xq = r.randn(B, 3 * H * D).astype("float32")
        out, new_ckv = IF.masked_multihead_attention(
            paddle.to_tensor(xq), paddle.to_tensor(ckv),
            sequence_lengths=paddle.to_tensor(lens))
        q = xq.reshape(B, 3, H, D)[:, 0]
        kn, vn = _np(new_ckv)[0], _np(new_ckv)[1]
        ref = _dense_ragged(jnp.asarray(q)[:, None], jnp.asarray(kn),
                            jnp.asarray(vn),
                            jnp.asarray(lens.reshape(-1)))
        assert np.abs(_np(out).reshape(B, 1, H, D)
                      - np.asarray(ref)).max() < 1e-5
        # the new kv landed at each row's own position (ragged write)
        assert np.allclose(kn[0, :, 3, :], xq.reshape(B, 3, H, D)[0, 1])
        assert np.allclose(kn[1, :, 5, :], xq.reshape(B, 3, H, D)[1, 1])

    def test_masked_multihead_attention_refuses_unserved_knobs(self):
        """src_mask/cum_offsets/beam_cache_offset and the quant knobs
        are not served on TPU — they must refuse loudly, not silently
        ignore (mirrors block_multihead_attention)."""
        import pytest

        import paddle_tpu.incubate.nn.functional as IF

        r = np.random.RandomState(2)
        B, H, M, D = 2, 4, 16, 8
        ckv = jnp.stack([jnp.asarray(r.randn(B, H, M, D), jnp.float32),
                         jnp.asarray(r.randn(B, H, M, D), jnp.float32)])
        xq = paddle.to_tensor(r.randn(B, 3 * H * D).astype("float32"))
        lens = paddle.to_tensor(np.array([[3], [5]], np.int32))
        for kw in ({"src_mask": paddle.to_tensor(np.zeros((B, 1, 1, M),
                                                          "float32"))},
                   {"cum_offsets": paddle.to_tensor(
                       np.zeros((B, 1), "int32"))},
                   {"beam_cache_offset": paddle.to_tensor(
                       np.zeros((B, 1), "int32"))},
                   {"qkv_out_scale": paddle.to_tensor(
                       np.ones((3 * H * D,), "float32"))},
                   {"out_scale": 0.5},
                   {"compute_dtype": "fp16"}):
            with pytest.raises(Exception):
                IF.masked_multihead_attention(
                    xq, paddle.to_tensor(ckv), sequence_lengths=lens,
                    **kw)
