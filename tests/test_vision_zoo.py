"""Vision model zoo completion (reference: python/paddle/vision/models/
__init__.py — full factory surface). One eval forward per family;
small inputs where the topology allows."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def _run(model, hw):
    model.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(1, 3, hw, hw).astype("float32"))
    return model(x)


@pytest.mark.parametrize("name,hw", [
    ("squeezenet1_0", 64), ("squeezenet1_1", 64),
    ("mobilenet_v1", 32), ("mobilenet_v3_small", 32),
    ("mobilenet_v3_large", 32),
    ("shufflenet_v2_x0_25", 64), ("shufflenet_v2_swish", 64),
    ("resnext50_32x4d", 32), ("wide_resnet50_2", 32),
    ("densenet121", 32),
])
def test_small_input_families(name, hw):
    out = _run(getattr(M, name)(num_classes=10), hw)
    assert out.shape == [1, 10]
    assert np.isfinite(np.asarray(out._value)).all()


def test_alexnet():
    out = _run(M.alexnet(num_classes=10), 224)
    assert out.shape == [1, 10]


def test_googlenet_aux_heads():
    out, out1, out2 = _run(M.googlenet(num_classes=10), 224)
    assert out.shape == [1, 10]
    assert out1.shape == [1, 10]
    assert out2.shape == [1, 10]


def test_inception_v3():
    out = _run(M.inception_v3(num_classes=10), 299)
    assert out.shape == [1, 10]


def test_factories_exist():
    for name in ["resnet18", "resnet34", "resnet50", "resnet101",
                 "resnet152", "resnext50_32x4d", "resnext50_64x4d",
                 "resnext101_32x4d", "resnext101_64x4d",
                 "resnext152_32x4d", "resnext152_64x4d",
                 "wide_resnet50_2", "wide_resnet101_2", "vgg11", "vgg13",
                 "vgg16", "vgg19", "mobilenet_v1", "mobilenet_v2",
                 "mobilenet_v3_small", "mobilenet_v3_large", "alexnet",
                 "densenet121", "densenet161", "densenet169",
                 "densenet201", "densenet264", "inception_v3",
                 "googlenet", "squeezenet1_0", "squeezenet1_1",
                 "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
                 "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
                 "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
                 "shufflenet_v2_swish"]:
        assert callable(getattr(M, name)), name


def test_mobilenet_v3_trains():
    """One SGD step decreases loss on a tiny overfit batch."""
    paddle.seed(0)
    m = M.mobilenet_v3_small(num_classes=4)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(1).rand(4, 3, 32, 32)
                         .astype("float32"))
    y = paddle.to_tensor(np.array([0, 1, 2, 3]))
    losses = []
    for _ in range(3):
        loss = paddle.nn.functional.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
