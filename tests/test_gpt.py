"""GPT flagship model: single-device training + dp x mp parallel parity
(the reference's hybrid_parallel_mp_model test pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                               gpt_tiny)


def _batch(B=4, S=16, vocab=256, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(0, vocab, (B, S + 1))
    return ids[:, :-1], ids[:, 1:]


def test_gpt_single_device_train_decreases_loss():
    paddle.seed(42)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    x, y = _batch()
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    losses = []
    for _ in range(5):
        loss = crit(model(xt), yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpt_dp_mp_parity_with_single_device():
    paddle.seed(42)
    cfg = gpt_tiny()
    golden = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(42)
    model = GPTForCausalLM(cfg)  # same seed -> same init as golden
    for (n1, p1), (n2, p2) in zip(golden.named_parameters(),
                                  model.named_parameters()):
        np.testing.assert_allclose(np.asarray(p1._value),
                                   np.asarray(p2._value), err_msg=n1)

    x, y = _batch(B=8)
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)

    g_opt = paddle.optimizer.SGD(learning_rate=0.05,
                                 parameters=golden.parameters())
    g_losses = []
    for _ in range(3):
        loss = crit(golden(xt), yt)
        loss.backward()
        g_opt.step()
        g_opt.clear_grad()
        g_losses.append(float(loss))

    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(
        lambda m, b: crit(m(b["x"]), b["y"]))
    d_losses = [float(step({"x": xt, "y": yt})) for _ in range(3)]

    np.testing.assert_allclose(d_losses, g_losses, rtol=2e-4)
