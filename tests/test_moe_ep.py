"""Expert parallelism as a first-class hybrid axis.

Under test:
- 'ep' mesh axis: strategy/fleet plumbing, HCG degree/group/rank
  accessors, MoELayer defaulting to the ep group, custom-order guard
- gate correctness: GShard/Switch top-k dense dispatch parity vs a
  numpy reference (capacity overflow/drop behavior, tie handling)
- capacity-factor bucketing onto the core/bucketing lattice
- MoE-on-mesh loss/param parity <= 1e-5 vs the single-device
  dense-dispatch golden WITH capacity drops, 0 recompiles after warmup
- ep_async_dispatch: the fused dispatch->FFN->combine ppermute ring
  (collective_matmul.moe_a2a_ffn) is numerically identical to the
  unfused a2a path, fwd and bwd
- expert-load / drop-rate / aux-loss gauges through the compiled step
- moe_utils.global_scatter/global_gather: the named uniform-count
  error, and the gradient of the a2a round trip
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import collective as C
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine, _shard_map
from paddle_tpu.incubate.distributed.models.moe import MoELayer
from paddle_tpu.incubate.distributed.models.moe.moe_layer import \
    _topk_dispatch
from paddle_tpu.tensor import Tensor


def _init_ep(dp=2, ep=2, mp=2, moe_configs=None):
    strategy = fleet.DistributedStrategy()
    hc = {"dp_degree": dp, "ep_degree": ep, "mp_degree": mp}
    if moe_configs:
        hc["moe_configs"] = moe_configs
    strategy.hybrid_configs = hc
    return fleet.init(is_collective=True, strategy=strategy), strategy


# ---------------------------------------------------------------------------
# plumbing: strategy -> fleet.init -> HCG -> MoELayer
# ---------------------------------------------------------------------------
class TestEpPlumbing:
    def test_strategy_defaults(self):
        s = fleet.DistributedStrategy()
        assert s.hybrid_configs["ep_degree"] == 1
        assert s.hybrid_configs["moe_configs"]["ep_async_dispatch"] \
            is False
        assert "ep" in s.hybrid_configs["order"]
        # sub-config merge keeps unset keys at their defaults
        s.hybrid_configs = {"moe_configs": {}}
        assert s.hybrid_configs["moe_configs"]["ep_async_dispatch"] \
            is False

    def test_hcg_accessors_and_mesh(self):
        hcg, _ = _init_ep(dp=2, ep=2, mp=2)
        assert hcg.get_expert_parallel_world_size() == 2
        g = hcg.get_expert_parallel_group()
        assert g.axis_names == ("ep",) and g.nranks == 2
        assert hcg.mesh.shape["ep"] == 2
        assert "ep=2" in repr(hcg)

    def test_moe_layer_prefers_ep_group(self):
        hcg, _ = _init_ep(dp=2, ep=2, mp=2)
        paddle.seed(0)
        moe = MoELayer(8, d_hidden=16, num_experts=4)
        assert moe._group.axis_names == ("ep",)
        assert moe.world_size == 2
        # expert stack sharded over 'ep' on dim 0
        assert tuple(moe.w1.dist_attr) == (("ep",), None, None)

    def test_custom_order_without_ep_raises(self):
        from paddle_tpu.distributed.fleet.base.topology import \
            HybridCommunicateGroup

        with pytest.raises(ValueError, match="'ep' axis"):
            HybridCommunicateGroup(
                dp_degree=2, ep_degree=2,
                order=["dp", "pp", "sharding", "sep", "mp"])


# ---------------------------------------------------------------------------
# gate correctness vs a numpy reference
# ---------------------------------------------------------------------------
def _np_topk_dispatch(probs, k, cap):
    """Independent numpy re-derivation of the dense GShard dispatch."""
    T, E = probs.shape
    masks, gates = [], []
    remaining = probs.copy()
    for _ in range(k):
        idx = remaining.argmax(-1)
        m = np.zeros((T, E), probs.dtype)
        m[np.arange(T), idx] = 1.0
        masks.append(m)
        gates.append((probs * m).sum(-1))
        remaining = remaining * (1.0 - m)
    density = masks[0].mean(0)
    aux = float((density * probs.mean(0)).sum() * E)
    denom = sum(gates) + 1e-9
    combine = np.zeros((T, E, cap), probs.dtype)
    offset = np.zeros(E, probs.dtype)
    for m, gate in zip(masks, gates):
        pos = np.cumsum(m, axis=0) - m + offset[None, :]
        pos_t = (pos * m).sum(-1)
        keep = ((pos_t < cap) & (m.sum(-1) > 0)).astype(probs.dtype)
        gate_k = gate / denom * keep
        for t in range(T):
            if keep[t]:
                e = int(m[t].argmax())
                combine[t, e, int(pos_t[t])] += gate_k[t]
        offset = offset + m.sum(0)
    dispatch = (combine > 0).astype(probs.dtype)
    return combine, dispatch, aux


class TestGateNumpyParity:
    @pytest.mark.parametrize("k,cap", [(1, 3), (2, 4), (2, 64)])
    def test_topk_dispatch_matches_numpy(self, k, cap):
        r = np.random.RandomState(0)
        logits = r.randn(24, 6).astype("float32")
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        cj, dj, aj = _topk_dispatch(jnp.asarray(probs), k, cap)
        cn, dn, an = _np_topk_dispatch(probs, k, cap)
        np.testing.assert_allclose(np.asarray(cj), cn, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_array_equal(np.asarray(dj) > 0, dn > 0)
        assert float(aj) == pytest.approx(an, rel=1e-5)

    def test_capacity_overflow_drops_in_arrival_order(self):
        # all tokens route to expert 0; cap=2 keeps the first two and
        # drops the rest (GShard queue position = cumulative count)
        probs = np.tile(np.asarray([[0.9, 0.1]], "float32"), (5, 1))
        combine, dispatch, _ = _topk_dispatch(jnp.asarray(probs), 1, 2)
        d = np.asarray(dispatch)
        assert d[:2, 0].sum() == 2          # first two tokens kept
        assert d[2:].sum() == 0             # later arrivals dropped
        # kept tokens occupy distinct capacity slots
        assert np.asarray(combine)[0, 0, 0] > 0
        assert np.asarray(combine)[1, 0, 1] > 0

    def test_tie_handling_matches_numpy_argmax(self):
        # exact ties pick the lowest expert index (argmax convention),
        # and the top-2 pick is the next tied expert, in both impls
        probs = np.asarray([[0.4, 0.4, 0.2],
                            [0.3, 0.3, 0.3]], "float32")
        cj, dj, _ = _topk_dispatch(jnp.asarray(probs), 2, 4)
        cn, dn, _ = _np_topk_dispatch(probs, 2, 4)
        np.testing.assert_array_equal(np.asarray(dj) > 0, dn > 0)
        d = np.asarray(dj)
        assert d[0, 0].sum() > 0 and d[0, 1].sum() > 0  # experts 0+1
        assert d[1, 0].sum() > 0 and d[1, 1].sum() > 0

    def test_switch_top1_is_k1(self):
        r = np.random.RandomState(1)
        probs = np.exp(r.randn(10, 4)).astype("float32")
        probs /= probs.sum(-1, keepdims=True)
        _, dispatch, _ = _topk_dispatch(jnp.asarray(probs), 1, 64)
        # top-1: each token occupies at most one (expert, slot)
        assert np.asarray(dispatch).sum(axis=(1, 2)).max() == 1


# ---------------------------------------------------------------------------
# capacity bucketing (core/bucketing lattice)
# ---------------------------------------------------------------------------
class TestCapacityBucketing:
    def test_caps_land_on_lattice(self):
        paddle.seed(0)
        moe = MoELayer(8, d_hidden=16, num_experts=8, gate="gshard",
                       group=False)
        caps = {T: moe._capacity(T) for T in range(8, 512, 8)}
        for T, cap in caps.items():
            assert cap <= T
            assert cap & (cap - 1) == 0, (T, cap)  # power of two
        # jittering T mints only a logarithmic number of capacities
        assert len(set(caps.values())) <= 8

    def test_naive_gate_keeps_full_capacity(self):
        paddle.seed(0)
        moe = MoELayer(8, d_hidden=16, num_experts=4, gate="naive",
                       group=False)
        assert moe._capacity(100) == 100   # no drops, no bucketing


# ---------------------------------------------------------------------------
# on-mesh parity vs the single-device dense-dispatch golden (WITH drops)
# ---------------------------------------------------------------------------
class TestMeshParity:
    def _losses(self, async_dispatch, steps=3):
        hcg, _ = _init_ep(dp=1, ep=4, mp=1, moe_configs={
            "ep_async_dispatch": async_dispatch})
        paddle.seed(7)
        d, h, E = 8, 16, 8
        model = MoELayer(d, d_hidden=h, num_experts=E, gate="gshard")
        # a tight capacity factor so the parity run actually drops
        # tokens (the gate asserts drop_rate > 0 below)
        model.gate.capacity_factor = 0.5
        assert model.world_size == 4
        state = {k: np.asarray(v._value)
                 for k, v in model.state_dict().items()}

        np.random.seed(3)
        x = np.random.randn(16, 4, d).astype("float32")
        y = np.random.randn(16, 4, d).astype("float32")

        def loss_fn(m, batch):
            out = m(batch["x"])
            return paddle.mean((out - batch["y"]) ** 2) \
                + 0.01 * m.aux_loss

        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=model.parameters())
        eng = ParallelEngine(model, opt, hcg.mesh)
        step = eng.train_step(loss_fn)
        batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}
        losses = [float(step(batch)) for _ in range(steps)]
        compiles_warm = eng.stats.compiles
        losses.append(float(step(batch)))
        # the acceptance gate: steady state is recompile-free
        assert eng.stats.compiles == compiles_warm
        params = {n: np.asarray(p._value)
                  for n, p in model.named_parameters()}
        return state, x, y, losses, params, eng

    def test_gshard_parity_with_drops(self):
        state, x, y, losses, params, eng = self._losses(False)

        # golden: the dense single-device MoE applied per batch SHARD
        # (same per-rank token count -> same capacity bucket -> the
        # same GShard queue/drop decisions), losses averaged like the
        # engine's pmean. Trained with plain Adam: its grads are the
        # mean over shards, exactly the engine's grad semantics.
        paddle.seed(7)
        golden = MoELayer(8, d_hidden=16, num_experts=8, gate="gshard",
                          group=False)
        golden.gate.capacity_factor = 0.5
        golden.set_state_dict({k: paddle.to_tensor(v)
                               for k, v in state.items()})
        g_opt = paddle.optimizer.Adam(learning_rate=0.05,
                                      parameters=golden.parameters())
        shards = 4
        Bl = x.shape[0] // shards

        g_losses = []
        for _ in range(len(losses)):
            total = None
            for i in range(shards):
                xb = paddle.to_tensor(x[i * Bl:(i + 1) * Bl])
                yb = paddle.to_tensor(y[i * Bl:(i + 1) * Bl])
                out = golden(xb)
                li = paddle.mean((out - yb) ** 2) \
                    + 0.01 * golden.aux_loss
                total = li if total is None else total + li
            total = total / shards
            total.backward()
            g_opt.step()
            g_opt.clear_grad()
            g_losses.append(float(total))

        np.testing.assert_allclose(losses, g_losses, rtol=1e-5,
                                   atol=1e-6)
        for n, pg in golden.named_parameters():
            np.testing.assert_allclose(params[n], np.asarray(pg._value),
                                       rtol=1e-5, atol=1e-5, err_msg=n)
        # the test must actually exercise capacity drops
        snap = eng.metrics_snapshot()["metrics"]
        drop = snap["paddle_tpu_moe_token_drop_rate"]["series"][0]
        assert drop["value"] > 0, "config did not drop any token"

    def test_async_dispatch_ring_matches_unfused(self):
        s0, x0, y0, l0, p0, _ = self._losses(False)
        s1, x1, y1, l1, p1, eng = self._losses(True)
        np.testing.assert_array_equal(x0, x1)
        for k in s0:
            np.testing.assert_array_equal(s0[k], s1[k])
        np.testing.assert_allclose(l0, l1, rtol=1e-6, atol=1e-7)
        for n in p0:
            np.testing.assert_allclose(p0[n], p1[n], rtol=1e-6,
                                       atol=1e-7, err_msg=n)
        # the fused program rides ppermute rings, not all_to_all
        led = eng.comm_ledger()
        assert led.ops_for(axis="ep", op="all_to_all") == 0
        assert led.ops_for(axis="ep", op="ppermute") > 0


# ---------------------------------------------------------------------------
# GPT-MoE end-to-end on the TP x EP x DP mesh (the bench config)
# ---------------------------------------------------------------------------
class TestGptMoeHybrid:
    def test_trains_with_ring_and_matches_golden_first_step(self):
        from paddle_tpu.models import (GPTForCausalLM,
                                       GPTPretrainingCriterion,
                                       gpt_moe_tiny)

        cfg = gpt_moe_tiny()
        # golden BEFORE fleet.init: plain layers, dense dispatch
        paddle.seed(0)
        golden = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion(cfg)

        hcg, _ = _init_ep(dp=2, ep=2, mp=2,
                          moe_configs={"ep_async_dispatch": True})
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        eng = ParallelEngine(model, opt, hcg.mesh)

        def loss_fn(m, b):
            return crit(m(b["x"]), b["y"]) + m.aux_loss

        step = eng.train_step(loss_fn)
        r = np.random.RandomState(0)
        B, S = 8, 16
        ids = r.randint(0, cfg.vocab_size, (B, S + 1))
        x, y = ids[:, :-1], ids[:, 1:]
        batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}

        # golden loss = mean over the (dp x ep) batch shards of the
        # dense model's loss (same per-shard token count -> identical
        # capacity/drop decisions)
        shards, Bl = 4, B // 4
        g = np.mean([float(loss_fn(golden, {
            "x": paddle.to_tensor(x[i * Bl:(i + 1) * Bl]),
            "y": paddle.to_tensor(y[i * Bl:(i + 1) * Bl])}))
            for i in range(shards)])
        loss0 = float(step(batch))
        assert abs(loss0 - g) <= 1e-5, (loss0, g)

        compiles_warm = eng.stats.compiles
        losses = [float(step(batch)) for _ in range(3)]
        assert eng.stats.compiles == compiles_warm  # 0 recompiles
        assert losses[-1] < loss0                   # it trains
        # expert traffic rode the 'ep' axis (ring form)
        led = eng.comm_ledger()
        assert led.bytes_for(axis="ep", op="ppermute") > 0


# ---------------------------------------------------------------------------
# telemetry gauges through the compiled step
# ---------------------------------------------------------------------------
class TestMoeGauges:
    def test_gauges_present_and_schema_valid(self):
        import json

        from paddle_tpu import observability as obs
        from paddle_tpu.observability import catalog

        obs.reset_registry()
        hcg, _ = _init_ep(dp=2, ep=2, mp=2)
        paddle.seed(0)
        moe = MoELayer(8, d_hidden=16, num_experts=4, gate="gshard")
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=moe.parameters())
        eng = ParallelEngine(moe, opt, hcg.mesh)
        step = eng.train_step(
            lambda m, b: paddle.mean(m(b["x"]) ** 2) + 0.01 * m.aux_loss)
        r = np.random.RandomState(0)
        batch = {"x": paddle.to_tensor(
            r.randn(8, 4, 8).astype("float32"))}
        float(step(batch))
        float(step(batch))
        snap = eng.metrics_snapshot()["metrics"]
        with open(catalog.SCHEMA_PATH) as f:
            schema = json.load(f)
        loads = snap["paddle_tpu_moe_expert_load"]["series"]
        assert {row["labels"]["expert"] for row in loads} == \
            {"0", "1", "2", "3"}
        assert sum(row["value"] for row in loads) == pytest.approx(1.0)
        for name in ("paddle_tpu_moe_expert_load",
                     "paddle_tpu_moe_token_drop_rate",
                     "paddle_tpu_moe_aux_loss"):
            assert name in schema
            for row in snap[name]["series"]:
                assert sorted(row["labels"]) == schema[name]["labels"]
        assert snap["paddle_tpu_moe_aux_loss"]["series"][0]["value"] > 0
        # the ledger publishes the ep axis into the comm counters
        assert eng._metrics["comm_bytes"].value(
            axis="ep", op="all_to_all") > 0


# ---------------------------------------------------------------------------
# moe_utils: uniform-count error + a2a round-trip gradient
# ---------------------------------------------------------------------------
class TestMoeUtils:
    def test_non_uniform_counts_error_is_actionable(self):
        from paddle_tpu.distributed.utils.moe_utils import global_scatter

        g = C.new_group(axis_names=("ep",), nranks=4, name="ep_err")
        x = paddle.to_tensor(np.zeros((8, 4), "float32"))
        with C.spmd_region():
            with pytest.raises(Exception) as ei:
                global_scatter(x, local_count=[3, 1, 2, 2], group=g)
        msg = str(ei.value)
        assert "non-uniform per-rank token counts" in msg
        assert "[3, 1, 2, 2]" in msg          # what was seen
        assert "uniform-slot" in msg          # what the layout requires
        assert "capacity" in msg and "MoELayer" in msg  # the fix

    def test_uniform_and_none_counts_pass(self):
        from paddle_tpu.distributed.utils.moe_utils import _check_uniform

        _check_uniform(None, 4, "global_scatter")
        _check_uniform([2, 2, 2, 2], 4, "global_scatter")
        _check_uniform(paddle.to_tensor(np.asarray([5, 5])), 2,
                       "global_gather")

    def test_roundtrip_grad_is_identity(self):
        """grad of global_gather(global_scatter(x)) == grad without the
        a2a pair: the round trip is the identity permutation, and the
        recorded backward is the reverse a2a pair."""
        from paddle_tpu.distributed.utils.moe_utils import (
            global_gather, global_scatter)

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("ep",))
        g = C.new_group(axis_names=("ep",), nranks=8, name="ep_rt")
        E, Cap, d = 8, 2, 4
        r = np.random.RandomState(0)
        xv = jnp.asarray(r.randn(E, Cap, d), jnp.float32)
        wv = jnp.asarray(r.randn(d), jnp.float32)

        def f(xv, wv, roundtrip):
            with C.spmd_region():
                x = Tensor(xv, stop_gradient=False)
                w = Tensor(wv, stop_gradient=False)
                h = x * w
                if roundtrip:
                    h = global_scatter(h, group=g)
                    h = global_gather(h, group=g)
                loss = paddle.mean(h * h)
                loss.backward()
                return loss._value, x.grad._value, w.grad._value

        rt = jax.jit(_shard_map(lambda a, b: f(a, b, True), mesh,
                                (P(), P()), (P(), P(), P())))
        plain = jax.jit(_shard_map(lambda a, b: f(a, b, False), mesh,
                                   (P(), P()), (P(), P(), P())))
        lr, gxr, gwr = rt(xv, wv)
        lp, gxp, gwp = plain(xv, wv)
        assert float(lr) == pytest.approx(float(lp), rel=1e-6)
        np.testing.assert_allclose(np.asarray(gxr), np.asarray(gxp),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(gwr), np.asarray(gwp),
                                   rtol=1e-6, atol=1e-7)

    def test_roundtrip_values_2d_form(self):
        """[E*C, d] squeeze form round-trips to the identity too."""
        from paddle_tpu.distributed.utils.moe_utils import (
            global_gather, global_scatter)

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("ep",))
        g = C.new_group(axis_names=("ep",), nranks=8, name="ep_rt2")
        r = np.random.RandomState(1)
        xv = jnp.asarray(r.randn(16, 4), jnp.float32)

        def f(xv):
            with C.spmd_region():
                x = Tensor(xv, stop_gradient=True)
                return global_gather(global_scatter(x, group=g),
                                     group=g)._value

        out = jax.jit(_shard_map(f, mesh, (P(),), P()))(xv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(xv),
                                   rtol=1e-6)
