"""Elastic recovery end-to-end (VERDICT item 8; reference:
fleet/elastic/manager.py:237-264 — scale-in detection -> launcher
restart -> resume). A 2-process dp pod loses a rank mid-run; jax's
coordination service fatally takes down the surviving rank with it, so
recovery is launcher-shaped exactly like the reference: the launcher
(played here by this test, in production distributed/launch/main.py's
pod watcher or the TCPStore ElasticManager across hosts) sees the
children die, relaunches with the new world, and the relaunched job
reshard-loads the sharded checkpoint (params + AdamW moments + step) —
the loss curve must CONTINUE exactly where an uninterrupted run would
be."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_WORKER = os.path.join(_REPO, "tests", "workers", "elastic_worker.py")

RESTART_RC = 3


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(rank, world, port, extra):
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PADDLE_", "JAX_", "XLA_")):
            del env[k]
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["JAX_PLATFORMS"] = "cpu"
    env["OMP_NUM_THREADS"] = "1"
    env["OPENBLAS_NUM_THREADS"] = "1"
    env["PADDLE_TRAINER_ID"] = str(rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    env["PADDLE_MASTER"] = f"127.0.0.1:{port}"
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _run_once(world, extra, timeout):
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, _WORKER],
        env=_env(rank, world, port, extra), cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(world)]
    try:
        rcs, logs = [], []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            rcs.append(p.returncode)
            logs.append(out.decode(errors="replace")[-3000:])
        return rcs, logs
    except subprocess.TimeoutExpired:
        from utils import kill_and_reap

        kill_and_reap(procs)
        raise


def _run(world, extra, timeout=600):
    # one retry: under heavy CI load the survivor rank can stall on the
    # dead peer's coordination channel past the worker timeout instead
    # of failing fast (observed once in 10 loaded runs); each phase is
    # self-contained, so a clean re-run is equivalent
    from utils import retry_once

    return retry_once(lambda: _run_once(world, extra, timeout))


def test_scale_in_detect_and_resume(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    total, save_every, die_at = 8, 2, 4

    # phase 1: 2-proc dp pod; rank 1 dies at step 4 (checkpoint at 4
    # is already on disk); rank 0 detects and exits RESTART
    rcs, logs = _run(2, {"CKPT_DIR": ckpt, "TOTAL_STEPS": total,
                         "SAVE_EVERY": save_every, "DIE_AT": die_at,
                         "TEST_OUT": str(tmp_path / "p1")})
    assert rcs[1] == 17, logs[1]
    assert rcs[0] != 0, logs[0]  # survivor goes down with the pod
    with open(str(tmp_path / "p1") + ".0.log") as f:
        p1_losses = [float(l) for l in f.read().split()]
    assert len(p1_losses) >= die_at  # progress up to the kill is on disk
    p1_losses = p1_losses[:die_at]

    # phase 2: relaunched world=1 resumes from the checkpoint
    rcs, logs = _run(1, {"CKPT_DIR": ckpt, "TOTAL_STEPS": total,
                         "SAVE_EVERY": 100, "RESUME": "1",
                         "TEST_OUT": str(tmp_path / "p2")})
    assert rcs == [0], logs[0]
    with open(str(tmp_path / "p2") + ".0") as f:
        assert json.load(f)["start"] == die_at
    with open(str(tmp_path / "p2") + ".0.log") as f:
        p2_losses = [float(l) for l in f.read().split()]

    # golden: uninterrupted world=1 run of the same schedule
    gckpt = str(tmp_path / "gold_ckpt")
    rcs, logs = _run(1, {"CKPT_DIR": gckpt, "TOTAL_STEPS": total,
                         "SAVE_EVERY": 100,
                         "TEST_OUT": str(tmp_path / "gold")})
    assert rcs == [0], logs[0]
    with open(str(tmp_path / "gold") + ".0.log") as f:
        gold_losses = [float(l) for l in f.read().split()]

    # pre-kill pod losses match the golden (dp2 == dp1 on the same
    # global batch), and the resumed run CONTINUES the golden curve —
    # params, AdamW moments and step count all survived the reshard
    np.testing.assert_allclose(p1_losses, gold_losses[:die_at],
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(p2_losses, gold_losses[die_at:],
                               rtol=2e-4, atol=2e-5)
