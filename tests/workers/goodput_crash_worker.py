"""Goodput-journal crash worker: train with rolling checkpoints and a
crash-durable goodput ledger, optionally dying at an armed failpoint
(PADDLE_TPU_FAILPOINTS, e.g. "ckpt.write_shard=kill@2"); on relaunch,
auto-resume from the newest COMMITTED checkpoint and CONTINUE the same
goodput journal (the dangling segment the kill left behind is closed
as recovery_restart).

Env: CKPT_BASE, TOTAL_STEPS, SAVE_EVERY, TEST_OUT, HYBRID (1 = the
gpt13b smoke topology mp2 x pp2 x sharding2 on 8 virtual devices —
export XLA_FLAGS accordingly), SAVE_ASYNC, KEEP_LAST_K.

On clean completion <TEST_OUT>.json records {"start": resumed-from
step, "goodput": <ledger summary>, "compiles": engine XLA compiles}.
Losses stream to <TEST_OUT>.log one per line (flushed per step).
"""
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=1")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.distributed.checkpoint import (CheckpointManager,  # noqa: E402
                                               latest_committed)
from paddle_tpu.observability import goodput  # noqa: E402


def _build_simple():
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import (GPTForCausalLM,
                                   GPTPretrainingCriterion, gpt_tiny)

    paddle.seed(42)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
    eng = ParallelEngine(model, opt)
    step_fn = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    return cfg, eng, None, step_fn, 8


def _build_hybrid():
    """The gpt13b smoke topology (mp2 x pp2 x sharding2, vpp2)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    paddle.seed(42)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
        "pp_configs": {"num_virtual_pipeline_stages": 2}}
    strategy.sharding_configs = {"stage": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_position_embeddings=32)
    model = GPTForCausalLMPipe(cfg)
    dm = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters()))

    def step_fn(batch):
        return dm.train_batch([batch["x"], batch["y"]], opt)

    return cfg, dm, opt, step_fn, 8


def batch(step, B, S, V):
    r = np.random.RandomState(1000 + step)
    ids = r.randint(0, V, (B, S + 1))
    return {"x": paddle.to_tensor(ids[:, :-1]),
            "y": paddle.to_tensor(ids[:, 1:])}


def main():
    out = os.environ["TEST_OUT"]
    base = os.environ["CKPT_BASE"]
    total = int(os.environ.get("TOTAL_STEPS", "10"))
    save_every = int(os.environ.get("SAVE_EVERY", "2"))
    async_save = os.environ.get("SAVE_ASYNC", "") == "1"
    keep = int(os.environ.get("KEEP_LAST_K", "2"))
    hybrid = os.environ.get("HYBRID", "") == "1"

    # the journal FIRST: a relaunch closes the killed run's dangling
    # segment as recovery_restart before anything else books time
    led = goodput.attach_dir(base)

    if hybrid:
        cfg, eng, opt, step_fn, B = _build_hybrid()
    else:
        cfg, eng, opt, step_fn, B = _build_simple()

    start = 0
    latest = latest_committed(base)
    if latest is not None:
        # the hybrid wrapper builds its engine lazily: restoring
        # before the first train_batch needs the optimizer
        meta = (eng.restore_checkpoint(latest, optimizer=opt)
                if hybrid else eng.restore_checkpoint(latest))
        start = int(meta["step"])

    mgr = CheckpointManager(base, keep_last_k=keep,
                            async_save=async_save)
    log = open(f"{out}.log", "a")
    S, V = 16, cfg.vocab_size
    for step in range(start, total):
        with goodput.segment("input_wait"):
            b = batch(step, B, S, V)
        loss = step_fn(b)
        log.write(f"{float(loss)!r}\n")
        log.flush()
        if (step + 1) % save_every == 0 and step + 1 < total:
            eng.save_checkpoint(manager=mgr, step=step + 1)
    mgr.wait()
    mgr.close()
    log.close()
    stats = (eng._engine.stats if hybrid and eng._engine is not None
             else getattr(eng, "stats", None))
    compiles = stats.compiles if stats is not None else None
    with open(f"{out}.json", "w") as f:
        json.dump({"start": start, "goodput": led.summary(),
                   "compiles": compiles}, f)


if __name__ == "__main__":
    main()
