"""RPC worker: one ranked process of a 2-worker RPC pod (reference
test/legacy_test/test_rpc* pattern). Exercises rpc_sync/rpc_async/
worker infos/remote exceptions over the TCPStore agent."""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from paddle_tpu.distributed import rpc  # noqa: E402


def add(a, b):
    return a + b


def whoami():
    return os.environ.get("PADDLE_TRAINER_ID")


def boom():
    raise ValueError("remote boom")


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    out = os.environ["TEST_OUT"]
    rpc.init_rpc(name=f"worker{rank}")
    result = {}
    peer = f"worker{1 - rank}"
    result["sync"] = rpc.rpc_sync(peer, add, args=(rank, 10))
    futs = [rpc.rpc_async(peer, add, args=(i, i)) for i in range(4)]
    result["async"] = [f.wait() for f in futs]
    result["peer_rank"] = rpc.get_worker_info(peer).rank
    result["all"] = sorted(w.name for w in rpc.get_all_worker_infos())
    try:
        rpc.rpc_sync(peer, boom)
        result["exc"] = "none"
    except ValueError as e:
        result["exc"] = str(e)
    result["self_env"] = rpc.rpc_sync(peer, whoami)
    with open(f"{out}.{rank}", "w") as f:
        json.dump(result, f)
    rpc.shutdown()


if __name__ == "__main__":
    main()
