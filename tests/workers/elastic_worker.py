"""Elastic-recovery worker: train with periodic sharded checkpoints,
optionally die mid-run (scale-in) or resume from a checkpoint with a
DIFFERENT world size (env: PADDLE_TRAINER_ID/TRAINERS_NUM/MASTER,
CKPT_DIR, TOTAL_STEPS, SAVE_EVERY, DIE_AT, RESUME, TEST_OUT)."""
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=1")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402
from paddle_tpu.distributed.engine import ParallelEngine  # noqa: E402
from paddle_tpu.distributed.fleet.elastic import (  # noqa: E402
    load_train_state, save_train_state)
from paddle_tpu.models import (GPTForCausalLM,  # noqa: E402
                               GPTPretrainingCriterion, gpt_tiny)

def global_batch(step, B, S, V):
    r = np.random.RandomState(1000 + step)
    ids = r.randint(0, V, (B, S + 1))
    return ids[:, :-1], ids[:, 1:]


def main():
    out_path = os.environ["TEST_OUT"]
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    ckpt = os.environ["CKPT_DIR"]
    total = int(os.environ.get("TOTAL_STEPS", "10"))
    save_every = int(os.environ.get("SAVE_EVERY", "2"))
    die_at = int(os.environ.get("DIE_AT", "-1"))
    resume = os.environ.get("RESUME", "") == "1"

    dist.init_parallel_env()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": world, "mp_degree": 1,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(42)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())

    start = 0
    if resume:
        meta = load_train_state(ckpt, model, opt)
        start = int(meta["step"])

    eng = ParallelEngine(model, opt, hcg.mesh)
    step_fn = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))

    # losses stream to disk per step: when a peer dies, jax's
    # coordination service FATALLY terminates the survivors too (the
    # whole pod restarts — which is exactly the launcher-level recovery
    # flow), so progress must be readable after a crash
    log = open(f"{out_path}.{rank}.log", "w")

    B, S, V = 8, 16, cfg.vocab_size
    for step in range(start, total):
        if die_at >= 0 and step == die_at and rank == world - 1 \
                and world > 1:
            os._exit(17)  # scale-in: this rank vanishes without goodbye
        x, y = global_batch(step, B, S, V)
        if world > 1:
            lo, hi = rank * B // world, (rank + 1) * B // world
            x, y = x[lo:hi], y[lo:hi]
        loss = step_fn({"x": paddle.to_tensor(x),
                        "y": paddle.to_tensor(y)})
        log.write(f"{float(loss)!r}\n")
        log.flush()
        if (step + 1) % save_every == 0 and step + 1 < total:
            save_train_state(ckpt, model, opt, step=step + 1)

    log.close()
    with open(f"{out_path}.{rank}", "w") as f:
        json.dump({"rank": rank, "start": start}, f)


if __name__ == "__main__":
    main()
