"""Trainer worker for the multi-process runtime tests.

Run as one ranked process of a pod (env: PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_MASTER, TEST_DP, TEST_OUT). Trains GPT-tiny
for a few steps under the ParallelEngine over a dp mesh that may span
processes (jax.distributed over the native TCPStore), then exercises the
host-side object collectives and p2p. The parent test asserts loss
parity between a 1-process and a 2-process run of the same global batch
(the reference's TestDistBase._run_cluster_gloo loss-parity pattern,
test/legacy_test/test_dist_base.py:959).
"""
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=1")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402
from paddle_tpu.distributed.engine import ParallelEngine  # noqa: E402
from paddle_tpu.models import (GPTForCausalLM,  # noqa: E402
                               GPTPretrainingCriterion, gpt_tiny)


def main():
    out_path = os.environ["TEST_OUT"]
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    dp = int(os.environ.get("TEST_DP", "2"))

    dist.init_parallel_env()
    assert len(jax.devices()) >= dp, \
        f"global devices {len(jax.devices())} < dp {dp}"

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(42)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))

    B, S, V = 8, 16, cfg.vocab_size
    r = np.random.RandomState(0)
    losses = []
    for _ in range(3):
        ids = r.randint(0, V, (B, S + 1))
        x, y = ids[:, :-1], ids[:, 1:]
        if world > 1:
            lo, hi = rank * B // world, (rank + 1) * B // world
            x, y = x[lo:hi], y[lo:hi]
        loss = step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)})
        losses.append(float(loss))

    result = {"rank": rank, "losses": losses}
    if world > 1:
        gathered = []
        dist.all_gather_object(gathered, {"rank": rank, "tag": "hello"})
        result["gathered"] = gathered
        objs = [{"payload": 123} if rank == 0 else None]
        dist.broadcast_object_list(objs, src=0)
        result["bcast"] = objs[0]
        if rank == 0:
            dist.send(paddle.to_tensor(
                np.arange(4, dtype=np.float32) + 1.0), dst=1)
        elif rank == 1:
            t = paddle.to_tensor(np.zeros(4, dtype=np.float32))
            dist.recv(t, src=0)
            result["recv"] = np.asarray(t._value).tolist()
        dist.barrier()
    with open(f"{out_path}.{rank}", "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
