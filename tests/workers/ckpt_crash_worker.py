"""Crash-consistency worker: train with rolling atomic checkpoints and
(optionally) die at an armed failpoint mid-save; on relaunch,
auto-resume from the newest COMMITTED checkpoint.

The failpoint table arms itself from PADDLE_TPU_FAILPOINTS in the
environment (e.g. "ckpt.commit=kill@2" SIGKILLs this process during the
second save), so the driving test only sets env vars:
CKPT_BASE, TOTAL_STEPS, SAVE_EVERY, TEST_OUT, SAVE_ASYNC, KEEP_LAST_K,
OFFLOAD (=1 runs the engine with the host-memory offload tier on —
"offload.prefetch=kill@N" then SIGKILLs mid-prefetch, between the
page-out of one step and the dispatch of the next).

Losses stream to <TEST_OUT>.log one per line (flushed per step) so
progress is readable after a SIGKILL; on clean completion
<TEST_OUT>.json records where the run started (0 = cold,
>0 = resumed from that committed step).
"""
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=1")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.distributed.checkpoint import (CheckpointManager,  # noqa: E402
                                               latest_committed)
from paddle_tpu.distributed.engine import ParallelEngine  # noqa: E402
from paddle_tpu.models import (GPTForCausalLM,  # noqa: E402
                               GPTPretrainingCriterion, gpt_tiny)


def batch(step, B, S, V):
    r = np.random.RandomState(1000 + step)
    ids = r.randint(0, V, (B, S + 1))
    return (paddle.to_tensor(ids[:, :-1]),
            paddle.to_tensor(ids[:, 1:]))


def main():
    out = os.environ["TEST_OUT"]
    base = os.environ["CKPT_BASE"]
    total = int(os.environ.get("TOTAL_STEPS", "8"))
    save_every = int(os.environ.get("SAVE_EVERY", "2"))
    async_save = os.environ.get("SAVE_ASYNC", "") == "1"
    keep = int(os.environ.get("KEEP_LAST_K", "2"))

    paddle.seed(42)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
    offload = None
    if os.environ.get("OFFLOAD", "") == "1":
        # single bucket on the plan-less engine -> prefetch hit N is
        # exactly step N's prefetch (deterministic kill placement)
        offload = {"optimizer": True, "prefetch_buckets": 1}
    eng = ParallelEngine(model, opt, offload=offload)
    step_fn = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))

    start = 0
    latest = latest_committed(base)
    if latest is not None:
        meta = eng.restore_checkpoint(latest)
        start = int(meta["step"])

    mgr = CheckpointManager(base, keep_last_k=keep,
                            async_save=async_save)
    log = open(f"{out}.log", "a")
    B, S, V = 8, 16, cfg.vocab_size
    for step in range(start, total):
        x, y = batch(step, B, S, V)
        loss = step_fn({"x": x, "y": y})
        log.write(f"{float(loss)!r}\n")
        log.flush()
        if (step + 1) % save_every == 0 and step + 1 < total:
            eng.save_checkpoint(manager=mgr, step=step + 1)
    mgr.wait()
    mgr.close()
    log.close()
    with open(f"{out}.json", "w") as f:
        json.dump({"start": start}, f)


if __name__ == "__main__":
    main()
