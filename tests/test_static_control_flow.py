"""Data-dependent control flow: static.nn.cond / while_loop / case /
switch_case (reference: python/paddle/static/nn/control_flow.py —
cond:1166, while_loop:1380, case:2310, switch_case:2517; the same
capability the reference's dy2static/SOT tracer provides for implicit
Python branching, python/paddle/jit/sot/).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


def _np(t):
    return np.asarray(t._value)


class TestCond:
    def test_basic_branch(self):
        x = paddle.to_tensor(np.float32(3.0))
        out = snn.cond(x > 2.0, lambda: x * 2.0, lambda: x - 1.0)
        assert float(_np(out)) == 6.0
        out = snn.cond(x > 5.0, lambda: x * 2.0, lambda: x - 1.0)
        assert float(_np(out)) == 2.0

    def test_nested_structure(self):
        a = paddle.to_tensor(np.arange(4, dtype="float32"))
        out = snn.cond(paddle.to_tensor(True),
                       lambda: (a + 1.0, {"k": a * 2.0}),
                       lambda: (a - 1.0, {"k": a / 2.0}))
        assert (_np(out[0]) == np.arange(4) + 1).all()
        assert (_np(out[1]["k"]) == np.arange(4) * 2).all()

    def test_mismatched_branches_raise(self):
        x = paddle.to_tensor(np.float32(1.0))
        with pytest.raises(Exception, match="same structure|shape"):
            snn.cond(x > 0, lambda: (x, x), lambda: x)

    def test_single_branch_concrete(self):
        hits = []
        snn.cond(paddle.to_tensor(True), lambda: hits.append(1))
        snn.cond(paddle.to_tensor(False), lambda: hits.append(2))
        assert hits == [1]


class TestWhileLoop:
    def test_counter(self):
        i = paddle.to_tensor(np.int32(0))
        s = paddle.to_tensor(np.float32(0.0))
        i_out, s_out = snn.while_loop(
            lambda i, s: i < 10,
            lambda i, s: (i + 1, s + 2.0), [i, s])
        assert int(_np(i_out)) == 10
        assert float(_np(s_out)) == 20.0

    def test_tensor_carried_shape(self):
        x = paddle.to_tensor(np.ones((2, 3), "float32"))
        n = paddle.to_tensor(np.int32(0))
        n_out, x_out = snn.while_loop(
            lambda n, x: n < 4,
            lambda n, x: (n + 1, x * 2.0), [n, x])
        assert (_np(x_out) == 16.0).all()

    def test_shape_change_raises(self):
        x = paddle.to_tensor(np.ones((3,), "float32"))
        n = paddle.to_tensor(np.int32(0))
        with pytest.raises(Exception, match="invariant|shape"):
            snn.while_loop(lambda n, x: n < 2,
                           lambda n, x: (n + 1, paddle.concat([x, x])),
                           [n, x])


class TestCaseSwitch:
    def test_case_first_true_wins(self):
        x = paddle.to_tensor(np.float32(0.3))
        out = snn.case([(x < 0.1, lambda: x * 1.0),
                        (x < 0.5, lambda: x * 10.0)],
                       default=lambda: x * 100.0)
        assert abs(float(_np(out)) - 3.0) < 1e-6

    def test_case_default(self):
        x = paddle.to_tensor(np.float32(0.9))
        out = snn.case([(x < 0.1, lambda: x * 1.0),
                        (x < 0.5, lambda: x * 10.0)],
                       default=lambda: x * 100.0)
        assert abs(float(_np(out)) - 90.0) < 1e-4

    def test_switch_case(self):
        one = paddle.to_tensor(np.float32(1.0))
        fns = {1: lambda: one * 10.0, 3: lambda: one * 30.0}
        out = snn.switch_case(paddle.to_tensor(np.int32(3)), fns,
                              default=lambda: one * -1.0)
        assert float(_np(out)) == 30.0
        out = snn.switch_case(paddle.to_tensor(np.int32(7)), fns,
                              default=lambda: one * -1.0)
        assert float(_np(out)) == -1.0


class TestUnderToStatic:
    """The dy2static scenario: tensor-valued loops/branches INSIDE a
    compiled function (reference test style: dygraph_to_static loop
    tests)."""

    def test_while_loop_traces(self):
        @paddle.jit.to_static
        def collatz_steps(x):
            n = paddle.to_tensor(np.int32(0))
            def body(v, n):
                nxt = snn.cond((v % 2) == 0,
                               lambda: v // 2, lambda: 3 * v + 1)
                return nxt, n + 1
            v, n = snn.while_loop(lambda v, n: v > 1, body,
                                  [x, n])
            return n

        out = collatz_steps(paddle.to_tensor(np.int32(6)))
        # 6 -> 3 -> 10 -> 5 -> 16 -> 8 -> 4 -> 2 -> 1 : 8 steps
        assert int(_np(out)) == 8

    def test_python_branch_error_points_to_cond(self):
        @paddle.jit.to_static
        def bad(x):
            if x > 0:           # Python branch on a traced tensor
                return x * 2.0
            return x

        with pytest.raises(TypeError, match="static.nn.cond"):
            bad(paddle.to_tensor(np.float32(1.0)))

    def test_cond_inside_compiled_step(self):
        @paddle.jit.to_static
        def clipped_double(x):
            return snn.cond(x.sum() > 0.0,
                            lambda: x * 2.0,
                            lambda: x * 0.0)

        x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
        assert (_np(clipped_double(x)) == [2.0, 4.0]).all()
        y = paddle.to_tensor(np.array([-1.0, -2.0], "float32"))
        assert (_np(clipped_double(y)) == [0.0, 0.0]).all()


class TestProgramRecordingGate:
    """Declare-then-run Programs replay a flat op list — control-flow
    regions cannot be recorded; every entry path must fail LOUDLY
    (pointing at to_static) and must not corrupt the live Program."""

    def test_symbolic_predicate(self):
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            with pytest.raises(Exception, match="to_static"):
                snn.cond(x.sum() > 0, lambda: x * 2.0, lambda: x * 0.0)

    def test_closure_captured_variable(self):
        """Concrete predicate + Variables only inside branch closures —
        the common static-mode pattern — must also gate, without
        recording branch ops into the Program."""
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            with pytest.raises(Exception, match="to_static"):
                snn.cond(paddle.to_tensor(True),
                         lambda: x * 2.0, lambda: x * 0.0)
            assert len(main._nodes) == 0  # no corruption

    def test_nested_loop_var(self):
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            with pytest.raises(Exception, match="to_static"):
                snn.while_loop(lambda i: i < 2, lambda i: (i + 1,),
                               [[x]])
