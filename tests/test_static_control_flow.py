"""Data-dependent control flow: static.nn.cond / while_loop / case /
switch_case (reference: python/paddle/static/nn/control_flow.py —
cond:1166, while_loop:1380, case:2310, switch_case:2517; the same
capability the reference's dy2static/SOT tracer provides for implicit
Python branching, python/paddle/jit/sot/).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


def _np(t):
    return np.asarray(t._value)


class TestCond:
    def test_basic_branch(self):
        x = paddle.to_tensor(np.float32(3.0))
        out = snn.cond(x > 2.0, lambda: x * 2.0, lambda: x - 1.0)
        assert float(_np(out)) == 6.0
        out = snn.cond(x > 5.0, lambda: x * 2.0, lambda: x - 1.0)
        assert float(_np(out)) == 2.0

    def test_nested_structure(self):
        a = paddle.to_tensor(np.arange(4, dtype="float32"))
        out = snn.cond(paddle.to_tensor(True),
                       lambda: (a + 1.0, {"k": a * 2.0}),
                       lambda: (a - 1.0, {"k": a / 2.0}))
        assert (_np(out[0]) == np.arange(4) + 1).all()
        assert (_np(out[1]["k"]) == np.arange(4) * 2).all()

    def test_mismatched_branches_raise(self):
        x = paddle.to_tensor(np.float32(1.0))
        with pytest.raises(Exception, match="same structure|shape"):
            snn.cond(x > 0, lambda: (x, x), lambda: x)

    def test_single_branch_concrete(self):
        hits = []
        snn.cond(paddle.to_tensor(True), lambda: hits.append(1))
        snn.cond(paddle.to_tensor(False), lambda: hits.append(2))
        assert hits == [1]


class TestWhileLoop:
    def test_counter(self):
        i = paddle.to_tensor(np.int32(0))
        s = paddle.to_tensor(np.float32(0.0))
        i_out, s_out = snn.while_loop(
            lambda i, s: i < 10,
            lambda i, s: (i + 1, s + 2.0), [i, s])
        assert int(_np(i_out)) == 10
        assert float(_np(s_out)) == 20.0

    def test_tensor_carried_shape(self):
        x = paddle.to_tensor(np.ones((2, 3), "float32"))
        n = paddle.to_tensor(np.int32(0))
        n_out, x_out = snn.while_loop(
            lambda n, x: n < 4,
            lambda n, x: (n + 1, x * 2.0), [n, x])
        assert (_np(x_out) == 16.0).all()

    def test_shape_change_raises(self):
        x = paddle.to_tensor(np.ones((3,), "float32"))
        n = paddle.to_tensor(np.int32(0))
        with pytest.raises(Exception, match="invariant|shape"):
            snn.while_loop(lambda n, x: n < 2,
                           lambda n, x: (n + 1, paddle.concat([x, x])),
                           [n, x])


class TestCaseSwitch:
    def test_case_first_true_wins(self):
        x = paddle.to_tensor(np.float32(0.3))
        out = snn.case([(x < 0.1, lambda: x * 1.0),
                        (x < 0.5, lambda: x * 10.0)],
                       default=lambda: x * 100.0)
        assert abs(float(_np(out)) - 3.0) < 1e-6

    def test_case_default(self):
        x = paddle.to_tensor(np.float32(0.9))
        out = snn.case([(x < 0.1, lambda: x * 1.0),
                        (x < 0.5, lambda: x * 10.0)],
                       default=lambda: x * 100.0)
        assert abs(float(_np(out)) - 90.0) < 1e-4

    def test_switch_case(self):
        one = paddle.to_tensor(np.float32(1.0))
        fns = {1: lambda: one * 10.0, 3: lambda: one * 30.0}
        out = snn.switch_case(paddle.to_tensor(np.int32(3)), fns,
                              default=lambda: one * -1.0)
        assert float(_np(out)) == 30.0
        out = snn.switch_case(paddle.to_tensor(np.int32(7)), fns,
                              default=lambda: one * -1.0)
        assert float(_np(out)) == -1.0


class TestUnderToStatic:
    """The dy2static scenario: tensor-valued loops/branches INSIDE a
    compiled function (reference test style: dygraph_to_static loop
    tests)."""

    def test_while_loop_traces(self):
        @paddle.jit.to_static
        def collatz_steps(x):
            n = paddle.to_tensor(np.int32(0))
            def body(v, n):
                nxt = snn.cond((v % 2) == 0,
                               lambda: v // 2, lambda: 3 * v + 1)
                return nxt, n + 1
            v, n = snn.while_loop(lambda v, n: v > 1, body,
                                  [x, n])
            return n

        out = collatz_steps(paddle.to_tensor(np.int32(6)))
        # 6 -> 3 -> 10 -> 5 -> 16 -> 8 -> 4 -> 2 -> 1 : 8 steps
        assert int(_np(out)) == 8

    def test_python_branch_error_points_to_cond(self):
        @paddle.jit.to_static
        def bad(x):
            if x > 0:           # Python branch on a traced tensor
                return x * 2.0
            return x

        with pytest.raises(TypeError, match="static.nn.cond"):
            bad(paddle.to_tensor(np.float32(1.0)))

    def test_cond_inside_compiled_step(self):
        @paddle.jit.to_static
        def clipped_double(x):
            return snn.cond(x.sum() > 0.0,
                            lambda: x * 2.0,
                            lambda: x * 0.0)

        x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
        assert (_np(clipped_double(x)) == [2.0, 4.0]).all()
        y = paddle.to_tensor(np.array([-1.0, -2.0], "float32"))
        assert (_np(clipped_double(y)) == [0.0, 0.0]).all()


class TestProgramRecordingGate:
    """Declare-then-run Programs replay a flat op list — control-flow
    regions cannot be recorded; every entry path must fail LOUDLY
    (pointing at to_static) and must not corrupt the live Program."""

    def test_symbolic_predicate(self):
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            with pytest.raises(Exception, match="to_static"):
                snn.cond(x.sum() > 0, lambda: x * 2.0, lambda: x * 0.0)

    def test_closure_captured_variable(self):
        """Concrete predicate + Variables only inside branch closures —
        the common static-mode pattern — must also gate, without
        recording branch ops into the Program."""
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            with pytest.raises(Exception, match="to_static"):
                snn.cond(paddle.to_tensor(True),
                         lambda: x * 2.0, lambda: x * 0.0)
            assert len(main._nodes) == 0  # no corruption

    def test_nested_loop_var(self):
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            with pytest.raises(Exception, match="to_static"):
                snn.while_loop(lambda i: i < 2, lambda i: (i + 1,),
                               [[x]])


class TestCondGrad:
    """cond IS differentiable (lax.cond supports reverse mode; the
    reference's cond does too): gradients flow to tensors the branch
    closures capture, under the eager tape and under to_static."""

    def test_taken_branch_grad(self):
        w = paddle.to_tensor(np.array([2.0, 3.0], "float32"))
        w.stop_gradient = False
        x = paddle.to_tensor(np.array([1.0, 4.0], "float32"))
        out = snn.cond(paddle.to_tensor(True),
                       lambda: (w * x).sum(), lambda: (w - x).sum())
        assert not out.stop_gradient
        out.backward()
        assert (_np(w.grad) == [1.0, 4.0]).all()

    def test_untaken_branch_grad(self):
        w = paddle.to_tensor(np.array([2.0, 3.0], "float32"))
        w.stop_gradient = False
        x = paddle.to_tensor(np.array([1.0, 4.0], "float32"))
        out = snn.cond(paddle.to_tensor(False),
                       lambda: (w * x).sum(), lambda: (w * w).sum())
        out.backward()
        assert (_np(w.grad) == [4.0, 6.0]).all()

    def test_traced_predicate_grad(self):
        z = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
        z.stop_gradient = False
        loss = snn.cond(z.sum() > 0.0,
                        lambda: (z * z).sum(), lambda: z.sum())
        loss.backward()
        assert (_np(z.grad) == [2.0, 4.0]).all()

    def test_branches_capturing_different_tensors(self):
        a = paddle.to_tensor(np.array(3.0, "float32"))
        b = paddle.to_tensor(np.array(5.0, "float32"))
        a.stop_gradient = False
        b.stop_gradient = False
        out = snn.cond(paddle.to_tensor(True),
                       lambda: a * 2.0, lambda: b * 7.0)
        out.backward()
        # taken branch grad flows; untaken branch's capture gets zero
        assert float(_np(a.grad)) == 2.0
        assert b.grad is None or float(_np(b.grad)) == 0.0

    def test_no_grad_still_works(self):
        with paddle.no_grad():
            x = paddle.to_tensor(np.array([1.0], "float32"))
            out = snn.cond(paddle.to_tensor(True),
                           lambda: x * 2.0, lambda: x * 3.0)
        assert out.stop_gradient
        assert (_np(out) == [2.0]).all()

    def test_chained_into_tape(self):
        """cond output feeds further tape ops; grads route through."""
        w = paddle.to_tensor(np.array([1.0, -2.0], "float32"))
        w.stop_gradient = False
        h = w * 3.0
        out = snn.cond(paddle.to_tensor(True),
                       lambda: h * h, lambda: h)
        loss = out.sum()
        loss.backward()
        # d/dw (3w)^2 = 18w
        assert (_np(w.grad) == [18.0, -36.0]).all()


class TestWhileLoopNonDiff:
    def test_grad_loop_var_raises_loudly(self):
        v = paddle.to_tensor(np.array(0.0, "float32"))
        v.stop_gradient = False
        with pytest.raises(Exception, match="not differentiable"):
            snn.while_loop(lambda a: a < 3.0, lambda a: (a + 1.0,), [v])

    def test_detached_vars_still_run(self):
        v = paddle.to_tensor(np.array(0.0, "float32"))
        v.stop_gradient = False
        (out,) = snn.while_loop(lambda a: a < 3.0, lambda a: (a + 1.0,),
                                [v.detach()])
        assert float(_np(out)) == 3.0

    def test_no_grad_context_still_runs(self):
        v = paddle.to_tensor(np.array(0.0, "float32"))
        v.stop_gradient = False
        with paddle.no_grad():
            (out,) = snn.while_loop(lambda a: a < 2.0,
                                    lambda a: (a + 1.0,), [v])
        assert float(_np(out)) == 2.0
