"""LazyGuard + shard-local materialization (reference:
python/paddle/nn/initializer/lazy_init.py LazyGuard — here each process
materializes only its addressable shard windows, O(shard) bytes)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import (ParallelEngine,
                                           materialize_lazy_params)
from paddle_tpu.framework.lazy_init import LazySpec
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)


def _cfg():
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=64)


def test_lazy_build_has_no_storage():
    with paddle.LazyGuard():
        model = GPTForCausalLM(_cfg())
    assert all(isinstance(p._value, LazySpec) for p in model.parameters())
    # reading values before materialization must fail loudly
    with pytest.raises(RuntimeError, match="LazyGuard"):
        np.asarray(model.parameters()[0]._value)
    # shapes/dtypes visible without storage
    p0 = model.parameters()[0]
    assert p0._value.ndim == len(p0._value.shape)


def test_lazy_engine_trains():
    """LazyGuard model -> ParallelEngine materializes sharded -> loss
    decreases (the 13B-construction path at tiny scale)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    cfg = _cfg()
    with paddle.LazyGuard():
        model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    assert not any(isinstance(p._value, LazySpec)
                   for p in model.parameters())
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 16))
    batch = {"x": paddle.to_tensor(ids), "y": paddle.to_tensor(ids)}
    first = float(step(batch))
    for _ in range(9):
        last = float(step(batch))
    assert np.isfinite(first) and first - last > 0.5, (first, last)


def test_materialize_windows_are_shard_sized(monkeypatch):
    """The scalability property VERDICT item 6 asks for: per-process
    host bytes for a sharded param ~ shard size, not global size."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 4}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    with paddle.LazyGuard():
        model = GPTForCausalLM(_cfg())

    from paddle_tpu.nn import initializer as I
    seen = []
    orig = I._generate_window

    def spy(init, full_shape, window, dtype, key):
        out = orig(init, full_shape, window, dtype, key)
        seen.append((tuple(full_shape), tuple(out.shape)))
        return out

    monkeypatch.setattr(I, "_generate_window", spy)
    import paddle_tpu.distributed.engine as E

    monkeypatch.setattr(E, "_generate_window", spy, raising=False)
    materialize_lazy_params(model, hcg.mesh)
    # mp-sharded params (e.g. qkv ColumnParallel [64, 192]) must be
    # generated in windows of 1/4 the global size, never full size
    sharded = [(f, w) for f, w in seen if f != w]
    assert sharded, "expected at least one sharded-window generation"
    for full, win in sharded:
        full_n = int(np.prod(full))
        win_n = int(np.prod(win))
        assert win_n <= full_n // 4, (full, win)


def test_materialize_deterministic():
    with paddle.LazyGuard():
        m1 = GPTForCausalLM(_cfg())
    with paddle.LazyGuard():
        m2 = GPTForCausalLM(_cfg())
    materialize_lazy_params(m1, None, seed=7)
    materialize_lazy_params(m2, None, seed=7)
    for (n1, p1), (n2, p2) in zip(m1.named_parameters(),
                                  m2.named_parameters()):
        np.testing.assert_array_equal(np.asarray(p1._value),
                                      np.asarray(p2._value), err_msg=n1)
    with paddle.LazyGuard():
        m3 = GPTForCausalLM(_cfg())
    materialize_lazy_params(m3, None, seed=8)
    diff = any(
        not np.array_equal(np.asarray(a._value), np.asarray(b._value))
        for (_, a), (_, b) in zip(m1.named_parameters(),
                                  m3.named_parameters())
        if a.trainable and np.asarray(a._value).std() > 0)
    assert diff, "different seeds must give different params"


def test_lazy_astype_flows_to_materialization():
    """Layer.astype on a lazy model re-dtypes the LazySpecs (the llama
    bf16-at-construction path)."""
    with paddle.LazyGuard():
        model = GPTForCausalLM(_cfg())
        model.astype("bfloat16")
    assert all(p._value.dtype == np.dtype("bfloat16") or
               str(p._value.dtype) == "bfloat16"
               for p in model.parameters())
    materialize_lazy_params(model, None)
    assert all(str(p._value.dtype) == "bfloat16"
               for p in model.parameters())
