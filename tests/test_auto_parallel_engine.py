"""Auto-parallel Engine + per-op SPMD propagation (VERDICT item 5).

The reference proves its planner with program-parity tests
(test/auto_parallel/*); here the proof is loss parity: a PLAIN dense
GPT whose parameters were only shard_tensor'd trains identically to
single-device eager execution — GSPMD inferred every intermediate
sharding and inserted the collectives."""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import (Engine, ProcessMesh,
                                                  Replicate, Shard,
                                                  shard_tensor)
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def _cfg():
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=64)


def _mesh():
    return ProcessMesh(np.arange(8), dim_names=["mp"])


def _megatron_annotate(model, mesh):
    """Megatron-style placement by NAME ONLY — no layer rewrites."""
    for name, p in model.named_parameters():
        nd = p._value.ndim
        if "qkv_proj.weight" in name or "fc1.weight" in name:
            pl = [Shard(1)]
        elif "out_proj.weight" in name or "fc2.weight" in name:
            pl = [Shard(0)]
        elif "word_embeddings.weight" in name:
            pl = [Shard(0)]
        elif "qkv_proj.bias" in name or "fc1.bias" in name:
            pl = [Shard(0)]
        else:
            pl = [Replicate()]
        v = shard_tensor(p, mesh, pl)
        p._value = v._value
        p.dist_attr = v.dist_attr


def _eager_losses(model, crit, ids, lr, steps):
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
    losses = []
    for _ in range(steps):
        loss = crit(model(paddle.to_tensor(ids[:, :-1])),
                    paddle.to_tensor(ids[:, 1:]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def test_engine_matches_single_device():
    """shard_tensor'd params + zero layer rewrites == eager golden."""
    cfg = _cfg()
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 17))
    paddle.seed(21)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)

    snap = [(p, p._value) for p in model.parameters()]
    golden = _eager_losses(model, crit, ids, 1e-3, steps=3)
    for p, v in snap:
        p._value = v
        p.grad = None
        p._grad_node = None

    mesh = _mesh()
    _megatron_annotate(model, mesh)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = Engine(model,
                 loss_fn=lambda m, b: crit(m(b["x"]), b["y"]),
                 optimizer=opt, mesh=mesh)
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    losses = [float(eng.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(losses, golden, rtol=2e-4, atol=2e-5)
    # params stayed physically sharded through the compiled updates
    qkv = [p for n, p in model.named_parameters()
           if "qkv_proj.weight" in n][0]
    assert not qkv._value.sharding.is_fully_replicated


def test_engine_predict_runs_sharded():
    cfg = _cfg()
    paddle.seed(22)
    model = GPTForCausalLM(cfg)
    mesh = _mesh()
    _megatron_annotate(model, mesh)
    eng = Engine(model, mesh=mesh)
    x = paddle.to_tensor(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 16)))
    out = eng.predict(x)
    assert tuple(out._value.shape) == (2, 16, cfg.vocab_size)


def test_spmd_rules_eager_metadata():
    """Eager dist_attr propagation through the dispatch chokepoint
    (reference per-op InferSpmd, phi/infermeta/spmd_rules/)."""
    mesh = _mesh()
    a = shard_tensor(np.ones((16, 32), "float32"), mesh, [Shard(0)])
    b = shard_tensor(np.ones((32, 8), "float32"), mesh, [Replicate()])

    mm = paddle.matmul(a, b)
    assert tuple(mm.dist_attr) == ("mp", None), mm.dist_attr

    # elementwise merges; unary passes through
    s = a + a
    assert tuple(s.dist_attr)[0] == "mp"
    r = paddle.nn.functional.relu(s)
    assert tuple(r.dist_attr)[0] == "mp"

    # reduction drops the reduced dim's sharding
    m = paddle.sum(a, axis=0)
    assert m.dist_attr is None or tuple(m.dist_attr)[0] is None

    # transpose permutes
    t = paddle.transpose(a, perm=[1, 0])
    assert tuple(t.dist_attr) == (None, "mp")

    # matmul contracted-dim sharding is dropped (partial -> replicated)
    c = shard_tensor(np.ones((16, 32), "float32"), mesh, [Replicate()])
    c.dist_attr = P(None, "mp")
    d = shard_tensor(np.ones((32, 8), "float32"), mesh, [Shard(0)])
    out = paddle.matmul(c, d)
    assert out.dist_attr is None or all(
        e != "mp" for e in tuple(out.dist_attr))


def test_spmd_rules_embedding_and_reshape():
    mesh = _mesh()
    w = shard_tensor(np.ones((256, 64), "float32"), mesh, [Shard(0)])
    # embedding output inherits the table's embed-dim sharding (none
    # here: vocab dim was the sharded one)
    ids = paddle.to_tensor(np.zeros((2, 8), "int64"))
    emb = paddle.nn.functional.embedding(ids, w)
    w2 = shard_tensor(np.ones((256, 64), "float32"), mesh, [Shard(1)])
    emb2 = paddle.nn.functional.embedding(ids, w2)
    assert tuple(emb2.dist_attr) == (None, None, "mp")

    x = shard_tensor(np.ones((8, 64), "float32"), mesh, [Shard(0)])
    y = paddle.reshape(x, (8, 8, 8))
    assert tuple(y.dist_attr)[0] == "mp"
