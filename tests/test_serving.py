"""Continuous-batching ServingEngine over the ragged paged KV cache.

Under test (inference/serving.py + the Predictor compile-stability
layer):
- token-level parity with one-request-at-a-time Predictor.generate
- arrivals mid-decode join the in-flight batch (continuous batching)
- early-EOS rows are evicted, their pages return to the free list, and
  queued requests backfill the freed slots
- the compile counter stays FLAT after warmup across varied length
  mixes (the acceptance gate: bucketed (B, Sb, P) program lattice)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (Config, ServingEngine, create_predictor)
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(11)
    return LlamaForCausalLM(llama_tiny())


@pytest.fixture()
def paged_pred(tiny_model):
    return create_predictor(
        Config().set_model(tiny_model).enable_paged_kv(page_size=8))


def _solo(tiny_model, prompt, n_new):
    """One-request-at-a-time Predictor reference output."""
    pred = create_predictor(
        Config().set_model(tiny_model).enable_paged_kv(page_size=8))
    return np.asarray(pred.generate(paddle.to_tensor(prompt[None]),
                                    max_new_tokens=n_new)._value)[0]


def _prompts(lens, vocab, seed=0):
    r = np.random.RandomState(seed)
    return [r.randint(1, vocab, (L,)) for L in lens]


class TestServingParity:
    def test_mixed_length_stream_matches_sequential(self, tiny_model,
                                                    paged_pred):
        """A stream longer than the batch, mixed lengths: every request
        produces exactly the tokens it gets decoded alone."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=2)
        prompts = _prompts([7, 4, 11, 5, 9], V)
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        done = eng.run()
        assert sorted(done) == sorted(rids)
        for rid, p in zip(rids, prompts):
            ref = _solo(tiny_model, p, 6)
            np.testing.assert_array_equal(done[rid].output_ids, ref)

    def test_chunked_decode_matches_sequential(self, tiny_model,
                                               paged_pred):
        """decode_chunk > 1 fuses steps into one scan launch without
        changing any emitted token."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=3, decode_chunk=4)
        prompts = _prompts([9, 13, 6], V, seed=1)
        rids = [eng.submit(p, max_new_tokens=7) for p in prompts]
        done = eng.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(done[rid].output_ids,
                                          _solo(tiny_model, p, 7))

    def test_arrival_mid_decode(self, tiny_model, paged_pred):
        """A request submitted while others are mid-decode joins the
        batch (continuous batching) and still decodes exactly."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=3)
        a, b, c = _prompts([8, 5, 12], V, seed=2)
        ra = eng.submit(a, max_new_tokens=8)
        rb = eng.submit(b, max_new_tokens=8)
        for _ in range(3):
            eng.step()                       # a, b are mid-decode
        assert eng.num_active == 2
        rc = eng.submit(c, max_new_tokens=4)  # arrival mid-decode
        done = eng.run()
        for rid, p, n in ((ra, a, 8), (rb, b, 8), (rc, c, 4)):
            np.testing.assert_array_equal(done[rid].output_ids,
                                          _solo(tiny_model, p, n))


class TestEvictionBackfill:
    def test_eos_evicts_and_backfills(self, tiny_model, paged_pred):
        """A row hitting EOS early frees its slot+pages; a queued
        request backfills while the other row keeps decoding."""
        V = tiny_model.config.vocab_size
        a, b, c = _prompts([7, 9, 6], V, seed=3)
        ref_a = _solo(tiny_model, a, 8)
        eos = int(ref_a[len(a) + 1])          # a's 2nd new token
        eng = ServingEngine(paged_pred, max_batch=2)
        free0 = len(eng._free_pages)
        ra = eng.submit(a, max_new_tokens=8, eos_token_id=eos)
        rb = eng.submit(b, max_new_tokens=8)
        rc = eng.submit(c, max_new_tokens=3)  # queued: batch is full
        eng.step()
        assert rc not in eng.finished and eng.queue  # c waits
        done = eng.run()
        # a stopped AT the eos token, well before its budget
        assert done[ra].new_tokens[-1] == eos
        assert len(done[ra].new_tokens) == 2
        # c was admitted after a's eviction and decoded exactly
        np.testing.assert_array_equal(done[rc].output_ids,
                                      _solo(tiny_model, c, 3))
        # b never saw any of it
        np.testing.assert_array_equal(done[rb].output_ids,
                                      _solo(tiny_model, b, 8))
        # every page returned to the free list
        assert len(eng._free_pages) == free0
        assert (eng.tables == eng.trash).all()

    def test_pool_capacity_gates_admission(self, tiny_model):
        """Admission waits for pages, not just slots; a request that
        can never fit is refused loudly at submit."""
        pred = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(page_size=8))
        V = tiny_model.config.vocab_size
        # pool bucketed to 8 pages (7 usable): two 3-page requests fit,
        # a third must wait for an eviction
        eng = ServingEngine(pred, max_batch=3, pool_pages=7)
        prompts = _prompts([17, 18, 16], V, seed=4)
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        eng.step()
        assert eng.num_active == 2 and len(eng.queue) == 1
        done = eng.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(done[rid].output_ids,
                                          _solo(tiny_model, p, 5))
        with pytest.raises(Exception, match="pool"):
            eng.submit(np.ones(60, np.int64), max_new_tokens=5)


class TestCompileStability:
    def test_engine_compiles_flat_across_mixes(self, tiny_model,
                                               paged_pred):
        """After warmup on ONE length mix, serving >= 4 different
        length mixes triggers ZERO additional compiles (acceptance
        criterion)."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=4)
        for p in _prompts([7, 12], V, seed=5):        # warmup mix
            eng.submit(p, max_new_tokens=5)
        eng.run()
        warm = eng.stats.compiles
        assert warm > 0
        mixes = [(3, 9, 21), (5, 5), (30, 2, 14, 8), (13,)]
        for i, mix in enumerate(mixes):
            for p in _prompts(list(mix), V, seed=6 + i):
                eng.submit(p, max_new_tokens=5)
            eng.run()
        assert eng.stats.compiles == warm, (
            f"recompiled under traffic: {eng.stats.as_dict()}")
        assert eng.stats.cache_hits > 0
        assert eng.stats.tokens > 0

    def test_predictor_pool_bucket_reuses_programs(self, tiny_model):
        """The Predictor side of the tentpole: P bucketed like S, so
        varied ragged mixes reuse one (prefill, decode) program pair."""
        pred = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(page_size=8))
        V = tiny_model.config.vocab_size
        r = np.random.RandomState(9)

        def gen(lens):
            ids = np.zeros((len(lens), max(lens)), np.int64)
            for b, L in enumerate(lens):
                ids[b, :L] = r.randint(1, V, (L,))
            return pred.generate(paddle.to_tensor(ids),
                                 lengths=np.array(lens),
                                 max_new_tokens=6)

        gen([11, 24, 17])                     # warmup mix
        warm = pred.stats.compiles
        for lens in ([9, 30, 4], [16, 16, 23], [5, 19, 8], [25, 7, 13]):
            gen(lens)
        assert pred.stats.compiles == warm, pred.stats.as_dict()

    def test_paged_pool_size_is_bucketed(self, tiny_model):
        pred = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(page_size=8))
        import jax.numpy as jnp

        _, P1 = pred._paged_caches([11, 24, 17], 4, 64, 8, jnp.float32)
        _, P2 = pred._paged_caches([9, 30, 4], 4, 64, 8, jnp.float32)
        assert P1 == P2                       # same bucket, same shape
        assert P1 & (P1 - 1) == 0             # power of two


def test_engine_requires_paged_config(tiny_model):
    pred = create_predictor(Config().set_model(tiny_model))
    with pytest.raises(Exception, match="paged"):
        ServingEngine(pred)
