"""Long-tail nn layer classes (reference: python/paddle/nn/layer/*) —
torch parity where applicable."""
import numpy as np
import torch

import paddle_tpu as paddle
from paddle_tpu import nn

rng = np.random.RandomState(0)


def _t(x):
    return paddle.to_tensor(x)


def _np(t):
    return np.asarray(t._value)


class TestPooling:
    def test_pool1d_parity(self):
        x = rng.randn(2, 3, 12).astype("float32")
        np.testing.assert_allclose(
            _np(nn.MaxPool1D(3, 2)(_t(x))),
            torch.nn.functional.max_pool1d(torch.tensor(x), 3, 2).numpy(),
            rtol=1e-6)
        np.testing.assert_allclose(
            _np(nn.AvgPool1D(4, 4)(_t(x))),
            torch.nn.functional.avg_pool1d(torch.tensor(x), 4, 4).numpy(),
            rtol=1e-5)

    def test_adaptive_pools_parity(self):
        x = rng.randn(2, 3, 11).astype("float32")
        np.testing.assert_allclose(
            _np(nn.AdaptiveAvgPool1D(5)(_t(x))),
            torch.nn.functional.adaptive_avg_pool1d(
                torch.tensor(x), 5).numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            _np(nn.AdaptiveMaxPool1D(4)(_t(x))),
            torch.nn.functional.adaptive_max_pool1d(
                torch.tensor(x), 4).numpy(), rtol=1e-6)
        x3 = rng.randn(1, 2, 6, 7, 8).astype("float32")
        np.testing.assert_allclose(
            _np(nn.AdaptiveAvgPool3D(3)(_t(x3))),
            torch.nn.functional.adaptive_avg_pool3d(
                torch.tensor(x3), 3).numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            _np(nn.AdaptiveMaxPool3D((2, 3, 4))(_t(x3))),
            torch.nn.functional.adaptive_max_pool3d(
                torch.tensor(x3), (2, 3, 4)).numpy(), rtol=1e-6)

    def test_pool3d_layers(self):
        x3 = rng.randn(1, 2, 6, 6, 6).astype("float32")
        np.testing.assert_allclose(
            _np(nn.MaxPool3D(2, 2)(_t(x3))),
            torch.nn.functional.max_pool3d(torch.tensor(x3), 2, 2)
            .numpy(), rtol=1e-6)

    def test_unpool1d_roundtrip_positions(self):
        x = rng.randn(1, 1, 8).astype("float32")
        pooled, idx = paddle.max_pool2d_with_index(
            _t(x[:, :, None]), (1, 2), (1, 2))
        from paddle_tpu.ops.manipulation import squeeze

        up = nn.MaxUnPool1D(2, 2)(squeeze(pooled, 2), squeeze(idx, 2))
        assert up.shape == [1, 1, 8]


class TestConvs:
    def test_conv3d_layer(self):
        paddle.seed(0)
        c = nn.Conv3D(2, 4, 3, padding=1)
        x = rng.randn(1, 2, 5, 5, 5).astype("float32")
        ref = torch.nn.functional.conv3d(
            torch.tensor(x), torch.tensor(_np(c.weight)),
            torch.tensor(_np(c.bias)), padding=1)
        np.testing.assert_allclose(_np(c(_t(x))), ref.numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_conv_transpose_parity(self):
        paddle.seed(0)
        for cin, cout, k, s, p in [(3, 5, 4, 2, 1), (2, 3, 3, 1, 0)]:
            ct = nn.Conv1DTranspose(cin, cout, k, stride=s, padding=p)
            x = rng.randn(2, cin, 9).astype("float32")
            ref = torch.nn.functional.conv_transpose1d(
                torch.tensor(x), torch.tensor(_np(ct.weight)),
                torch.tensor(_np(ct.bias)), stride=s, padding=p)
            np.testing.assert_allclose(_np(ct(_t(x))), ref.numpy(),
                                       rtol=1e-4, atol=1e-5)
        c3 = nn.Conv3DTranspose(2, 4, 3, stride=2, padding=1)
        x3 = rng.randn(1, 2, 4, 4, 4).astype("float32")
        ref3 = torch.nn.functional.conv_transpose3d(
            torch.tensor(x3), torch.tensor(_np(c3.weight)),
            torch.tensor(_np(c3.bias)), stride=2, padding=1)
        np.testing.assert_allclose(_np(c3(_t(x3))), ref3.numpy(),
                                   rtol=1e-3, atol=1e-4)


class TestLosses:
    def test_torch_parity_losses(self):
        a = rng.randn(4, 6).astype("float32")
        b = rng.randn(4, 6).astype("float32")
        lab = np.array([1, -1, 1, -1])
        assert abs(float(nn.CosineEmbeddingLoss(0.2)(_t(a), _t(b),
                                                     _t(lab)))
                   - float(torch.nn.CosineEmbeddingLoss(margin=0.2)(
                       torch.tensor(a), torch.tensor(b),
                       torch.tensor(lab)))) < 1e-5
        assert abs(float(nn.TripletMarginLoss()(
            _t(a), _t(b), _t(b[::-1].copy())))
            - float(torch.nn.TripletMarginLoss()(
                torch.tensor(a), torch.tensor(b),
                torch.tensor(b[::-1].copy())))) < 1e-4
        y = rng.randint(0, 6, 4)
        assert abs(float(nn.MultiMarginLoss()(_t(a), _t(y)))
                   - float(torch.nn.MultiMarginLoss()(
                       torch.tensor(a), torch.tensor(y)))) < 1e-5
        ml = (rng.rand(4, 6) > 0.5).astype("float32")
        assert abs(float(nn.MultiLabelSoftMarginLoss()(_t(a), _t(ml)))
                   - float(torch.nn.MultiLabelSoftMarginLoss()(
                       torch.tensor(a), torch.tensor(ml)))) < 1e-5
        sl = np.sign(rng.randn(4, 6)).astype("float32")
        assert abs(float(nn.SoftMarginLoss()(_t(a), _t(sl)))
                   - float(torch.nn.SoftMarginLoss()(
                       torch.tensor(a), torch.tensor(sl)))) < 1e-5
        hl = np.sign(rng.randn(4, 6)).astype("int64")
        assert abs(float(nn.HingeEmbeddingLoss()(_t(a), _t(hl)))
                   - float(torch.nn.HingeEmbeddingLoss()(
                       torch.tensor(a), torch.tensor(hl)))) < 1e-5
        var = np.abs(rng.randn(4, 6)).astype("float32") + 0.1
        assert abs(float(nn.GaussianNLLLoss()(_t(a), _t(b), _t(var)))
                   - float(torch.nn.GaussianNLLLoss()(
                       torch.tensor(a), torch.tensor(b),
                       torch.tensor(var)))) < 1e-4
        pos = np.abs(rng.randn(4, 6)).astype("float32")
        assert abs(float(nn.PoissonNLLLoss()(_t(a), _t(pos)))
                   - float(torch.nn.PoissonNLLLoss()(
                       torch.tensor(a), torch.tensor(pos)))) < 1e-4

    def test_ctc_loss_layer(self):
        T, B, C, L = 10, 2, 5, 3
        lp = torch.log_softmax(torch.tensor(
            rng.randn(T, B, C).astype("float32")), -1).numpy()
        labels = rng.randint(1, C, (B, L))
        out = nn.CTCLoss()(_t(lp), _t(labels),
                           _t(np.array([10, 8])), _t(np.array([3, 2])))
        assert np.isfinite(float(out))

    def test_hsigmoid_loss_trains(self):
        paddle.seed(0)
        feat, C = 8, 10
        hs = nn.HSigmoidLoss(feat, C)
        emb = nn.Linear(4, feat)
        params = list(hs.parameters()) + list(emb.parameters())
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=params)
        x = _t(rng.rand(16, 4).astype("float32"))
        y = _t(rng.randint(0, C, 16))
        first = None
        for _ in range(20):
            loss = hs(emb(x), y).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.8


class TestMisc:
    def test_bilinear(self):
        paddle.seed(0)
        bl = nn.Bilinear(5, 4, 3)
        x1 = rng.randn(2, 5).astype("float32")
        x2 = rng.randn(2, 4).astype("float32")
        out = _np(bl(_t(x1), _t(x2)))
        ref = np.einsum("bi,oij,bj->bo", x1, _np(bl.weight), x2) \
            + _np(bl.bias)
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_distance_similarity(self):
        a = rng.randn(4, 6).astype("float32")
        b = rng.randn(4, 6).astype("float32")
        np.testing.assert_allclose(
            _np(nn.PairwiseDistance()(_t(a), _t(b))),
            torch.nn.PairwiseDistance()(torch.tensor(a),
                                        torch.tensor(b)).numpy(),
            rtol=1e-4)
        np.testing.assert_allclose(
            _np(nn.CosineSimilarity(axis=1)(_t(a), _t(b))),
            torch.nn.CosineSimilarity(dim=1)(torch.tensor(a),
                                             torch.tensor(b)).numpy(),
            rtol=1e-5)

    def test_spectral_norm(self):
        paddle.seed(0)
        sn = nn.SpectralNorm((6, 4), power_iters=25)
        # own generator: convergence rate depends on the drawn matrix's
        # spectral gap, so pin the matrix regardless of test order
        w = np.random.RandomState(42).randn(6, 4).astype("float32")
        wn = _np(sn(_t(w)))
        s_max = np.linalg.svd(wn, compute_uv=False)[0]
        assert abs(s_max - 1.0) < 0.05

    def test_pads_and_shapes(self):
        x = rng.randn(1, 2, 4, 4).astype("float32")
        out = nn.ZeroPad2D([1, 1, 2, 2])(_t(x))
        assert out.shape == [1, 2, 8, 6]
        out = nn.Pad1D(2)(_t(rng.randn(1, 2, 5).astype("float32")))
        assert out.shape == [1, 2, 9]
        un = nn.Unflatten(1, [2, 3])(
            _t(rng.randn(4, 6).astype("float32")))
        assert un.shape == [4, 2, 3]
        s2d = nn.Softmax2D()(_t(x))
        np.testing.assert_allclose(_np(s2d).sum(axis=1),
                                   np.ones((1, 4, 4)), rtol=1e-5)

    def test_activation_layers(self):
        x = _t(rng.randn(3, 6).astype("float32"))
        assert nn.LogSigmoid()(x).shape == [3, 6]
        assert nn.Maxout(2)(x).shape == [3, 3]
        assert nn.ThresholdedReLU(0.5)(x).shape == [3, 6]
        r = nn.RReLU()
        r.eval()
        assert r(x).shape == [3, 6]

    def test_instance_norm_1d_3d(self):
        x = _t(rng.randn(2, 3, 10).astype("float32"))
        o = _np(nn.InstanceNorm1D(3)(x))
        np.testing.assert_allclose(o.mean(axis=-1), 0, atol=1e-5)
        x3 = _t(rng.randn(2, 3, 4, 4, 4).astype("float32"))
        o3 = _np(nn.InstanceNorm3D(3)(x3))
        np.testing.assert_allclose(o3.mean(axis=(-3, -2, -1)), 0,
                                   atol=1e-5)

    def test_dropout_variants_eval_identity(self):
        x = _t(rng.randn(2, 3, 4, 4, 4).astype("float32"))
        d3 = nn.Dropout3D(0.5)
        d3.eval()
        np.testing.assert_allclose(_np(d3(x)), _np(x))
        ad = nn.AlphaDropout(0.5)
        ad.eval()
        np.testing.assert_allclose(_np(ad(x)), _np(x))
        ad.train()
        out = _np(ad(x))
        assert out.std() > 0.5  # distribution roughly preserved

    def test_upsampling_nearest(self):
        x = rng.randn(1, 2, 3, 3).astype("float32")
        out = nn.UpsamplingNearest2D(scale_factor=2)(_t(x))
        assert out.shape == [1, 2, 6, 6]


class TestReviewRegressions:
    def test_poisson_full_zero_labels(self):
        a = rng.randn(4, 6).astype("float32")
        lab = np.zeros((4, 6), "float32")
        lab[0, 0] = 3.0
        ours = float(nn.PoissonNLLLoss(full=True)(_t(a), _t(lab)))
        ref = float(torch.nn.PoissonNLLLoss(full=True)(
            torch.tensor(a), torch.tensor(lab)))
        assert np.isfinite(ours) and abs(ours - ref) < 1e-4

    def test_multi_margin_weight(self):
        a = rng.randn(4, 6).astype("float32")
        y = rng.randint(0, 6, 4)
        w = np.abs(rng.randn(6)).astype("float32")
        ours = float(nn.MultiMarginLoss(weight=_t(w))(_t(a), _t(y)))
        ref = float(torch.nn.MultiMarginLoss(weight=torch.tensor(w))(
            torch.tensor(a), torch.tensor(y)))
        assert abs(ours - ref) < 1e-5

    def test_conv_transpose_dilation_output_padding(self):
        paddle.seed(1)
        ct = nn.Conv1DTranspose(2, 3, 3, stride=2, padding=1,
                                dilation=2, output_padding=1)
        x = rng.randn(1, 2, 6).astype("float32")
        ref = torch.nn.functional.conv_transpose1d(
            torch.tensor(x), torch.tensor(_np(ct.weight)),
            torch.tensor(_np(ct.bias)), stride=2, padding=1,
            output_padding=1, dilation=2)
        np.testing.assert_allclose(_np(ct(_t(x))), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_spectral_norm_converges_with_persisted_state(self):
        paddle.seed(0)
        sn = nn.SpectralNorm((6, 4), power_iters=1)
        w = _t(np.random.RandomState(1).randn(6, 4).astype("float32"))
        for _ in range(30):   # 1 iteration/call amortizes via buffers
            wn = sn(w)
        s_max = np.linalg.svd(_np(wn), compute_uv=False)[0]
        assert abs(s_max - 1.0) < 0.01

    def test_dropout3d_drops_whole_channels(self):
        paddle.seed(3)
        d = nn.Dropout3D(0.5)
        d.train()
        x = _t(np.ones((4, 8, 2, 2, 2), "float32"))
        out = _np(d(x))
        per_channel = out.reshape(4, 8, -1)
        # each channel slab is either all zero or all scaled
        assert all(len(np.unique(ch)) == 1
                   for b in per_channel for ch in b)

    def test_pool_ceil_mode_raises(self):
        import pytest
        with pytest.raises(Exception, match="ceil_mode"):
            nn.MaxPool1D(2, ceil_mode=True)
