"""Collective-matmul overlap: exact parity (ring-order fp tolerance) of
the fused ring decompositions against the unfused collective+GEMM
chains on the 8-virtual-device CPU mesh, plus end-to-end loss parity of
the TP/SP linears with ``mp_async_allreduce`` on vs off (the reference
loss-parity strategy, SURVEY.md §4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import collective_matmul as cm
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine, _shard_map
from paddle_tpu.distributed.fleet.utils import \
    sequence_parallel_utils as spu

AXES = ("mp",)
TOL = dict(rtol=1e-5, atol=1e-5)


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("mp",))


def _sm(f, mesh, in_specs, out_specs):
    return _shard_map(f, mesh, in_specs, out_specs)


# -- raw ring ops vs the unfused reference on 8 devices -------------------

@pytest.mark.slow  # ~17s 8-vdev ring fwd+bwd compile; 1-cpu tier-1 budget
def test_ag_matmul_fwd_bwd_parity():
    mesh = _mesh()
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(16, 4, 12), jnp.float32)   # [s, b, k]
    w = jnp.asarray(r.randn(12, 24), jnp.float32)
    g = jnp.asarray(r.randn(16, 4, 24), jnp.float32)

    def fused(xs, wv, gv):
        out, vjp = jax.vjp(lambda a, b: cm.ag_matmul(a, b, AXES, 0),
                           xs, wv)
        return (out,) + vjp(gv)

    def ref(xs, wv, gv):
        out, vjp = jax.vjp(
            lambda a, b: lax.all_gather(a, AXES, axis=0, tiled=True) @ b,
            xs, wv)
        return (out,) + vjp(gv)

    specs = (P("mp"), P(), P(None))
    outs = (P(None), P("mp"), P())
    of, dxf, dwf = _sm(fused, mesh, specs, outs)(x, w, g)
    orr, dxr, dwr = _sm(ref, mesh, specs, outs)(x, w, g)
    np.testing.assert_allclose(np.asarray(of), np.asarray(orr), **TOL)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(dxr), **TOL)
    np.testing.assert_allclose(np.asarray(dwf), np.asarray(dwr), **TOL)


@pytest.mark.slow  # ~19s 8-vdev ring fwd+bwd compile; 1-cpu tier-1 budget
def test_matmul_rs_fwd_bwd_parity():
    mesh = _mesh()
    r = np.random.RandomState(1)
    x = jnp.asarray(r.randn(16, 4, 96), jnp.float32)   # k sharded over mp
    w = jnp.asarray(r.randn(96, 24), jnp.float32)
    g = jnp.asarray(r.randn(16, 4, 24), jnp.float32)   # seq-sharded grad

    def fused(xs, wv, gv):
        out, vjp = jax.vjp(lambda a, b: cm.matmul_rs(a, b, AXES, 0),
                           xs, wv)
        return (out,) + vjp(gv)

    def ref(xs, wv, gv):
        out, vjp = jax.vjp(
            lambda a, b: lax.psum_scatter(a @ b, "mp",
                                          scatter_dimension=0, tiled=True),
            xs, wv)
        return (out,) + vjp(gv)

    specs = (P(None, None, "mp"), P("mp"), P("mp"))
    outs = (P("mp"), P(None, None, "mp"), P("mp"))
    of, dxf, dwf = _sm(fused, mesh, specs, outs)(x, w, g)
    orr, dxr, dwr = _sm(ref, mesh, specs, outs)(x, w, g)
    np.testing.assert_allclose(np.asarray(of), np.asarray(orr), **TOL)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(dxr), **TOL)
    np.testing.assert_allclose(np.asarray(dwf), np.asarray(dwr), **TOL)


def test_matmul_allreduce_megatron_pairing():
    """Fused forward == psum(x @ w); backward keeps the identity-bwd
    pairing (local GEMMs) of _mp_allreduce."""
    mesh = _mesh()
    r = np.random.RandomState(2)
    x = jnp.asarray(r.randn(16, 96), jnp.float32)
    w = jnp.asarray(r.randn(96, 24), jnp.float32)
    g = jnp.asarray(r.randn(16, 24), jnp.float32)

    def fused(xs, wv, gv):
        out, vjp = jax.vjp(
            lambda a, b: cm.matmul_allreduce(a, b, AXES, 0), xs, wv)
        return (out,) + vjp(gv)

    specs = (P(None, "mp"), P("mp"), P())
    outs = (P(None), P(None, "mp"), P("mp"))
    of, dxf, dwf = _sm(fused, mesh, specs, outs)(x, w, g)
    np.testing.assert_allclose(np.asarray(of), np.asarray(x @ w), **TOL)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(g @ w.T), **TOL)
    np.testing.assert_allclose(np.asarray(dwf), np.asarray(x.T @ g), **TOL)


def test_matmul_gather_parity():
    mesh = _mesh()
    r = np.random.RandomState(3)
    x = jnp.asarray(r.randn(16, 12), jnp.float32)
    w = jnp.asarray(r.randn(12, 48), jnp.float32)   # cols sharded
    g = jnp.asarray(r.randn(16, 48), jnp.float32)

    def fused(xs, wv, gv):
        out, vjp = jax.vjp(
            lambda a, b: cm.matmul_gather(a, b, AXES, 8), xs, wv)
        return (out,) + vjp(gv)

    def ref(xs, wv, gv):
        # the unfused layer path: local matmul + _c_concat's custom
        # slice-backward pairing (NOT all_gather's true transpose, which
        # psums — the Megatron convention the layers rely on)
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import \
            allgather_slice_bwd

        out, vjp = jax.vjp(
            lambda a, b: allgather_slice_bwd(a @ b, AXES, -1), xs, wv)
        return (out,) + vjp(gv)

    specs = (P(), P(None, "mp"), P(None, None))
    outs = (P(None, None), P(), P(None, "mp"))
    of, dxf, dwf = _sm(fused, mesh, specs, outs)(x, w, g)
    orr, dxr, dwr = _sm(ref, mesh, specs, outs)(x, w, g)
    np.testing.assert_allclose(np.asarray(of), np.asarray(orr), **TOL)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(dxr), **TOL)
    np.testing.assert_allclose(np.asarray(dwf), np.asarray(dwr), **TOL)


@pytest.mark.parametrize("p", [2, 3, 5, 8])
def test_ring_sizes(p):
    """Odd and even ring sizes place every chunk exactly once."""
    mesh = _mesh(p)
    r = np.random.RandomState(p)
    x = jnp.asarray(r.randn(4 * p, 6), jnp.float32)
    w = jnp.asarray(r.randn(6, 10), jnp.float32)

    def ag(xs, wv):
        return cm.ag_matmul(xs, wv, AXES, 0)

    def rs(xs, wv):
        return cm.matmul_rs(xs, wv, AXES, 0)

    out = _sm(ag, mesh, (P("mp"), P()), P(None))(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), **TOL)
    out = _sm(rs, mesh, (P(), P()), P("mp"))(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w) * p,
                               **TOL)


# -- end-to-end loss parity: knob on vs off vs dense golden ---------------

class _TPBlock(paddle.nn.Layer):
    """Plain TP pair: column (gather side) + row (reduce side)."""

    def __init__(self, d=16, h=32):
        super().__init__()
        from paddle_tpu.distributed.fleet.layers import mpu

        self.fc1 = mpu.ColumnParallelLinear(d, h, gather_output=True)
        self.fc2 = mpu.RowParallelLinear(h, d, input_is_parallel=False)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


class _SPBlock(paddle.nn.Layer):
    """SP pair on [b, s, d]: seq all-gather linear + seq reduce-scatter
    linear."""

    def __init__(self, d=16, h=32):
        super().__init__()
        self.fc1 = spu.ColumnSequenceParallelLinear(
            d, h, gather_output=False, seq_axis=1)
        self.fc2 = spu.RowSequenceParallelLinear(
            h, d, input_is_parallel=True, seq_axis=1)

    def forward(self, x):
        x = spu.scatter(x, axis=1)
        x = self.fc2(paddle.nn.functional.relu(self.fc1(x)))
        return spu.gather(x, axis=1)


class _Dense(paddle.nn.Layer):
    def __init__(self, d=16, h=32):
        super().__init__()
        self.fc1 = paddle.nn.Linear(d, h)
        self.fc2 = paddle.nn.Linear(h, d)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _loss_fn(model, batch):
    out = model(batch["x"])
    return paddle.mean((out - batch["y"]) ** 2)


def _train(block_cls, x, y, overlap, steps=3, seed=7):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 2, "mp_degree": 4, "pp_degree": 1,
        "mp_configs": {"mp_async_allreduce": overlap}}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    model = block_cls()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(_loss_fn)
    losses = [float(step({"x": paddle.to_tensor(x),
                          "y": paddle.to_tensor(y)}))
              for _ in range(steps)]
    params = {n: np.asarray(p._value)
              for n, p in model.named_parameters()}
    return losses, params


@pytest.mark.parametrize("block_cls", [_TPBlock, _SPBlock],
                         ids=["tp", "sp"])
def test_linear_loss_parity_knob_on_vs_off(block_cls):
    np.random.seed(0)
    shape = (4, 16) if block_cls is _TPBlock else (4, 8, 16)
    x = np.random.randn(*shape).astype("float32")
    y = np.random.randn(*shape).astype("float32")

    l_off, p_off = _train(block_cls, x, y, overlap=False)
    l_on, p_on = _train(block_cls, x, y, overlap=True)
    np.testing.assert_allclose(l_on, l_off, rtol=1e-5, atol=1e-6)
    for n in p_off:
        np.testing.assert_allclose(p_on[n], p_off[n], rtol=1e-4,
                                   atol=1e-5, err_msg=n)

    # and both match the dense single-device golden
    paddle.seed(7)
    golden = _Dense()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=golden.parameters())
    g_losses = []
    for _ in range(3):
        loss = _loss_fn(golden, {"x": paddle.to_tensor(x),
                                 "y": paddle.to_tensor(y)})
        loss.backward()
        opt.step()
        opt.clear_grad()
        g_losses.append(float(loss))
    np.testing.assert_allclose(l_on, g_losses, rtol=1e-4, atol=1e-6)


def test_knob_defaults_off_and_plumbs():
    strategy = fleet.DistributedStrategy()
    assert strategy.hybrid_configs["mp_configs"]["mp_async_allreduce"] \
        is False
    strategy.hybrid_configs = {"mp_configs": {"mp_async_allreduce": True}}
    assert strategy.hybrid_configs["mp_configs"]["mp_async_allreduce"]
    fleet.init(is_collective=True, strategy=strategy)
    assert cm.overlap_enabled()
    # outside an SPMD region the fused path must not engage
    assert not cm.overlap_available(("mp",)) or False  # in_spmd gate

    # a second strategy object must not inherit the first one's knob
    assert fleet.DistributedStrategy() \
        .hybrid_configs["mp_configs"]["mp_async_allreduce"] is False


def test_engine_compile_stats_flat_with_overlap():
    """ParallelEngine's CompileStats: one compile per (shape, spec)
    signature, cache hits after — and the overlap path must not force
    steady-state recompiles."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 2, "mp_degree": 4, "pp_degree": 1,
        "mp_configs": {"mp_async_allreduce": True}}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(1)
    model = _TPBlock()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(_loss_fn)
    np.random.seed(2)
    x = np.random.randn(4, 16).astype("float32")
    y = np.random.randn(4, 16).astype("float32")
    batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}
    step(batch)
    assert eng.stats.compiles == 1 and eng.stats.cache_hits == 0
    for _ in range(3):
        step(batch)
    assert eng.stats.compiles == 1          # steady state: no recompiles
    assert eng.stats.cache_hits == 3
    d = eng.stats.as_dict()
    assert d["compiles"] == 1 and d["cache_hits"] == 3

    # eval steps key separately but are also compile-stable
    ev = eng.eval_step(lambda m, b: m(b["x"]))
    ev({"x": paddle.to_tensor(x)})
    ev({"x": paddle.to_tensor(x)})
    assert eng.stats.compiles == 2 and eng.stats.cache_hits == 4


def test_overlap_eager_fallback():
    """Knob on, but eager (no SPMD region): layers run the unfused path
    and still produce the single-device result."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 8, "pp_degree": 1,
        "mp_configs": {"mp_async_allreduce": True}}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(11)
    block = _TPBlock()
    x = paddle.to_tensor(np.random.RandomState(4)
                         .randn(4, 16).astype("float32"))
    out = block(x)                      # eager: identity collectives
    assert tuple(out.shape) == (4, 16)
