"""jit.save/load StableHLO export + static InputSpec
(reference: TranslatedLayer save/load tests in test/dygraph_to_static)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import InputSpec


def _net():
    return paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                                paddle.nn.Linear(16, 4))


def test_jit_save_load_exported_program(tmp_path):
    paddle.seed(0)
    net = _net()
    net.eval()
    x = np.random.RandomState(0).randn(3, 8).astype("float32")
    ref = np.asarray(net(paddle.to_tensor(x))._value)

    p = str(tmp_path / "m" / "infer")
    paddle.jit.save(net, p, input_spec=[InputSpec([None, 8], "float32")])

    loaded = paddle.jit.load(p)
    out = loaded(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5,
                               atol=1e-6)
    # dynamic batch: a different batch size runs through the same program
    x2 = np.random.RandomState(1).randn(7, 8).astype("float32")
    out2 = loaded(paddle.to_tensor(x2))
    np.testing.assert_allclose(np.asarray(out2._value),
                               np.asarray(net(paddle.to_tensor(x2))._value),
                               rtol=1e-5, atol=1e-6)


def test_jit_save_requires_spec(tmp_path):
    with pytest.raises(ValueError):
        paddle.jit.save(_net(), str(tmp_path / "x"))


def test_input_spec_helpers():
    t = paddle.to_tensor(np.zeros((2, 3), "float32"))
    s = InputSpec.from_tensor(t)
    assert s.shape == (2, 3)
    s2 = InputSpec.from_numpy(np.zeros((4, 5), "int64"))
    assert str(s2.dtype) == "int64"


def test_static_executor_shim():
    ex = paddle.static.Executor()
    net = _net()
    compiled = paddle.jit.to_static(net)
    out = ex.run(lambda x: compiled(x),
                 feed={"x": paddle.to_tensor(
                     np.zeros((2, 8), "float32"))})
    assert out[0].shape == (2, 4)
