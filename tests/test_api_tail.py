"""Top-level API tail (reference: python/paddle/__init__.py exports) —
predicates, math leftovers, scatter views, inplace family, summary."""
import numpy as np
import pytest
import scipy.special as sp

import paddle_tpu as paddle
from paddle_tpu import nn

rng = np.random.RandomState(0)


def _t(x):
    return paddle.to_tensor(x)


def _np(t):
    return np.asarray(t._value)


def test_predicates():
    assert paddle.is_tensor(_t(np.zeros(2))) and not paddle.is_tensor(3)
    assert paddle.is_floating_point(_t(np.zeros(2, "float32")))
    assert paddle.is_integer(_t(np.zeros(2, "int32")))
    assert paddle.is_complex(_t(np.zeros(2, "complex64")))
    assert int(paddle.rank(_t(np.zeros((2, 3)))).numpy()) == 2


def test_math_tail():
    np.testing.assert_allclose(
        _np(paddle.gcd(_t(np.array([12])), _t(np.array([18])))), [6])
    np.testing.assert_allclose(
        _np(paddle.lcm(_t(np.array([4])), _t(np.array([6])))), [12])
    x = np.array([3.0, 4.0], "float32")
    np.testing.assert_allclose(_np(paddle.multigammaln(_t(x), 2)),
                               sp.multigammaln(x, 2), rtol=1e-4)
    pol = _np(paddle.polar(_t(np.array([2.0], "float32")),
                           _t(np.array([np.pi], "float32"))))
    assert abs(pol[0].real + 2) < 1e-5
    np.testing.assert_allclose(
        _np(paddle.sgn(_t(np.array([-3.0, 0.0, 2.0], "float32")))),
        [-1, 0, 1])
    c = paddle.sgn(_t(np.array([3 + 4j], "complex64")))
    np.testing.assert_allclose(_np(c), [0.6 + 0.8j], rtol=1e-5)
    assert _np(paddle.signbit(_t(np.array([-1.0, 1.0])))).tolist() == \
        [True, False]
    np.testing.assert_allclose(
        _np(paddle.deg2rad(_t(np.array([180.0], "float32")))),
        [np.pi], rtol=1e-6)
    nq = paddle.nanquantile(
        _t(np.array([1.0, np.nan, 3.0], "float32")), 0.5)
    assert abs(float(nq) - 2.0) < 1e-6


def test_take_and_tensordot():
    tk = paddle.take(_t(np.arange(12).reshape(3, 4)),
                     _t(np.array([-1, 0, 5])))
    np.testing.assert_allclose(_np(tk), [11, 0, 5])
    a = rng.rand(3, 4).astype("float32")
    b = rng.rand(4, 5).astype("float32")
    np.testing.assert_allclose(
        _np(paddle.tensordot(_t(a), _t(b), axes=1)), a @ b, rtol=1e-5)


def test_splits_and_stacks():
    parts = paddle.tensor_split(_t(np.arange(10)), 3)
    assert [p.shape[0] for p in parts] == [4, 3, 3]
    parts = paddle.tensor_split(_t(np.arange(10)), [3, 7])
    assert [p.shape[0] for p in parts] == [3, 4, 3]
    v = paddle.vsplit(_t(np.zeros((4, 2))), 2)
    assert len(v) == 2 and v[0].shape == [2, 2]
    assert paddle.vstack([_t(np.ones((2, 3))),
                          _t(np.ones((1, 3)))]).shape == [3, 3]
    assert paddle.hstack([_t(np.ones((2, 2))),
                          _t(np.ones((2, 1)))]).shape == [2, 3]
    assert paddle.row_stack is paddle.vstack
    assert paddle.column_stack([_t(np.ones(3)),
                                _t(np.ones(3))]).shape == [3, 2]


def test_scatter_views():
    sn = _np(paddle.scatter_nd(_t(np.array([[0, 1], [2, 3]])),
                               _t(np.array([9.0, 8.0], "float32")),
                               [3, 4]))
    assert sn[0, 1] == 9 and sn[2, 3] == 8
    ss = _np(paddle.select_scatter(_t(np.zeros((3, 4), "float32")),
                                   _t(np.ones(4, "float32")), 0, 1))
    assert ss[1].sum() == 4 and ss[0].sum() == 0
    sl = _np(paddle.slice_scatter(_t(np.zeros((4, 4), "float32")),
                                  _t(np.ones((2, 4), "float32")),
                                  [0], [1], [3], [1]))
    assert sl[1:3].sum() == 8 and sl[0].sum() == 0
    ms = _np(paddle.masked_scatter(
        _t(np.zeros(5, "float32")),
        _t(np.array([True, False, True, False, True])),
        _t(np.array([1.0, 2.0, 3.0], "float32"))))
    np.testing.assert_allclose(ms, [1, 0, 2, 0, 3])


def test_shapes_and_views():
    assert paddle.mm(_t(rng.rand(2, 3).astype("float32")),
                     _t(rng.rand(3, 2).astype("float32"))).shape == [2, 2]
    assert paddle.view(_t(np.zeros((2, 6), "float32")),
                       [3, 4]).shape == [3, 4]
    assert paddle.view_as(_t(np.zeros((2, 6))),
                          _t(np.zeros((12,)))).shape == [12]
    assert paddle.unflatten(_t(np.zeros((4, 6))), 1,
                            [2, 3]).shape == [4, 2, 3]
    assert paddle.tolist(_t(np.array([1, 2]))) == [1, 2]
    assert paddle.standard_normal([3, 2]).shape == [3, 2]
    rl = paddle.randint_like(_t(np.zeros((2, 3), "int64")), 0, 10)
    assert rl.shape == [2, 3]


def test_inplace_family():
    x = _t(np.array([1.0, 4.0], "float32"))
    y = paddle.log_(x)
    assert y is x
    np.testing.assert_allclose(_np(x), np.log([1.0, 4.0]), rtol=1e-6)
    xr = _t(np.arange(6, dtype="float32"))
    paddle.reshape_(xr, [2, 3])
    assert xr.shape == [2, 3]
    xs = _t(np.array([[1.0, 2.0]], "float32"))
    paddle.squeeze_(xs, 0)
    assert xs.shape == [2]
    xt = _t(np.eye(3, dtype="float32") * 5)
    paddle.tril_(xt, -1)
    assert _np(xt).sum() == 0
    xw = _t(np.array([1.0, -1.0], "float32"))
    paddle.multiply_(xw, _t(np.array([2.0, 2.0], "float32")))
    np.testing.assert_allclose(_np(xw), [2, -2])


def test_inplace_grad_flows():
    x = _t(np.array([1.0, 2.0], "float32"))
    x.stop_gradient = False
    y = x * 2.0
    paddle.log_(y)
    y.sum().backward()
    np.testing.assert_allclose(_np(x.grad), [1.0, 0.5], rtol=1e-5)


def test_set_printoptions():
    paddle.set_printoptions(precision=3)
    try:
        s = repr(_t(np.array([1.23456789], "float32")))
        assert "1.235" in s
    finally:
        np.set_printoptions(precision=8)


def test_summary():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    info = paddle.summary(net, (1, 4))
    assert info["total_params"] == 4 * 8 + 8 + 8 * 2 + 2
    assert info["layers"] >= 3


def test_where_inplace_mutates_x():
    cond = _t(np.array([True, False]))
    x = _t(np.array([1.0, 2.0], "float32"))
    y = _t(np.array([9.0, 9.0], "float32"))
    out = paddle.where_(cond, x, y)
    assert out is x
    np.testing.assert_allclose(_np(x), [1.0, 9.0])
    assert _np(cond).dtype == np.bool_  # condition untouched


def test_randint_like_matches_dtype():
    f = paddle.randint_like(_t(np.zeros((2, 2), "float32")), 0, 5)
    assert "float32" in str(f.dtype)


def test_take_clip_negative_goes_to_zero():
    out = paddle.take(_t(np.arange(5)), _t(np.array([-1, 10])),
                      mode="clip")
    np.testing.assert_allclose(_np(out), [0, 4])


def test_tensor_split_negative_and_oob_indices():
    parts = paddle.tensor_split(_t(np.arange(10)), [-3])
    assert [p.shape[0] for p in parts] == [7, 3]
    parts = paddle.tensor_split(_t(np.arange(5)), [3, 10])
    assert [p.shape[0] for p in parts] == [3, 2, 0]


def test_summary_shared_layer_counts_once():
    class Twice(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            return self.lin(self.lin(x))

    info = paddle.summary(Twice(), (1, 4))
    assert info["total_params"] == 4 * 4 + 4  # one instance, not two


def test_masked_scatter_too_few_values_raises():
    with pytest.raises(Exception, match="numel"):
        paddle.masked_scatter(
            _t(np.zeros(5, "float32")),
            _t(np.array([True, True, True, False, False])),
            _t(np.array([1.0, 2.0], "float32")))


def test_take_bad_mode_raises():
    with pytest.raises(Exception, match="mode"):
        paddle.take(_t(np.arange(5)), _t(np.array([0])), mode="clamp")


def test_sgn_tiny_complex():
    out = _np(paddle.sgn(_t(np.array([1e-35 + 0j], "complex64"))))
    assert abs(out[0] - 1.0) < 1e-5


def test_reference_tensor_method_surface_complete():
    """Every name in the reference's tensor_method_func list must be a
    Tensor attribute (the package-import patch pass binds them)."""
    t = _t(np.zeros((2, 3), "float32"))
    import os

    ref = "/root/reference/python/paddle/tensor/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference tree not mounted")
    import re

    src = open(ref).read()
    m = re.search(r"tensor_method_func = \[(.*?)\]", src, re.S)
    names = sorted(set(re.findall(r"'(\w+)'", m.group(1))))
    missing = [n for n in names
               if not hasattr(t, n) and not n.startswith("_")]
    assert missing == [], missing


def test_new_tail_functions():
    np.testing.assert_allclose(
        _np(paddle.as_strided(_t(np.arange(12, dtype="float32")),
                              [3, 2], [4, 1])),
        [[0, 1], [4, 5], [8, 9]])
    assert paddle.add_n([_t(np.ones(3)), _t(np.ones(3))]).shape == [3]
    assert paddle.atleast_2d(_t(np.array([1.0]))).shape == [1, 1]
    assert paddle.atleast_3d(_t(np.array([[1.0]]))).shape == [1, 1, 1]
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    cd = _np(paddle.cdist(_t(np.zeros((2, 3), "float32")),
                          _t(np.ones((4, 3), "float32"))))
    np.testing.assert_allclose(cd, np.full((2, 4), np.sqrt(3)),
                               rtol=1e-5)
    assert int(paddle.count_nonzero(
        _t(np.array([0, 1, 2, 0])))) == 2
    u = paddle.to_tensor(np.arange(10, dtype="float32")).unfold(0, 4, 2)
    assert u.shape == [4, 4]
    x = _t(np.zeros(4, "float32"))
    paddle.normal_(x)
    assert np.abs(_np(x)).sum() > 0
    # methods from the bulk bind: stft on a tensor
    sig = paddle.to_tensor(np.random.rand(512).astype("float32"))
    assert sig.stft(64, 16).shape[0] == 33


def test_review_regressions_tail2():
    # histogramdd: (hist, edges_list) contract
    h, edges = paddle.histogramdd(
        _t(np.random.RandomState(0).rand(6, 2).astype("float32")),
        bins=3)
    assert h.shape == [3, 3] and len(edges) == 2
    # atleast_3d reference placement
    assert paddle.atleast_3d(_t(np.zeros(5))).shape == [1, 5, 1]
    assert paddle.atleast_3d(_t(np.zeros((2, 5)))).shape == [2, 5, 1]
    assert paddle.atleast_2d(_t(np.zeros(5))).shape == [1, 5]
    # diagonal_scatter rectangular
    d = paddle.diagonal_scatter(_t(np.zeros((3, 5), "float32")),
                                _t(np.ones(3, "float32")), 1)
    assert _np(d).sum() == 3
    # lu_unpack roundtrip on a square matrix
    import jax.scipy.linalg as jsl
    import jax.numpy as jnp

    a = np.random.RandomState(1).rand(4, 4).astype("float32")
    lu, piv = jsl.lu_factor(jnp.asarray(a))
    P, L, U = paddle.lu_unpack(_t(np.asarray(lu)),
                               _t(np.asarray(piv) + 1))
    rec = _np(P) @ _np(L) @ _np(U)
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)
    # geometric_ fills continuous values (no flooring)
    paddle.seed(5)
    g = _t(np.zeros(2000, "float32"))
    paddle.to_tensor  # noqa
    g.geometric_(0.5)
    vals = _np(g)
    assert (np.abs(vals - np.round(vals)) > 1e-6).any()


def test_namespace_tails():
    # paddle.linalg must be the namespace module, not ops.linalg
    assert "ops" not in paddle.linalg.__file__
    assert float(paddle.linalg.vector_norm(
        _t(np.array([3.0, 4.0], "float32"))).numpy()) == 5.0
    m = _t(np.eye(3, dtype="float32") * 2)
    assert abs(float(paddle.linalg.matrix_norm(m).numpy())
               - np.sqrt(12)) < 1e-5
    assert callable(paddle.linalg.lu_unpack)
    assert callable(paddle.linalg.pca_lowrank)
    assert paddle.amp.is_bfloat16_supported()
    assert paddle.amp.is_float16_supported()
    h = paddle.fft.hfft2(_t((np.random.rand(4, 8)
                             + 1j * np.random.rand(4, 8))
                            .astype("complex64")))
    assert h.shape == [4, 14]
    assert "complex" in str(paddle.fft.ihfft2(
        _t(np.random.rand(4, 8).astype("float32"))).dtype)


def test_io_tails():
    from paddle_tpu.io import (ChainDataset, ComposeDataset,
                               SubsetRandomSampler, TensorDataset,
                               WeightedRandomSampler, get_worker_info)

    d1 = TensorDataset([_t(np.arange(4))])
    d2 = TensorDataset([_t(np.arange(4) * 10)])
    comp = ComposeDataset([d1, d2])
    assert len(comp) == 4 and len(comp[1]) == 2
    assert sorted(list(SubsetRandomSampler([1, 3]))) == [1, 3]
    assert list(WeightedRandomSampler([0.0, 1.0, 0.0], 5)) == [1] * 5
    with pytest.raises(ValueError, match="non-negative"):
        WeightedRandomSampler([-1.0, 1.0], 2)
    assert get_worker_info() is None

    class It(paddle.io.IterableDataset):
        def __init__(self, n):
            self.n = n

        def __iter__(self):
            return iter(range(self.n))

    assert list(ChainDataset([It(2), It(3)])) == [0, 1, 0, 1, 2]


def test_namespace_tail_regressions():
    # hfft2 honors s on the leading axis too
    x = _t((np.random.RandomState(0).rand(8, 8)
            + 1j * np.random.RandomState(1).rand(8, 8))
           .astype("complex64"))
    out = paddle.fft.hfft2(x, s=(4, 6))
    assert out.shape == [4, 6]
    # hfftn infers the last len(s) axes
    x3 = _t((np.random.rand(3, 8, 8) + 0j).astype("complex64"))
    assert paddle.fft.hfftn(x3, s=(4, 6)).shape == [3, 4, 6]
    # vector_norm inf on 2-D is max|x|, not the matrix norm
    m = _t(np.array([[1.0, -5.0], [2.0, 3.0]], "float32"))
    assert float(paddle.linalg.vector_norm(m, p=float("inf"))
                 .numpy()) == 5.0
    # new names exported via __all__
    assert "vector_norm" in paddle.linalg.__all__
    import paddle_tpu.io as io_mod

    assert "WeightedRandomSampler" in io_mod.__all__
    with pytest.raises(ValueError, match="all zero"):
        io_mod.WeightedRandomSampler([0.0, 0.0], 2)


def test_get_worker_info_in_workers():
    """The shm multiprocess path must expose worker context."""
    import paddle_tpu.io as io_mod
    from paddle_tpu.io import shm_loader

    if not shm_loader.available():
        pytest.skip("native shm ring unavailable")

    class DS(io_mod.Dataset):
        def __getitem__(self, i):
            return np.float32(i)

        def __len__(self):
            return 8

    def collate(items):
        info = io_mod.get_worker_info()
        return (info.id if info else -1,
                info.num_workers if info else -1,
                np.stack(items))

    seen = []
    for wid, nw, batch in shm_loader.iter_multiprocess(
            DS(), [[0, 1], [2, 3], [4, 5], [6, 7]], collate, 2):
        seen.append((wid, nw))
    assert all(nw == 2 for _, nw in seen)
    assert {w for w, _ in seen} == {0, 1}
