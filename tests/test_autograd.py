"""Autograd engine semantics (reference: test/legacy_test autograd tests +
fluid/eager/backward.cc behaviors)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def t(x, sg=False):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32), stop_gradient=sg)


class TestBackward:
    def test_simple_chain(self):
        x = t([2.0])
        y = x * x + 3.0 * x
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_grad_accumulation(self):
        x = t([1.0, 2.0])
        (x * 2.0).sum().backward()
        (x * 3.0).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_shared_input(self):
        x = t([3.0])
        y = x * x  # both edges to same leaf
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_diamond(self):
        x = t([2.0])
        a = x * 2.0
        b = x * 3.0
        (a * b).backward()  # d/dx 6x^2 = 12x
        np.testing.assert_allclose(x.grad.numpy(), [24.0])

    def test_stop_gradient(self):
        x = t([1.0])
        y = t([2.0], sg=True)
        (x * y).backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_no_grad_context(self):
        x = t([1.0])
        with paddle.no_grad():
            y = x * 5.0
        assert y.stop_gradient
        assert y._grad_node is None

    def test_detach(self):
        x = t([1.0])
        y = (x * 2.0).detach()
        z = y * 3.0
        z.backward()
        assert x.grad is None

    def test_backward_with_grad_tensor(self):
        x = t([1.0, 2.0])
        y = x * 2.0
        y.backward(paddle.to_tensor(np.array([0.5, 2.0], np.float32)))
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 4.0])

    def test_multi_output_op(self):
        x = t(np.arange(6.0).reshape(2, 3))
        a, b = paddle.split(x, 2, axis=0)
        (a.sum() * 2.0 + b.sum() * 3.0).backward()
        np.testing.assert_allclose(
            x.grad.numpy(), [[2, 2, 2], [3, 3, 3]])

    def test_retain_graph(self):
        x = t([2.0])
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_paddle_grad_api(self):
        x = t([3.0])
        y = x * x
        (gx,) = paddle.grad(y, x)
        np.testing.assert_allclose(gx.numpy(), [6.0])
        assert x.grad is None  # paddle.grad does not pollute .grad

    def test_leaf_hook(self):
        x = t([1.0])
        seen = []
        x.register_hook(lambda g: seen.append(g.numpy().copy()))
        (x * 4.0).backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [4.0])

    def test_hook_modifies_grad(self):
        x = t([1.0])
        x.register_hook(lambda g: g * 2.0)
        (x * 3.0).backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])


class TestPyLayer:
    def test_custom_fwd_bwd(self):
        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2.0

            @staticmethod
            def backward(ctx, gout):
                (x,) = ctx.saved_tensor
                return gout * 2.0

        x = t([1.5])
        y = Double.apply(x)
        np.testing.assert_allclose(y.numpy(), [3.0])
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_multi_io(self):
        class MulAdd(PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                ctx.save_for_backward(a, b)
                return a * b, a + b

            @staticmethod
            def backward(ctx, g1, g2):
                a, b = ctx.saved_tensor
                return g1 * b + g2, g1 * a + g2

        a, b = t([2.0]), t([3.0])
        p, s = MulAdd.apply(a, b)
        (p + s).backward()
        np.testing.assert_allclose(a.grad.numpy(), [4.0])
        np.testing.assert_allclose(b.grad.numpy(), [3.0])


class TestInplace:
    def test_add_(self):
        x = t([1.0])
        x.add_(t([2.0], sg=True))
        np.testing.assert_allclose(x.numpy(), [3.0])

    def test_setitem(self):
        x = paddle.to_tensor(np.zeros((3, 3), np.float32))
        x[1, 1] = 5.0
        assert x.numpy()[1, 1] == 5.0
