"""Communication ledger + exposed-comm attribution + serving spans.

Under test:
- closed-form wire-byte formulas (commledger.wire_bytes)
- trace-time capture: exact records for a hand-built shard_map program,
  empty capture on cached executions (the per-program ledger contract)
- ring closed forms: ag_matmul / matmul_rs / matmul_allreduce ledger
  bytes match the analytic ring costs EXACTLY on the 8-vdev mesh
- DP grad all-reduce: ParallelEngine's compiled step ledger matches the
  per-parameter closed form; comm counters accumulate per step; zero
  recompiles after warmup with the ledger enabled
- ablation: every collective's local stand-in preserves shape/dtype
- profile_exposed_comm: report shape, gauge publication, engine state
  restored bit-exactly, program cache intact (no recompile after)
- per-request serving spans: lifecycle stages, bounded ring, Chrome
  trace export, stage-latency histogram
- the stdlib /metrics HTTP exporter round-trips the exposition
- tools/bench_compare: regression verdicts + trajectory on synthetic
  rounds
"""
import json
import sys
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.distributed import collective as C
from paddle_tpu.distributed import collective_matmul as cm
from paddle_tpu.distributed.engine import ParallelEngine, _shard_map
from paddle_tpu.observability import commledger as cl

F32 = 4  # bytes


def _mesh(n=8, axis="mp"):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), (axis,))


# ---------------------------------------------------------------------------
# closed-form wire bytes
# ---------------------------------------------------------------------------
class TestWireBytes:
    def test_formulas(self):
        assert cl.wire_bytes("psum", 800, 8) == 2 * 7 / 8 * 800
        assert cl.wire_bytes("pmax", 800, 8) == 2 * 7 / 8 * 800
        assert cl.wire_bytes("all_gather", 100, 8) == 700
        assert cl.wire_bytes("reduce_scatter", 800, 8) == 700
        assert cl.wire_bytes("all_to_all", 800, 8) == 700
        assert cl.wire_bytes("ppermute", 256, 8) == 256
        # a group of one moves nothing
        for op in cl.OPS:
            assert cl.wire_bytes(op, 1234, 1) == 0.0
        with pytest.raises(ValueError):
            cl.wire_bytes("bogus", 1, 2)


# ---------------------------------------------------------------------------
# capture on a hand-built SPMD program
# ---------------------------------------------------------------------------
class TestCapture:
    def test_exact_records_and_cached_reuse(self):
        mesh = _mesh()

        def f(x):
            y = C.t_psum(x, ("mp",))
            z = C.t_all_gather(x, ("mp",), axis=0)
            w = C.t_psum_scatter(z, ("mp",), scatter_dimension=0)
            return y.sum() + z.sum() + w.sum()

        step = jax.jit(_shard_map(f, mesh, (P("mp"),), P()))
        x = jnp.ones((16, 4), jnp.float32)
        with cl.capture() as led:
            step(x)
        # local shard [2, 4] f32 = 32 bytes
        assert [(r.op, r.axis, r.shape) for r in led.records] == [
            ("psum", "mp", (2, 4)), ("all_gather", "mp", (2, 4)),
            ("reduce_scatter", "mp", (16, 4))]
        assert led.bytes_for(op="psum") == 2 * 7 / 8 * 32
        assert led.bytes_for(op="all_gather") == 7 * 32
        assert led.bytes_for(op="reduce_scatter") == 7 / 8 * 256
        # second execution hits the compiled program: nothing re-notes
        with cl.capture() as led2:
            step(x)
        assert len(led2) == 0

    def test_publish_increments_counters(self):
        reg = obs.MetricsRegistry()
        from paddle_tpu.observability.catalog import comm_metrics

        m = comm_metrics(reg)
        led = cl.CommLedger()
        cl._state.captures.append(led)
        try:
            cl.note("psum", ("dp",), (4, 4), np.dtype("float32"), 8)
            cl.note("ppermute", ("pp",), (2,), np.dtype("float32"), 2,
                    ((0, 1), (1, 0)))
        finally:
            cl._state.captures.remove(led)
        led.publish(m["comm_bytes"], m["comm_ops"])
        led.publish(m["comm_bytes"], m["comm_ops"])
        assert m["comm_bytes"].value(axis="dp", op="psum") == \
            2 * (2 * 7 / 8 * 64)
        assert m["comm_ops"].value(axis="pp", op="ppermute") == 2


# ---------------------------------------------------------------------------
# ring closed forms (the acceptance gate)
# ---------------------------------------------------------------------------
class TestRingClosedForms:
    S, K, N, p = 128, 8, 16, 8

    def _trace(self, fn, in_specs, out_specs, *args):
        mesh = _mesh(self.p)
        step = jax.jit(_shard_map(fn, mesh, in_specs, out_specs))
        with cl.capture() as led:
            out = step(*args)
        jax.block_until_ready(out)
        return led

    def test_ag_matmul_ring_bytes(self):
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(self.S, self.K), jnp.float32)
        w = jnp.asarray(r.randn(self.K, self.N), jnp.float32)
        led = self._trace(lambda a, b: cm.ag_matmul(a, b, ("mp",), 0),
                          (P("mp"), P(None, "mp")), P("mp"), x, w)
        shard_bytes = (self.S // self.p) * self.K * F32
        # bidirectional ring: p-1 shard-sized ppermutes, nothing else
        assert led.ops_for(op="ppermute") == self.p - 1
        assert led.bytes_for(op="ppermute") == (self.p - 1) * shard_bytes
        assert led.bytes_for() == led.bytes_for(op="ppermute")

    def test_matmul_rs_ring_bytes(self):
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(self.S, self.K), jnp.float32)
        w = jnp.asarray(r.randn(self.K, self.N), jnp.float32)
        led = self._trace(lambda a, b: cm.matmul_rs(a, b, ("mp",), 0),
                          (P("mp"), P(None, "mp")), P("mp"), x, w)
        # accumulator chunk: [S/p^2, N/p] partial sums (w is column-
        # sharded, so the local feature dim is N/p) shifted p-1 times
        acc_bytes = (self.S // self.p // self.p) \
            * (self.N // self.p) * F32
        assert led.ops_for(op="ppermute") == self.p - 1
        assert led.bytes_for(op="ppermute") == (self.p - 1) * acc_bytes
        assert led.bytes_for() == led.bytes_for(op="ppermute")

    def test_matmul_allreduce_ring_bytes(self):
        r = np.random.RandomState(2)
        x = jnp.asarray(r.randn(self.S, self.K), jnp.float32)
        w = jnp.asarray(r.randn(self.K, self.N), jnp.float32)
        led = self._trace(
            lambda a, b: cm.matmul_allreduce(a, b, ("mp",), 0),
            (P("mp"), P(None, "mp")), P("mp"), x, w)
        acc_bytes = (self.S // self.p // self.p) \
            * (self.N // self.p) * F32
        # rs-ring (p-1 shifts) + tiled all_gather of the acc chunk
        assert led.bytes_for(op="ppermute") == (self.p - 1) * acc_bytes
        assert led.ops_for(op="all_gather") == 1
        assert led.bytes_for(op="all_gather") == (self.p - 1) * acc_bytes
        assert led.bytes_for() == 2 * (self.p - 1) * acc_bytes


# ---------------------------------------------------------------------------
# DP grad all-reduce through the compiled train step
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def dp_engine():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    obs.reset_registry()
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    r = np.random.RandomState(0)
    ids = r.randint(0, 128, (8, 17))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    losses = [float(step(batch)) for _ in range(3)]
    return eng, step, batch, losses


class TestDpGradSyncLedger:
    def test_ledger_matches_closed_form(self, dp_engine):
        eng, _, _, _ = dp_engine
        led = eng.comm_ledger()
        p = 8
        # per trainable param: one grad pmean; plus one scalar loss
        # pmean — nothing else crosses 'dp' in this config
        expect = sum(
            2 * (p - 1) / p
            * int(np.prod(q._value.shape)) * q._value.dtype.itemsize
            for q in eng.trainable) + 2 * (p - 1) / p * F32
        assert led.bytes_for(axis="dp", op="psum") == expect
        assert led.ops_for(axis="dp", op="psum") == \
            len(eng.trainable) + 1
        assert led.axis_labels() == ["dp"]

    def test_counters_accumulate_per_step(self, dp_engine):
        eng, _, _, losses = dp_engine
        led = eng.comm_ledger()
        per_step = led.bytes_for(axis="dp", op="psum")
        got = eng._metrics["comm_bytes"].value(axis="dp", op="psum")
        assert got == len(losses) * per_step
        assert eng._metrics["comm_ops"].value(axis="dp", op="psum") \
            == len(losses) * led.ops_for(axis="dp", op="psum")

    def test_zero_recompiles_with_ledger_enabled(self, dp_engine):
        eng, step, batch, _ = dp_engine
        c0 = eng.stats.compiles
        float(step(batch))
        float(step(batch))
        assert eng.stats.compiles == c0      # ledger adds no signatures

    def test_snapshot_stays_inside_schema(self, dp_engine):
        from paddle_tpu.observability import catalog

        eng, _, _, _ = dp_engine
        with open(catalog.SCHEMA_PATH) as f:
            schema = json.load(f)
        m = eng.metrics_snapshot()["metrics"]
        for name in ("paddle_tpu_comm_bytes_total",
                     "paddle_tpu_comm_ops_total"):
            assert name in m and name in schema
            for row in m[name]["series"]:
                assert sorted(row["labels"]) == schema[name]["labels"]


# ---------------------------------------------------------------------------
# EP all-to-all through the compiled MoE step (the expert-parallel axis)
# ---------------------------------------------------------------------------
class TestEpA2aLedger:
    def _engine(self, async_dispatch):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.incubate.distributed.models.moe import MoELayer

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "ep_degree": 8, "mp_degree": 1,
            "moe_configs": {"ep_async_dispatch": async_dispatch}}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        model = MoELayer(8, d_hidden=16, num_experts=8, gate="gshard")
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=model.parameters())
        eng = ParallelEngine(model, opt, hcg.mesh)
        step = eng.train_step(
            lambda m, b: paddle.mean(m(b["x"]) ** 2) + 0.01 * m.aux_loss)
        r = np.random.RandomState(0)
        batch = {"x": paddle.to_tensor(
            r.randn(16, 8, 8).astype("float32"))}
        float(step(batch))
        # per-rank shapes of the dispatch tensor [E, C, d]
        T_local = 16 * 8 // 8
        C_cap = model._capacity(T_local)
        return eng, 8 * C_cap * 8 * F32

    def test_unfused_a2a_closed_form(self):
        """dispatch + combine, fwd + bwd = 4 all_to_alls of the full
        [E, C, d] dispatch tensor, each (p-1)/p x payload on the wire
        (the _ledger_a2a custom VJP keeps the backward pair visible)."""
        eng, payload = self._engine(False)
        led = eng.comm_ledger()
        p = 8
        assert led.ops_for(axis="ep", op="all_to_all") == 4
        assert led.bytes_for(axis="ep", op="all_to_all") == \
            4 * (p - 1) / p * payload
        assert led.ops_for(axis="ep", op="ppermute") == 0

    def test_fused_ring_same_wire_bytes(self):
        """ep_async_dispatch rides ppermutes: 2(p-1) per direction per
        pass = 4(p-1) block-sized shifts, totalling EXACTLY the a2a
        closed form (the ring re-chunks the exchange, it does not move
        more bytes)."""
        eng, payload = self._engine(True)
        led = eng.comm_ledger()
        p = 8
        block = payload // p                  # [E/p, C, d] per tick
        assert led.ops_for(axis="ep", op="all_to_all") == 0
        assert led.ops_for(axis="ep", op="ppermute") == 4 * (p - 1)
        assert led.bytes_for(axis="ep", op="ppermute") == \
            4 * (p - 1) * block == 4 * (p - 1) / p * payload


# ---------------------------------------------------------------------------
# bucketed grad sync (comm_overlap): exact wire bytes + scan trip counts
# ---------------------------------------------------------------------------
def _zero2_engine(overlap):
    """dp2 x sharding4 ZeRO stage-2 MLP engine (grad_buckets target)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 2, "sharding_degree": 4,
        "sharding_configs": {"comm_overlap": overlap,
                             "comm_buffer_size_MB": 1e-6}}
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(3)

    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(16, 32)
            self.fc2 = paddle.nn.Linear(32, 16)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    model = MLP()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    model, opt, _ = dist.group_sharded_parallel(model, opt, "os_g")
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(
        lambda m, b: paddle.mean((m(b["x"]) - b["y"]) ** 2))
    x = np.zeros((8, 16), "float32")
    float(step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(x)}))
    return eng


class TestBucketedGradSyncLedger:
    """The satellite pins: per-bucket collectives move EXACTLY the
    unbucketed closed-form bytes (coalescing re-chunks, it never moves
    more), and the ledger op count equals the bucket count."""

    def test_bucketed_bytes_match_unbucketed_closed_form(self):
        eng_on = _zero2_engine(True)
        eng_off = _zero2_engine(False)
        led_on, led_off = eng_on.comm_ledger(), eng_off.comm_ledger()
        plan = eng_on._bucket_plan
        p_sh, p_dp = 4, 2
        payload = sum(
            int(np.prod(q._value.shape)) * q._value.dtype.itemsize
            for q in eng_on.trainable)
        # stage-2 reduce-scatter over 'sharding': sum over buckets ==
        # (p-1)/p x total grad payload == the per-param closed form
        assert led_on.bytes_for(axis="sharding", op="reduce_scatter") \
            == (p_sh - 1) / p_sh * payload \
            == led_off.bytes_for(axis="sharding", op="reduce_scatter")
        # grad pmean over plain dp: 2(p-1)/p x total payload, same both
        # ways (the ledger books pmean under the "psum" kind); knob-off
        # adds nothing else on dp, knob-on adds nothing else on dp
        dp_grad = 2 * (p_dp - 1) / p_dp * payload
        assert led_on.bytes_for(axis="dp", op="psum") == dp_grad
        assert led_off.bytes_for(axis="dp", op="psum") == dp_grad
        # op count == bucket count (the tiny buffer forces one param
        # per bucket here), vs one op per parameter unbucketed
        nb = plan.num_buckets
        assert nb == len(eng_on.trainable)
        assert led_on.ops_for(axis="sharding", op="reduce_scatter") == nb
        assert led_on.ops_for(axis="dp", op="psum") == nb
        assert led_off.ops_for(axis="sharding", op="reduce_scatter") \
            == len(eng_off.trainable)
        # the folded grad-norm: ONE psum per signature group over
        # spec+zero axes, instead of one per parameter
        assert led_on.ops_for(axis="sharding", op="psum") \
            == len(plan.groups)

    def test_scan_trips_scales_ledger_and_survives_ablation(self):
        """A collective noted under scan_trips(nb) counts nb times —
        the bucket scan's exact accounting (plain scan bodies stay the
        documented once-counted lower bound)."""
        mesh = _mesh()
        nb = 4

        def prog(x):
            def tick(c, xt):
                return c + C.t_psum_scatter(
                    xt, ("mp",), scatter_dimension=0, tiled=True).sum(), \
                    None

            with cl.scan_trips(nb):
                out, _ = jax.lax.scan(tick, jnp.float32(0.0),
                                      x.reshape(nb, 16, 4))
            # an unmarked scan body still counts once (lower bound)
            def tick2(c, xt):
                return c + C.t_psum(xt, ("mp",)).sum(), None

            out2, _ = jax.lax.scan(tick2, jnp.float32(0.0),
                                   x.reshape(nb, 16, 4))
            return out + out2

        step = jax.jit(_shard_map(prog, mesh, (P(None, "mp"),), P()))
        x = jnp.ones((64, 32), jnp.float32)   # local shard [64, 4]
        with cl.capture() as led:
            step(x)
        tick_payload = 16 * 4 * F32
        assert led.ops_for(op="reduce_scatter") == nb
        assert led.bytes_for(op="reduce_scatter") == \
            nb * 7 / 8 * tick_payload
        assert led.ops_for(op="psum") == 1          # unmarked scan
        assert [r.trips for r in led.records] == [nb, 1]
        # the trip-scaled records replay trip-count times and the
        # ablated compile keeps shapes (the exposed-comm machinery
        # works unchanged over the bucket scan)
        rfn = cl.replay_callable(
            [r for r in led.records if r.op == "reduce_scatter"],
            mesh, _shard_map, jax.jit)
        assert float(rfn()) == 0.0
        with cl.ablate({"mp"}):
            abl = jax.jit(_shard_map(prog, mesh, (P(None, "mp"),),
                                     P()))(x)
        assert abl.shape == ()

    def test_trips_default_and_nesting(self):
        led = cl.CommLedger()
        cl._state.captures.append(led)
        try:
            cl.note("psum", ("dp",), (4,), np.dtype("float32"), 2)
            with cl.scan_trips(3):
                cl.note("psum", ("dp",), (4,), np.dtype("float32"), 2)
                with cl.scan_trips(2):
                    cl.note("psum", ("dp",), (4,), np.dtype("float32"),
                            2)
        finally:
            cl._state.captures.remove(led)
        assert [r.trips for r in led.records] == [1, 3, 6]
        assert led.ops_for(op="psum") == 10
        one = 2 * (2 - 1) / 2 * 16
        assert led.bytes_for(op="psum") == 10 * one
        assert led.totals()[("dp", "psum")]["ops"] == 10


# ---------------------------------------------------------------------------
# pipeline ring trips-exact accounting
# ---------------------------------------------------------------------------
class TestPipelineRingLedger:
    """The pp ring's per-tick ppermute rides _pipe_fn's lax.scan under
    ``scan_trips(E + S - 1)``: the ledger is trips-EXACT on the pp
    axis, pinned to the closed form trips x carry bytes. AD synthesizes
    the reverse ring outside the noting shim, so the forward schedule
    is the entire pp record set (the docstring caveat, asserted here)."""

    def test_ring_bytes_match_closed_form(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.models import GPTForCausalLMPipe
        from paddle_tpu.models.gpt import GPTConfig

        S, V, M, sh = 2, 2, 2, 2        # pp, vpp, microbatches, sharding
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                        num_heads=4, max_position_embeddings=32)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 2, "pp_degree": S,
            "sharding_degree": sh,
            "pp_configs": {"num_virtual_pipeline_stages": V}}
        strategy.pipeline_configs = {"accumulate_steps": M,
                                     "micro_batch_size": 2}
        fleet._fleet_state.update(initialized=False, hcg=None,
                                  strategy=None)
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        model = GPTForCausalLMPipe(cfg)
        dm = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=model.parameters()))
        r = np.random.RandomState(0)
        ids = r.randint(0, cfg.vocab_size, (8, 17))
        x, y = paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])
        float(dm.train_batch([x, y], opt))
        led = dm._engine.comm_ledger()
        pp_recs = [q for q in led.records
                   if q.op == "ppermute" and "pp" in q.axes]
        # ONE traced site, trips == E + S - 1 forward ticks
        assert len(pp_recs) == 1
        trips = V * M + S - 1
        assert pp_recs[0].trips == trips
        # carry payload: one microbatch of stage-boundary activations,
        # [B_local/M, seq, hidden] f32 (dp x sharding splits the batch)
        seq = ids.shape[1] - 1
        mb = ids.shape[0] // (1 * sh) // M
        payload = mb * seq * cfg.hidden_size * F32
        assert pp_recs[0].payload_bytes == payload
        # trips-exact totals: bytes == trips x payload (ppermute wire
        # == payload), ops counted once per tick
        assert led.bytes_for(axis="pp", op="ppermute") == trips * payload
        assert led.ops_for(axis="pp", op="ppermute") == trips
        # no reverse-ring record exists: the backward ppermute never
        # re-enters the noting shim (grad-norm psums etc. still cross
        # pp as part of wider axis groups — only the ring is pinned)
        assert [q.op for q in led.records if q.op == "ppermute"] \
            == ["ppermute"]


# ---------------------------------------------------------------------------
# ablation stand-ins
# ---------------------------------------------------------------------------
class TestAblation:
    def test_shape_and_dtype_parity(self):
        mesh = _mesh()

        def prog(x):
            a = C.t_psum(x, ("mp",))
            b = C.t_all_gather(x, ("mp",), axis=0)
            c = C.t_psum_scatter(b, ("mp",), scatter_dimension=0)
            d = C.t_all_to_all(b, ("mp",), split_axis=0, concat_axis=1)
            e = C.t_ppermute(x, ("mp",),
                             [(i, (i + 1) % 8) for i in range(8)])
            return a, b, c, d, e

        x = jnp.ones((16, 8), jnp.bfloat16)
        real = jax.jit(_shard_map(prog, mesh, (P("mp"),),
                                  (P("mp"), P(), P("mp"), P(),
                                   P("mp"))))(x)
        with cl.ablate({"mp"}):
            abl = jax.jit(_shard_map(prog, mesh, (P("mp"),),
                                     (P("mp"), P(), P("mp"), P(),
                                      P("mp"))))(x)
        for r, a in zip(real, abl):
            assert r.shape == a.shape and r.dtype == a.dtype

    def test_token_and_scoping(self):
        assert cl.ablation_token() is None
        with cl.ablate({"dp"}):
            assert cl.ablation_token() == frozenset({"dp"})
            assert cl.ablating("dp") and not cl.ablating("mp")
            with cl.ablate({"mp"}):
                assert cl.ablation_token() == frozenset({"dp", "mp"})
        assert cl.ablation_token() is None


# ---------------------------------------------------------------------------
# exposed-comm attribution
# ---------------------------------------------------------------------------
class TestExposedComm:
    def test_build_report_math(self):
        rep = cl.build_report(1.0, {"dp": 0.2, "mp": -0.05},
                              {"dp": 0.5, "mp": 0.1})
        assert rep.exposed_seconds == {"dp": 0.2, "mp": 0.0}
        assert rep.exposed_fraction["dp"] == pytest.approx(0.4)
        assert rep.exposed_fraction["mp"] == 0.0
        assert rep.grad_sync_exposed_seconds == pytest.approx(0.2)
        # exposed above replay: fraction clamps to 1
        rep2 = cl.build_report(1.0, {"sharding+dp": 0.4},
                               {"sharding+dp": 0.1})
        assert rep2.exposed_fraction["sharding+dp"] == 1.0
        assert rep2.grad_sync_exposed_seconds == pytest.approx(0.4)

    def test_profile_restores_state_and_cache(self, dp_engine):
        eng, step, batch, _ = dp_engine
        before_p = [np.asarray(p._value) for p in eng.params]
        before_sc = eng.optimizer._step_count
        c0 = eng.stats.compiles
        rep = eng.profile_exposed_comm(step, batch, repeats=2)
        assert set(rep.exposed_seconds) == {"dp"}
        assert 0.0 <= rep.exposed_fraction["dp"] <= 1.0
        assert rep.replay_seconds["dp"] > 0
        assert rep.step_seconds > 0
        # dp IS a grad-sync axis
        assert rep.grad_sync_exposed_seconds == \
            pytest.approx(rep.exposed_seconds["dp"])
        # engine state restored bit-exactly
        for b, p in zip(before_p, eng.params):
            assert (b == np.asarray(p._value)).all()
        assert eng.optimizer._step_count == before_sc
        # ablated replays are evicted from the cache; the next real
        # step reuses the original executable (and CompileStats never
        # saw the replays)
        assert eng.stats.compiles == c0
        assert all(k[-1] is None for k in eng._compiled)
        float(step(batch))
        assert eng.stats.compiles == c0
        # gauges published
        m = eng.metrics_snapshot()["metrics"]
        assert m["paddle_tpu_comm_exposed_fraction"]["series"][0][
            "labels"] == {"axis": "dp"}
        assert m["paddle_tpu_grad_sync_exposed_seconds"]["series"][0][
            "value"] == pytest.approx(rep.grad_sync_exposed_seconds)

    def test_pipeline_wrapper_requires_train_batch(self):
        from paddle_tpu.core.enforce import PreconditionNotMetError
        from paddle_tpu.distributed.fleet.meta_parallel import (
            pipeline_parallel as pp)

        class _Fake:
            _train_step = None

        with pytest.raises(PreconditionNotMetError):
            pp.PipelineParallel.profile_exposed_comm(_Fake(), [1, 2])


# ---------------------------------------------------------------------------
# serving request spans
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def span_engine():
    from paddle_tpu.inference import (Config, ServingEngine,
                                      create_predictor)
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    obs.reset_registry()
    paddle.seed(11)
    model = LlamaForCausalLM(llama_tiny())
    pred = create_predictor(
        Config().set_model(model).enable_paged_kv(page_size=8))
    eng = ServingEngine(pred, max_batch=2, decode_chunk=2)
    r = np.random.RandomState(0)
    V = model.config.vocab_size
    lens = [7, 12, 24, 9, 5]
    rids = [eng.submit(r.randint(1, V, (L,)), max_new_tokens=6)
            for L in lens]
    done = eng.run()
    return eng, rids, done


class TestServingSpans:
    def test_every_request_has_lifecycle_spans(self, span_engine):
        eng, rids, _ = span_engine
        traces = {t["rid"]: t for t in eng.request_traces()}
        assert set(traces) == set(rids)
        for t in traces.values():
            names = [s["name"] for s in t["spans"]]
            for stage in ("queued", "prefill", "decode", "e2e"):
                assert stage in names
            assert "decode_round" in names
            for s in t["spans"]:
                assert s["t1"] is not None and s["seconds"] >= 0
            e2e = next(s for s in t["spans"] if s["name"] == "e2e")
            assert e2e["seconds"] == max(
                s["seconds"] for s in t["spans"])
            assert t["meta"]["new_tokens"] == 6

    def test_stage_histogram_counts(self, span_engine):
        eng, rids, _ = span_engine
        m = eng.metrics_snapshot()["metrics"]
        rows = {s["labels"]["stage"]: s["count"]
                for s in m["paddle_tpu_serving_request_stage_seconds"]
                ["series"]}
        for stage in ("queued", "prefill", "decode", "e2e"):
            assert rows[stage] == len(rids)

    def test_chrome_trace_export(self, span_engine, tmp_path):
        eng, rids, _ = span_engine
        path = tmp_path / "trace.json"
        doc = eng.export_request_traces(str(path))
        back = json.load(open(path))
        assert back == doc
        evs = doc["traceEvents"]
        lanes = {e["tid"] for e in evs}
        assert set(rids) <= lanes
        xs = [e for e in evs if e["ph"] == "X"]
        assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
        assert {"queued", "prefill", "decode", "decode_round",
                "e2e"} <= {e["name"] for e in xs}
        assert any(e["ph"] == "M" for e in evs)   # lane names

    def test_ring_is_bounded(self):
        ring = obs.SpanRing(maxlen=3)
        for i in range(7):
            tr = obs.RequestTrace(i)
            tr.add("e2e", 0.0, 1.0)
            ring.add(tr)
        assert len(ring) == 3
        assert [t["rid"] for t in ring.to_dicts()] == [4, 5, 6]

    def test_no_recompiles_with_spans_enabled(self, span_engine):
        eng, _, _ = span_engine
        # spans + ledger capture must not touch the program lattice
        c0 = eng.stats.compiles
        r = np.random.RandomState(3)
        eng.submit(r.randint(1, 64, (10,)), max_new_tokens=4)
        eng.run()
        assert eng.stats.compiles == c0


# ---------------------------------------------------------------------------
# /metrics HTTP exporter
# ---------------------------------------------------------------------------
class TestExporter:
    def test_scrape_round_trip(self):
        reg = obs.MetricsRegistry()
        reg.counter("scrape_tokens_total",
                    labelnames=("phase",)).inc(5, phase="decode")
        reg.gauge("scrape_depth").set(2)
        with obs.serve_metrics(0, registry=reg) as srv:
            assert srv.port > 0
            url = f"http://127.0.0.1:{srv.port}/metrics"
            body = urllib.request.urlopen(url, timeout=10).read()
            parsed = obs.parse_prometheus_text(body.decode())
            assert parsed["scrape_tokens_total"][
                (("phase", "decode"),)] == 5
            assert parsed["scrape_depth"][()] == 2
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=10)

    def test_close_releases_port(self):
        reg = obs.MetricsRegistry()
        srv = obs.serve_metrics(0, registry=reg)
        port = srv.port
        srv.close()
        srv2 = obs.serve_metrics(port, registry=reg)   # rebindable
        assert srv2.port == port
        srv2.close()


# ---------------------------------------------------------------------------
# tools/bench_compare
# ---------------------------------------------------------------------------
class TestBenchCompare:
    def _round(self, n, lines):
        return {"n": n, "cmd": "python bench.py", "rc": 0,
                "tail": "\n".join(json.dumps(ln) for ln in lines)}

    def _write(self, tmp_path, docs):
        for i, doc in enumerate(docs, 1):
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(
                json.dumps(doc))

    def test_regression_and_trajectory(self, tmp_path):
        repo = Path(__file__).resolve().parents[1]
        sys.path.insert(0, str(repo))
        try:
            from tools import bench_compare as bc
        finally:
            sys.path.remove(str(repo))
        mk = lambda v, ms: [
            {"metric": "gpt_smoke_train_tokens_per_sec", "value": v,
             "unit": "tokens/s", "vs_baseline": 0.0},
            {"metric": "llama_ms_per_token", "value": ms, "unit": "ms",
             "vs_baseline": 0.0},
            {"metric": "pallas_kernel_parity_interpret", "value": 1.0,
             "unit": "pass", "vs_baseline": 1.0},
            {"metric": "bench_moe", "value": 0.0, "unit": "error",
             "vs_baseline": 0.0, "error": "boom"},
        ]
        self._write(tmp_path, [self._round(1, mk(1000.0, 10.0)),
                               self._round(2, mk(600.0, 6.0))])
        rounds = bc.load_rounds(str(tmp_path))
        assert [n for n, _ in rounds] == [1, 2]
        rows = {r["metric"]: r for r in bc.compare(
            bc.parse_metrics(rounds[0][1]),
            bc.parse_metrics(rounds[1][1]), threshold=0.25)}
        # tokens/s dropped 40% -> regressed; ms dropped -> improved
        assert rows["gpt_smoke_train_tokens_per_sec"]["verdict"] == \
            "regressed"
        assert rows["llama_ms_per_token"]["verdict"] == "improved"
        assert rows["pallas_kernel_parity_interpret"]["verdict"] == "ok"
        assert rows["bench_moe"]["verdict"] == "unmeasured"
        traj = bc.trajectory(rounds)
        assert traj["gpt_smoke_train_tokens_per_sec"] == [1000.0, 600.0]
        assert traj["bench_moe"] == [None, None]
        # CLI: default exit 0, --strict exits 1 on the regression
        assert bc.main(["--dir", str(tmp_path)]) == 0
        assert bc.main(["--dir", str(tmp_path), "--strict"]) == 1
        assert bc.main(["--dir", str(tmp_path), "--strict",
                        "--json"]) == 1

    def test_exact_gate_and_insufficient_rounds(self, tmp_path):
        repo = Path(__file__).resolve().parents[1]
        sys.path.insert(0, str(repo))
        try:
            from tools import bench_compare as bc
        finally:
            sys.path.remove(str(repo))
        assert bc.main(["--dir", str(tmp_path)]) == 2   # no rounds
        lines1 = [{"metric": "pallas_kernel_parity_interpret",
                   "value": 1.0, "unit": "pass", "vs_baseline": 1.0}]
        lines2 = [{"metric": "pallas_kernel_parity_interpret",
                   "value": 0.0, "unit": "pass", "vs_baseline": 0.0}]
        self._write(tmp_path, [self._round(1, lines1),
                               self._round(2, lines2)])
        rounds = bc.load_rounds(str(tmp_path))
        rows = bc.compare(bc.parse_metrics(rounds[0][1]),
                          bc.parse_metrics(rounds[1][1]), 0.25)
        assert rows[0]["verdict"] == "regressed"   # parity is exact


# ---------------------------------------------------------------------------
# tpulint: the new modules must stay clean with ZERO baseline entries
# ---------------------------------------------------------------------------
def test_tpulint_commledger_surface_zero_baseline():
    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths(
            [repo / "paddle_tpu" / "observability",
             repo / "tools" / "bench_compare.py"],
            ALL_RULES, root=repo)
    finally:
        sys.path.remove(str(repo))
    assert findings == [], [str(f) for f in findings]
