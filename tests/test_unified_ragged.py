"""Chunked prefill + the unified ragged paged-attention kernel.

Under test (the ISSUE-12 tentpole):
- kernel parity: the Pallas ragged kernel vs its dense XLA fallback on
  decode-only, prefill-only, and mixed batches, with chunk starts that
  straddle page boundaries and dead (seq_len 0) rows
- two-program equivalence: the unified dense math collapses EXACTLY
  (bit-level) onto the legacy paged prefill path when every slot is
  valid
- ServingEngine chunked mode: token-level parity with one-request-at-
  a-time Predictor.generate across mixed streams, chunk boundaries off
  the page lattice, arrivals mid-decode, prefill-only requests
- the compile-stability acceptance: after one warmup mix, arbitrary
  length mixes trigger ZERO additional compiles on the unified lattice
- incremental page accounting: a long prompt is admitted on its FIRST
  chunk's pages, so a short request co-admits where the legacy
  whole-footprint reservation would have queued it
- preemption liveness: a page-starved pool completes exactly (youngest
  mid-prefill row bounces to the queue head, elders drain first)
- per-chunk spans in the request traces; tpulint zero-baseline pins
"""
import sys
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
import jax.numpy as jnp
from paddle_tpu.inference import Config, ServingEngine, create_predictor
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention, ragged_paged_attention_dense)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(11)
    return LlamaForCausalLM(llama_tiny())


@pytest.fixture()
def paged_pred(tiny_model):
    return create_predictor(
        Config().set_model(tiny_model).enable_paged_kv(page_size=8))


def _solo(tiny_model, prompt, n_new):
    """One-request-at-a-time Predictor reference output."""
    pred = create_predictor(
        Config().set_model(tiny_model).enable_paged_kv(page_size=8))
    return np.asarray(pred.generate(paddle.to_tensor(prompt[None]),
                                    max_new_tokens=n_new)._value)[0]


def _prompts(lens, vocab, seed=0):
    r = np.random.RandomState(seed)
    return [r.randint(1, vocab, (L,)) for L in lens]


# ---------------------------------------------------------------------------
# kernel parity: Pallas ragged kernel vs dense XLA fallback
# ---------------------------------------------------------------------------
def _pool(r, B, npages, KV, page, D, extra=5):
    P = B * npages + extra
    kp = jnp.asarray(r.randn(P, KV, page, D), jnp.float32)
    vp = jnp.asarray(r.randn(P, KV, page, D), jnp.float32)
    # scrambled physical page order: proves the table indirection
    tbl = jnp.asarray(r.permutation(P)[:B * npages].reshape(B, npages),
                      jnp.int32)
    return kp, vp, tbl


class TestRaggedKernelParity:
    B, Sq, H, KV, D, page, npages = 4, 16, 8, 2, 128, 8, 16

    def _check(self, starts, seq_lens, seed=3):
        r = np.random.RandomState(seed)
        q = jnp.asarray(r.randn(self.B, self.Sq, self.H, self.D),
                        jnp.float32)
        kp, vp, tbl = _pool(r, self.B, self.npages, self.KV, self.page,
                            self.D)
        st = jnp.asarray(starts, jnp.int32)
        nv = jnp.asarray(seq_lens, jnp.int32)
        out = ragged_paged_attention(q, kp, vp, tbl, st, nv,
                                     interpret=True)
        ref = ragged_paged_attention_dense(q, kp, vp, tbl, st, nv)
        assert float(jnp.abs(out - ref).max()) < 1e-4

    def test_mixed_batch_chunk_straddles_pages(self):
        # row 0: chunk starting mid-page (5 + 16 crosses two page
        # boundaries); row 1: decode deep in the cache; row 2: chunk
        # from position 0; row 3: dead slot
        self._check([5, 77, 0, 0], [16, 1, 16, 0])

    def test_decode_only_batch(self):
        self._check([10, 1, 55, 127], [1, 1, 1, 1], seed=4)

    def test_prefill_only_batch(self):
        self._check([0, 8, 16, 3], [16, 16, 16, 16], seed=5)

    def test_partial_chunks_and_dead_rows(self):
        # ragged seq_lens below the Sq lattice (token-budget splits)
        self._check([31, 0, 9, 64], [7, 0, 3, 12], seed=6)

    def test_dead_rows_output_exact_zero(self):
        r = np.random.RandomState(7)
        q = jnp.asarray(r.randn(self.B, self.Sq, self.H, self.D),
                        jnp.float32)
        kp, vp, tbl = _pool(r, self.B, self.npages, self.KV, self.page,
                            self.D)
        nv = jnp.asarray([0, 4, 0, 1], jnp.int32)
        st = jnp.asarray([0, 11, 0, 30], jnp.int32)
        for fn in (lambda: ragged_paged_attention(
                       q, kp, vp, tbl, st, nv, interpret=True),
                   lambda: ragged_paged_attention_dense(
                       q, kp, vp, tbl, st, nv)):
            out = np.asarray(fn())
            assert (out[0] == 0).all() and (out[2] == 0).all()
            # and invalid tail slots of live rows are zeroed too
            assert (out[1, 4:] == 0).all() and (out[3, 1:] == 0).all()

    def test_fully_valid_matches_two_program_path_bitwise(self):
        """With every slot valid, the unified dense math must collapse
        BIT-EXACTLY onto the legacy paged dense path (same gather, same
        mask, same einsums) — the two-program equivalence the serving
        parity tests lean on."""
        from paddle_tpu.ops.pallas.decode_attention import \
            paged_attention_dense

        r = np.random.RandomState(8)
        q = jnp.asarray(r.randn(self.B, self.Sq, self.H, self.D),
                        jnp.float32)
        kp, vp, tbl = _pool(r, self.B, self.npages, self.KV, self.page,
                            self.D)
        st = jnp.asarray([0, 24, 5, 80], jnp.int32)
        nv = jnp.full((self.B,), self.Sq, jnp.int32)
        uni = np.asarray(ragged_paged_attention_dense(
            q, kp, vp, tbl, st, nv))
        legacy = np.asarray(paged_attention_dense(q, kp, vp, tbl, st))
        np.testing.assert_array_equal(uni, legacy)


# ---------------------------------------------------------------------------
# ServingEngine chunked mode: parity with sequential serving
# ---------------------------------------------------------------------------
class TestChunkedServingParity:
    def test_mixed_stream_matches_sequential(self, tiny_model,
                                             paged_pred):
        """Chunk boundaries off the page lattice (L=7, 19, 33), prompts
        both under and over Sc, a stream longer than the batch: every
        request produces exactly the tokens it gets decoded alone."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=2, prefill_chunk=16)
        prompts = _prompts([7, 4, 19, 33, 5], V)
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        done = eng.run()
        assert sorted(done) == sorted(rids)
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(done[rid].output_ids,
                                          _solo(tiny_model, p, 6))

    def test_token_budget_partial_chunks(self, tiny_model, paged_pred):
        """A budget below the chunk bucket splits feeds mid-chunk (and
        mid-page) without changing any emitted token."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=3, prefill_chunk=16,
                            prefill_token_budget=10)
        prompts = _prompts([23, 9, 17], V, seed=1)
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        done = eng.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(done[rid].output_ids,
                                          _solo(tiny_model, p, 5))

    def test_arrival_mid_decode_chunks_interleave(self, tiny_model,
                                                  paged_pred):
        """A long prompt submitted while others decode feeds its chunks
        through the unified step WITHOUT stopping the decode rows, and
        still matches the sequential reference."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=3, prefill_chunk=16)
        a, b, c = _prompts([8, 5, 40], V, seed=2)
        ra = eng.submit(a, max_new_tokens=8)
        rb = eng.submit(b, max_new_tokens=8)
        for _ in range(3):
            eng.step()
        assert eng.num_active == 2
        na = len(eng.slots[[i for i in range(3)
                            if eng.slots[i] is not None
                            and eng.slots[i].req.rid == ra][0]]
                 .req.new_tokens)
        rc = eng.submit(c, max_new_tokens=4)   # long arrival mid-decode
        eng.step()                             # one unified chunk round
        # the decode rows advanced THROUGH the chunk round (no HOL)
        sa = [s for s in eng.slots if s is not None
              and s.req.rid == ra]
        if sa:                                  # not finished yet
            assert len(sa[0].req.new_tokens) > na
        done = eng.run()
        for rid, p, n in ((ra, a, 8), (rb, b, 8), (rc, c, 4)):
            np.testing.assert_array_equal(done[rid].output_ids,
                                          _solo(tiny_model, p, n))

    def test_prefill_only_requests(self, tiny_model, paged_pred):
        """max_new_tokens=1: the unified step serves pure prefill-chunk
        batches (no decode rows ever)."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=2, prefill_chunk=16)
        prompts = _prompts([21, 34], V, seed=3)
        rids = [eng.submit(p, max_new_tokens=1) for p in prompts]
        done = eng.run()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(done[rid].output_ids,
                                          _solo(tiny_model, p, 1))


# ---------------------------------------------------------------------------
# compile stability on the unified lattice
# ---------------------------------------------------------------------------
class TestUnifiedCompileStability:
    def test_zero_recompiles_after_warmup(self, tiny_model, paged_pred):
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=4, prefill_chunk=16)
        for p in _prompts([7, 40], V, seed=5):        # warmup mix
            eng.submit(p, max_new_tokens=5)
        eng.run()
        warm = eng.stats.compiles
        assert warm > 0
        mixes = [(3, 9, 21), (33, 5), (30, 2, 14, 8), (13,)]
        for i, mix in enumerate(mixes):
            for p in _prompts(list(mix), V, seed=6 + i):
                eng.submit(p, max_new_tokens=5)
            eng.run()
        assert eng.stats.compiles == warm, (
            f"recompiled under traffic: {eng.stats.as_dict()}")
        assert eng.stats.cache_hits > 0

    def test_unified_site_ledgers_registered(self, tiny_model):
        """The ("unified", Sc) site shows up in the CompileStats notes
        and the memory-ledger map (the bench's HBM acceptance hook)."""
        pred = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(page_size=8))
        V = tiny_model.config.vocab_size
        eng = ServingEngine(pred, max_batch=2, prefill_chunk=16,
                            mem_ledger=True)
        eng.submit(_prompts([20], V, seed=9)[0], max_new_tokens=4)
        eng.run()
        assert eng.Sc == 16
        assert eng.memory_ledger(("unified", 16)) is not None
        assert any(k[0] == "unified" for k in eng.stats.bucket_tokens)


# ---------------------------------------------------------------------------
# incremental page accounting + preemption liveness
# ---------------------------------------------------------------------------
class TestIncrementalPages:
    def test_long_prompt_coadmits_short(self, tiny_model):
        """pool = 15 usable pages; the long request's full footprint is
        14 pages, the short one needs 2. Legacy whole-footprint
        reservation leaves 1 free page — the short request queues.
        Chunked admission reserves only the first chunk (2 pages), so
        BOTH are in flight immediately — and both still decode
        exactly."""
        V = tiny_model.config.vocab_size
        long_p = _prompts([104], V, seed=10)[0]   # ceil(112/8)=14 pages
        short_p = _prompts([8], V, seed=11)[0]    # ceil(16/8)=2 pages

        def mk(**kw):
            pred = create_predictor(Config().set_model(tiny_model)
                                    .enable_paged_kv(page_size=8))
            return ServingEngine(pred, max_batch=2, pool_pages=15, **kw)

        legacy = mk()
        legacy.submit(long_p, max_new_tokens=8)
        legacy.submit(short_p, max_new_tokens=8)
        legacy.step()
        assert legacy.num_active == 1 and len(legacy.queue) == 1

        eng = mk(prefill_chunk=16)
        rl = eng.submit(long_p, max_new_tokens=8)
        rs = eng.submit(short_p, max_new_tokens=8)
        eng.step()
        assert eng.num_active == 2 and not eng.queue
        done = eng.run()
        np.testing.assert_array_equal(done[rl].output_ids,
                                      _solo(tiny_model, long_p, 8))
        np.testing.assert_array_equal(done[rs].output_ids,
                                      _solo(tiny_model, short_p, 8))
        # every page came back
        assert len(eng._free_pages) == 15

    def test_page_starved_pool_preempts_and_completes(self, tiny_model):
        """Two prompts whose combined footprint exceeds the pool: both
        co-admit on first-chunk pages, collide mid-prefill, and the
        youngest bounces back to the queue (preemption by exact
        recomputation — no token sampled yet). The stream drains with
        exact outputs."""
        V = tiny_model.config.vocab_size
        a, b = _prompts([40, 40], V, seed=12)     # 6 pages each, 7 usable

        def mk():
            pred = create_predictor(Config().set_model(tiny_model)
                                    .enable_paged_kv(page_size=8))
            return ServingEngine(pred, max_batch=2, pool_pages=7,
                                 prefill_chunk=16)

        eng = mk()
        ra = eng.submit(a, max_new_tokens=8)
        rb = eng.submit(b, max_new_tokens=8)
        eng.step()
        assert eng.num_active == 2                # both co-admitted
        done = eng.run()
        np.testing.assert_array_equal(done[ra].output_ids,
                                      _solo(tiny_model, a, 8))
        np.testing.assert_array_equal(done[rb].output_ids,
                                      _solo(tiny_model, b, 8))
        assert len(eng._free_pages) == 7          # pool fully returned
        # the loser's trace records the preemption instant
        spans = [sp["name"] for t in eng.request_traces()
                 for sp in t["spans"]]
        assert "preempt" in spans


# ---------------------------------------------------------------------------
# per-chunk spans + TTFT semantics
# ---------------------------------------------------------------------------
class TestChunkSpans:
    def test_chunk_spans_cover_the_prompt(self, tiny_model, paged_pred):
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=1, prefill_chunk=16)
        p = _prompts([39], V, seed=13)[0]          # 3 chunks: 16+16+7
        rid = eng.submit(p, max_new_tokens=3)
        done = eng.run()
        tr = [t for t in eng.request_traces() if t["rid"] == rid][0]
        chunks = [sp for sp in tr["spans"]
                  if sp["name"] == "prefill_chunk"]
        assert [c["meta"]["chunk"] for c in chunks] == [0, 1, 2]
        assert [c["meta"]["tokens"] for c in chunks] == [16, 16, 7]
        assert sum(c["meta"]["tokens"] for c in chunks) == len(p)
        # TTFT stays first-token time: the prefill stage span closes
        # when the LAST chunk samples, not per chunk
        req = done[rid]
        assert req.t_first_token >= chunks[-1]["t0"]
        names = [sp["name"] for sp in tr["spans"]]
        assert "prefill" in names and "decode" in names \
            and "e2e" in names

    def test_chunk_rounds_interleave_decode_rounds(self, tiny_model,
                                                   paged_pred):
        """The Chrome-trace view of the tentpole: while a long prompt
        chunks in, the other request's decode_round spans keep landing
        BETWEEN its prefill_chunk spans."""
        V = tiny_model.config.vocab_size
        eng = ServingEngine(paged_pred, max_batch=2, prefill_chunk=16)
        short, long_p = _prompts([6, 48], V, seed=14)
        rs = eng.submit(short, max_new_tokens=10)
        for _ in range(2):
            eng.step()                  # short is mid-decode
        eng.submit(long_p, max_new_tokens=2)
        eng.run()
        tr = [t for t in eng.request_traces() if t["rid"] == rs][0]
        rounds = [sp for sp in tr["spans"]
                  if sp["name"] == "decode_round"
                  and sp["meta"].get("unified")]
        # the short request decoded through unified (chunk-carrying)
        # rounds — the no-head-of-line-blocking acceptance
        assert rounds, [sp["name"] for sp in tr["spans"]]


# ---------------------------------------------------------------------------
# tpulint: the rewritten scheduler + new kernel stay at ZERO baseline
# ---------------------------------------------------------------------------
def test_tpulint_unified_serving_zero_baseline():
    sys.path.insert(0, str(REPO))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths(
            [REPO / "paddle_tpu" / "inference" / "serving.py",
             REPO / "paddle_tpu" / "ops" / "pallas"
                  / "ragged_paged_attention.py"],
            ALL_RULES, root=REPO)
    finally:
        sys.path.remove(str(REPO))
    assert findings == [], [str(f) for f in findings]
