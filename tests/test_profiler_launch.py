"""Profiler, launcher, and AMP debugging tools."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def test_profiler_records_ops_and_exports(tmp_path):
    import paddle_tpu.profiler as profiler

    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 8)
                         .astype("float32"))
    with profiler.Profiler(timer_only=True) as p:
        with profiler.RecordEvent("user_block"):
            for _ in range(3):
                y = paddle.matmul(x, x)
        p.step()
        for _ in range(2):
            y = paddle.matmul(x, x)
        p.step()
    out = p.summary()
    assert "matmul" in out and "user_block" in out
    trace = str(tmp_path / "trace.json")
    p._export_chrome(trace)
    data = json.load(open(trace))
    names = {e["name"] for e in data["traceEvents"]}
    assert "matmul" in names and "user_block" in names


def test_profiler_scheduler():
    import paddle_tpu.profiler as profiler

    sched = profiler.make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(5)]
    assert states[0] == profiler.ProfilerState.CLOSED
    assert states[1] == profiler.ProfilerState.READY
    assert states[2] == profiler.ProfilerState.RECORD
    assert states[3] == profiler.ProfilerState.RECORD_AND_RETURN
    assert states[4] == profiler.ProfilerState.CLOSED


def test_operator_stats_collection(capsys):
    from paddle_tpu.amp.debugging import collect_operator_stats

    x = paddle.to_tensor(np.ones((4, 4), "float32"))
    with collect_operator_stats():
        paddle.matmul(x, x)
        paddle.matmul(x, x)
        x + x
    out = capsys.readouterr().out
    assert "matmul" in out and "float32" in out


def test_check_numerics():
    from paddle_tpu.amp.debugging import DebugMode, check_numerics

    good = paddle.to_tensor(np.ones(4, "float32"))
    assert check_numerics(good) == (0, 0, 4)
    bad = paddle.to_tensor(np.array([1.0, np.nan, np.inf], "float32"))
    with pytest.raises(FloatingPointError):
        check_numerics(bad, "my_op", "x")
    n_nan, n_inf, n_num = check_numerics(
        bad, debug_mode=DebugMode.CHECK_NAN_INF)
    assert (n_nan, n_inf, n_num) == (1, 1, 1)


def test_launch_single(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import os\n"
        "assert os.environ['PADDLE_TRAINER_ID'] == '0'\n"
        "assert os.environ['PADDLE_TRAINERS_NUM'] == '1'\n"
        "print('LAUNCH_OK')\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         str(script)], capture_output=True, text=True,
        cwd="/root/repo", timeout=120)
    assert r.returncode == 0, r.stderr
    assert "LAUNCH_OK" in r.stdout


def test_launch_multiproc_pod(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "rank = os.environ['PADDLE_TRAINER_ID']\n"
        "print('RANK', rank, 'of', os.environ['PADDLE_TRAINERS_NUM'])\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "3", "--log_dir", str(tmp_path / "logs"),
         str(script)], capture_output=True, text=True,
        cwd="/root/repo", timeout=120)
    assert r.returncode == 0, r.stderr
    logs = sorted(os.listdir(tmp_path / "logs"))
    assert logs == ["workerlog.0", "workerlog.1", "workerlog.2"]
    content = "".join(open(tmp_path / "logs" / f).read() for f in logs)
    for i in range(3):
        assert f"RANK {i} of 3" in content


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        capture_output=True, text=True, cwd="/root/repo", timeout=120)
    assert r.returncode == 3
