"""paddle.geometric + paddle.text (reference: python/paddle/geometric/,
python/paddle/text/viterbi_decode.py) — numpy-reference parity."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle


def test_segment_ops_vs_numpy():
    rng = np.random.RandomState(0)
    data = rng.rand(10, 3).astype("float32")
    ids = np.array([0, 0, 1, 1, 1, 3, 3, 5, 5, 5])
    d, i = paddle.to_tensor(data), paddle.to_tensor(ids)

    out = np.asarray(paddle.geometric.segment_sum(d, i)._value)
    assert out.shape == (6, 3)
    for s in range(6):
        np.testing.assert_allclose(out[s], data[ids == s].sum(0)
                                   if (ids == s).any() else 0, rtol=1e-6)

    out = np.asarray(paddle.geometric.segment_mean(d, i)._value)
    for s in range(6):
        ref = data[ids == s].mean(0) if (ids == s).any() else np.zeros(3)
        np.testing.assert_allclose(out[s], ref, rtol=1e-6)

    out = np.asarray(paddle.geometric.segment_max(d, i)._value)
    for s in range(6):
        ref = data[ids == s].max(0) if (ids == s).any() else np.zeros(3)
        np.testing.assert_allclose(out[s], ref, rtol=1e-6)

    out = np.asarray(paddle.geometric.segment_min(d, i)._value)
    for s in range(6):
        ref = data[ids == s].min(0) if (ids == s).any() else np.zeros(3)
        np.testing.assert_allclose(out[s], ref, rtol=1e-6)


def test_segment_sum_grad():
    data = paddle.to_tensor(np.ones((4, 2), dtype=np.float32))
    data.stop_gradient = False
    ids = paddle.to_tensor(np.array([0, 1, 1, 2]))
    out = paddle.geometric.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(data.grad._value),
                               np.ones((4, 2), dtype=np.float32))


@pytest.mark.parametrize("reduce_op", ["sum", "mean", "min", "max"])
def test_send_u_recv(reduce_op):
    rng = np.random.RandomState(1)
    x = rng.rand(5, 4).astype("float32")
    src = np.array([0, 1, 2, 0, 3])
    dst = np.array([1, 2, 1, 0, 0])
    out = np.asarray(paddle.geometric.send_u_recv(
        paddle.to_tensor(x), paddle.to_tensor(src), paddle.to_tensor(dst),
        reduce_op)._value)
    assert out.shape == (5, 4)
    for d in range(5):
        msgs = x[src[dst == d]]
        if len(msgs) == 0:
            np.testing.assert_allclose(out[d], 0)
        else:
            ref = {"sum": msgs.sum(0), "mean": msgs.mean(0),
                   "min": msgs.min(0), "max": msgs.max(0)}[reduce_op]
            np.testing.assert_allclose(out[d], ref, rtol=1e-6)


def test_send_ue_recv_and_uv():
    rng = np.random.RandomState(2)
    x = rng.rand(4, 3).astype("float32")
    y_edge = rng.rand(5, 3).astype("float32")
    src = np.array([0, 1, 2, 3, 0])
    dst = np.array([1, 0, 3, 2, 2])
    out = np.asarray(paddle.geometric.send_ue_recv(
        paddle.to_tensor(x), paddle.to_tensor(y_edge),
        paddle.to_tensor(src), paddle.to_tensor(dst), "mul", "sum")._value)
    ref = np.zeros((4, 3), np.float32)
    for e in range(5):
        ref[dst[e]] += x[src[e]] * y_edge[e]
    np.testing.assert_allclose(out, ref, rtol=1e-5)

    uv = np.asarray(paddle.geometric.send_uv(
        paddle.to_tensor(x), paddle.to_tensor(x),
        paddle.to_tensor(src), paddle.to_tensor(dst), "add")._value)
    np.testing.assert_allclose(uv, x[src] + x[dst], rtol=1e-6)


def test_out_size():
    x = paddle.to_tensor(np.ones((3, 2), dtype=np.float32))
    src = paddle.to_tensor(np.array([0, 1]))
    dst = paddle.to_tensor(np.array([0, 1]))
    out = paddle.geometric.send_u_recv(x, src, dst, "sum", out_size=7)
    assert out.shape == [7, 2]


def test_edge_shape_mismatch_raises():
    x = paddle.to_tensor(np.ones((3, 2), dtype=np.float32))
    y = paddle.to_tensor(np.ones((3, 2), dtype=np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2]))
    dst = paddle.to_tensor(np.array([1, 0]))
    for fn in (lambda: paddle.geometric.send_u_recv(x, src, dst),
               lambda: paddle.geometric.send_ue_recv(x, y, src, dst),
               lambda: paddle.geometric.send_uv(x, x, src, dst)):
        with pytest.raises(Exception, match="same shape"):
            fn()


def test_reindex_graph_reference_example():
    # the worked example in the reference's docstring
    # (python/paddle/geometric/reindex.py)
    s, d, nodes = paddle.geometric.reindex_graph(
        paddle.to_tensor(np.array([0, 1, 2])),
        paddle.to_tensor(np.array([8, 9, 0, 4, 7, 6, 7])),
        paddle.to_tensor(np.array([2, 3, 2])))
    assert np.asarray(s._value).tolist() == [3, 4, 0, 5, 6, 7, 6]
    assert np.asarray(d._value).tolist() == [0, 0, 1, 1, 1, 2, 2]
    assert np.asarray(nodes._value).tolist() == [0, 1, 2, 8, 9, 4, 7, 6]


def test_sample_neighbors():
    # CSC graph: node j's in-neighbors are row[colptr[j]:colptr[j+1]]
    row = np.array([1, 2, 3, 0, 2, 0, 1, 3, 0])
    colptr = np.array([0, 3, 5, 8, 9])
    paddle.seed(7)
    nbrs, cnt = paddle.geometric.sample_neighbors(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([0, 2])), sample_size=2)
    cnt = np.asarray(cnt._value)
    assert cnt.tolist() == [2, 2]
    nbrs = np.asarray(nbrs._value)
    assert set(nbrs[:2]) <= {1, 2, 3} and set(nbrs[2:]) <= {0, 1, 3}
    # full sampling (sample_size=-1) returns every neighbor in order
    nbrs, cnt = paddle.geometric.sample_neighbors(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([1, 3])), sample_size=-1)
    assert np.asarray(cnt._value).tolist() == [2, 1]
    assert np.asarray(nbrs._value).tolist() == [0, 2, 0]
    # eids passthrough
    nbrs, cnt, eids = paddle.geometric.sample_neighbors(
        paddle.to_tensor(row), paddle.to_tensor(colptr),
        paddle.to_tensor(np.array([3])), sample_size=-1,
        eids=paddle.to_tensor(np.arange(100, 109)), return_eids=True)
    assert np.asarray(eids._value).tolist() == [108]


def _brute_viterbi(pot, trans, L):
    N = pot.shape[-1]
    best, bp = -1e30, None
    for p in itertools.product(range(N), repeat=int(L)):
        s = pot[0, p[0]] + sum(pot[t, p[t]] + trans[p[t - 1], p[t]]
                               for t in range(1, L))
        if s > best:
            best, bp = s, p
    return best, list(bp)


def test_viterbi_decode_vs_bruteforce():
    rng = np.random.RandomState(0)
    B, T, N = 3, 5, 4
    pot = rng.rand(B, T, N).astype("float32")
    trans = rng.rand(N, N).astype("float32")
    lens = np.array([5, 3, 1])
    sc, path = paddle.text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lens), include_bos_eos_tag=False)
    sc, path = np.asarray(sc._value), np.asarray(path._value)
    for b in range(B):
        ref_s, ref_p = _brute_viterbi(pot[b], trans, lens[b])
        assert abs(float(sc[b]) - ref_s) < 1e-4
        assert path[b][:lens[b]].tolist() == ref_p


def test_viterbi_decode_bos_eos():
    rng = np.random.RandomState(4)
    B, T, N = 2, 4, 5  # last two tags are stop/start per the convention
    pot = rng.rand(B, T, N).astype("float32")
    trans = rng.rand(N, N).astype("float32")
    lens = np.array([4, 4])
    sc, path = paddle.text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lens), include_bos_eos_tag=True)
    # brute force with start/stop rows added
    for b in range(B):
        best, bp = -1e30, None
        for p in itertools.product(range(N), repeat=T):
            s = (trans[-1, p[0]] + pot[b, 0, p[0]]
                 + sum(pot[b, t, p[t]] + trans[p[t - 1], p[t]]
                       for t in range(1, T)) + trans[p[-1], -2])
            if s > best:
                best, bp = s, p
        assert abs(float(np.asarray(sc._value)[b]) - best) < 1e-4
        assert np.asarray(path._value)[b].tolist() == list(bp)


def test_viterbi_decoder_layer():
    trans = paddle.to_tensor(np.eye(3, dtype=np.float32))
    dec = paddle.text.ViterbiDecoder(trans, include_bos_eos_tag=False)
    pot = paddle.to_tensor(np.random.RandomState(5).rand(1, 3, 3)
                           .astype("float32"))
    sc, path = dec(pot, paddle.to_tensor(np.array([3])))
    assert np.asarray(path._value).shape == (1, 3)


def test_reindex_rejects_duplicate_nodes():
    with pytest.raises(ValueError, match="unique"):
        paddle.geometric.reindex_graph(
            paddle.to_tensor(np.array([5, 5, 7])),
            paddle.to_tensor(np.array([9, 9, 9])),
            paddle.to_tensor(np.array([1, 1, 1])))


def test_reindex_rejects_count_mismatch():
    with pytest.raises(ValueError, match="count.sum"):
        paddle.geometric.reindex_graph(
            paddle.to_tensor(np.array([0, 1])),
            paddle.to_tensor(np.array([5, 6, 7])),
            paddle.to_tensor(np.array([2, 2])))
