"""Quantization: QAT fake-quant (STE) + PTQ observers
(reference: test/quantization/ — QAT/PTQ workflow tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.quantization import (AbsmaxObserver,
                                     FakeQuanterWithAbsMaxObserver, PTQ,
                                     QAT, QuantConfig, QuantedLinear,
                                     quant_dequant)


def test_quant_dequant_grid_and_ste():
    x = paddle.to_tensor(np.linspace(-1, 1, 11).astype("float32"),
                         stop_gradient=False)
    out = quant_dequant(x, 1.0, bit_length=8)
    v = np.asarray(out._value)
    grid = np.round(np.linspace(-1, 1, 11) * 127) / 127
    np.testing.assert_allclose(v, grid, atol=1e-6)

    loss = paddle.sum(out)
    loss.backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), np.ones(11),
                               atol=1e-6)  # STE: identity inside range

    y = paddle.to_tensor(np.array([5.0, -7.0], "float32"),
                         stop_gradient=False)
    out2 = quant_dequant(y, 1.0)
    paddle.sum(out2).backward()
    np.testing.assert_allclose(np.asarray(y.grad._value), np.zeros(2),
                               atol=1e-6)  # clipped region: zero grad


def _net():
    return paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                                paddle.nn.Linear(16, 4))


def test_qat_quantize_and_train():
    paddle.seed(0)
    model = _net()
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                      weight=FakeQuanterWithAbsMaxObserver())
    qat = QAT(cfg)
    qmodel = qat.quantize(model, inplace=False)
    assert isinstance(qmodel[0], QuantedLinear)
    # original untouched
    assert not isinstance(model[0], QuantedLinear)

    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=qmodel.parameters())
    x = np.random.RandomState(0).randn(16, 8).astype("float32")
    y = np.random.RandomState(1).randn(16, 4).astype("float32")
    losses = []
    for _ in range(15):
        loss = paddle.mean((qmodel(paddle.to_tensor(x))
                            - paddle.to_tensor(y)) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_ptq_observe_convert():
    paddle.seed(1)
    model = _net()
    cfg = QuantConfig(activation=AbsmaxObserver(), weight=AbsmaxObserver())
    ptq = PTQ(cfg)
    qmodel = ptq.quantize(model, inplace=False)

    x = np.random.RandomState(2).randn(32, 8).astype("float32")
    ref = np.asarray(model(paddle.to_tensor(x))._value)
    for _ in range(4):  # calibration passes
        qmodel(paddle.to_tensor(x))
    scale = qmodel[0].activation_quanter.scales()
    assert scale > 0

    final = ptq.convert(qmodel)
    out = np.asarray(final(paddle.to_tensor(x))._value)
    # int8 simulation stays close to fp32
    assert np.mean(np.abs(out - ref)) < 0.1 * (np.abs(ref).mean() + 1e-6)


def test_type_and_layer_config():
    model = _net()
    cfg = QuantConfig()
    cfg.add_type_config(paddle.nn.Linear,
                        weight=FakeQuanterWithAbsMaxObserver())
    q = QAT(cfg).quantize(model, inplace=False)
    assert isinstance(q[0], QuantedLinear)
    assert q[0].activation_quanter is None
    assert q[0].weight_quanter is not None


def test_ptq_int8_export_inference():
    """convert(to_int8=True): int8 weights + int8 matmul inference
    tracks the float model within quantization error (the deployable
    export path, VERDICT row 64)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.quantization import (AbsmaxObserver, Int8Linear,
                                         PTQ, QuantConfig)

    paddle.seed(5)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    r = np.random.RandomState(0)
    xs = [paddle.to_tensor(r.randn(4, 8).astype("float32"))
          for _ in range(4)]
    ref = np.asarray(model(xs[0])._value)

    cfg = QuantConfig(activation=AbsmaxObserver(), weight=AbsmaxObserver())
    ptq = PTQ(cfg)
    q = ptq.quantize(model, inplace=False)
    for x in xs:  # calibration
        q(x)
    ptq.convert(q, to_int8=True)
    assert any(isinstance(l, Int8Linear)
               for l in q.sublayers(include_self=True))
    # int8 weights actually stored as int8
    int8_layers = [l for l in q.sublayers(include_self=True)
                   if isinstance(l, Int8Linear)]
    assert all(str(l.weight_int8._value.dtype) == "int8"
               for l in int8_layers)
    out = np.asarray(q(xs[0])._value)
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.1, err  # 8-bit quantization error budget
