"""Fleet telemetry plane (observability/timeseries.py + fleet.py).

Under test:
- the durable metrics journal: sample round-trip, SIGKILL-truncated
  tail recovery (every COMPLETED sample survives), resumed-run
  headers, background sampler thread with bounded overhead,
  retention/compaction, range queries + aligned resampling
- the fleet collector: exposition parsing, counter-sum / gauge-stats /
  bucket-exact histogram merges — merged percentiles EXACTLY equal to
  a single registry fed the union of observations (property-style
  over random shards), the /healthz rollup (degraded / unreachable /
  stale members), and the stdlib HTTP front door (scrape + push)
- trace identity: W3C traceparent helpers, ServingEngine.submit
  accepting/creating trace ids, spans + chrome export + trace_context
  carrying them end to end
- exporter satellites: ?names= prefix filtering, charset, and the
  filtered scrape never refreshing the liveness age
- engine wiring: PADDLE_TPU_TIMESERIES_DIR attaches a sampler with
  bit-identical losses and zero extra compiles
- reports: tools/fleet_report.py and tools/run_report.py --merge over
  per-host journals
- tpulint: the new tool lints clean with ZERO baseline entries
"""
import json
import math
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import fleet as fl
from paddle_tpu.observability import goodput as _gp
from paddle_tpu.observability import spans as sp
from paddle_tpu.observability import timeseries as ts
from paddle_tpu.observability.metrics import MetricsRegistry


@pytest.fixture()
def reg():
    return MetricsRegistry()


def _journal(tmp_path, name="metrics.jsonl"):
    return str(tmp_path / name)


# ---------------------------------------------------------------------------
# the durable journal
# ---------------------------------------------------------------------------
class TestJournal:
    def test_sample_round_trip(self, reg, tmp_path):
        c = reg.counter("steps_total", "steps")
        g = reg.gauge("depth", "queue depth")
        h = reg.histogram("lat", buckets=(0.5, 2.0))
        c.inc(3)
        g.set(7)
        h.observe(0.25)
        h.observe(5.0)
        with ts.MetricsSampler(_journal(tmp_path), registry=reg,
                               interval_s=60) as smp:
            smp.sample_now()
            c.inc()
            smp.sample_now()
        recs = ts.read_journal(_journal(tmp_path))
        assert recs[0]["ev"] == "run" and not recs[0]["resumed"]
        samp = ts.samples(recs)
        assert [r["seq"] for r in samp] == [0, 1]
        assert samp[0]["m"]["steps_total"]["s"] == [[{}, 3.0]]
        assert samp[1]["m"]["steps_total"]["s"] == [[{}, 4.0]]
        assert samp[0]["m"]["depth"]["s"] == [[{}, 7.0]]
        hist = samp[0]["m"]["lat"]["s"][0][1]
        assert hist["count"] == 2 and hist["sum"] == 5.25
        assert hist["min"] == 0.25 and hist["max"] == 5.0
        assert hist["buckets"] == {"0.5": 1, "2.0": 0, "+Inf": 1}

    def test_truncated_tail_recovers_completed_samples(self, reg,
                                                       tmp_path):
        """The SIGKILL acceptance: a torn final line is skipped, every
        completed sample before it is recovered."""
        path = _journal(tmp_path)
        g = reg.gauge("v")
        with ts.MetricsSampler(path, registry=reg,
                               interval_s=60) as smp:
            for i in range(5):
                g.set(i)
                smp.sample_now()
        with open(path, "a") as f:       # a kill mid-write
            f.write('{"ev": "s", "ts": 1.0, "seq": 99, "m": {"v"')
        recs = ts.read_journal(path)
        samp = ts.samples(recs)
        assert [r["seq"] for r in samp] == [0, 1, 2, 3, 4]
        assert [r["m"]["v"]["s"][0][1] for r in samp] == \
            [0.0, 1.0, 2.0, 3.0, 4.0]

    def test_resumed_run_header_continues_seq(self, reg, tmp_path):
        path = _journal(tmp_path)
        reg.gauge("v").set(1)
        with ts.MetricsSampler(path, registry=reg,
                               interval_s=60) as smp:
            smp.sample_now()
            smp.sample_now()
        # a "new process" re-opens the same journal
        with ts.MetricsSampler(path, registry=reg,
                               interval_s=60) as smp2:
            smp2.sample_now()
        recs = ts.read_journal(path)
        runs = [r for r in recs if r["ev"] == "run"]
        assert [r["resumed"] for r in runs] == [False, True]
        assert [r["seq"] for r in ts.samples(recs)] == [0, 1, 2]

    def test_background_thread_bounded_overhead(self, reg, tmp_path):
        reg.gauge("v").set(1)
        smp = ts.MetricsSampler(_journal(tmp_path), registry=reg,
                                interval_s=0.02).start()
        deadline = time.time() + 5.0
        while smp.stats()["samples"] < 3 and time.time() < deadline:
            time.sleep(0.01)
        smp.close()
        st = smp.stats()
        assert st["samples"] >= 3
        # bounded per-sample cost: one snapshot + one flushed line
        assert st["overhead_seconds"] <= 0.25 * st["samples"]
        assert st["journal_bytes"] == \
            os.path.getsize(_journal(tmp_path))
        # close() stopped the thread: no further samples land
        n = st["samples"]
        time.sleep(0.06)
        assert smp.stats()["samples"] == n

    def test_sampler_publishes_its_own_metrics(self, reg, tmp_path):
        reg.gauge("v").set(1)
        with ts.MetricsSampler(_journal(tmp_path), registry=reg,
                               interval_s=60) as smp:
            smp.sample_now()
        snap = reg.snapshot()["metrics"]
        assert snap["paddle_tpu_timeseries_samples_total"][
            "series"][0]["value"] == 1
        assert snap["paddle_tpu_timeseries_journal_bytes"][
            "series"][0]["value"] > 0

    def test_retention_compaction(self, reg, tmp_path):
        path = _journal(tmp_path)
        g = reg.gauge("v")
        with ts.MetricsSampler(path, registry=reg, interval_s=60,
                               retention_samples=16) as smp:
            for i in range(40):
                g.set(i)
                smp.sample_now()
            st = smp.stats()
        assert st["compactions"] >= 1
        recs = ts.read_journal(path)
        marks = [r for r in recs if r["ev"] == "c"]
        assert marks and all(m["dropped"] > 0 for m in marks)
        samp = ts.samples(recs)
        # bounded in-file history, newest samples kept verbatim
        assert len(samp) <= 17
        assert samp[-1]["seq"] == 39
        assert samp[-1]["m"]["v"]["s"][0][1] == 39.0
        seqs = [r["seq"] for r in samp]
        assert seqs == sorted(seqs)

    def test_compaction_is_atomic_rewrite(self, reg, tmp_path):
        """After compaction the journal stays appendable and lenient-
        readable (the handle swap kept writes flowing)."""
        path = _journal(tmp_path)
        g = reg.gauge("v")
        with ts.MetricsSampler(path, registry=reg, interval_s=60,
                               retention_samples=16) as smp:
            for i in range(20):
                g.set(i)
                smp.sample_now()
            assert smp.stats()["compactions"] == 1
            g.set(123)
            smp.sample_now()
        samp = ts.samples(ts.read_journal(path))
        assert samp[-1]["m"]["v"]["s"][0][1] == 123.0
        assert not os.path.exists(path + ".compact.tmp")

    def test_query_label_filter_and_sum(self, reg, tmp_path):
        c = reg.counter("bytes_total", labelnames=("axis", "op"))
        c.inc(10, axis="mp", op="psum")
        c.inc(5, axis="mp", op="all_gather")
        c.inc(2, axis="dp", op="psum")
        with ts.MetricsSampler(_journal(tmp_path), registry=reg,
                               interval_s=60) as smp:
            smp.sample_now()
            recs = ts.read_journal(_journal(tmp_path))
        assert ts.query(recs, "bytes_total")[0][1] == 17.0
        assert ts.query(recs, "bytes_total",
                        labels={"axis": "mp"})[0][1] == 15.0
        assert ts.query(recs, "bytes_total",
                        labels={"axis": "mp", "op": "psum"}
                        )[0][1] == 10.0
        assert ts.query(recs, "bytes_total",
                        labels={"axis": "nope"}) == []
        assert ts.query(recs, "unknown_metric") == []

    def test_query_histogram_fields_and_range(self, reg, tmp_path):
        h = reg.histogram("lat", buckets=(1.0,))
        path = _journal(tmp_path)
        with ts.MetricsSampler(path, registry=reg,
                               interval_s=60) as smp:
            h.observe(0.5)
            smp.sample_now()
            h.observe(3.0)
            smp.sample_now()
        recs = ts.read_journal(path)
        counts = ts.query(recs, "lat", field="count")
        assert [v for _, v in counts] == [1.0, 2.0]
        sums = ts.query(recs, "lat", field="sum")
        assert [v for _, v in sums] == [0.5, 3.5]
        # "value" defaults to count for histograms
        assert [v for _, v in ts.query(recs, "lat")] == [1.0, 2.0]
        t_mid = counts[0][0]
        assert ts.query(recs, "lat", t0=t_mid + 1e-6) == [counts[1]] \
            or len(ts.query(recs, "lat", t0=t_mid + 1e-6)) <= 1

    def test_resample_grid(self):
        pts = [(10.2, 1.0), (10.7, 3.0), (11.4, 5.0), (13.1, 7.0)]
        out = ts.resample(pts, step=1.0)
        assert out == [(10.0, 3.0), (11.0, 5.0), (12.0, None),
                       (13.0, 7.0)]
        out = ts.resample(pts, step=1.0, how="mean", ffill=True)
        assert out == [(10.0, 2.0), (11.0, 5.0), (12.0, 5.0),
                       (13.0, 7.0)]
        assert ts.resample(pts, step=1.0, how="sum")[0][1] == 4.0
        assert ts.resample([], step=1.0) == []
        with pytest.raises(ValueError):
            ts.resample(pts, step=0.0)
        with pytest.raises(ValueError):
            ts.resample(pts, step=1.0, how="median")

    def test_attach_dir_get_or_create(self, reg, tmp_path):
        base = str(tmp_path / "run")
        smp = ts.attach_dir(base, interval_s=60, registry=reg)
        try:
            assert ts.attach_dir(base, interval_s=60) is smp
            assert ts.current() is smp
            other = ts.attach_dir(str(tmp_path / "other"),
                                  interval_s=60, registry=reg)
            assert other is not smp
            assert ts.current() is other
            other.close()
        finally:
            smp.close()
            ts.detach()
        assert ts.current() is None


# ---------------------------------------------------------------------------
# fleet merge semantics
# ---------------------------------------------------------------------------
BUCKETS = (0.5, 1.0, 2.5, 5.0, 7.5)


def _norm_buckets(b):
    return {math.inf if k == "+Inf" else float(k): int(v)
            for k, v in b.items()}


class TestFleetMerge:
    def test_parse_exposition_histogram_deaccumulates(self, reg):
        h = reg.histogram("lat", buckets=(1.0, 2.0))
        for v in (0.5, 1.5, 1.7, 9.0):
            h.observe(v)
        fam = fl.parse_exposition(reg.prometheus_text())["lat"]
        assert fam["type"] == "histogram"
        s = fam["series"][()]
        assert _norm_buckets(s["buckets"]) == \
            {1.0: 1, 2.0: 2, math.inf: 1}
        assert s["count"] == 4 and s["min"] == 0.5 and s["max"] == 9.0

    def test_counter_totals_sum_of_members(self):
        col = fl.FleetCollector(registry=MetricsRegistry())
        per_host = {"h0": 3, "h1": 11, "h2": 7}
        for host, n in per_host.items():
            r = MetricsRegistry()
            r.counter("steps_total").inc(n)
            col.ingest(host, r.prometheus_text())
        fam = col.merged()["steps_total"]
        assert fam["type"] == "counter"
        assert fam["fleet"][()] == sum(per_host.values())
        assert {h: s[()] for h, s in fam["hosts"].items()} == \
            {h: float(n) for h, n in per_host.items()}

    def test_gauge_min_max_mean(self):
        col = fl.FleetCollector(registry=MetricsRegistry())
        for host, v in (("h0", 2.0), ("h1", 8.0), ("h2", 5.0)):
            r = MetricsRegistry()
            r.gauge("depth").set(v)
            col.ingest(host, r.prometheus_text())
        agg = col.merged()["depth"]["fleet"][()]
        assert agg == {"min": 2.0, "max": 8.0, "mean": 5.0}

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_histogram_merge_exactness_property(self, seed):
        """The tentpole acceptance: fleet-merged fixed-bucket
        histograms reproduce the EXACT per-bucket counts AND the
        EXACT interpolated percentiles of one registry fed the union
        of every host's observations — over random shards."""
        rng = np.random.RandomState(seed)
        n_hosts = 2 + seed % 3
        union_reg = MetricsRegistry()
        union = union_reg.histogram("lat", buckets=BUCKETS,
                                    labelnames=("stage",))
        col = fl.FleetCollector(registry=MetricsRegistry())
        for host in range(n_hosts):
            r = MetricsRegistry()
            h = r.histogram("lat", buckets=BUCKETS,
                            labelnames=("stage",))
            for stage in ("prefill", "decode"):
                # binary-fraction grid: exact through text exposition
                for _ in range(int(rng.randint(5, 60))):
                    v = float(rng.randint(0, 81)) / 8.0
                    h.observe(v, stage=stage)
                    union.observe(v, stage=stage)
            col.ingest(f"host{host}", r.prometheus_text())
        fam = col.merged()["lat"]
        usnap = union_reg.snapshot()["metrics"]["lat"]["series"]
        for row in usnap:
            stage = row["labels"]["stage"]
            key = (("stage", stage),)
            merged = fam["fleet"][key]
            # bucket-for-bucket exact
            assert _norm_buckets(merged["buckets"]) == \
                _norm_buckets(row["buckets"]), stage
            assert merged["count"] == row["count"]
            assert merged["min"] == row["min"]
            assert merged["max"] == row["max"]
            # percentiles exactly equal to the union registry's
            for q in (50, 90, 99, 100):
                assert fl.merged_percentile(merged, q) == \
                    union.percentile(q, stage=stage), (stage, q)

    def test_merge_survives_chained_exposition(self):
        """Collector-of-collectors: re-parsing the fleet exposition's
        host rows keeps histogram state exact (repr extrema)."""
        col = fl.FleetCollector(registry=MetricsRegistry())
        r = MetricsRegistry()
        h = r.histogram("lat", buckets=BUCKETS)
        for v in (0.1234567890123, 3.3, 6.6, 9.9):
            h.observe(v)
        col.ingest("h0", r.prometheus_text())
        text = col.fleet_prometheus_text()
        refam = fl.parse_exposition(text)["lat"]
        key = (("host", "fleet"),)
        s = refam["series"][key]
        assert s["min"] == 0.1234567890123
        assert s["max"] == 9.9
        for q in (50, 99):
            assert fl.merged_percentile(s, q) == h.percentile(q)


# ---------------------------------------------------------------------------
# fleet health rollup
# ---------------------------------------------------------------------------
class TestFleetHealth:
    def _col(self, **kw):
        kw.setdefault("registry", MetricsRegistry())
        return fl.FleetCollector(**kw)

    def test_ok_member(self):
        col = self._col()
        col.ingest("h0", "x 1\n",
                   healthz={"status": "ok",
                            "snapshot_age_seconds": 0.5})
        assert col.member_health("h0")["status"] == "ok"
        assert col.fleet_healthz()["status"] == "ok"

    def test_degraded_member_degrades_fleet(self):
        col = self._col()
        col.ingest("h0", "x 1\n", healthz={"status": "ok",
                                           "snapshot_age_seconds": 0.1})
        col.ingest("h1", "x 1\n", healthz={
            "status": "degraded", "snapshot_age_seconds": 0.1,
            "components": [{"component": "serving_admission",
                            "status": "degraded"}]})
        doc = col.fleet_healthz()
        assert doc["status"] == "degraded"
        assert doc["members"]["h1"]["reason"] == "member degraded"
        assert doc["members"]["h0"]["status"] == "ok"

    def test_stale_snapshot_age_degrades(self):
        col = self._col(stale_after_s=1.0)
        # port answers, but the engine's snapshots froze long ago
        col.ingest("h0", "x 1\n",
                   healthz={"status": "ok",
                            "snapshot_age_seconds": 99.0})
        m = col.member_health("h0")
        assert m["status"] == "degraded" and m["reason"] == "stale"
        assert col.fleet_healthz()["status"] == "degraded"

    def test_push_mode_staleness_uses_last_heard(self):
        col = self._col(stale_after_s=1000.0)
        col.ingest("h0", "x 1\n")            # no healthz doc at all
        m = col.member_health("h0")
        assert m["status"] == "ok"
        assert 0 <= m["snapshot_age_seconds"] < 1000.0
        col.stale_after_s = 0.0
        time.sleep(0.01)
        assert col.member_health("h0")["reason"] == "stale"

    def test_unreachable_and_unknown_members(self):
        col = self._col()
        col.add_member("gone")               # registered, never heard
        assert col.member_health("gone")["reason"] == "unreachable"
        assert col.member_health("never")["reason"] == "unknown member"
        assert col.fleet_healthz()["status"] == "degraded"

    def test_members_gauge_by_state(self):
        r = MetricsRegistry()
        col = fl.FleetCollector(registry=r)
        col.ingest("h0", "x 1\n", healthz={"status": "ok",
                                           "snapshot_age_seconds": 0.1})
        col.add_member("gone")
        col.fleet_healthz()
        m = r.snapshot()["metrics"]["paddle_tpu_fleet_members"]
        vals = {s["labels"]["state"]: s["value"]
                for s in m["series"]}
        assert vals == {"ok": 1, "degraded": 1}


# ---------------------------------------------------------------------------
# the HTTP front door (scrape + push, end to end)
# ---------------------------------------------------------------------------
def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return (resp.headers.get("Content-Type"),
                resp.read().decode("utf-8"))


class TestFleetHTTP:
    def test_scrape_merge_serve(self):
        regs, srvs = [], []
        try:
            for i, n in enumerate((4, 9)):
                r = MetricsRegistry()
                r.counter("steps_total").inc(n)
                r.snapshot()                 # arm the liveness age
                regs.append(r)
                srvs.append(obs.serve_metrics(0, registry=r))
            col = fl.FleetCollector(registry=MetricsRegistry())
            for i, srv in enumerate(srvs):
                col.add_member(f"host{i}",
                               f"http://127.0.0.1:{srv.port}")
            errs = col.scrape()
            assert errs == {"host0": None, "host1": None}
            assert col.merged()["steps_total"]["fleet"][()] == 13.0
            assert col.fleet_healthz()["status"] == "ok"
            with col.serve(0, scrape_on_get=True) as fsrv:
                ctype, text = _get(
                    f"http://127.0.0.1:{fsrv.port}/metrics")
                assert "charset=utf-8" in ctype
                rows = obs.parse_prometheus_text(text)["steps_total"]
                assert rows[(("host", "fleet"),)] == 13.0
                assert rows[(("host", "host0"),)] == 4.0
                assert rows[(("host", "host1"),)] == 9.0
                _, hz = _get(f"http://127.0.0.1:{fsrv.port}/healthz")
                assert json.loads(hz)["status"] == "ok"
        finally:
            for srv in srvs:
                srv.close()

    def test_scrape_error_marks_unreachable(self):
        col = fl.FleetCollector(registry=MetricsRegistry(),
                                scrape_timeout_s=0.2)
        col.add_member("dead", "http://127.0.0.1:9")   # discard port
        errs = col.scrape()
        assert errs["dead"] is not None
        assert col.member_health("dead")["reason"] == "unreachable"
        assert col.fleet_healthz()["status"] == "degraded"

    def test_push_endpoints(self):
        col = fl.FleetCollector(registry=MetricsRegistry())
        with col.serve(0, scrape_on_get=False) as fsrv:
            url = f"http://127.0.0.1:{fsrv.port}"
            r = MetricsRegistry()
            r.counter("steps_total").inc(5)
            req = urllib.request.Request(
                f"{url}/push?host=pushed", method="POST",
                data=r.prometheus_text().encode("utf-8"))
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert json.loads(resp.read())["ok"] is True
            doc = {"host": "jsonhost", "metrics": "x 1\n",
                   "healthz": {"status": "ok",
                               "snapshot_age_seconds": 0.1}}
            req = urllib.request.Request(
                f"{url}/push", method="POST",
                data=json.dumps(doc).encode("utf-8"),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5):
                pass
            merged = col.merged()
            assert merged["steps_total"]["hosts"]["pushed"][()] == 5.0
            assert merged["x"]["hosts"]["jsonhost"][()] == 1.0
            assert col.member_health("jsonhost")["status"] == "ok"


# ---------------------------------------------------------------------------
# exporter satellites: ?names= filtering + charset + touch=False
# ---------------------------------------------------------------------------
class TestExporterFilter:
    def test_names_prefix_filter_and_charset(self):
        r = MetricsRegistry()
        r.counter("alpha_total").inc(1)
        r.counter("beta_total").inc(2)
        r.gauge("alpha_depth").set(3)
        with obs.serve_metrics(0, registry=r) as srv:
            url = f"http://127.0.0.1:{srv.port}"
            ctype, text = _get(f"{url}/metrics?names=alpha")
            assert "charset=utf-8" in ctype
            rows = obs.parse_prometheus_text(text)
            assert set(rows) == {"alpha_total", "alpha_depth"}
            # comma-separated prefixes widen the filter
            _, text = _get(f"{url}/metrics?names=alpha_total,beta")
            assert set(obs.parse_prometheus_text(text)) == \
                {"alpha_total", "beta_total"}
            # no filter: everything
            _, text = _get(f"{url}/metrics")
            assert set(obs.parse_prometheus_text(text)) >= \
                {"alpha_total", "beta_total", "alpha_depth"}

    def test_filtered_scrape_does_not_touch_liveness(self):
        r = MetricsRegistry()
        r.counter("alpha_total").inc(1)
        r.snapshot()                         # arm the age clock
        time.sleep(0.05)
        with obs.serve_metrics(0, registry=r) as srv:
            _get(f"http://127.0.0.1:{srv.port}/metrics?names=alpha")
            _get(f"http://127.0.0.1:{srv.port}/metrics")
        # scrapes (filtered or not) never reset the in-process age
        assert r.snapshot_age_seconds() >= 0.05


# ---------------------------------------------------------------------------
# W3C trace identity
# ---------------------------------------------------------------------------
class TestTraceIdentity:
    def test_make_format_parse_round_trip(self):
        tid, sid = sp.make_trace_id(), sp.make_span_id()
        assert len(tid) == 32 and len(sid) == 16
        assert tid != "0" * 32 and sid != "0" * 16
        hdr = sp.format_traceparent(tid, sid)
        assert hdr == f"00-{tid}-{sid}-01"
        assert sp.parse_traceparent(hdr) == (tid, sid)

    @pytest.mark.parametrize("bad", [
        "", "00-zz-xx-01", "00-" + "0" * 32 + "-" + "1" * 16 + "-01",
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",
        "01-" + "a" * 32 + "-" + "b" * 16,
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01"])
    def test_parse_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            sp.parse_traceparent(bad)

    def test_request_trace_carries_identity(self):
        tr = sp.RequestTrace(7)
        assert len(tr.trace_id) == 32 and len(tr.span_id) == 16
        assert tr.traceparent == \
            sp.format_traceparent(tr.trace_id, tr.span_id)
        tr.begin("prefill", 1.0)
        tr.end("prefill", 2.0)
        d = tr.to_dict()
        assert d["trace_id"] == tr.trace_id
        assert d["spans"][0]["parent_span_id"] == tr.span_id
        assert d["spans"][0]["span_id"] != tr.span_id

    def test_request_trace_joins_inbound_context(self):
        tid, psid = sp.make_trace_id(), sp.make_span_id()
        tr = sp.RequestTrace(1, trace_id=tid, parent_span_id=psid)
        assert tr.trace_id == tid
        assert tr.parent_span_id == psid
        assert tr.span_id not in (psid, "0" * 16)
        with pytest.raises(ValueError):
            sp.RequestTrace(2, trace_id="nothex")


class TestServingTracePropagation:
    @pytest.fixture(scope="class")
    def served(self):
        from paddle_tpu.distributed import fleet as _fleet
        from paddle_tpu.inference import (Config, ServingEngine,
                                          create_predictor)
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        _fleet._fleet_state.update(initialized=False, hcg=None,
                                   strategy=None)
        obs.reset_registry()
        paddle.seed(3)
        model = LlamaForCausalLM(llama_tiny())
        pred = create_predictor(
            Config().set_model(model).enable_paged_kv(page_size=8))
        eng = ServingEngine(pred, max_batch=2, decode_chunk=2)
        V = model.config.vocab_size
        r = np.random.RandomState(0)
        inbound = sp.format_traceparent(sp.make_trace_id(),
                                        sp.make_span_id())
        rid_hdr = eng.submit(r.randint(1, V, (6,)), max_new_tokens=3,
                             trace_id=inbound)
        rid_auto = eng.submit(r.randint(1, V, (9,)), max_new_tokens=3)
        eng.run()
        return eng, inbound, rid_hdr, rid_auto

    def test_submit_accepts_traceparent_header(self, served):
        eng, inbound, rid_hdr, _ = served
        tid, psid = sp.parse_traceparent(inbound)
        ctx = eng.trace_context(rid_hdr)
        assert ctx["trace_id"] == tid
        assert ctx["parent_span_id"] == psid
        assert ctx["span_id"] not in (psid, None)
        assert ctx["traceparent"] == \
            sp.format_traceparent(tid, ctx["span_id"])

    def test_submit_mints_fresh_identity(self, served):
        eng, inbound, rid_hdr, rid_auto = served
        ctx = eng.trace_context(rid_auto)
        assert len(ctx["trace_id"]) == 32
        assert ctx["trace_id"] != sp.parse_traceparent(inbound)[0]
        assert ctx["parent_span_id"] is None
        assert eng.trace_context(rid_hdr)["trace_id"] != \
            ctx["trace_id"]
        assert eng.trace_context(10_000) is None

    def test_every_exported_span_carries_trace_id(self, served,
                                                  tmp_path):
        eng, inbound, rid_hdr, rid_auto = served
        tid = sp.parse_traceparent(inbound)[0]
        by_rid = {t["rid"]: t for t in eng.request_traces()}
        for rid in (rid_hdr, rid_auto):
            tr = by_rid[rid]
            assert len(tr["spans"]) > 0
            assert all(s["parent_span_id"] == tr["span_id"]
                       for s in tr["spans"])
        assert by_rid[rid_hdr]["trace_id"] == tid
        assert by_rid[rid_hdr]["traceparent"].startswith(f"00-{tid}-")
        doc = eng.export_request_traces(str(tmp_path / "t.json"))
        evs = [e for e in doc["traceEvents"]
               if e["tid"] == rid_hdr and e["ph"] != "M"]
        assert evs
        assert all(e["args"]["trace_id"] == tid for e in evs)
        assert all("span_id" in e["args"] for e in evs)

    def test_serving_request_traceparent_property(self, served):
        eng, _, rid_hdr, _ = served
        req = eng.finished[rid_hdr]
        assert req.traceparent == \
            eng.trace_context(rid_hdr)["traceparent"]


# ---------------------------------------------------------------------------
# engine wiring: env-knob sampler, bit-identical losses, flat compiles
# ---------------------------------------------------------------------------
def _tiny_train_run(steps=3):
    from paddle_tpu.core.rng import get_rng_tracker
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    fleet._fleet_state.update(initialized=False, hcg=None,
                              strategy=None)
    get_rng_tracker().reset()
    obs.reset_registry()
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=16)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    r = np.random.RandomState(0)
    ids = r.randint(0, 64, (4, 9))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    losses = [float(step(batch)) for _ in range(steps)]
    return eng, losses


class TestEngineWiring:
    def test_env_knob_sampler_parity(self, tmp_path, monkeypatch):
        """The acceptance gate: the sampler attached via
        PADDLE_TPU_TIMESERIES_DIR changes NOTHING about the run —
        bit-identical losses, equal compile counts — while the journal
        fills."""
        monkeypatch.delenv("PADDLE_TPU_TIMESERIES_DIR", raising=False)
        eng_off, losses_off = _tiny_train_run()
        assert eng_off.sampler is None

        ts_dir = str(tmp_path / "tsdir")
        monkeypatch.setenv("PADDLE_TPU_TIMESERIES_DIR", ts_dir)
        monkeypatch.setenv("PADDLE_TPU_TIMESERIES_S", "60")
        eng_on, losses_on = _tiny_train_run()
        try:
            assert eng_on.sampler is not None
            eng_on.sampler.sample_now()
            assert losses_on == losses_off          # bit-identical
            assert eng_on.stats.compiles == eng_off.stats.compiles
            recs = ts.read_journal(os.path.join(ts_dir,
                                                ts.JOURNAL_NAME))
            samp = ts.samples(recs)
            assert samp
            pts = ts.query(recs, "paddle_tpu_train_steps_total")
            assert pts and pts[-1][1] == 3.0
            hist = ts.query(recs, "paddle_tpu_train_step_seconds",
                            field="count")
            assert hist[-1][1] == 3.0
        finally:
            eng_on.sampler.close()
            ts.detach()

    def test_checkpoint_manager_metrics_sample_knob(self, tmp_path):
        from paddle_tpu.distributed.checkpoint.manager import \
            CheckpointManager

        obs.reset_registry()
        base = str(tmp_path / "ckpt")
        mgr = CheckpointManager(base, metrics_sample_s=60)
        try:
            assert mgr._sampler is not None
            assert mgr._sampler is ts.attach_dir(base, interval_s=60)
            mgr._sampler.sample_now()
            assert ts.samples(ts.read_journal(
                os.path.join(base, ts.JOURNAL_NAME)))
            # the goodput journal lives right beside it
            assert os.path.exists(os.path.join(base, _gp.JOURNAL_NAME))
        finally:
            if mgr._sampler is not None:
                mgr._sampler.close()
            ts.detach()
            _gp.detach()

    def test_checkpoint_manager_default_no_sampler(self, tmp_path):
        from paddle_tpu.distributed.checkpoint.manager import \
            CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ckpt2"))
        assert mgr._sampler is None
        _gp.detach()


# ---------------------------------------------------------------------------
# reports: fleet_report + run_report --merge
# ---------------------------------------------------------------------------
def _write_goodput(path, t0, steps, restart=False):
    """A synthetic goodput journal: run header + compile +
    step_compute segments (+ an optional restart)."""
    recs = [{"ev": "run", "ts": t0, "pid": 1, "resumed": False},
            {"ev": "e", "seg": "compile", "t0": t0, "t1": t0 + 2.0},
            {"ev": "e", "seg": "step_compute", "t0": t0 + 2.0,
             "t1": t0 + 2.0 + steps}]
    if restart:
        recs += [{"ev": "e", "seg": "recovery_restart",
                  "t0": t0 + 2.0 + steps, "t1": t0 + 4.0 + steps},
                 {"ev": "run", "ts": t0 + 4.0 + steps, "pid": 2,
                  "resumed": True},
                 {"ev": "e", "seg": "step_compute",
                  "t0": t0 + 4.0 + steps, "t1": t0 + 6.0 + steps}]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _write_host_dir(tmp_path, name, t0, steps, step_mean,
                    comm_bytes, restart=False):
    d = tmp_path / name
    d.mkdir()
    _write_goodput(str(d / _gp.JOURNAL_NAME), t0, steps,
                   restart=restart)
    reg = MetricsRegistry()
    from paddle_tpu.observability.catalog import (comm_metrics,
                                                  train_metrics)
    m = train_metrics(reg)
    for _ in range(4):
        m["step_seconds"].observe(step_mean)
    comm_metrics(reg)["comm_bytes"].inc(comm_bytes, axis="mp",
                                        op="psum")
    with ts.MetricsSampler(str(d / ts.JOURNAL_NAME), registry=reg,
                           interval_s=60) as smp:
        smp.sample_now()
    return str(d)


class TestReports:
    def test_fleet_report_structure(self, tmp_path):
        from tools.fleet_report import fleet_report

        d0 = _write_host_dir(tmp_path, "host0", 1000.0, 10.0, 0.5,
                             1024.0)
        d1 = _write_host_dir(tmp_path, "host1", 1001.0, 10.0, 0.7,
                             2048.0, restart=True)
        rep = fleet_report([d0, d1])
        assert rep["fleet"]["members"] == 2
        lanes = {h["host"]: h for h in rep["hosts"]}
        assert lanes["host0"]["goodput"]["goodput_pct"] > 0
        assert lanes["host1"]["goodput"]["restarts"] == 1
        assert lanes["host0"]["step_time"]["mean_s"] == 0.5
        assert lanes["host1"]["step_time"]["mean_s"] == 0.7
        sk = rep["fleet"]["step_time_skew"]
        assert sk["slowest_host"] == "host1"
        assert sk["median_s"] == 0.6 and sk["max_s"] == 0.7
        assert sk["skew_pct"] == round(100 * (0.7 - 0.6) / 0.6, 2)
        assert rep["fleet"]["bytes"][
            "paddle_tpu_comm_bytes_total"] == 3072.0
        # combined timeline on one clock, tagged by host
        assert rep["timeline"][0]["t"] == 0.0
        whats = [(e["host"], e["what"]) for e in rep["timeline"]]
        assert ("host1", "recovery_restart") in whats
        assert ("host0", "start") in whats

    def test_fleet_report_cli(self, tmp_path, capsys):
        from tools.fleet_report import main

        d0 = _write_host_dir(tmp_path, "host0", 1000.0, 10.0, 0.5,
                             64.0)
        assert main([d0, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["fleet"]["members"] == 1
        assert main([d0]) == 0
        out = capsys.readouterr().out
        assert "goodput lanes" in out and "host0" in out
        empty = tmp_path / "empty"
        empty.mkdir()
        assert main([str(empty)]) == 2

    def test_run_report_merge(self, tmp_path, capsys):
        from tools.run_report import main, merge_report

        d0 = _write_host_dir(tmp_path, "host0", 1000.0, 10.0, 0.5,
                             64.0)
        d1 = _write_host_dir(tmp_path, "host1", 1002.0, 6.0, 0.6,
                             64.0, restart=True)
        rep = merge_report([d0, d1])
        lanes = {h["host"]: h for h in rep["hosts"]}
        assert lanes["host0"]["summary"]["goodput_pct"] > 0
        assert lanes["host1"]["summary"]["restarts"] == 1
        g = rep["fleet_goodput_pct"]
        assert g["min"] <= g["mean"] <= g["max"]
        whats = [(e["host"], e["what"]) for e in rep["timeline"]]
        assert ("host1", "recovery_restart") in whats
        assert ("host1", "resume") in whats
        ts0 = [e["t"] for e in rep["timeline"]]
        assert ts0 == sorted(ts0) and ts0[0] == 0.0
        # CLI: json + text + empty-dir exit code
        assert main(["--merge", d0, d1, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["hosts"]) == 2
        assert main(["--merge", d0, d1]) == 0
        out = capsys.readouterr().out
        assert "host lane" in out and "restart timeline" in out
        empty = tmp_path / "empty"
        empty.mkdir()
        assert main(["--merge", str(empty)]) == 2


# ---------------------------------------------------------------------------
# tpulint gate: the new tool lints clean with ZERO baseline entries
# (timeseries.py / fleet.py ride the observability-package gate in
# test_observability.py)
# ---------------------------------------------------------------------------
def test_tpulint_fleet_report_zero_baseline():
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths([repo / "tools" / "fleet_report.py",
                               repo / "tools" / "run_report.py"],
                              ALL_RULES, root=repo)
    finally:
        sys.path.remove(str(repo))
    assert findings == [], [str(f) for f in findings]
