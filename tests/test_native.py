"""Native C++ runtime components: TCPStore + shm-ring dataloader
(reference patterns: phi/core/distributed/store/tcp_store.h unit tests;
multiprocess dataloader tests in test/legacy_test)."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import native

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native library unavailable")


def test_tcpstore_set_get_add_wait():
    from paddle_tpu.distributed import TCPStore

    master = TCPStore("127.0.0.1", 0, is_master=True, timeout=10)
    client = TCPStore("127.0.0.1", master.port, is_master=False,
                      timeout=10)

    master.set("k1", b"hello")
    assert client.get("k1") == b"hello"
    assert client.check("k1") and not client.check("nope")

    assert client.add("ctr", 3) == 3
    assert master.add("ctr", 4) == 7

    # wait unblocks when another connection sets the key
    done = []

    def waiter():
        client.wait("later", timeout=10)
        done.append(client.get("later"))

    t = threading.Thread(target=waiter)
    t.start()
    master.set("later", b"v")
    t.join(timeout=10)
    assert done == [b"v"]

    client.delete_key("k1")
    assert not master.check("k1")
    client.close()
    master.close()


def test_tcpstore_barrier():
    from paddle_tpu.distributed import TCPStore

    master = TCPStore("127.0.0.1", 0, is_master=True, timeout=10)
    clients = [TCPStore("127.0.0.1", master.port) for _ in range(3)]
    results = []

    def enter(store, i):
        store.barrier("b0", 3, timeout=10)
        results.append(i)

    threads = [threading.Thread(target=enter, args=(c, i))
               for i, c in enumerate(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert sorted(results) == [0, 1, 2]
    for c in clients:
        c.close()
    master.close()


def test_shm_ring_roundtrip():
    import ctypes

    lib = native.load()
    h = lib.shmring_create(b"/ptpu_test_ring", 1 << 16)
    assert h
    payloads = [bytes([i]) * (100 + i) for i in range(50)]
    for p in payloads:
        buf = (ctypes.c_uint8 * len(p)).from_buffer_copy(p)
        assert lib.shmring_write(h, buf, len(p), 1000) == 0
    out = ctypes.POINTER(ctypes.c_uint8)()
    for p in payloads:
        n = lib.shmring_read(h, ctypes.byref(out), 1000)
        assert n == len(p)
        assert ctypes.string_at(out, n) == p
        lib.shmring_free(out)
    # empty + closed → -2 after close
    lib.shmring_close(h)
    assert lib.shmring_read(h, ctypes.byref(out), 100) == -2
    lib.shmring_detach(h)


def test_multiprocess_dataloader():
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __init__(self):
            self.x = np.arange(64, dtype="float32").reshape(32, 2)

        def __getitem__(self, i):
            return self.x[i], np.int64(i % 4)

        def __len__(self):
            return 32

    loader = DataLoader(DS(), batch_size=4, shuffle=False, num_workers=2)
    batches = list(loader)
    assert len(batches) == 8
    # ordering must match the single-process loader exactly
    ref = list(DataLoader(DS(), batch_size=4, shuffle=False,
                          num_workers=0))
    for (xa, ya), (xb, yb) in zip(batches, ref):
        np.testing.assert_array_equal(np.asarray(xa._value),
                                      np.asarray(xb._value))
        np.testing.assert_array_equal(np.asarray(ya._value),
                                      np.asarray(yb._value))
