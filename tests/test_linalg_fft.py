"""linalg decompositions + fft (reference: test/legacy_test/
test_svd_op.py, test_qr_op.py, test_eigh_op.py, test_cholesky_op.py,
test_solve_op.py, test_lstsq_op.py, test_fft.py — the OpTest pattern:
value parity vs numpy + gradient checks for differentiable ops)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle


def _rand(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype("float32")


def _spd(n, seed=0):
    a = np.random.RandomState(seed).randn(n, n).astype("float32")
    return a @ a.T + n * np.eye(n, dtype="float32")


def test_svd_reconstructs_and_grads():
    x = _rand(6, 4, seed=1)
    u, s, vh = paddle.linalg.svd(paddle.to_tensor(x))
    rec = np.asarray(u._value) @ np.diag(np.asarray(s._value)) @ \
        np.asarray(vh._value)
    np.testing.assert_allclose(rec, x, rtol=1e-4, atol=1e-4)
    # gradient flows through the singular values
    t = paddle.to_tensor(x, stop_gradient=False)
    _, s2, _ = paddle.linalg.svd(t)
    paddle.sum(s2).backward()
    # d(sum of singvals)/dx = u @ vh for distinct singvals
    ref = np.asarray(u._value) @ np.asarray(vh._value)
    np.testing.assert_allclose(np.asarray(t.grad._value), ref,
                               rtol=1e-3, atol=1e-3)


def test_qr_and_cholesky():
    x = _rand(5, 3, seed=2)
    q, r = paddle.linalg.qr(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(q._value) @ np.asarray(r._value),
                               x, rtol=1e-4, atol=1e-4)
    a = _spd(4, seed=3)
    L = paddle.linalg.cholesky(paddle.to_tensor(a))
    Lv = np.asarray(L._value)
    np.testing.assert_allclose(Lv @ Lv.T, a, rtol=1e-3, atol=1e-3)
    U = paddle.linalg.cholesky(paddle.to_tensor(a), upper=True)
    np.testing.assert_allclose(np.asarray(U._value), Lv.T, rtol=1e-5)


def test_eigh_parity_and_grad():
    a = _spd(5, seed=4)
    w, v = paddle.linalg.eigh(paddle.to_tensor(a))
    wn, vn = np.linalg.eigh(a)
    np.testing.assert_allclose(np.asarray(w._value), wn, rtol=1e-3,
                               atol=1e-3)
    t = paddle.to_tensor(a, stop_gradient=False)
    w2, _ = paddle.linalg.eigh(t)
    paddle.sum(w2).backward()
    # d(trace of eigvals)/dA = I for symmetric A
    np.testing.assert_allclose(np.asarray(t.grad._value),
                               np.eye(5, dtype="float32"), rtol=1e-3,
                               atol=1e-3)


def test_solve_family():
    a = _spd(4, seed=5)
    b = _rand(4, 2, seed=6)
    x = paddle.linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(a @ np.asarray(x._value), b, rtol=1e-3,
                               atol=1e-3)
    L = np.linalg.cholesky(a).astype("float32")
    y = paddle.linalg.cholesky_solve(paddle.to_tensor(b),
                                     paddle.to_tensor(L))
    np.testing.assert_allclose(a @ np.asarray(y._value), b, rtol=1e-3,
                               atol=1e-3)
    t = paddle.linalg.triangular_solve(
        paddle.to_tensor(L), paddle.to_tensor(b), upper=False)
    np.testing.assert_allclose(L @ np.asarray(t._value), b, rtol=1e-3,
                               atol=1e-3)


def test_lstsq_and_pinv():
    a = _rand(8, 3, seed=7)
    b = _rand(8, 2, seed=8)
    sol, res, rank, sv = paddle.linalg.lstsq(paddle.to_tensor(a),
                                             paddle.to_tensor(b))
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(np.asarray(sol._value), ref, rtol=1e-3,
                               atol=1e-3)
    p = paddle.linalg.pinv(paddle.to_tensor(a))
    np.testing.assert_allclose(np.asarray(p._value), np.linalg.pinv(a),
                               rtol=1e-3, atol=1e-3)


def test_det_inv_power_rank():
    a = _spd(4, seed=9)
    assert abs(float(paddle.linalg.det(paddle.to_tensor(a))._value)
               - np.linalg.det(a)) / abs(np.linalg.det(a)) < 1e-3
    sign, logdet = paddle.linalg.slogdet(paddle.to_tensor(a))
    assert float(sign._value) == pytest.approx(1.0)
    inv = paddle.linalg.inv(paddle.to_tensor(a))
    np.testing.assert_allclose(a @ np.asarray(inv._value),
                               np.eye(4), rtol=1e-3, atol=1e-3)
    p3 = paddle.linalg.matrix_power(paddle.to_tensor(a), 3)
    np.testing.assert_allclose(np.asarray(p3._value), a @ a @ a,
                               rtol=1e-2)
    r = paddle.linalg.matrix_rank(paddle.to_tensor(_rand(6, 4, seed=10)))
    assert int(r._value) == 4


def test_lu_and_misc():
    a = _spd(4, seed=11)
    lu_, piv = paddle.linalg.lu(paddle.to_tensor(a))
    assert tuple(lu_._value.shape) == (4, 4)
    m = paddle.linalg.multi_dot([paddle.to_tensor(_rand(3, 4, seed=1)),
                                 paddle.to_tensor(_rand(4, 5, seed=2)),
                                 paddle.to_tensor(_rand(5, 2, seed=3))])
    assert tuple(m._value.shape) == (3, 2)
    e = paddle.linalg.matrix_exp(paddle.to_tensor(
        np.zeros((3, 3), "float32")))
    np.testing.assert_allclose(np.asarray(e._value), np.eye(3), atol=1e-6)


def test_fft_roundtrip_and_parity():
    x = _rand(16, seed=12)
    X = paddle.fft.fft(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(X._value), np.fft.fft(x),
                               rtol=1e-3, atol=1e-4)
    back = paddle.fft.ifft(X)
    np.testing.assert_allclose(np.asarray(back._value).real, x,
                               rtol=1e-3, atol=1e-4)
    r = paddle.fft.rfft(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(r._value), np.fft.rfft(x),
                               rtol=1e-3, atol=1e-4)
    ir = paddle.fft.irfft(r, n=16)
    np.testing.assert_allclose(np.asarray(ir._value), x, rtol=1e-3,
                               atol=1e-4)
    x2 = _rand(4, 8, seed=13)
    X2 = paddle.fft.fft2(paddle.to_tensor(x2))
    np.testing.assert_allclose(np.asarray(X2._value), np.fft.fft2(x2),
                               rtol=1e-3, atol=1e-4)
    f = paddle.fft.fftfreq(8, d=0.5)
    np.testing.assert_allclose(np.asarray(f._value),
                               np.fft.fftfreq(8, d=0.5), rtol=1e-6)
    sh = paddle.fft.fftshift(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(sh._value), np.fft.fftshift(x))


def test_fft_grad_flows():
    x = paddle.to_tensor(_rand(8, seed=14), stop_gradient=False)
    X = paddle.fft.rfft(x)
    loss = paddle.sum(paddle.real(X * paddle.conj(X)))
    loss.backward()
    g = np.asarray(x.grad._value)
    assert np.isfinite(g).all() and np.abs(g).max() > 0
    # Parseval: sum|X|^2 gradient is 2*N'*x-ish; numeric check
    eps = 1e-3
    xv = np.asarray(x._value).copy()

    def f(v):
        X = np.fft.rfft(v)
        return float(np.sum(np.abs(X) ** 2))

    num = np.zeros_like(xv)
    for i in range(8):
        vp = xv.copy(); vp[i] += eps
        vm = xv.copy(); vm[i] -= eps
        num[i] = (f(vp) - f(vm)) / (2 * eps)
    np.testing.assert_allclose(g, num, rtol=2e-2, atol=1e-2)


def test_new_math_surface():
    x = _rand(4, 6, seed=15)
    t = paddle.to_tensor(x)
    assert float(paddle.trace(t)._value) == pytest.approx(
        np.trace(x), rel=1e-5)
    np.testing.assert_allclose(np.asarray(paddle.diagonal(t)._value),
                               np.diagonal(x))
    np.testing.assert_allclose(np.asarray(paddle.diff(t)._value),
                               np.diff(x), rtol=1e-6)
    xn = x.copy(); xn[0, 0] = np.nan
    assert np.isfinite(float(paddle.nansum(paddle.to_tensor(xn))._value))
    np.testing.assert_allclose(
        float(paddle.logaddexp(paddle.to_tensor(np.float32(1.0)),
                               paddle.to_tensor(np.float32(2.0)))._value),
        np.logaddexp(1.0, 2.0), rtol=1e-5)
    v, i = paddle.kthvalue(t, 2)
    np.testing.assert_allclose(np.asarray(v._value),
                               np.sort(x, -1)[:, 1], rtol=1e-6)
    h = paddle.histogram(t, bins=10, min=-3, max=3)
    assert int(np.asarray(h._value).sum()) <= x.size
    b = paddle.bucketize(t, paddle.to_tensor(
        np.array([-1.0, 0.0, 1.0], "float32")))
    assert tuple(b._value.shape) == (4, 6)
