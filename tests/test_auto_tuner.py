"""Auto-tuner: candidate generation, pruning, model ranking, trials
(reference: distributed/auto_tuner tests)."""
import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import (AutoTuner,
                                               default_candidates,
                                               estimate_memory_gb,
                                               estimate_step_time)

MODEL = {"hidden_size": 768, "num_layers": 12, "num_heads": 12,
         "vocab_size": 50304}


def test_candidates_respect_divisibility():
    cands = default_candidates(8, MODEL, global_batch=32)
    assert cands
    for c in cands:
        assert (c["dp_degree"] * c["mp_degree"] * c["pp_degree"]
                * c["sharding_degree"]) == 8
        assert MODEL["num_heads"] % c["mp_degree"] == 0
        assert MODEL["num_layers"] % c["pp_degree"] == 0
        assert 32 % (c["dp_degree"] * c["sharding_degree"]) == 0
    # mp=5 etc. never appear
    assert all(c["mp_degree"] in (1, 2, 4) for c in cands)


def test_memory_model_monotonic():
    base = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "micro_batch_size": 8}
    m1 = estimate_memory_gb(MODEL, base, 8, 1024)
    mp2 = estimate_memory_gb(MODEL, dict(base, mp_degree=2), 8, 1024)
    sh2 = estimate_memory_gb(MODEL, dict(base, sharding_degree=2), 8, 1024)
    assert mp2 < m1 and sh2 < m1
    rem = estimate_memory_gb(MODEL, base, 8, 1024, recompute=True)
    assert rem < m1


def test_cost_model_prefers_parallelism_for_big_models():
    big = {"hidden_size": 4096, "num_layers": 32, "num_heads": 32,
           "vocab_size": 32000}
    single = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
              "sharding_degree": 1}
    t1 = estimate_step_time(big, single, 64, 2048)
    t8 = estimate_step_time(big, dict(single, dp_degree=8), 64, 2048)
    assert t8 < t1


def test_tuner_prune_and_trials(tmp_path):
    tuner = AutoTuner(MODEL, num_devices=8, global_batch=32,
                      seq_len=1024, hbm_gb=16.0, max_trials=100)
    ranked = tuner.pruned()
    assert ranked and all(c["_pred_mem_gb"] <= 16.0 for c in ranked)
    assert ranked == sorted(ranked, key=lambda c: c["_pred_time"])

    best_model = tuner.best_by_model()
    assert "_pred_time" in best_model

    # measured trials: pretend dp=2/mp=4 is the fastest
    def trial(cfg):
        if cfg["mp_degree"] == 4 and cfg["dp_degree"] == 2:
            return 100.0
        if cfg["pp_degree"] > 1:
            raise MemoryError("oom")  # failures are pruned, not fatal
        return 10.0

    best = tuner.tune(trial)
    assert best["mp_degree"] == 4 and best["dp_degree"] == 2
    assert any(h["status"].startswith("failed") or h["metric"] == 10.0
               for h in tuner.history)
    tuner.save_history(str(tmp_path / "hist.json"))


def test_tiny_memory_budget_raises():
    tuner = AutoTuner(MODEL, num_devices=1, global_batch=8,
                      seq_len=1024, hbm_gb=0.001)
    with pytest.raises(RuntimeError):
        tuner.best_by_model()
