"""tpulint — the trace-safety & API-fidelity static analyzer (tools/
tpulint) wired into tier-1.

Under test:
- each shipped rule fires on a positive fixture and stays silent on the
  clean equivalent (the enforce-or-implement / bucketed versions)
- suppression pragmas (same line, comment line above, whole file)
- baseline fingerprint matching (line-number shifts don't break it,
  fixed findings surface as stale)
- the WHOLE-TREE GATE: paddle_tpu/ has zero findings outside the
  checked-in baseline — this is the CI teeth; a new silent-ignore knob
  or unbucketed jit-factory int fails tier-1
- CLI exit codes incl. a seeded violation (acceptance criteria)
"""
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:                     # direct pytest invocation
    sys.path.insert(0, str(REPO))

from tools.tpulint import (ALL_RULES, RULES_BY_ID, Project,  # noqa: E402
                           baseline_entry, lint_paths, lint_project,
                           lint_source, load_baseline, select_rules,
                           split_by_baseline)


def run_rule(rule_id, src, relpath="fixture.py", resources=None):
    return lint_source(src, relpath, select_rules([rule_id]),
                       resources=resources)


def run_project(rule_id, sources, resources=None):
    """Lint a multi-file in-memory project with one rule (the
    interprocedural fixtures)."""
    project = Project.from_sources(sources, resources=resources)
    return lint_project(project, select_rules([rule_id]))


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# rule fixtures: positive fires, negative is silent
# ---------------------------------------------------------------------------
class TestUnusedKnob:
    POS = """
def pool3d(x, kernel_size, ceil_mode=False):
    return x + kernel_size
"""
    NEG_READ = """
def pool3d(x, kernel_size, ceil_mode=False):
    return x + kernel_size + (1 if ceil_mode else 0)
"""
    NEG_ENFORCED = """
from paddle_tpu.core.enforce import enforce

def pool3d(x, kernel_size, ceil_mode=False):
    enforce(not ceil_mode, "ceil_mode is not served here")
    return x + kernel_size
"""

    def test_positive(self):
        fs = run_rule("unused-knob", self.POS)
        assert rule_ids(fs) == ["unused-knob"]
        assert "'ceil_mode'" in fs[0].message and fs[0].symbol == "pool3d"

    def test_negative_read(self):
        assert run_rule("unused-knob", self.NEG_READ) == []

    def test_negative_enforce_guard(self):
        assert run_rule("unused-knob", self.NEG_ENFORCED) == []

    def test_name_param_and_private_fn_exempt(self):
        src = """
def rank(x, name=None):
    return x.ndim

def _helper(x, internal_knob=3):
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_stub_exempt(self):
        src = """
class BaseTransform:
    def _apply_image(self, img):
        raise NotImplementedError
"""
        assert run_rule("unused-knob", src) == []


class TestHostSyncInJit:
    POS = """
import jax
import jax.numpy as jnp
import numpy as np

def body(x):
    s = jnp.sum(x)
    return np.asarray(s)

step = jax.jit(body)
"""
    NEG_NOT_JITTED = """
import jax.numpy as jnp
import numpy as np

def body(x):
    s = jnp.sum(x)
    return np.asarray(s)
"""
    NEG_STAYS_TRACED = """
import jax
import jax.numpy as jnp

def body(x):
    return jnp.sum(x)

step = jax.jit(body)
"""

    def test_positive(self):
        fs = run_rule("host-sync-in-jit", self.POS)
        assert rule_ids(fs) == ["host-sync-in-jit"]
        assert "np.asarray" in fs[0].message

    def test_negative_outside_jit(self):
        assert run_rule("host-sync-in-jit", self.NEG_NOT_JITTED) == []

    def test_negative_pure_jnp(self):
        assert run_rule("host-sync-in-jit", self.NEG_STAYS_TRACED) == []

    def test_item_in_def_op_kernel(self):
        src = """
from paddle_tpu.core.dispatch import def_op

@def_op("bad_kernel")
def bad_kernel(x):
    return x.item()
"""
        fs = run_rule("host-sync-in-jit", src)
        assert rule_ids(fs) == ["host-sync-in-jit"]
        assert ".item()" in fs[0].message

    def test_int_of_static_knob_allowed(self):
        # int() on a static Python knob inside a traced kernel is fine;
        # only tainted (traced-array) expressions count
        src = """
from paddle_tpu.core.dispatch import def_op
import jax.numpy as jnp

@def_op("k")
def k(x, sampling_ratio=-1):
    sr = int(sampling_ratio)
    return jnp.sum(x) * sr
"""
        assert run_rule("host-sync-in-jit", src) == []

    def test_float_of_traced_value_flagged(self):
        src = """
import jax
import jax.numpy as jnp

def body(x):
    return float(jnp.max(x))

f = jax.jit(body)
"""
        fs = run_rule("host-sync-in-jit", src)
        assert rule_ids(fs) == ["host-sync-in-jit"]


class TestTracedBool:
    POS = """
import jax
import jax.numpy as jnp

def body(x):
    y = jnp.sum(x)
    if y > 0:
        return x
    return -x

f = jax.jit(body)
"""
    NEG_STATIC_KNOB = """
import jax
import jax.numpy as jnp

def body(x, ceil_mode=False):
    if ceil_mode:
        return jnp.ceil(x)
    return x

f = jax.jit(body)
"""
    NEG_SHAPE_AND_NONE = """
import jax
import jax.numpy as jnp

def body(x, mask=None):
    y = jnp.abs(x)
    if y.ndim == 2:
        y = y[None]
    if mask is not None:
        y = y * mask
    return y

f = jax.jit(body)
"""

    def test_positive(self):
        fs = run_rule("traced-bool", self.POS)
        assert rule_ids(fs) == ["traced-bool"]
        assert "'y'" in fs[0].message

    def test_negative_static_knob(self):
        assert run_rule("traced-bool", self.NEG_STATIC_KNOB) == []

    def test_negative_shape_and_none_checks(self):
        assert run_rule("traced-bool", self.NEG_SHAPE_AND_NONE) == []

    def test_while_on_traced(self):
        src = """
import jax
import jax.numpy as jnp

def body(x):
    n = jnp.sum(x)
    while n > 0:
        n = n - 1
    return n

f = jax.jit(body)
"""
        fs = run_rule("traced-bool", src)
        assert rule_ids(fs) == ["traced-bool"]
        assert "`while`" in fs[0].message


class TestNonhashableStatic:
    POS_DECORATOR = """
from functools import partial
import jax

@partial(jax.jit, static_argnames=("sizes",))
def f(x, sizes=[1, 2]):
    return x
"""
    POS_ARGNUMS = """
import jax

def f(x, sizes=[8, 16]):
    return x

g = jax.jit(f, static_argnums=(1,))
"""
    NEG_TUPLE = """
from functools import partial
import jax

@partial(jax.jit, static_argnames=("sizes",))
def f(x, sizes=(1, 2)):
    return x
"""

    def test_positive_decorator(self):
        fs = run_rule("nonhashable-static", self.POS_DECORATOR)
        assert rule_ids(fs) == ["nonhashable-static"]
        assert "'sizes'" in fs[0].message

    def test_positive_call_form(self):
        fs = run_rule("nonhashable-static", self.POS_ARGNUMS)
        assert rule_ids(fs) == ["nonhashable-static"]

    def test_negative_tuple_default(self):
        assert run_rule("nonhashable-static", self.NEG_TUPLE) == []


class TestRecompileHazard:
    POS = """
def serve(pred, prompts):
    B = len(prompts)
    prefill = pred._prefill_fn(B, 128)
    return prefill(prompts)
"""
    NEG_BUCKETED = """
def _bucket(n, lo=64):
    b = lo
    while b < n:
        b *= 2
    return b

def serve(pred, prompts):
    B = _bucket(len(prompts))
    prefill = pred._prefill_fn(B, 128)
    return prefill(prompts)
"""
    NEG_SANITIZING_HELPER = """
def _max_len(self, S0):
    return _bucket(S0)

def serve(self, pred, ids):
    B, S0 = ids.shape
    M = self._max_len(S0)
    fn = pred._decode_fn(M, 4)
    return fn(ids)
"""

    def test_positive(self):
        fs = run_rule("recompile-hazard", self.POS)
        assert rule_ids(fs) == ["recompile-hazard"]
        assert "'B'" in fs[0].message and "_prefill_fn" in fs[0].message

    def test_negative_bucketed(self):
        assert run_rule("recompile-hazard", self.NEG_BUCKETED) == []

    def test_negative_bucketing_helper_sanitizes(self):
        assert run_rule("recompile-hazard", self.NEG_SANITIZING_HELPER) \
            == []

    def test_shape_attr_direct_arg(self):
        src = """
def serve(pred, ids):
    fn = pred._decode_fn(ids.shape[0], 4)
    return fn(ids)
"""
        fs = run_rule("recompile-hazard", src)
        assert rule_ids(fs) == ["recompile-hazard"]

    def test_jitted_callable_args_not_boundaries(self):
        # python ints into the RETURNED jitted fn become weak-typed
        # traced scalars — no recompile, no finding
        src = """
def serve(pred, ids):
    fn = pred._decode_fn(4, 128)
    pos = ids.shape[1]
    return fn(ids, pos)
"""
        assert run_rule("recompile-hazard", src) == []


# ---------------------------------------------------------------------------
# interprocedural contract rules (the tpulint v2 Project pass)
# ---------------------------------------------------------------------------
class TestRawCollective:
    POS = """
import jax
from jax import lax

def grad_sync(g):
    return lax.psum(g, "dp")
"""
    NEG_SHIM = """
from ..distributed.collective import t_psum

def grad_sync(g):
    return t_psum(g, "dp")
"""

    def test_positive(self):
        fs = run_rule("raw-collective", self.POS,
                      relpath="paddle_tpu/models/foo.py")
        assert rule_ids(fs) == ["raw-collective"]
        assert "t_psum" in fs[0].message

    def test_negative_through_shim(self):
        assert run_rule("raw-collective", self.NEG_SHIM,
                        relpath="paddle_tpu/models/foo.py") == []

    def test_allowlisted_modules(self):
        # the shim itself and the ledger's ablation/replay lowering
        # are the two places that must touch lax
        for rel in ("paddle_tpu/distributed/collective.py",
                    "paddle_tpu/observability/commledger.py"):
            assert run_rule("raw-collective", self.POS, relpath=rel) == []

    def test_all_wrapped_ops_flagged(self):
        src = """
from jax import lax

def f(x):
    a = lax.all_gather(x, "mp", axis=0, tiled=True)
    b = lax.psum_scatter(x, "mp")
    c = lax.all_to_all(x, "ep", 0, 1)
    d = lax.ppermute(x, "pp", [(0, 1)])
    return a, b, c, d
"""
        fs = run_rule("raw-collective", src,
                      relpath="paddle_tpu/models/foo.py")
        assert len(fs) == 4

    def test_local_helper_named_psum_not_flagged(self):
        src = """
def psum(x, axes):
    return x

def f(x):
    return psum(x, "dp")
"""
        assert run_rule("raw-collective", src,
                        relpath="paddle_tpu/models/foo.py") == []


class TestUnregisteredMetric:
    SCHEMA = {"pt_requests_total": {"type": "counter"},
              "pt_depth": {"type": "gauge"}}
    CATALOG = """
from .metrics import get_registry

def serving_metrics():
    r = get_registry()
    return {
        "requests": r.counter("pt_requests_total", "requests"),
        "depth": r.gauge("pt_depth", "queue depth"),
    }
"""

    def test_clean_when_in_sync(self):
        fs = run_project(
            "unregistered-metric",
            {"pkg/observability/catalog.py": self.CATALOG},
            resources={"metric_schema": self.SCHEMA})
        assert fs == []

    def test_direction1_unknown_registration(self):
        # a registration anywhere in the tree outside the schema —
        # including a module that is NOT the catalog (cross-module)
        extra = """
from .observability.metrics import get_registry

def init():
    get_registry().counter("pt_rogue_total", "untracked")
"""
        fs = run_project(
            "unregistered-metric",
            {"pkg/observability/catalog.py": self.CATALOG,
             "pkg/engine.py": extra},
            resources={"metric_schema": self.SCHEMA})
        assert rule_ids(fs) == ["unregistered-metric"]
        assert fs[0].path == "pkg/engine.py"
        assert "pt_rogue_total" in fs[0].message

    def test_direction2_stale_schema_entry(self):
        schema = dict(self.SCHEMA)
        schema["pt_dead_gauge"] = {"type": "gauge"}
        fs = run_project(
            "unregistered-metric",
            {"pkg/observability/catalog.py": self.CATALOG},
            resources={"metric_schema": schema})
        assert rule_ids(fs) == ["unregistered-metric"]
        assert fs[0].symbol == "<schema>"
        assert "pt_dead_gauge" in fs[0].message and "stale" in \
            fs[0].message

    def test_jnp_histogram_not_a_registration(self):
        src = """
import jax.numpy as jnp

def h(x):
    return jnp.histogram(x, bins=10)
"""
        fs = run_project(
            "unregistered-metric",
            {"pkg/observability/catalog.py": self.CATALOG,
             "pkg/ops.py": src},
            resources={"metric_schema": self.SCHEMA})
        assert fs == []

    def test_silent_without_schema_resource(self):
        assert run_project("unregistered-metric",
                           {"pkg/catalog.py": self.CATALOG}) == []


class TestVjpLedgerSymmetry:
    def test_mirrored_ring_accepted_cross_module(self):
        # fwd/bwd collective facts resolved through an impl helper in
        # ANOTHER module (the collective_matmul delegation shape)
        rings = """
def ring_fwd_impl(x, axes):
    return t_ppermute(x, axes, [(0, 1)])

def ring_bwd_impl(g, axes):
    return t_ppermute(g, axes, [(1, 0)])
"""
        op = """
import jax
from functools import partial
from .rings import ring_fwd_impl, ring_bwd_impl

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def ring_op(x, axes):
    return ring_fwd_impl(x, axes)

def _fwd(x, axes):
    return ring_fwd_impl(x, axes), None

def _bwd(axes, res, g):
    return (ring_bwd_impl(g, axes),)

ring_op.defvjp(_fwd, _bwd)
"""
        assert run_project("vjp-ledger-symmetry",
                           {"pkg/rings.py": rings,
                            "pkg/op.py": op}) == []

    def test_missing_bwd_shim_rejected(self):
        src = """
import jax
from functools import partial

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def dispatch(x, axes):
    return t_all_to_all(x, axes, 0, 1)

def _fwd(x, axes):
    return dispatch(x, axes), None

def _bwd(axes, res, g):
    return (g,)

dispatch.defvjp(_fwd, _bwd)
"""
        fs = run_project("vjp-ledger-symmetry", {"pkg/op.py": src})
        assert rule_ids(fs) == ["vjp-ledger-symmetry"]
        assert "no t_* collective" in fs[0].message

    def test_non_mirrored_bwd_rejected(self):
        src = """
import jax
from functools import partial

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather(x, axes):
    return t_all_gather(x, axes, axis=0, tiled=True)

def _fwd(x, axes):
    return gather(x, axes), None

def _bwd(axes, res, g):
    return (t_all_gather(g, axes, axis=0, tiled=True),)

gather.defvjp(_fwd, _bwd)
"""
        fs = run_project("vjp-ledger-symmetry", {"pkg/op.py": src})
        assert rule_ids(fs) == ["vjp-ledger-symmetry"]
        assert "mirrored" in fs[0].message

    def test_psum_identity_pairing_accepted(self):
        # the Megatron pairing: reduce-family fwd, identity bwd
        src = """
import jax
from functools import partial

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_allreduce(x, axes):
    return t_psum(x, axes)

mp_allreduce.defvjp(lambda x, axes: (t_psum(x, axes), None),
                    lambda axes, res, g: (g,))
"""
        assert run_project("vjp-ledger-symmetry",
                           {"pkg/op.py": src}) == []

    def test_gather_slice_pairing_accepted(self):
        # the _c_concat pairing: replicated cotangent, local slice bwd
        src = """
import jax
from functools import partial
from jax import lax

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def concat(x, axes):
    return t_all_gather(x, axes, axis=0, tiled=True)

def _fwd(x, axes):
    return concat(x, axes), x.shape[0]

def _bwd(axes, local, g):
    return (lax.dynamic_slice_in_dim(g, 0, local, axis=0),)

concat.defvjp(_fwd, _bwd)
"""
        assert run_project("vjp-ledger-symmetry",
                           {"pkg/op.py": src}) == []

    def test_collective_free_vjp_skipped(self):
        src = """
import jax
from functools import partial

@partial(jax.custom_vjp, nondiff_argnums=())
def sq(x):
    return x * x

sq.defvjp(lambda x: (x * x, x), lambda x, g: (2 * x * g,))
"""
        assert run_project("vjp-ledger-symmetry",
                           {"pkg/op.py": src}) == []

    def test_quantized_allreduce_keeps_psum_identity_pairing(self):
        # the quant_comm wrappers map to their LOGICAL collective kind
        # in the shim table (an int8 allreduce lowers to a2a+all_gather
        # internally, but the contract is a psum) — so the Megatron
        # psum/identity pairing stays recognizable through a quantized
        # forward. Without the mapping this fwd would read as
        # {all_to_all, all_gather} vs an identity bwd and flag.
        src = """
import jax
from functools import partial
from . import quant_comm as _qc

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_allreduce(x, axes):
    out, _ = _qc.quantized_allreduce(x, axes, None)
    return out

mp_allreduce.defvjp(
    lambda x, axes: (mp_allreduce(x, axes), None),
    lambda axes, res, g: (g,))
"""
        helper = """
def quantized_allreduce(v, axes, cfg):
    q = v
    qq = t_all_to_all(q, axes, 0, 0, tiled=True)
    full = t_all_gather(qq, axes, axis=0, tiled=True)
    return full, v
"""
        assert run_project("vjp-ledger-symmetry",
                           {"pkg/quant_comm.py": helper,
                            "pkg/op.py": src}) == []

    def test_quantized_ring_mirrored_pairing_accepted(self):
        # quantized rings ship (payload, scales) pairs through
        # permute_packed -> t_ppermute: the ppermute<->ppermute mirror
        # must resolve through the packing helper
        helper = """
def permute_packed(q, s, name, perm, ratio):
    return t_ppermute(q, name, perm), t_ppermute(s, name, perm)
"""
        op = """
import jax
from functools import partial
from .quant_comm import permute_packed

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def qring(x, axes):
    q, s = permute_packed(x, x, axes, [(0, 1)], 0.25)
    return q

def _fwd(x, axes):
    return qring(x, axes), None

def _bwd(axes, res, g):
    q, s = permute_packed(g, g, axes, [(1, 0)], 0.25)
    return (q,)

qring.defvjp(_fwd, _bwd)
"""
        assert run_project("vjp-ledger-symmetry",
                           {"pkg/quant_comm.py": helper,
                            "pkg/op.py": op}) == []


class TestDonationReuse:
    STORE = """
import jax

class Engine:
    def _step_fn(self):
        self._fns = {}
        self._fns["k"] = jax.jit(lambda p, x, c: (x, c),
                                 donate_argnums=(2,))
        return self._fns["k"]

    def _run(self, site, fn, *args):
        return fn(*args)
"""

    def test_read_after_donation_flagged(self):
        src = self.STORE + """
    def bad(self, p, x, cache):
        fn = self._step_fn()
        out = fn(p, x, cache)
        return out, cache.sum()
"""
        fs = run_project("donation-reuse", {"pkg/engine.py": src})
        assert rule_ids(fs) == ["donation-reuse"]
        assert "'cache'" in fs[0].message

    def test_rebound_from_results_clean(self):
        src = self.STORE + """
    def good(self, p, x, cache):
        fn = self._step_fn()
        out, cache = fn(p, x, cache)
        return out, cache.sum()
"""
        assert run_project("donation-reuse",
                           {"pkg/engine.py": src}) == []

    def test_through_forwarder_wrapper(self):
        # self._run(site, fn, *payload) shifts the donated position —
        # the ServingEngine._run_captured shape
        src = self.STORE + """
    def bad(self, p, x, cache):
        fn = self._step_fn()
        out = self._run("site", fn, p, x, cache)
        return out, cache
"""
        fs = run_project("donation-reuse", {"pkg/engine.py": src})
        assert rule_ids(fs) == ["donation-reuse"]

    def test_forwarder_rebind_clean(self):
        src = self.STORE + """
    def good(self, p, x, cache):
        fn = self._step_fn()
        out, cache = self._run("site", fn, p, x, cache)
        return out, cache
"""
        assert run_project("donation-reuse",
                           {"pkg/engine.py": src}) == []

    def test_direct_call_of_store_subscript(self):
        src = self.STORE + """
    def bad(self, p, x, cache):
        self._step_fn()
        out = self._fns["k"](p, x, cache)
        return out, cache
"""
        fs = run_project("donation-reuse", {"pkg/engine.py": src})
        assert rule_ids(fs) == ["donation-reuse"]

    def test_undonated_jit_clean(self):
        src = """
import jax

def go(p, cache):
    fn = jax.jit(lambda a, b: b)
    out = fn(p, cache)
    return out, cache
"""
        assert run_project("donation-reuse", {"pkg/m.py": src}) == []


class TestUnguardedSharedMutation:
    POS = """
import threading

class Agg:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.count += 1

    def read_and_reset(self):
        v = self.count
        self.count = 0
        return v
"""
    NEG_LOCKED = """
import threading

class Agg:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self.count += 1

    def read_and_reset(self):
        with self._lock:
            v = self.count
            self.count = 0
        return v
"""

    def test_positive(self):
        fs = run_project("unguarded-shared-mutation",
                         {"pkg/observability/agg.py": self.POS})
        assert rule_ids(fs) == ["unguarded-shared-mutation"]
        assert "'self.count'" in fs[0].message
        assert fs[0].symbol == "Agg._loop"

    def test_negative_common_lock(self):
        assert run_project(
            "unguarded-shared-mutation",
            {"pkg/observability/agg.py": self.NEG_LOCKED}) == []

    def test_out_of_scope_module_not_reported(self):
        # reachability is whole-tree but findings are scoped to the
        # concurrent subsystems
        assert run_project("unguarded-shared-mutation",
                           {"pkg/models/agg.py": self.POS}) == []

    def test_cross_module_thread_target(self):
        # Thread target in one module reaches a mutating method of a
        # class defined in a scoped module two hops away
        driver = """
import threading
from .observability.sink import SINK

def _work():
    SINK.record(1)

def start():
    threading.Thread(target=_work, daemon=True).start()
"""
        sink = """
class Sink:
    def __init__(self):
        self.total = 0

    def record(self, n):
        self.total += n

    def flush(self):
        v = self.total
        self.total = 0
        return v

SINK = Sink()
"""
        fs = run_project("unguarded-shared-mutation",
                         {"pkg/driver.py": driver,
                          "pkg/observability/sink.py": sink})
        assert rule_ids(fs) == ["unguarded-shared-mutation"]
        assert fs[0].path == "pkg/observability/sink.py"
        assert "Sink.record" in fs[0].message or \
            fs[0].symbol == "Sink.record"

    def test_lock_held_on_entry_fixpoint(self):
        # a private helper only ever called under the lock is guarded
        # even though its own body shows no `with` (goodput.py's
        # _close_interval shape)
        src = """
import threading

class Led:
    def __init__(self):
        self.total = 0
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self._bump()

    def _bump(self):
        self.total += 1

    def read(self):
        with self._lock:
            return self.total
"""
        assert run_project("unguarded-shared-mutation",
                           {"pkg/observability/led.py": src}) == []

    def test_init_only_mutation_exempt(self):
        src = """
import threading

class Led:
    def __init__(self):
        self._setup()
        threading.Thread(target=self._loop, daemon=True).start()

    def _setup(self):
        self.total = 0

    def _loop(self):
        with self._lock:
            pass

    def read(self):
        return self.total
"""
        assert run_project("unguarded-shared-mutation",
                           {"pkg/observability/led.py": src}) == []

    def test_threadsafe_attr_exempt(self):
        src = """
import queue
import threading

class Pump:
    def __init__(self):
        self._q = queue.Queue()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self._q.put(1)

    def drain(self):
        return self._q.get_nowait()
"""
        assert run_project("unguarded-shared-mutation",
                           {"pkg/observability/pump.py": src}) == []


# ---------------------------------------------------------------------------
# lock-order-cycle (ISSUE 17): the lock-graph deadlock prover
# ---------------------------------------------------------------------------
class TestLockOrderCycle:
    # two module-level locks, two Thread entrypoints, opposite
    # acquisition order across modules: the classic AB/BA deadlock
    A_THEN_B = """
import threading
from pkg import beta

_lock_a = threading.Lock()

def start():
    threading.Thread(target=loop_a, daemon=True).start()

def loop_a():
    with _lock_a:
        with beta._lock_b:
            pass
"""
    B_THEN_A = """
import threading
from pkg import alpha

_lock_b = threading.Lock()

def start():
    threading.Thread(target=loop_b, daemon=True).start()

def loop_b():
    with _lock_b:
        with alpha._lock_a:
            pass
"""

    def test_two_thread_ab_ba_cycle_across_modules(self):
        fs = run_project("lock-order-cycle",
                         {"pkg/alpha.py": self.A_THEN_B,
                          "pkg/beta.py": self.B_THEN_A})
        assert rule_ids(fs) == ["lock-order-cycle"]
        msg = fs[0].message
        assert "pkg/alpha.py:_lock_a" in msg
        assert "pkg/beta.py:_lock_b" in msg
        # both thread entrypoints named as the interleaving witnesses
        assert "loop_a" in msg and "loop_b" in msg

    def test_acyclic_nested_locks_clean(self):
        # same two threads, same two locks, CONSISTENT A-then-B order
        b_same_order = """
import threading
from pkg import alpha

_lock_b = threading.Lock()

def start():
    threading.Thread(target=loop_b, daemon=True).start()

def loop_b():
    with alpha._lock_a:
        with _lock_b:
            pass
"""
        assert run_project("lock-order-cycle",
                           {"pkg/alpha.py": self.A_THEN_B,
                            "pkg/beta.py": b_same_order}) == []

    def test_single_thread_cycle_not_flagged(self):
        # both orders exercised, but from ONE entrypoint — a single
        # thread acquires sequentially and cannot deadlock itself
        src = """
import threading

_a = threading.Lock()
_b = threading.Lock()

def start():
    threading.Thread(target=loop, daemon=True).start()

def loop():
    with _a:
        with _b:
            pass
    with _b:
        with _a:
            pass
"""
        assert run_project("lock-order-cycle", {"pkg/m.py": src}) == []

    def test_cycle_through_entry_held_helper(self):
        # three locks, three contexts: Pump._loop holds self._lock and
        # calls a helper that takes beta._lock_b (interprocedural
        # edge); beta's watch thread orders _lock_b -> _lock_c; the
        # main-thread flush() closes the cycle _lock_c -> Pump._lock
        src_a = """
import threading
from pkg import beta

class Pump:
    def __init__(self):
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self._drain()

    def _drain(self):
        with beta._lock_b:
            pass

    def flush(self):
        with beta._lock_c:
            with self._lock:
                pass
"""
        src_b = """
import threading

_lock_b = threading.Lock()
_lock_c = threading.Lock()

def start():
    threading.Thread(target=watch, daemon=True).start()

def watch():
    with _lock_b:
        with _lock_c:
            pass
"""
        fs = run_project("lock-order-cycle",
                         {"pkg/alpha.py": src_a, "pkg/beta.py": src_b})
        assert rule_ids(fs) == ["lock-order-cycle"]
        assert "Pump._lock" in fs[0].message


# ---------------------------------------------------------------------------
# blocking-under-lock (ISSUE 17)
# ---------------------------------------------------------------------------
class TestBlockingUnderLock:
    def test_jit_dispatch_under_lock(self):
        src = """
import jax
import threading

class Engine:
    def __init__(self):
        self._lock = threading.RLock()
        self._step = jax.jit(lambda x: x)

    def run(self, x):
        with self._lock:
            return self._step(x)
"""
        fs = run_project("blocking-under-lock",
                         {"pkg/inference/serving.py": src})
        assert rule_ids(fs) == ["blocking-under-lock"]
        assert "jitted dispatch" in fs[0].message
        assert fs[0].symbol == "Engine.run"

    def test_rebind_under_lock_dispatch_after_release_clean(self):
        # the sanctioned pattern: grab the callable reference under
        # the lock, pay compile + device time outside it
        src = """
import jax
import threading

class Engine:
    def __init__(self):
        self._lock = threading.RLock()
        self._step = jax.jit(lambda x: x)

    def run(self, x):
        with self._lock:
            fn = self._step
        return fn(x)
"""
        assert run_project("blocking-under-lock",
                           {"pkg/inference/serving.py": src}) == []

    def test_local_jit_alias_under_lock_still_flagged(self):
        src = """
import jax
import threading

class Engine:
    def __init__(self):
        self._lock = threading.RLock()
        self._step = jax.jit(lambda x: x)

    def run(self, x):
        fn = self._step
        with self._lock:
            return fn(x)
"""
        fs = run_project("blocking-under-lock",
                         {"pkg/inference/serving.py": src})
        assert rule_ids(fs) == ["blocking-under-lock"]

    def test_cv_wait_outside_predicate_loop(self):
        src = """
import threading

class Box:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False

    def take(self):
        with self._cv:
            if not self.ready:
                self._cv.wait()
"""
        fs = run_project("blocking-under-lock",
                         {"pkg/observability/box.py": src})
        assert rule_ids(fs) == ["blocking-under-lock"]
        assert "predicate loop" in fs[0].message

    def test_cv_wait_in_predicate_loop_clean(self):
        src = """
import threading

class Box:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False

    def take(self):
        with self._cv:
            while not self.ready:
                self._cv.wait()

    def put(self):
        with self._cv:
            self.ready = True
            self._cv.notify_all()
"""
        assert run_project("blocking-under-lock",
                           {"pkg/observability/box.py": src}) == []

    def test_notify_without_cv_held(self):
        src = """
import threading

class Box:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False

    def put(self):
        self.ready = True
        self._cv.notify_all()
"""
        fs = run_project("blocking-under-lock",
                         {"pkg/observability/box.py": src})
        assert rule_ids(fs) == ["blocking-under-lock"]
        assert "without holding" in fs[0].message

    def test_timeoutless_queue_get_under_lock(self):
        src = """
import queue
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def drain(self):
        with self._lock:
            return self._q.get()
"""
        fs = run_project("blocking-under-lock",
                         {"pkg/observability/pump.py": src})
        assert rule_ids(fs) == ["blocking-under-lock"]
        assert "timeout-less" in fs[0].message

    def test_bounded_queue_get_clean(self):
        src = """
import queue
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def drain(self):
        with self._lock:
            return self._q.get(timeout=0.5)
"""
        assert run_project("blocking-under-lock",
                           {"pkg/observability/pump.py": src}) == []

    def test_thread_reachable_timeoutless_get_no_lock(self):
        # the CheckpointManager._writer_loop shape: no lock held, but
        # the loop can never observe shutdown -> close() hangs
        src = """
import queue
import threading

class Writer:
    def __init__(self):
        self._q = queue.Queue()
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            job = self._q.get()
            if job is None:
                return
"""
        fs = run_project("blocking-under-lock",
                         {"pkg/distributed/checkpoint/w.py": src})
        assert rule_ids(fs) == ["blocking-under-lock"]
        assert "Thread-reachable" in fs[0].message

    def test_file_io_under_lock(self):
        src = """
import threading

class Dump:
    def __init__(self):
        self._lock = threading.Lock()

    def write(self, path, rows):
        with self._lock:
            with open(path, "w") as fh:
                fh.write(str(rows))
"""
        fs = run_project("blocking-under-lock",
                         {"pkg/observability/dump.py": src})
        assert rule_ids(fs) == ["blocking-under-lock"]
        assert "file I/O" in fs[0].message

    def test_out_of_scope_module_not_reported(self):
        src = """
import jax
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._step = jax.jit(lambda x: x)

    def run(self, x):
        with self._lock:
            return self._step(x)
"""
        assert run_project("blocking-under-lock",
                           {"pkg/nn/functional.py": src}) == []


# ---------------------------------------------------------------------------
# mesh-axis-contract (ISSUE 17)
# ---------------------------------------------------------------------------
class TestMeshAxisContract:
    def test_unknown_axis_literal_in_collective(self):
        src = """
from paddle_tpu.distributed.collective import t_psum

def allreduce(x):
    return t_psum(x, "model")
"""
        fs = run_project("mesh-axis-contract", {"pkg/layers.py": src})
        assert rule_ids(fs) == ["mesh-axis-contract"]
        assert "'model'" in fs[0].message

    def test_canonical_axis_clean(self):
        src = """
from paddle_tpu.distributed.collective import t_psum, t_all_gather

def allreduce(x):
    x = t_psum(x, "dp")
    return t_all_gather(x, ("sharding",), axis=0, tiled=True)
"""
        assert run_project("mesh-axis-contract",
                           {"pkg/layers.py": src}) == []

    def test_shard_map_scoped_axis_clean(self):
        # an axis declared by an in-tree Mesh is in scope everywhere,
        # including a shard_map body that names it in specs
        src = """
import jax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(jax.devices(), ("x", "y"))

def f(v):
    return shard_map(lambda a: a, mesh=mesh,
                     in_specs=P("x", None), out_specs=P("x", None))(v)
"""
        assert run_project("mesh-axis-contract",
                           {"pkg/maps.py": src}) == []

    def test_unknown_axis_in_partition_spec(self):
        src = """
from jax.sharding import PartitionSpec as P

def spec():
    return P("modle", None)
"""
        fs = run_project("mesh-axis-contract", {"pkg/specs.py": src})
        assert rule_ids(fs) == ["mesh-axis-contract"]
        assert "'modle'" in fs[0].message

    def test_nested_tuple_spec_entry_checked(self):
        src = """
from jax.sharding import PartitionSpec as P

def spec():
    return P(("dp", "zz"), None)
"""
        fs = run_project("mesh-axis-contract", {"pkg/specs.py": src})
        assert rule_ids(fs) == ["mesh-axis-contract"]
        assert "'zz'" in fs[0].message

    def test_dynamic_axis_skipped(self):
        src = """
from paddle_tpu.distributed.collective import t_psum

def allreduce(x, axis_name):
    return t_psum(x, axis_name)
"""
        assert run_project("mesh-axis-contract",
                           {"pkg/layers.py": src}) == []

    def test_order_constant_extends_vocabulary(self):
        topo = 'CUSTOM_AXIS_ORDER = ("rowwise", "colwise")\n'
        use = """
from paddle_tpu.distributed.collective import t_psum

def allreduce(x):
    return t_psum(x, "rowwise")
"""
        assert run_project("mesh-axis-contract",
                           {"pkg/topo.py": topo, "pkg/use.py": use}) == []

    def test_scatter_dim_contradicts_spec(self):
        src = """
from jax.sharding import PartitionSpec as P
from paddle_tpu.distributed.collective import t_psum_scatter

def shard(g):
    spec = P(None, "sharding")
    return t_psum_scatter(g, "sharding", scatter_dimension=0,
                          tiled=True)
"""
        fs = run_project("mesh-axis-contract", {"pkg/zero.py": src})
        assert rule_ids(fs) == ["mesh-axis-contract"]
        assert "scatter_dimension=0" in fs[0].message

    def test_scatter_dim_matches_spec_clean(self):
        src = """
from jax.sharding import PartitionSpec as P
from paddle_tpu.distributed.collective import t_psum_scatter

def shard(g):
    spec = P(None, "sharding")
    return t_psum_scatter(g, "sharding", scatter_dimension=1,
                          tiled=True)
"""
        assert run_project("mesh-axis-contract",
                           {"pkg/zero.py": src}) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------
class TestSuppression:
    def test_same_line_pragma(self):
        src = """
def pool3d(x, ceil_mode=False):  # tpulint: disable=unused-knob
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_comment_line_above(self):
        src = """
# static-graph-only knob, meaningless eagerly
# tpulint: disable=unused-knob
def pool3d(x, ceil_mode=False):
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_disable_file(self):
        src = """
# tpulint: disable-file=unused-knob

def pool3d(x, ceil_mode=False):
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_wrong_rule_id_does_not_suppress(self):
        src = """
def pool3d(x, ceil_mode=False):  # tpulint: disable=traced-bool
    return x
"""
        assert rule_ids(run_rule("unused-knob", src)) == ["unused-knob"]


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
class TestBaseline:
    SRC_V1 = """
def pool3d(x, ceil_mode=False):
    return x
"""
    # same violation, shifted three lines down — must still match
    SRC_V2 = "\n# moved\n# around\n" + SRC_V1

    def test_fingerprint_survives_line_shift(self):
        f1 = run_rule("unused-knob", self.SRC_V1)
        f2 = run_rule("unused-knob", self.SRC_V2)
        base = [baseline_entry(f) for f in f1]
        new, matched, stale = split_by_baseline(f2, base)
        assert new == [] and len(matched) == 1 and stale == []

    def test_new_violation_not_absorbed(self):
        f1 = run_rule("unused-knob", self.SRC_V1)
        base = [baseline_entry(f) for f in f1]
        src = self.SRC_V1 + """
def pool2d(x, exclusive=True):
    return x
"""
        new, matched, stale = split_by_baseline(
            run_rule("unused-knob", src), base)
        assert len(matched) == 1
        assert [f.symbol for f in new] == ["pool2d"]

    def test_fixed_violation_reports_stale(self):
        f1 = run_rule("unused-knob", self.SRC_V1)
        base = [baseline_entry(f) for f in f1]
        new, matched, stale = split_by_baseline([], base)
        assert new == [] and matched == [] and len(stale) == 1


# ---------------------------------------------------------------------------
# the tier-1 whole-tree gate
# ---------------------------------------------------------------------------
class TestWholeTreeGate:
    def test_tree_clean_outside_baseline(self):
        """THE gate: paddle_tpu/ must produce zero findings that are
        not in tools/tpulint/baseline.json. To fix a failure here:
        enforce-or-implement the knob (preferred), add a justified
        `# tpulint: disable=<rule>` pragma, or — for pre-existing debt
        only — regenerate the baseline with --write-baseline."""
        findings = lint_paths([REPO / "paddle_tpu"], ALL_RULES,
                              root=REPO)
        baseline = load_baseline(REPO / "tools/tpulint/baseline.json")
        new, _matched, _stale = split_by_baseline(findings, baseline)
        msg = "\n".join(
            f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in new)
        assert not new, f"new tpulint violations:\n{msg}"

    def test_rule_catalog_complete(self):
        # five per-module trace-safety rules (ISSUE 2) + five
        # interprocedural contract rules (ISSUE 13) + the lock-graph
        # and mesh-axis contract rules (ISSUE 17 acceptance)
        assert set(RULES_BY_ID) == {
            "unused-knob", "host-sync-in-jit", "traced-bool",
            "nonhashable-static", "recompile-hazard",
            "raw-collective", "unregistered-metric",
            "vjp-ledger-symmetry", "donation-reuse",
            "unguarded-shared-mutation",
            "lock-order-cycle", "blocking-under-lock",
            "mesh-axis-contract"}


# ---------------------------------------------------------------------------
# baseline policy for the v2 contract rules
# ---------------------------------------------------------------------------
NEW_RULES = {"raw-collective", "unregistered-metric",
             "vjp-ledger-symmetry", "donation-reuse",
             "unguarded-shared-mutation"}
LOCK_MESH_RULES = {"lock-order-cycle", "blocking-under-lock",
                   "mesh-axis-contract"}
PINNED_ZERO_PREFIXES = ("paddle_tpu/observability/",
                        "paddle_tpu/distributed/checkpoint/",
                        "paddle_tpu/inference/serving.py",
                        # the disaggregated-serving data plane (ISSUE
                        # 20): the migration wire and the front door
                        # mutate shared engine state across replica
                        # boundaries — races or ledger bypasses here
                        # are fixed, never baselined
                        "paddle_tpu/inference/router.py",
                        "paddle_tpu/inference/disagg.py",
                        # the bidirectional bucketed-collective engine
                        # + the stage-3 gather paths in the train step:
                        # ledger bypasses / races here corrupt the
                        # exactness story, never baseline them
                        "paddle_tpu/distributed/grad_buckets.py",
                        "paddle_tpu/distributed/engine.py")


class TestContractRulePins:
    def test_pinned_subsystems_have_zero_new_rule_baseline(self):
        """The instrument-panel and checkpoint subsystems (and the
        serving engine) are pinned at ZERO baseline entries for the
        five contract rules: a new ledger bypass / unregistered metric
        / race there must be fixed, never baselined."""
        baseline = load_baseline(REPO / "tools/tpulint/baseline.json")
        bad = [e for e in baseline
               if e["rule"] in NEW_RULES
               and e["path"].startswith(PINNED_ZERO_PREFIXES)]
        assert bad == [], f"contract-rule debt in pinned dirs: {bad}"

    def test_lock_mesh_rules_have_zero_baseline_in_pinned_dirs(self):
        """ISSUE 17 pin: serving.py, distributed/checkpoint/ and
        observability/ carry ZERO baseline entries for the lock-graph
        and mesh-axis rules — a deadlock edge, a blocking call under
        the admission lock, or a bad axis literal there is fixed in
        the PR that introduces it, never grandfathered."""
        baseline = load_baseline(REPO / "tools/tpulint/baseline.json")
        bad = [e for e in baseline
               if e["rule"] in LOCK_MESH_RULES
               and e["path"].startswith(
                   ("paddle_tpu/inference/serving.py",
                    "paddle_tpu/distributed/checkpoint/",
                    "paddle_tpu/observability/"))]
        assert bad == [], f"lock/mesh-rule debt in pinned dirs: {bad}"

    def test_lock_mesh_rules_whole_tree_clean(self):
        """Stronger than the pin: the three ISSUE 17 rules currently
        hold tree-wide with an EMPTY baseline (no grandfathered
        entries anywhere)."""
        baseline = load_baseline(REPO / "tools/tpulint/baseline.json")
        assert [e for e in baseline if e["rule"] in LOCK_MESH_RULES] == []
        findings = lint_paths([REPO / "paddle_tpu"],
                              select_rules(sorted(LOCK_MESH_RULES)),
                              root=REPO)
        assert findings == [], "\n".join(
            f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in findings)

    def test_every_baseline_entry_is_justified(self):
        baseline = load_baseline(REPO / "tools/tpulint/baseline.json")
        missing = [e for e in baseline if not e.get("justification")]
        assert missing == [], (
            f"{len(missing)} baseline entries lack the mandatory "
            f"justification string")

    def test_whole_tree_runtime_budget(self):
        """Acceptance: the whole-tree run with every rule (the
        interprocedural pass included) stays well under the 60s CI
        budget."""
        import time

        t0 = time.monotonic()
        lint_paths([REPO / "paddle_tpu"], ALL_RULES, root=REPO)
        assert time.monotonic() - t0 < 60.0


# ---------------------------------------------------------------------------
# CLI (exit codes + JSON report)
# ---------------------------------------------------------------------------
def _cli(*args, cwd=REPO):
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", str(REPO))
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=300)


class TestCLI:
    def test_json_clean_tree_exits_zero(self):
        r = _cli("paddle_tpu/", "--json")
        assert r.returncode == 0, r.stdout + r.stderr
        report = json.loads(r.stdout)
        assert report["new"] == 0
        assert report["baseline_size"] == report["baselined"]
        assert set(report["rules"]) == set(RULES_BY_ID)

    def test_seeded_violation_exits_nonzero(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        r = _cli(str(bad))
        assert r.returncode == 1
        assert "unused-knob" in r.stdout

    def test_sarif_format(self, tmp_path):
        """--format sarif: valid SARIF 2.1.0 with the rule catalog as
        reportingDescriptors, new findings at warning level, and the
        same exit-code contract as text/json."""
        bad = tmp_path / "seeded.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        r = _cli(str(bad), "--format", "sarif")
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "tpulint"
        assert {d["id"] for d in run["tool"]["driver"]["rules"]} \
            == set(RULES_BY_ID)
        res = [x for x in run["results"] if x["level"] == "warning"]
        assert res and res[0]["ruleId"] == "unused-knob"
        loc = res[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("seeded.py")
        assert loc["region"]["startLine"] == 1

    def test_sarif_clean_tree_exits_zero_with_notes(self):
        r = _cli("paddle_tpu/", "--format", "sarif")
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr
        doc = json.loads(r.stdout)
        results = doc["runs"][0]["results"]
        # every result is a baselined note, none a new warning
        assert all(x["level"] == "note"
                   and x["baselineState"] == "unchanged"
                   for x in results)

    def test_select_and_list_rules(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        # narrowed to an unrelated rule the file is clean → exit 0
        r = _cli(str(bad), "--select", "traced-bool")
        assert r.returncode == 0
        r = _cli("--list-rules")
        assert r.returncode == 0 and "recompile-hazard" in r.stdout

    def test_prune_baseline_drops_unmatched(self, tmp_path):
        """--prune-baseline drops entries whose fingerprints no longer
        match any linted file (fixed violations, deleted files) and
        keeps live + out-of-scope-but-existing ones."""
        tree = tmp_path / "pkg"
        tree.mkdir()
        bad = tree / "bad.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        other = tmp_path / "outside.py"
        other.write_text("def api2(y, flag=False):\n    return y\n")
        baseline = tmp_path / "baseline.json"
        entries = [
            # live: matches bad.py's unused-knob finding
            {"rule": "unused-knob", "path": "pkg/bad.py", "symbol": "api",
             "line_text": "def api(x, knob=False):"},
            # fixed: fingerprint matches nothing anymore
            {"rule": "unused-knob", "path": "pkg/bad.py", "symbol": "gone",
             "line_text": "def gone(x, dead_knob=False):"},
            # deleted file: can never match again
            {"rule": "traced-bool", "path": "pkg/removed.py",
             "symbol": "f", "line_text": "if x:"},
            # out of linted scope but still on disk: kept
            {"rule": "unused-knob", "path": "outside.py", "symbol": "api2",
             "line_text": "def api2(y, flag=False):"},
        ]
        baseline.write_text(json.dumps({"findings": entries}))

        r = _cli("pkg", "--baseline", str(baseline), "--root",
                 str(tmp_path), cwd=tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr  # all baselined

        r = _cli("pkg", "--baseline", str(baseline), "--prune-baseline",
                 "--root", str(tmp_path), cwd=tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "pruned 2" in r.stdout
        kept = json.loads(baseline.read_text())["findings"]
        assert {(e["path"], e["symbol"]) for e in kept} == {
            ("pkg/bad.py", "api"), ("outside.py", "api2")}

        # pruned baseline still matches: clean run, zero stale
        r = _cli("pkg", "--baseline", str(baseline), "--root",
                 str(tmp_path), "--json", cwd=tmp_path)
        assert r.returncode == 0
        report = json.loads(r.stdout)
        assert report["new"] == 0 and report["baseline_stale"] == []

    def test_changed_mode_lints_only_changed_files(self, tmp_path):
        """--changed <ref>: findings only for files changed vs the
        ref (facts still whole-tree); an untouched violation stays
        unreported."""
        import subprocess

        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "old.py").write_text(
            "def api(x, knob=False):\n    return x\n")
        (pkg / "touched.py").write_text("def ok(x):\n    return x\n")

        def git(*a):
            return subprocess.run(
                ["git", "-c", "user.email=t@t", "-c", "user.name=t",
                 *a], cwd=tmp_path, capture_output=True, text=True,
                timeout=60)

        assert git("init", "-q").returncode == 0
        git("add", "-A")
        assert git("commit", "-qm", "seed").returncode == 0
        (pkg / "touched.py").write_text(
            "def api2(y, flag=False):\n    return y\n")

        r = _cli("pkg", "--changed", "HEAD", "--no-baseline", "--json",
                 cwd=tmp_path)
        assert r.returncode == 1, r.stdout + r.stderr
        report = json.loads(r.stdout)
        assert report["changed_files"] == ["pkg/touched.py"]
        assert {f["path"] for f in report["findings"]} == \
            {"pkg/touched.py"}
        # the ref itself clean vs HEAD when nothing changed
        git("add", "-A")
        git("commit", "-qm", "fix")
        r = _cli("pkg", "--changed", "HEAD", "--no-baseline", "--json",
                 cwd=tmp_path)
        assert r.returncode == 0
        assert json.loads(r.stdout)["total"] == 0

    def test_changed_mode_bad_ref_is_usage_error(self, tmp_path):
        r = _cli(str(tmp_path), "--changed", "no-such-ref",
                 cwd=tmp_path)
        assert r.returncode == 2
        assert "--changed" in r.stderr

    def test_stats_summary(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text(
            "def api(x, knob=False):\n    return x\n\n"
            "def quiet(x, other=False):  "
            "# tpulint: disable=unused-knob\n    return x\n")
        r = _cli(str(bad), "--no-baseline", "--stats", "--json")
        assert r.returncode == 1
        report = json.loads(r.stdout)
        s = report["stats"]["unused-knob"]
        assert s["total"] == 1 and s["new"] == 1
        assert s["suppressed"] == 1
        # human output carries the same table
        r = _cli(str(bad), "--no-baseline", "--stats")
        assert "per-rule stats" in r.stdout
        assert "unused-knob" in r.stdout

    def test_write_baseline_requires_justification(self, tmp_path):
        """--write-baseline refuses entries lacking a justification;
        --justification TEXT supplies one for new entries and existing
        justifications are carried over by fingerprint."""
        bad = tmp_path / "seeded.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        bl = tmp_path / "bl.json"
        r = _cli(str(bad), "--baseline", str(bl), "--write-baseline")
        assert r.returncode == 2
        assert "justification" in r.stderr and not bl.exists()

        r = _cli(str(bad), "--baseline", str(bl), "--write-baseline",
                 "--justification", "legacy stub kept for API parity")
        assert r.returncode == 0, r.stdout + r.stderr
        entries = json.loads(bl.read_text())["findings"]
        assert entries[0]["justification"] == \
            "legacy stub kept for API parity"

        # second write WITHOUT --justification succeeds: the existing
        # justification is carried over by fingerprint
        bad.write_text("# moved\n" + bad.read_text())
        r = _cli(str(bad), "--baseline", str(bl), "--write-baseline")
        assert r.returncode == 0, r.stdout + r.stderr
        entries = json.loads(bl.read_text())["findings"]
        assert entries[0]["justification"] == \
            "legacy stub kept for API parity"

    def test_prune_preserves_justifications(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text("def api(x, knob=False):\n    return x\n"
                       "def gone(x, dead=False):\n    return x\n")
        bl = tmp_path / "bl.json"
        r = _cli("seeded.py", "--baseline", str(bl), "--write-baseline",
                 "--justification", "grandfathered",
                 "--root", str(tmp_path), cwd=tmp_path)
        assert r.returncode == 0
        bad.write_text("def api(x, knob=False):\n    return x\n")
        r = _cli("seeded.py", "--baseline", str(bl), "--prune-baseline",
                 "--root", str(tmp_path), cwd=tmp_path)
        assert r.returncode == 0 and "pruned 1" in r.stdout
        entries = json.loads(bl.read_text())["findings"]
        assert len(entries) == 1
        assert entries[0]["justification"] == "grandfathered"

    def test_prune_baseline_noop_on_live_tree(self, tmp_path):
        """Pruning the checked-in baseline against the real tree drops
        nothing (every entry is live) and leaves the gate green."""
        import shutil

        from tools.tpulint.cli import DEFAULT_BASELINE

        copy = tmp_path / "baseline.json"
        shutil.copy(DEFAULT_BASELINE, copy)
        r = _cli("paddle_tpu/", "--baseline", str(copy),
                 "--prune-baseline")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "pruned 0" in r.stdout
        before = json.loads(DEFAULT_BASELINE.read_text())["findings"]
        after = json.loads(copy.read_text())["findings"]
        assert len(before) == len(after)
