"""tpulint — the trace-safety & API-fidelity static analyzer (tools/
tpulint) wired into tier-1.

Under test:
- each shipped rule fires on a positive fixture and stays silent on the
  clean equivalent (the enforce-or-implement / bucketed versions)
- suppression pragmas (same line, comment line above, whole file)
- baseline fingerprint matching (line-number shifts don't break it,
  fixed findings surface as stale)
- the WHOLE-TREE GATE: paddle_tpu/ has zero findings outside the
  checked-in baseline — this is the CI teeth; a new silent-ignore knob
  or unbucketed jit-factory int fails tier-1
- CLI exit codes incl. a seeded violation (acceptance criteria)
"""
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:                     # direct pytest invocation
    sys.path.insert(0, str(REPO))

from tools.tpulint import (ALL_RULES, RULES_BY_ID, baseline_entry,  # noqa: E402
                           lint_paths, lint_source, load_baseline,
                           select_rules, split_by_baseline)


def run_rule(rule_id, src):
    return lint_source(src, "fixture.py", select_rules([rule_id]))


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# rule fixtures: positive fires, negative is silent
# ---------------------------------------------------------------------------
class TestUnusedKnob:
    POS = """
def pool3d(x, kernel_size, ceil_mode=False):
    return x + kernel_size
"""
    NEG_READ = """
def pool3d(x, kernel_size, ceil_mode=False):
    return x + kernel_size + (1 if ceil_mode else 0)
"""
    NEG_ENFORCED = """
from paddle_tpu.core.enforce import enforce

def pool3d(x, kernel_size, ceil_mode=False):
    enforce(not ceil_mode, "ceil_mode is not served here")
    return x + kernel_size
"""

    def test_positive(self):
        fs = run_rule("unused-knob", self.POS)
        assert rule_ids(fs) == ["unused-knob"]
        assert "'ceil_mode'" in fs[0].message and fs[0].symbol == "pool3d"

    def test_negative_read(self):
        assert run_rule("unused-knob", self.NEG_READ) == []

    def test_negative_enforce_guard(self):
        assert run_rule("unused-knob", self.NEG_ENFORCED) == []

    def test_name_param_and_private_fn_exempt(self):
        src = """
def rank(x, name=None):
    return x.ndim

def _helper(x, internal_knob=3):
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_stub_exempt(self):
        src = """
class BaseTransform:
    def _apply_image(self, img):
        raise NotImplementedError
"""
        assert run_rule("unused-knob", src) == []


class TestHostSyncInJit:
    POS = """
import jax
import jax.numpy as jnp
import numpy as np

def body(x):
    s = jnp.sum(x)
    return np.asarray(s)

step = jax.jit(body)
"""
    NEG_NOT_JITTED = """
import jax.numpy as jnp
import numpy as np

def body(x):
    s = jnp.sum(x)
    return np.asarray(s)
"""
    NEG_STAYS_TRACED = """
import jax
import jax.numpy as jnp

def body(x):
    return jnp.sum(x)

step = jax.jit(body)
"""

    def test_positive(self):
        fs = run_rule("host-sync-in-jit", self.POS)
        assert rule_ids(fs) == ["host-sync-in-jit"]
        assert "np.asarray" in fs[0].message

    def test_negative_outside_jit(self):
        assert run_rule("host-sync-in-jit", self.NEG_NOT_JITTED) == []

    def test_negative_pure_jnp(self):
        assert run_rule("host-sync-in-jit", self.NEG_STAYS_TRACED) == []

    def test_item_in_def_op_kernel(self):
        src = """
from paddle_tpu.core.dispatch import def_op

@def_op("bad_kernel")
def bad_kernel(x):
    return x.item()
"""
        fs = run_rule("host-sync-in-jit", src)
        assert rule_ids(fs) == ["host-sync-in-jit"]
        assert ".item()" in fs[0].message

    def test_int_of_static_knob_allowed(self):
        # int() on a static Python knob inside a traced kernel is fine;
        # only tainted (traced-array) expressions count
        src = """
from paddle_tpu.core.dispatch import def_op
import jax.numpy as jnp

@def_op("k")
def k(x, sampling_ratio=-1):
    sr = int(sampling_ratio)
    return jnp.sum(x) * sr
"""
        assert run_rule("host-sync-in-jit", src) == []

    def test_float_of_traced_value_flagged(self):
        src = """
import jax
import jax.numpy as jnp

def body(x):
    return float(jnp.max(x))

f = jax.jit(body)
"""
        fs = run_rule("host-sync-in-jit", src)
        assert rule_ids(fs) == ["host-sync-in-jit"]


class TestTracedBool:
    POS = """
import jax
import jax.numpy as jnp

def body(x):
    y = jnp.sum(x)
    if y > 0:
        return x
    return -x

f = jax.jit(body)
"""
    NEG_STATIC_KNOB = """
import jax
import jax.numpy as jnp

def body(x, ceil_mode=False):
    if ceil_mode:
        return jnp.ceil(x)
    return x

f = jax.jit(body)
"""
    NEG_SHAPE_AND_NONE = """
import jax
import jax.numpy as jnp

def body(x, mask=None):
    y = jnp.abs(x)
    if y.ndim == 2:
        y = y[None]
    if mask is not None:
        y = y * mask
    return y

f = jax.jit(body)
"""

    def test_positive(self):
        fs = run_rule("traced-bool", self.POS)
        assert rule_ids(fs) == ["traced-bool"]
        assert "'y'" in fs[0].message

    def test_negative_static_knob(self):
        assert run_rule("traced-bool", self.NEG_STATIC_KNOB) == []

    def test_negative_shape_and_none_checks(self):
        assert run_rule("traced-bool", self.NEG_SHAPE_AND_NONE) == []

    def test_while_on_traced(self):
        src = """
import jax
import jax.numpy as jnp

def body(x):
    n = jnp.sum(x)
    while n > 0:
        n = n - 1
    return n

f = jax.jit(body)
"""
        fs = run_rule("traced-bool", src)
        assert rule_ids(fs) == ["traced-bool"]
        assert "`while`" in fs[0].message


class TestNonhashableStatic:
    POS_DECORATOR = """
from functools import partial
import jax

@partial(jax.jit, static_argnames=("sizes",))
def f(x, sizes=[1, 2]):
    return x
"""
    POS_ARGNUMS = """
import jax

def f(x, sizes=[8, 16]):
    return x

g = jax.jit(f, static_argnums=(1,))
"""
    NEG_TUPLE = """
from functools import partial
import jax

@partial(jax.jit, static_argnames=("sizes",))
def f(x, sizes=(1, 2)):
    return x
"""

    def test_positive_decorator(self):
        fs = run_rule("nonhashable-static", self.POS_DECORATOR)
        assert rule_ids(fs) == ["nonhashable-static"]
        assert "'sizes'" in fs[0].message

    def test_positive_call_form(self):
        fs = run_rule("nonhashable-static", self.POS_ARGNUMS)
        assert rule_ids(fs) == ["nonhashable-static"]

    def test_negative_tuple_default(self):
        assert run_rule("nonhashable-static", self.NEG_TUPLE) == []


class TestRecompileHazard:
    POS = """
def serve(pred, prompts):
    B = len(prompts)
    prefill = pred._prefill_fn(B, 128)
    return prefill(prompts)
"""
    NEG_BUCKETED = """
def _bucket(n, lo=64):
    b = lo
    while b < n:
        b *= 2
    return b

def serve(pred, prompts):
    B = _bucket(len(prompts))
    prefill = pred._prefill_fn(B, 128)
    return prefill(prompts)
"""
    NEG_SANITIZING_HELPER = """
def _max_len(self, S0):
    return _bucket(S0)

def serve(self, pred, ids):
    B, S0 = ids.shape
    M = self._max_len(S0)
    fn = pred._decode_fn(M, 4)
    return fn(ids)
"""

    def test_positive(self):
        fs = run_rule("recompile-hazard", self.POS)
        assert rule_ids(fs) == ["recompile-hazard"]
        assert "'B'" in fs[0].message and "_prefill_fn" in fs[0].message

    def test_negative_bucketed(self):
        assert run_rule("recompile-hazard", self.NEG_BUCKETED) == []

    def test_negative_bucketing_helper_sanitizes(self):
        assert run_rule("recompile-hazard", self.NEG_SANITIZING_HELPER) \
            == []

    def test_shape_attr_direct_arg(self):
        src = """
def serve(pred, ids):
    fn = pred._decode_fn(ids.shape[0], 4)
    return fn(ids)
"""
        fs = run_rule("recompile-hazard", src)
        assert rule_ids(fs) == ["recompile-hazard"]

    def test_jitted_callable_args_not_boundaries(self):
        # python ints into the RETURNED jitted fn become weak-typed
        # traced scalars — no recompile, no finding
        src = """
def serve(pred, ids):
    fn = pred._decode_fn(4, 128)
    pos = ids.shape[1]
    return fn(ids, pos)
"""
        assert run_rule("recompile-hazard", src) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------
class TestSuppression:
    def test_same_line_pragma(self):
        src = """
def pool3d(x, ceil_mode=False):  # tpulint: disable=unused-knob
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_comment_line_above(self):
        src = """
# static-graph-only knob, meaningless eagerly
# tpulint: disable=unused-knob
def pool3d(x, ceil_mode=False):
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_disable_file(self):
        src = """
# tpulint: disable-file=unused-knob

def pool3d(x, ceil_mode=False):
    return x
"""
        assert run_rule("unused-knob", src) == []

    def test_wrong_rule_id_does_not_suppress(self):
        src = """
def pool3d(x, ceil_mode=False):  # tpulint: disable=traced-bool
    return x
"""
        assert rule_ids(run_rule("unused-knob", src)) == ["unused-knob"]


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
class TestBaseline:
    SRC_V1 = """
def pool3d(x, ceil_mode=False):
    return x
"""
    # same violation, shifted three lines down — must still match
    SRC_V2 = "\n# moved\n# around\n" + SRC_V1

    def test_fingerprint_survives_line_shift(self):
        f1 = run_rule("unused-knob", self.SRC_V1)
        f2 = run_rule("unused-knob", self.SRC_V2)
        base = [baseline_entry(f) for f in f1]
        new, matched, stale = split_by_baseline(f2, base)
        assert new == [] and len(matched) == 1 and stale == []

    def test_new_violation_not_absorbed(self):
        f1 = run_rule("unused-knob", self.SRC_V1)
        base = [baseline_entry(f) for f in f1]
        src = self.SRC_V1 + """
def pool2d(x, exclusive=True):
    return x
"""
        new, matched, stale = split_by_baseline(
            run_rule("unused-knob", src), base)
        assert len(matched) == 1
        assert [f.symbol for f in new] == ["pool2d"]

    def test_fixed_violation_reports_stale(self):
        f1 = run_rule("unused-knob", self.SRC_V1)
        base = [baseline_entry(f) for f in f1]
        new, matched, stale = split_by_baseline([], base)
        assert new == [] and matched == [] and len(stale) == 1


# ---------------------------------------------------------------------------
# the tier-1 whole-tree gate
# ---------------------------------------------------------------------------
class TestWholeTreeGate:
    def test_tree_clean_outside_baseline(self):
        """THE gate: paddle_tpu/ must produce zero findings that are
        not in tools/tpulint/baseline.json. To fix a failure here:
        enforce-or-implement the knob (preferred), add a justified
        `# tpulint: disable=<rule>` pragma, or — for pre-existing debt
        only — regenerate the baseline with --write-baseline."""
        findings = lint_paths([REPO / "paddle_tpu"], ALL_RULES,
                              root=REPO)
        baseline = load_baseline(REPO / "tools/tpulint/baseline.json")
        new, _matched, _stale = split_by_baseline(findings, baseline)
        msg = "\n".join(
            f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in new)
        assert not new, f"new tpulint violations:\n{msg}"

    def test_rule_catalog_complete(self):
        # the five rules the analyzer ships with (ISSUE 2 acceptance)
        assert set(RULES_BY_ID) == {
            "unused-knob", "host-sync-in-jit", "traced-bool",
            "nonhashable-static", "recompile-hazard"}


# ---------------------------------------------------------------------------
# CLI (exit codes + JSON report)
# ---------------------------------------------------------------------------
def _cli(*args, cwd=REPO):
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", str(REPO))
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=300)


class TestCLI:
    def test_json_clean_tree_exits_zero(self):
        r = _cli("paddle_tpu/", "--json")
        assert r.returncode == 0, r.stdout + r.stderr
        report = json.loads(r.stdout)
        assert report["new"] == 0
        assert report["baseline_size"] == report["baselined"]
        assert set(report["rules"]) == set(RULES_BY_ID)

    def test_seeded_violation_exits_nonzero(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        r = _cli(str(bad))
        assert r.returncode == 1
        assert "unused-knob" in r.stdout

    def test_select_and_list_rules(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        # narrowed to an unrelated rule the file is clean → exit 0
        r = _cli(str(bad), "--select", "traced-bool")
        assert r.returncode == 0
        r = _cli("--list-rules")
        assert r.returncode == 0 and "recompile-hazard" in r.stdout

    def test_prune_baseline_drops_unmatched(self, tmp_path):
        """--prune-baseline drops entries whose fingerprints no longer
        match any linted file (fixed violations, deleted files) and
        keeps live + out-of-scope-but-existing ones."""
        tree = tmp_path / "pkg"
        tree.mkdir()
        bad = tree / "bad.py"
        bad.write_text("def api(x, knob=False):\n    return x\n")
        other = tmp_path / "outside.py"
        other.write_text("def api2(y, flag=False):\n    return y\n")
        baseline = tmp_path / "baseline.json"
        entries = [
            # live: matches bad.py's unused-knob finding
            {"rule": "unused-knob", "path": "pkg/bad.py", "symbol": "api",
             "line_text": "def api(x, knob=False):"},
            # fixed: fingerprint matches nothing anymore
            {"rule": "unused-knob", "path": "pkg/bad.py", "symbol": "gone",
             "line_text": "def gone(x, dead_knob=False):"},
            # deleted file: can never match again
            {"rule": "traced-bool", "path": "pkg/removed.py",
             "symbol": "f", "line_text": "if x:"},
            # out of linted scope but still on disk: kept
            {"rule": "unused-knob", "path": "outside.py", "symbol": "api2",
             "line_text": "def api2(y, flag=False):"},
        ]
        baseline.write_text(json.dumps({"findings": entries}))

        r = _cli("pkg", "--baseline", str(baseline), "--root",
                 str(tmp_path), cwd=tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr  # all baselined

        r = _cli("pkg", "--baseline", str(baseline), "--prune-baseline",
                 "--root", str(tmp_path), cwd=tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "pruned 2" in r.stdout
        kept = json.loads(baseline.read_text())["findings"]
        assert {(e["path"], e["symbol"]) for e in kept} == {
            ("pkg/bad.py", "api"), ("outside.py", "api2")}

        # pruned baseline still matches: clean run, zero stale
        r = _cli("pkg", "--baseline", str(baseline), "--root",
                 str(tmp_path), "--json", cwd=tmp_path)
        assert r.returncode == 0
        report = json.loads(r.stdout)
        assert report["new"] == 0 and report["baseline_stale"] == []

    def test_prune_baseline_noop_on_live_tree(self, tmp_path):
        """Pruning the checked-in baseline against the real tree drops
        nothing (every entry is live) and leaves the gate green."""
        import shutil

        from tools.tpulint.cli import DEFAULT_BASELINE

        copy = tmp_path / "baseline.json"
        shutil.copy(DEFAULT_BASELINE, copy)
        r = _cli("paddle_tpu/", "--baseline", str(copy),
                 "--prune-baseline")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "pruned 0" in r.stdout
        before = json.loads(DEFAULT_BASELINE.read_text())["findings"]
        after = json.loads(copy.read_text())["findings"]
        assert len(before) == len(after)
