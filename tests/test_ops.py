"""Op numerics vs numpy references (OpTest pattern, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from utils import check_grad, check_output

rng = np.random.RandomState(7)


def r(*shape):
    return rng.rand(*shape).astype(np.float32)


def rn(*shape):
    return rng.randn(*shape).astype(np.float32)


class TestElementwise:
    def test_add(self):
        check_output(paddle.add, np.add, [r(3, 4), r(3, 4)])

    def test_broadcast_add(self):
        check_output(paddle.add, np.add, [r(3, 4), r(4)])

    def test_subtract(self):
        check_output(paddle.subtract, np.subtract, [r(3, 4), r(3, 4)])

    def test_multiply(self):
        check_output(paddle.multiply, np.multiply, [r(3, 4), r(3, 4)])

    def test_divide(self):
        check_output(paddle.divide, np.divide, [r(3, 4), r(3, 4) + 0.5])

    def test_pow(self):
        check_output(paddle.pow, np.power, [r(3, 4) + 0.1, r(3, 4)])

    def test_maximum(self):
        check_output(paddle.maximum, np.maximum, [rn(3, 4), rn(3, 4)])

    def test_exp_log_sqrt(self):
        check_output(paddle.exp, np.exp, [rn(5)])
        check_output(paddle.log, np.log, [r(5) + 0.1])
        check_output(paddle.sqrt, np.sqrt, [r(5) + 0.1])

    def test_trig(self):
        check_output(paddle.sin, np.sin, [rn(5)])
        check_output(paddle.cos, np.cos, [rn(5)])
        check_output(paddle.tanh, np.tanh, [rn(5)])

    def test_clip(self):
        x = rn(4, 4)
        out = paddle.clip(paddle.to_tensor(x), min=-0.5, max=0.5)
        np.testing.assert_allclose(out.numpy(), np.clip(x, -0.5, 0.5))

    def test_scalar_ops(self):
        x = paddle.to_tensor(r(3, 3))
        np.testing.assert_allclose((x + 1.0).numpy(), x.numpy() + 1.0)
        np.testing.assert_allclose((2.0 * x).numpy(), 2.0 * x.numpy())
        np.testing.assert_allclose((1.0 - x).numpy(), 1.0 - x.numpy(),
                                   rtol=1e-6)


class TestReduction:
    def test_sum(self):
        check_output(paddle.sum, lambda x, **k: np.sum(x), [r(3, 4)])
        x = r(3, 4, 5)
        out = paddle.sum(paddle.to_tensor(x), axis=1, keepdim=True)
        np.testing.assert_allclose(out.numpy(), x.sum(1, keepdims=True),
                                   rtol=1e-6)

    def test_mean_max_min(self):
        x = rn(3, 4)
        np.testing.assert_allclose(paddle.mean(paddle.to_tensor(x)).numpy(),
                                   x.mean(), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.max(paddle.to_tensor(x), axis=1).numpy(), x.max(1))
        np.testing.assert_allclose(
            paddle.min(paddle.to_tensor(x), axis=0).numpy(), x.min(0))

    def test_cumsum(self):
        x = rn(3, 4)
        np.testing.assert_allclose(
            paddle.cumsum(paddle.to_tensor(x), axis=1).numpy(),
            np.cumsum(x, 1), rtol=1e-6)

    def test_argmax_topk(self):
        x = rn(4, 6)
        np.testing.assert_array_equal(
            paddle.argmax(paddle.to_tensor(x), axis=1).numpy(),
            np.argmax(x, 1))
        vals, idx = paddle.topk(paddle.to_tensor(x), 3, axis=-1)
        ref = np.sort(x, axis=-1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)


class TestMatmul:
    def test_2d(self):
        check_output(paddle.matmul, lambda a, b, **k: a @ b, [r(3, 4), r(4, 5)])

    def test_batched(self):
        check_output(paddle.matmul, lambda a, b, **k: a @ b,
                     [r(2, 3, 4), r(2, 4, 5)], rtol=1e-4)

    def test_transpose_flags(self):
        a, b = r(4, 3), r(4, 5)
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_x=True)
        np.testing.assert_allclose(out.numpy(), a.T @ b, rtol=1e-5)

    def test_einsum(self):
        a, b = r(2, 3, 4), r(2, 4, 5)
        out = paddle.einsum("bij,bjk->bik", paddle.to_tensor(a),
                            paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.einsum("bij,bjk->bik", a, b),
                                   rtol=1e-5)


class TestManipulation:
    def test_reshape_transpose(self):
        x = r(2, 3, 4)
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(
            t.reshape([6, 4]).numpy(), x.reshape(6, 4))
        np.testing.assert_array_equal(
            t.transpose([2, 0, 1]).numpy(), x.transpose(2, 0, 1))

    def test_concat_split_stack(self):
        a, b = r(2, 3), r(2, 3)
        cat = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_array_equal(cat.numpy(), np.concatenate([a, b], 0))
        parts = paddle.split(cat, 2, axis=0)
        np.testing.assert_array_equal(parts[0].numpy(), a)
        st = paddle.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_array_equal(st.numpy(), np.stack([a, b], 0))

    def test_squeeze_unsqueeze_tile(self):
        x = r(2, 1, 3)
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(t.squeeze(1).numpy(), x.squeeze(1))
        np.testing.assert_array_equal(
            t.unsqueeze(0).numpy(), x[None])
        np.testing.assert_array_equal(
            paddle.tile(t, [2, 1, 1]).numpy(), np.tile(x, (2, 1, 1)))

    def test_gather_indexing(self):
        x = r(5, 4)
        idx = np.array([0, 2, 4])
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(
            paddle.gather(t, paddle.to_tensor(idx), axis=0).numpy(), x[idx])
        np.testing.assert_array_equal(t[1:3, ::2].numpy(), x[1:3, ::2])
        np.testing.assert_array_equal(t[paddle.to_tensor(idx)].numpy(), x[idx])

    def test_where_tril(self):
        x, y = rn(3, 3), rn(3, 3)
        cond = x > 0
        out = paddle.where(paddle.to_tensor(cond), paddle.to_tensor(x),
                           paddle.to_tensor(y))
        np.testing.assert_array_equal(out.numpy(), np.where(cond, x, y))
        np.testing.assert_array_equal(
            paddle.tril(paddle.to_tensor(x)).numpy(), np.tril(x))

    def test_cast(self):
        x = r(3, 3)
        t = paddle.to_tensor(x).astype("float16")
        assert str(t.dtype) == "float16"

    def test_pad(self):
        x = r(2, 3)
        out = paddle.nn.functional.pad(paddle.to_tensor(x), [1, 2],
                                       mode="constant", value=0.0)
        np.testing.assert_array_equal(out.numpy(),
                                      np.pad(x, [(0, 0), (1, 2)]))


class TestNNOps:
    def test_softmax(self):
        x = rn(3, 5)
        out = F.softmax(paddle.to_tensor(x), axis=-1)
        e = np.exp(x - x.max(-1, keepdims=True))
        np.testing.assert_allclose(out.numpy(), e / e.sum(-1, keepdims=True),
                                   rtol=1e-5)

    def test_relu_gelu_silu(self):
        x = rn(4, 4)
        np.testing.assert_allclose(F.relu(paddle.to_tensor(x)).numpy(),
                                   np.maximum(x, 0))
        g = F.gelu(paddle.to_tensor(x)).numpy()
        from scipy.special import erf as serf  # scipy ships with image
        ref = 0.5 * x * (1 + serf(x / np.sqrt(2)))
        np.testing.assert_allclose(g, ref, rtol=1e-4, atol=1e-5)

    def test_layer_norm(self):
        x = rn(2, 3, 8)
        w, b = r(8), r(8)
        out = F.layer_norm(paddle.to_tensor(x), paddle.to_tensor(w),
                           paddle.to_tensor(b), epsilon=1e-5)
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_rms_norm(self):
        x = rn(2, 8)
        w = r(8)
        out = F.rms_norm(paddle.to_tensor(x), paddle.to_tensor(w))
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_cross_entropy(self):
        logits = rn(4, 7)
        label = np.array([1, 3, 0, 6])
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(label))
        lse = np.log(np.exp(logits).sum(-1))
        ref = (lse - logits[np.arange(4), label]).mean()
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-5)

    def test_conv2d(self):
        x = rn(1, 2, 5, 5)
        w = rn(3, 2, 3, 3)
        out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=1)
        assert out.shape == [1, 3, 5, 5]
        # centre value check vs manual correlation
        ref = sum((x[0, c, 1:4, 1:4] * w[0, c]).sum() for c in range(2))
        np.testing.assert_allclose(out.numpy()[0, 0, 2, 2], ref, rtol=1e-4)

    def test_max_avg_pool(self):
        x = rn(1, 1, 4, 4)
        mp = F.max_pool2d(paddle.to_tensor(x), kernel_size=2)
        np.testing.assert_allclose(
            mp.numpy()[0, 0],
            x[0, 0].reshape(2, 2, 2, 2).transpose(0, 2, 1, 3).reshape(
                2, 2, 4).max(-1))
        ap = F.avg_pool2d(paddle.to_tensor(x), kernel_size=2)
        np.testing.assert_allclose(
            ap.numpy()[0, 0],
            x[0, 0].reshape(2, 2, 2, 2).transpose(0, 2, 1, 3).reshape(
                2, 2, 4).mean(-1), rtol=1e-6)

    def test_embedding(self):
        w = rn(10, 4)
        ids = np.array([[1, 2], [3, 4]])
        out = F.embedding(paddle.to_tensor(ids), paddle.to_tensor(w))
        np.testing.assert_array_equal(out.numpy(), w[ids])

    def test_attention_causal(self):
        q = rn(2, 4, 2, 8)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            is_causal=True)
        assert out.shape == [2, 4, 2, 8]
        # first position attends only to itself -> equals v[0]
        np.testing.assert_allclose(out.numpy()[:, 0], q[:, 0], rtol=1e-5)


class TestGrads:
    def test_elementwise_grads(self):
        check_grad(paddle.multiply, [rn(3, 3), rn(3, 3)])
        check_grad(paddle.divide, [rn(3, 3), r(3, 3) + 0.5])
        check_grad(paddle.tanh, [rn(3, 3)])

    def test_matmul_grad(self):
        check_grad(paddle.matmul, [rn(3, 4), rn(4, 2)])

    def test_softmax_grad(self):
        check_grad(lambda x: F.softmax(x, axis=-1), [rn(3, 5)])

    def test_layernorm_grad(self):
        check_grad(lambda x, w, b: F.layer_norm(x, w, b), [rn(2, 6), r(6), r(6)])

    def test_conv_grad(self):
        check_grad(lambda x, w: F.conv2d(x, w, padding=1),
                   [rn(1, 2, 4, 4), rn(2, 2, 3, 3)])

    def test_embedding_grad(self):
        w = rn(6, 3)
        ids = np.array([0, 2, 2, 5])
        wt = paddle.to_tensor(w, stop_gradient=False)
        out = F.embedding(paddle.to_tensor(ids), wt)
        out.sum().backward()
        ref = np.zeros_like(w)
        for i in ids:
            ref[i] += 1.0
        np.testing.assert_allclose(wt.grad.numpy(), ref)

    def test_cross_entropy_grad(self):
        check_grad(lambda x: F.cross_entropy(x, paddle.to_tensor(
            np.array([1, 0, 2]))), [rn(3, 4)], rtol=2e-2)

    def test_broadcast_grad(self):
        check_grad(paddle.add, [rn(3, 4), rn(4)])
