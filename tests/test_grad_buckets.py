"""Bucketed backward grad sync (T3-style comm_overlap) — parity,
determinism, and plumbing.

Under test (distributed/grad_buckets.py + the engine integration):
- bucket-plan determinism: same model/strategy/comm_buffer_size_MB →
  identical plan (describe/pickle/digest), across fresh builds AND
  across processes (the assignment must agree on every rank)
- comm_buffer_size_MB actually sizes the buckets
- knob-on vs knob-off loss/param parity <= 1e-5 on the 8-vdev mesh
  with ZeRO stage-2 (flat model) and with pp2 x vpp2 (the stacked-
  params seam scan), with zero steady-state recompiles
- the per-bucket ZeRO plan: row_dims keeps the reduce-scatter dim off
  the stacked-layer row axis the seam scan chunks over
- paddle_tpu_train_grad_buckets gauge + schema registration
"""
import json
import os
import pickle
import subprocess
import sys
from pathlib import Path

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import grad_buckets as gb
from paddle_tpu.distributed.engine import ParallelEngine, _ZeroPlan

_PLAN_RECIPE = """
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine

strategy = fleet.DistributedStrategy()
strategy.hybrid_configs = {
    "dp_degree": 2, "sharding_degree": 4,
    "sharding_configs": {"comm_overlap": True,
                         "comm_buffer_size_MB": 0.0005}}
hcg = fleet.init(is_collective=True, strategy=strategy)
paddle.seed(3)


class MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(16, 32)
        self.fc2 = paddle.nn.Linear(32, 16)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


model = MLP()
opt = paddle.optimizer.Adam(learning_rate=0.1,
                            parameters=model.parameters())
model, opt, _ = dist.group_sharded_parallel(model, opt, "os_g")
eng = ParallelEngine(model, opt, hcg.mesh)
step = eng.train_step(lambda m, b: paddle.mean((m(b["x"]) - b["y"]) ** 2))
x = np.zeros((8, 16), "float32")
step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(x)})
print("DIGEST=" + eng._bucket_plan.digest())
"""


def _mlp():
    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(16, 32)
            self.fc2 = paddle.nn.Linear(32, 16)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    return MLP()


def _loss_fn(model, batch):
    return paddle.mean((model(batch["x"]) - batch["y"]) ** 2)


def _flat_engine(overlap, mb=0.0005, steps=3):
    """dp2 x sharding4 ZeRO stage-2 MLP engine, knob via the strategy
    (the reference hybrid_configs plumbing)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 2, "sharding_degree": 4,
        "sharding_configs": {"comm_overlap": overlap,
                             "comm_buffer_size_MB": mb}}
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(3)
    model = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    model, opt, _ = dist.group_sharded_parallel(model, opt, "os_g")
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(_loss_fn)
    np.random.seed(0)
    x = np.random.randn(8, 16).astype("float32")
    y = np.random.randn(8, 16).astype("float32")
    batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}
    losses = [float(step(batch)) for _ in range(steps)]
    eng._flush_pending_scalars()
    return eng, model, losses, batch, step


# ---------------------------------------------------------------------------
# plan determinism (identical bucket assignment across ranks/processes)
# ---------------------------------------------------------------------------
class TestPlanDeterminism:
    def test_fresh_builds_identical(self):
        eng1, _, _, _, _ = _flat_engine(True)
        plan1 = eng1._bucket_plan
        eng2, _, _, _, _ = _flat_engine(True)
        plan2 = eng2._bucket_plan
        assert plan1 is not None and plan2 is not None
        assert plan1.describe() == plan2.describe()
        assert plan1.digest() == plan2.digest()
        # the canonical description is plain data: picklable, and the
        # round trip preserves identity (what a rank-agreement check
        # over a real multi-host store would hash)
        assert pickle.loads(pickle.dumps(plan1.describe())) == \
            plan2.describe()

    def test_digest_identical_across_processes(self):
        eng, _, _, _, _ = _flat_engine(True)
        here = eng._bucket_plan.digest()
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, "-c", _PLAN_RECIPE],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=str(Path(__file__).resolve().parents[1]))
        assert out.returncode == 0, out.stderr[-2000:]
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("DIGEST=")][-1]
        assert line.split("=", 1)[1] == here

    def test_buffer_size_controls_bucket_count(self):
        eng_small, _, _, _, _ = _flat_engine(True, mb=1e-6)
        eng_big, _, _, _, _ = _flat_engine(True, mb=1e3)
        small, big = eng_small._bucket_plan, eng_big._bucket_plan
        assert small.num_buckets > big.num_buckets
        assert big.num_buckets == len(big.groups)   # one bucket/group
        assert small.digest() != big.digest()
        # every trainable param is covered either way (all are ZeRO-
        # eligible on this mesh), and payloads account for all of them
        assert len(small) == len(big) == len(eng_small.trainable)


# ---------------------------------------------------------------------------
# knob-on vs knob-off parity: flat model + ZeRO stage-2
# ---------------------------------------------------------------------------
class TestFlatParity:
    def test_loss_param_parity_and_compile_stability(self):
        eng0, model0, losses0, _, _ = _flat_engine(False)
        eng1, model1, losses1, batch, step = _flat_engine(True)
        assert eng0._bucket_plan is None
        assert eng1._bucket_plan is not None
        assert eng1._bucket_plan.num_buckets >= 2
        np.testing.assert_allclose(losses1, losses0, rtol=0, atol=1e-5)
        for p0, p1 in zip(model0.parameters(), model1.parameters()):
            np.testing.assert_allclose(
                np.asarray(p1._value), np.asarray(p0._value),
                rtol=0, atol=1e-5)
        # the folded grad-norm psum must agree with the per-param path
        g0 = eng0._metrics["grad_norm"].value()
        g1 = eng1._metrics["grad_norm"].value()
        np.testing.assert_allclose(g1, g0, rtol=1e-5, atol=1e-7)
        # bucketing adds no compile signatures: 1 compile + cache hits
        assert eng1.stats.compiles == 1
        float(step(batch))
        assert eng1.stats.compiles == 1

    def test_gauge_published(self):
        eng1, _, _, _, _ = _flat_engine(True)
        nb = eng1._bucket_plan.num_buckets
        assert eng1._metrics["grad_buckets"].value() == float(nb)
        eng0, _, _, _, _ = _flat_engine(False)
        assert eng0._metrics["grad_buckets"].value() == 0.0

    def test_constructor_override_beats_strategy(self):
        """Engines built outside fleet plumbing can force the knob."""
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "sharding_degree": 4}
        fleet._fleet_state.update(initialized=False, hcg=None,
                                  strategy=None)
        hcg = fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(3)
        model = _mlp()
        opt = paddle.optimizer.Adam(learning_rate=0.1,
                                    parameters=model.parameters())
        model, opt, _ = dist.group_sharded_parallel(model, opt, "os_g")
        eng = ParallelEngine(model, opt, hcg.mesh, comm_overlap=True,
                             comm_buffer_size_mb=1e-6)
        step = eng.train_step(_loss_fn)
        x = np.zeros((8, 16), "float32")
        float(step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(x)}))
        assert eng._bucket_plan is not None
        assert eng._bucket_plan.num_buckets >= 2


class TestAmpParity:
    def test_scaler_composes_with_buckets(self):
        """Bucketed sync runs pre-unscale (the plan sums scaled grads;
        the engine applies the scaler inverse squared to the folded
        grad-norm) — losses and the reported grad norm must match the
        unbucketed scaled run."""
        results = {}
        for overlap in (False, True):
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {
                "dp_degree": 2, "sharding_degree": 4,
                "sharding_configs": {"comm_overlap": overlap,
                                     "comm_buffer_size_MB": 1e-6}}
            fleet._fleet_state.update(initialized=False, hcg=None,
                                      strategy=None)
            hcg = fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(3)
            model = _mlp()
            opt = paddle.optimizer.Adam(learning_rate=0.1,
                                        parameters=model.parameters())
            model, opt, _ = dist.group_sharded_parallel(model, opt,
                                                        "os_g")
            eng = ParallelEngine(model, opt, hcg.mesh)
            scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10)
            step = eng.train_step(_loss_fn, scaler=scaler)
            np.random.seed(0)
            x = np.random.randn(8, 16).astype("float32")
            y = np.random.randn(8, 16).astype("float32")
            batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}
            losses = [float(step(batch)) for _ in range(3)]
            eng._flush_pending_scalars()
            results[overlap] = (losses,
                                eng._metrics["grad_norm"].value())
        np.testing.assert_allclose(results[True][0], results[False][0],
                                   rtol=0, atol=1e-5)
        np.testing.assert_allclose(results[True][1], results[False][1],
                                   rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# knob-on vs knob-off parity: the pp2 x vpp2 stacked-params seam scan
# ---------------------------------------------------------------------------
def _pipe_run(overlap, mb=1e-6):
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=2, max_position_embeddings=32)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
        "pp_configs": {"num_virtual_pipeline_stages": 2},
        "sharding_configs": {"comm_overlap": overlap,
                             "comm_buffer_size_MB": mb}}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(7)
    model = GPTForCausalLMPipe(cfg)
    dist_model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=1e-3, parameters=model.parameters()))
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 128, (8, 16)).astype("int32")
    labels = rs.randint(0, 128, (8, 16)).astype("int32")
    losses = [float(dist_model.train_batch(
        [paddle.to_tensor(ids), paddle.to_tensor(labels)], opt))
        for _ in range(3)]
    params = [np.asarray(p._value) for p in model.parameters()]
    return losses, params, dist_model._engine


class TestSeamParity:
    def test_pp2_vpp2_zero2_parity(self):
        l0, p0, eng0 = _pipe_run(False)
        l1, p1, eng1 = _pipe_run(True)
        assert eng0._bucket_plan is None
        plan = eng1._bucket_plan
        assert plan is not None
        # the stacked decoder blocks bucket along the chunk seam: at
        # least one scan group with several row-chunk ticks
        seam_groups = [g for g in plan.groups if g.seam]
        assert seam_groups and all(g.nb * g.R == g.rows
                                   for g in seam_groups)
        assert any(g.nb > 1 for g in seam_groups)
        np.testing.assert_allclose(l1, l0, rtol=0, atol=1e-5)
        for a, b in zip(p0, p1):
            np.testing.assert_allclose(b, a, rtol=0, atol=1e-5)
        # one compile, steady-state cache hits only
        assert eng1.stats.compiles == 1
        assert eng1.stats.cache_hits == 2

    def test_seam_exposed_in_plan_description(self):
        _, _, eng = _pipe_run(True)
        desc = eng._bucket_plan.describe()
        assert any("scan" in str(g) for g in desc[1])


# ---------------------------------------------------------------------------
# the per-bucket ZeRO plan: row_dims steers the scatter dim off the
# stacked-layer rows
# ---------------------------------------------------------------------------
class TestZeroPlanRowDims:
    def test_row_dims_skips_leading_dims(self):
        import jax
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("dp", "sharding"))

        class Opt:
            state_partition_axis = "sharding"

        class P_:
            trainable = True
            _zero3 = False

            def __init__(self, shape):
                self._value = np.zeros(shape, "float32")
                self.dist_attr = None

        # [4, 8, 12]: dim0 (=4, divisible by 4) wins by default; with
        # one leading row dim reserved for the bucket scan the entry
        # must move to dim1 (8 % 4 == 0)
        p = P_((4, 8, 12))
        plain = _ZeroPlan(mesh, [p], Opt())
        assert plain.entry(p)[0] == 0
        seam = _ZeroPlan(mesh, [p], Opt(), row_dims={id(p): 1})
        assert seam.entry(p)[0] == 1
        # no eligible dim behind the rows -> the param drops out of the
        # plan instead of colliding with the row axis
        q = P_((4, 9, 13))
        assert _ZeroPlan(mesh, [q], Opt(),
                         row_dims={id(q): 1}).entry(q) is None


# ---------------------------------------------------------------------------
# schema: the new gauge is declared
# ---------------------------------------------------------------------------
def test_grad_buckets_gauge_in_schema():
    from paddle_tpu.observability import catalog

    with open(catalog.SCHEMA_PATH) as f:
        schema = json.load(f)
    assert "paddle_tpu_train_grad_buckets" in schema
    assert schema["paddle_tpu_train_grad_buckets"]["type"] == "gauge"


def test_strategy_defaults_carry_knob():
    s = fleet.DistributedStrategy()
    sc = s.hybrid_configs["sharding_configs"]
    assert sc["comm_overlap"] is False
    assert sc["comm_buffer_size_MB"] == gb.DEFAULT_BUFFER_MB
    # partial user dicts merge over the defaults (reference setter)
    s.hybrid_configs = {"sharding_configs": {"comm_overlap": True}}
    sc = s.hybrid_configs["sharding_configs"]
    assert sc["comm_overlap"] is True
    assert sc["comm_buffer_size_MB"] == gb.DEFAULT_BUFFER_MB
