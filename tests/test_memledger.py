"""HBM memory ledger + roofline step report (observability/memledger).

Under test:
- per-executable memory ledger: memory_analysis totals present and
  byte-identical across re-analyses of the same program; gauges
  published under the schema'd names; ZERO recompiles of the live
  step with the ledger on
- model-state accounting pinned against the closed form (global shape
  / sharding degree) for the gpt13b hybrid smoke config — incl. ZeRO
  stage-2 scattered optimizer state and pp x vpp stacked-chunk
  ownership — and for a plain dp engine
- roofline verdicts: the pure math (fake TPU device -> known peaks,
  bound selection, headroom/util percentages, CPU -> "unknown"), and
  the engine/serving report plumbing
- serving: per-site ledgers (prefill buckets + the shared decode),
  compile stability with the ledger on, KV-pool closed form,
  suggest_pool_pages / pool_pages="auto"
- /healthz on the metrics exporter: 200 + snapshot age that scrapes
  do NOT refresh
- flight records carry the memory context
- tools/step_report over synthetic BENCH rounds
- tpulint: memledger + step_report stay clean with ZERO baseline
  entries
"""
import json
import sys
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.observability import memledger as ml

F32 = 4


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def dp_mem_engine():
    """dp8 tiny GPT with the memory ledger ON (ctor knob)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    obs.reset_registry()
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh, mem_ledger=True)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    r = np.random.RandomState(0)
    ids = r.randint(0, 128, (8, 17))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    for _ in range(3):
        float(step(batch))
    return eng, step, batch


@pytest.fixture(scope="module")
def hybrid_engine():
    """The gpt13b bench smoke config: mp2 x pp2 x sharding2 stage-2,
    vpp=2 — the pinned target for chunk-aware state accounting."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                    num_heads=4, max_position_embeddings=64)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
        "pp_configs": {"num_virtual_pipeline_stages": 2}}
    strategy.sharding_configs = {"stage": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    model = GPTForCausalLMPipe(cfg)
    dist_model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(
        paddle.optimizer.AdamW(learning_rate=1e-4,
                               parameters=model.parameters()))
    r = np.random.RandomState(0)
    B, S = 8, 16
    ids = r.randint(0, cfg.vocab_size, (B, S + 1))
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])
    float(dist_model.train_batch([x, y], opt))
    eng = dist_model._engine
    eng._mem_on = True          # knob after the fact: accessors only
    return eng, cfg, hcg


# ---------------------------------------------------------------------------
# per-executable ledger
# ---------------------------------------------------------------------------
class TestExecutableLedger:
    def test_totals_present(self, dp_mem_engine):
        eng, _, _ = dp_mem_engine
        led = eng.memory_ledger()
        assert led is not None and led.available
        assert led.argument_bytes > 0
        assert led.output_bytes > 0
        assert led.alias_bytes > 0          # donated params alias
        d = led.to_dict()
        for k in ("temp_bytes", "argument_bytes", "output_bytes",
                  "alias_bytes", "generated_code_bytes", "peak_bytes"):
            assert k in d
        # peak folds the donation alias out exactly once
        assert led.peak_bytes == (led.argument_bytes + led.output_bytes
                                  + led.temp_bytes
                                  + led.generated_code_bytes
                                  - led.alias_bytes)
        assert led.traffic_bytes == (led.argument_bytes
                                     + led.output_bytes
                                     + 2 * led.temp_bytes)

    def test_stable_across_reanalysis(self, dp_mem_engine):
        """Re-lowering the same program must reproduce the same byte
        classes (the 'stable across recompiles' contract)."""
        eng, _, _ = dp_mem_engine
        led1 = eng.memory_ledger()
        eng._mem_ledgers.pop(eng._last_key)
        led2 = eng.memory_ledger()
        assert led2 is not None and led1.same_totals(led2)

    def test_zero_recompiles_with_ledger_on(self, dp_mem_engine):
        eng, step, batch = dp_mem_engine
        c0 = eng.stats.compiles
        float(step(batch))
        float(step(batch))
        assert eng.stats.compiles == c0

    def test_gauges_published_inside_schema(self, dp_mem_engine):
        from paddle_tpu.observability import catalog

        eng, _, _ = dp_mem_engine
        snap = eng.metrics_snapshot()["metrics"]
        with open(catalog.SCHEMA_PATH) as f:
            schema = json.load(f)
        led = eng.memory_ledger()
        rows = {r["labels"]["program"]: r["value"] for r in
                snap["paddle_tpu_mem_temp_bytes"]["series"]}
        assert rows["train"] == led.temp_bytes
        for name in ("paddle_tpu_mem_temp_bytes",
                     "paddle_tpu_mem_argument_bytes",
                     "paddle_tpu_mem_output_bytes",
                     "paddle_tpu_mem_alias_bytes",
                     "paddle_tpu_mem_generated_code_bytes",
                     "paddle_tpu_mem_state_bytes",
                     "paddle_tpu_mem_analytic_drift",
                     "paddle_tpu_mem_live_bytes",
                     "paddle_tpu_mem_live_peak_bytes"):
            assert name in snap and name in schema
            for row in snap[name]["series"]:
                assert sorted(row["labels"]) == schema[name]["labels"]

    def test_unavailable_is_graceful(self):
        led = ml.analyze(object(), (), program="bogus")
        assert not led.available and led.note
        assert led.peak_bytes == 0

    def test_live_watermark_monotone(self, dp_mem_engine):
        eng, _, _ = dp_mem_engine
        m = eng._metrics
        assert m["mem_live_peak"].value() >= m["mem_live"].value() > 0


# ---------------------------------------------------------------------------
# model-state accounting
# ---------------------------------------------------------------------------
class TestStateAccounting:
    def test_dp_replicated_closed_form(self, dp_mem_engine):
        """dp-only: every param/state array is replicated, so one
        device holds the full bytes."""
        eng, _, _ = dp_mem_engine
        acct = eng.state_accounting()
        expect_params = sum(
            int(np.prod(p._value.shape)) * p._value.dtype.itemsize
            for p in eng.params)
        assert acct.components["params"] == expect_params
        assert acct.components["grads"] == expect_params
        # AdamW: two f32 moments per trainable param, replicated
        expect_state = 2 * sum(
            int(np.prod(p._value.shape)) * F32 for p in eng.trainable)
        assert acct.components["optimizer_state"] == expect_state
        assert acct.components == {
            **acct.components, **ml.closed_form_state_bytes(eng)}

    def test_hybrid_closed_form_zero2_vpp(self, hybrid_engine):
        """The pinned satellite: mp2 x pp2 x sharding2 stage-2, vpp=2.
        Param bytes = global / (spec degree); ZeRO-2 optimizer state
        additionally / sharding degree; the stacked block params carry
        the [vpp, L/(pp*vpp), ...] leading chunk axes sharded over
        'pp' — all of it must match the closed form byte-for-byte."""
        eng, cfg, hcg = hybrid_engine
        acct = eng.state_accounting()
        closed = ml.closed_form_state_bytes(eng)
        for k, v in closed.items():
            assert acct.components[k] == v, (k, acct.components[k], v)
        # independent sanity anchors, from first principles:
        # every param is stored at global_size / degree where degree
        # multiplies the axes in its spec (stage 2 leaves params
        # unscattered), so per-rank params < full model params
        full = sum(int(np.prod(p._value.shape))
                   * p._value.dtype.itemsize for p in eng.params)
        assert acct.components["params"] < full
        # the stacked decoder blocks: [vpp, L/(pp*vpp), ...] sharded
        # over pp on the chunk axis -> exactly half the rows per rank
        stacked = [p for n, p in eng.model.named_parameters()
                   if n.startswith("blocks__") and p._value.ndim >= 3]
        assert stacked, "expected stacked pp block params"
        for p in stacked:
            # global [vpp=2, L/vpp=2, ...]; axis 1 sharded over 'pp'
            # -> each rank owns exactly one K=1 row per circuit chunk
            assert tuple(p._value.shape)[:2] == (2, 2)
            local = p._value.sharding.shard_shape(
                tuple(p._value.shape))
            assert local[:2] == (2, 1)
            got = ml.shard_bytes(p._value)
            want = (int(np.prod(p._value.shape))
                    * p._value.dtype.itemsize
                    // ml._spec_degree(p, eng.mesh))
            assert got == want
        # ZeRO stage-2: eligible optimizer state is scattered over
        # 'sharding' — state bytes strictly below param bytes would
        # only hold without moments; instead pin: state of eligible
        # params == 2 x param shard bytes / sharding_degree (f32
        # moments over f32 params here)
        zero = eng._zero
        assert zero.axis == "sharding" and zero.n == 2
        assert zero.entries, "stage-2 plan should cover params"

    def test_drift_and_activation_term(self, hybrid_engine):
        eng, _, _ = hybrid_engine
        acct = eng.state_accounting()
        assert acct.components["activation_ckpt"] > 0
        assert acct.analytic_bytes > 0
        assert np.isfinite(acct.drift)
        d = acct.to_dict()
        assert set(d) == {"components", "groups", "measured_bytes",
                          "device_bytes", "analytic_bytes",
                          "analytic_drift"}
        # no offload on this engine: nothing host-resident
        assert d["device_bytes"] == d["measured_bytes"]
        json.dumps(d)     # bench lines must serialize

    def test_autotuner_crosscheck_matches_gauge_math(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        model = {"hidden_size": 64, "num_layers": 4, "vocab_size": 512,
                 "num_heads": 4}
        t = AutoTuner(model, num_devices=8, global_batch=8, seq_len=16)
        cfg = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
               "sharding_degree": 2, "micro_batch_size": 2}
        drift = t.crosscheck(cfg, measured_gb=0.001)
        from paddle_tpu.distributed.auto_tuner.cost_model import \
            estimate_memory_gb

        pred = estimate_memory_gb(model, cfg, 8, 16)
        assert drift == pytest.approx((pred - 0.001) / 0.001)


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------
class _FakeV5p:
    device_kind = "TPU v5p"
    platform = "tpu"


class TestRoofline:
    def test_hbm_bound_verdict(self):
        # v5p: 459e12 FLOPs, 2.765e12 HBM B/s, 600e9 ICI B/s
        rep = ml.roofline(step_seconds=0.01,
                          flops_per_step=459e12 * 1e-3,      # 1 ms
                          hbm_traffic_bytes=2.765e12 * 5e-3,  # 5 ms
                          wire_bytes=600e9 * 2e-3,            # 2 ms
                          device=_FakeV5p())
        assert rep.bound == "hbm-bound"
        assert rep.seconds["hbm"] == pytest.approx(5e-3)
        assert rep.headroom_pct["hbm"] == 0.0
        assert rep.headroom_pct["compute"] == pytest.approx(80.0)
        assert rep.headroom_pct["ici"] == pytest.approx(60.0)
        assert rep.util_pct["hbm"] == pytest.approx(50.0)

    def test_compute_bound_and_exposed_override(self):
        rep = ml.roofline(step_seconds=0.01,
                          flops_per_step=459e12 * 8e-3,
                          hbm_traffic_bytes=2.765e12 * 1e-3,
                          wire_bytes=600e9 * 100.0,   # huge analytic
                          exposed_ici_seconds=1e-3,   # ...but hidden
                          device=_FakeV5p())
        assert rep.bound == "compute-bound"
        assert rep.seconds["ici"] == pytest.approx(1e-3)

    def test_cpu_is_unknown(self):
        rep = ml.roofline(step_seconds=0.01, flops_per_step=1e12,
                          hbm_traffic_bytes=1e9, wire_bytes=1e9,
                          exposed_ici_seconds=0.5,
                          device=jax.devices()[0])
        assert rep.bound == "unknown"
        assert set(rep.headroom_pct) == set(ml.RESOURCES)
        json.dumps(rep.to_dict())

    def test_engine_report(self, hybrid_engine):
        eng, _, _ = hybrid_engine
        rep = eng.roofline_report()
        assert rep.bound == "unknown"          # CPU harness
        assert rep.program == "train"
        assert set(rep.seconds) == set(ml.RESOURCES)


# ---------------------------------------------------------------------------
# serving
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def serving_mem_engine():
    from paddle_tpu.inference import (Config, ServingEngine,
                                      create_predictor)
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(0)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    conf = Config().set_model(model).enable_paged_kv(page_size=8)
    pred = create_predictor(conf)
    eng = ServingEngine(pred, max_batch=4, decode_chunk=2,
                        mem_ledger=True)
    r = np.random.RandomState(0)
    for L in (7, 12):                               # warmup mix
        eng.submit(r.randint(1, cfg.vocab_size, (L,)), max_new_tokens=6)
    eng.run()
    warm = eng.stats.compiles
    for L in (24, 17, 11, 9, 5):                    # streamed mixes
        eng.submit(r.randint(1, cfg.vocab_size, (L,)), max_new_tokens=6)
    eng.run()
    return eng, warm, cfg


class TestServingMemLedger:
    def test_sites_analyzed(self, serving_mem_engine):
        eng, _, _ = serving_mem_engine
        led = eng.memory_ledger(("decode",))
        assert led is not None and led.available
        assert led.argument_bytes > 0
        prefill = [s for s in eng._mem_ledgers if s[0] == "prefill"]
        assert prefill, "prefill site should be analyzed"

    def test_zero_recompiles_after_warmup(self, serving_mem_engine):
        eng, warm, _ = serving_mem_engine
        assert eng.stats.compiles == warm

    def test_pool_closed_form_and_summary(self, serving_mem_engine):
        eng, _, cfg = serving_mem_engine
        mem = eng.memory_summary()
        st = mem["state"]
        # measured pool arrays == page_bytes x pool_pages closed form
        assert st["kv_pool_bytes"] == st["page_bytes"] * st["pool_pages"]
        assert st["page_bytes"] == (2 * cfg.num_layers
                                    * cfg.num_kv_heads * 8
                                    * cfg.head_dim * F32)
        assert "decode" in mem["executables"]
        json.dumps(mem)
        rep = eng.roofline_report()
        assert rep.program == "decode"
        assert rep.bound == "unknown"          # CPU harness

    def test_suggest_pool_pages(self):
        class Dev:
            def memory_stats(self):
                return {"bytes_limit": 1000}

        # (1000 * 0.9 - 300) // 50 = 12
        assert ml.suggest_pool_pages(Dev(), 50, 300) == 12
        assert ml.suggest_pool_pages(Dev(), 50, 899) is None
        assert ml.suggest_pool_pages(jax.devices()[0], 50, 0) is None

        class NoStats:
            def memory_stats(self):
                return None

        assert ml.suggest_pool_pages(NoStats(), 50, 0) is None

    def test_auto_pool_falls_back_on_cpu(self, serving_mem_engine):
        from paddle_tpu.inference import ServingEngine

        eng, _, _ = serving_mem_engine
        auto = ServingEngine(eng.pred, max_batch=4, pool_pages="auto")
        assert auto.P == eng.P                  # geometric default


# ---------------------------------------------------------------------------
# /healthz
# ---------------------------------------------------------------------------
class TestHealthz:
    def test_healthz_age_and_scrape_independence(self):
        from paddle_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.gauge("g").set(1.0)
        with obs.serve_metrics(0, registry=reg) as srv:
            url = f"http://127.0.0.1:{srv.port}"

            def get(path):
                with urllib.request.urlopen(url + path, timeout=5) as r:
                    return r.status, r.read().decode()

            code, body = get("/healthz")
            assert code == 200
            doc = json.loads(body)
            assert doc["status"] == "ok"
            assert doc["snapshot_age_seconds"] is None   # never ticked
            # a scrape must NOT refresh the liveness age
            code, _ = get("/metrics")
            assert code == 200
            assert json.loads(get("/healthz")[1])[
                "snapshot_age_seconds"] is None
            reg.snapshot()                               # an engine tick
            age = json.loads(get("/healthz")[1])["snapshot_age_seconds"]
            assert age is not None and 0.0 <= age < 60.0
            with pytest.raises(urllib.error.HTTPError):
                get("/bogus")


# ---------------------------------------------------------------------------
# flight-record memory context
# ---------------------------------------------------------------------------
class TestFlightMemoryContext:
    def test_record_carries_memory(self, dp_mem_engine, tmp_path):
        eng, _, _ = dp_mem_engine
        eng.metrics_snapshot()          # mem gauges are live
        rec = obs.get_recorder().record(reason="test")
        assert "memory" in rec
        gauges = rec["memory"]["gauges"]
        assert any(k.startswith("paddle_tpu_mem_temp_bytes")
                   for k in gauges)
        assert "device_memory_stats" in rec["memory"]
        path = obs.get_recorder().dump(str(tmp_path / "f.json"),
                                       reason="test")
        with open(path) as f:
            assert "memory" in json.load(f)


# ---------------------------------------------------------------------------
# tools/step_report
# ---------------------------------------------------------------------------
class TestStepReport:
    def _round(self, n, lines):
        return {"n": n, "cmd": "python bench.py", "rc": 0,
                "tail": "\n".join(json.dumps(ln) for ln in lines)}

    def _line(self, bound="hbm-bound"):
        return {
            "metric": "gpt13b_hybrid_smoke_tokens_per_sec",
            "value": 3000.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "memory": {
                "executable": {"program": "train", "temp_bytes": 10,
                               "argument_bytes": 20, "output_bytes": 30,
                               "alias_bytes": 5, "peak_bytes": 55},
                "state": {"components": {"params": 100,
                                         "optimizer_state": 200},
                          "analytic_drift": 0.25}},
            "roofline": {"bound": bound, "step_seconds": 0.01,
                         "seconds": {"compute": 0.002, "hbm": 0.006,
                                     "ici": 0.001},
                         "headroom_pct": {"compute": 66.7, "hbm": 0.0,
                                          "ici": 83.3},
                         "util_pct": {"compute": 20.0, "hbm": 60.0,
                                      "ici": 10.0}},
        }

    def _import(self):
        repo = Path(__file__).resolve().parents[1]
        sys.path.insert(0, str(repo))
        try:
            from tools import step_report as sr
        finally:
            sys.path.remove(str(repo))
        return sr

    def test_rows_and_trajectory(self, tmp_path):
        sr = self._import()
        from tools.bench_compare import load_rounds, parse_metrics

        docs = [self._round(1, [self._line("compute-bound")]),
                self._round(2, [self._line("hbm-bound")])]
        for i, doc in enumerate(docs, 1):
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(
                json.dumps(doc))
        rounds = load_rounds(str(tmp_path))
        metrics = parse_metrics(rounds[-1][1])
        roof = sr.roofline_rows(metrics)
        assert roof[0]["bound"] == "hbm-bound"
        assert roof[0]["headroom_pct"]["ici"] == 83.3
        mem = sr.memory_rows(metrics)
        assert mem[0]["executables"]["train"]["temp_bytes"] == 10
        assert mem[0]["state"]["params"] == 100
        assert mem[0]["analytic_drift"] == 0.25
        traj = sr.verdict_trajectory(rounds)
        assert traj["gpt13b_hybrid_smoke_tokens_per_sec"] == ["C", "H"]
        assert sr.main(["--dir", str(tmp_path)]) == 0
        assert sr.main(["--dir", str(tmp_path), "--json"]) == 0

    def test_serving_multi_executable_form(self, tmp_path):
        sr = self._import()
        from tools.bench_compare import parse_metrics

        line = {"metric": "serving", "value": 1.0, "unit": "tokens/s",
                "vs_baseline": 0.0,
                "memory": {"executables": {
                    "decode": {"temp_bytes": 1, "argument_bytes": 2,
                               "output_bytes": 3, "alias_bytes": 0,
                               "peak_bytes": 6}},
                    "state": {"params_bytes": 7, "kv_pool_bytes": 8}},
                "roofline": {"bound": "unknown", "step_seconds": 0.0,
                             "seconds": {}, "headroom_pct": {},
                             "util_pct": {}}}
        doc = self._round(1, [line])
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(doc))
        metrics = parse_metrics(doc["tail"])
        mem = sr.memory_rows(metrics)
        assert mem[0]["executables"]["decode"]["peak_bytes"] == 6
        assert mem[0]["state"]["kv_pool_bytes"] == 8

    def test_no_rounds_exit_code(self, tmp_path):
        sr = self._import()
        assert sr.main(["--dir", str(tmp_path)]) == 2


# ---------------------------------------------------------------------------
# tpulint: the new modules must stay clean with ZERO baseline entries
# ---------------------------------------------------------------------------
def test_tpulint_memledger_surface_zero_baseline():
    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths(
            [repo / "paddle_tpu" / "observability" / "memledger.py",
             repo / "tools" / "step_report.py"],
            ALL_RULES, root=repo)
    finally:
        sys.path.remove(str(repo))
    assert findings == [], [str(f) for f in findings]
