"""bf16 optimizer-moment convergence guard (VERDICT item 10).

The TPU bench trains with AdamW moments stored bfloat16 (state_dtype=
"bfloat16", re-quantized every step; update math stays f32 —
optimizer/__init__.py _cast_state_in). This guards that the loss curve
stays inside a tolerance band of f32 moments over 200 steps — if this
ever fails, flip the bench default or add stochastic rounding."""
import pytest
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               GPTPretrainingCriterion)

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def _run(state_dtype, steps=200):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=32)
    paddle.seed(123)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters(),
                                 state_dtype=state_dtype)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    r = np.random.RandomState(0)
    data = r.randint(0, cfg.vocab_size, (4, 17))
    batch = {"x": paddle.to_tensor(data[:, :-1]),
             "y": paddle.to_tensor(data[:, 1:])}
    return [float(step(batch)) for _ in range(steps)]


def test_bf16_moments_track_f32_loss_curve():
    f32 = _run(None)
    bf16 = _run("bfloat16")
    f32 = np.asarray(f32)
    bf16 = np.asarray(bf16)
    # same qualitative optimization: both must reach a deep overfit
    assert f32[-1] < 0.1 * f32[0]
    assert bf16[-1] < 0.1 * bf16[0], (f32[-1], bf16[-1])
    # and the curves stay inside a band: mean abs gap bounded relative
    # to the overall loss drop (bf16 moment noise must not change the
    # trajectory class)
    drop = f32[0] - f32[-1]
    gap = np.abs(f32 - bf16).mean()
    assert gap < 0.05 * drop, (gap, drop)
    # terminal quality within 15% of the f32 drop
    assert abs(f32[-1] - bf16[-1]) < 0.15 * drop, (f32[-1], bf16[-1])
