"""Threaded stress test for the PR-16 prefix-cache refcount machine.

The dynamic twin of the lock-order-cycle / blocking-under-lock static
rules on inference/serving.py: a submitter thread hammers submit()
(shared prefixes, tight deadlines, a bounded queue) while the decode
loop runs on the main thread with ``debug_invariants=True`` — every
admit / evict / preempt / shed / finish transition re-asserts the pool
partition ``free + idle + live == P - 1``, the per-page refcounts, and
the prefix hash-map bijection under the serving RLock.

The partition is a lock-quiescent-point invariant: an allocation and
its slot attach intentionally span two critical sections (the same
rebind-after-release discipline blocking-under-lock enforces), so the
explicit ``check_invariants()`` probes run on the decode thread
between rounds — the cross-thread pressure comes from the submitter
racing admission bookkeeping, queue mutation, shed accounting, and
prefix-cache registration against the running rounds.

A tiny pool (7 usable pages) against max_batch=3 keeps the engine
permanently page-starved, so the run actually exercises preemption,
LRU reclaim, and deadline/queue-full shedding — not just the happy
path."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import Config, ServingEngine, create_predictor
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

PAGE = 8
N_REQUESTS = 24


@pytest.fixture(scope="module")
def paged_pred():
    paddle.seed(11)
    model = LlamaForCausalLM(llama_tiny())
    return create_predictor(
        Config().set_model(model).enable_paged_kv(page_size=PAGE))


class TestServingRefcountStress:
    def test_threaded_submit_never_breaks_pool_partition(
            self, paged_pred):
        eng = ServingEngine(paged_pred, max_batch=3, prefill_chunk=16,
                            pool_pages=8, prefix_cache=True,
                            max_queue=6, debug_invariants=True)
        rng = np.random.RandomState(7)
        sysp = rng.randint(1, 256, (2 * PAGE,))   # shared 2-page prefix
        rids, errors = [], []
        done = threading.Event()

        def submitter():
            try:
                for i in range(N_REQUESTS):
                    if i % 3 == 0:
                        prompt = sysp                 # exact prefix hit
                    else:
                        tail = rng.randint(1, 256, (i % 8 + 1,))
                        prompt = np.concatenate([sysp, tail])
                    # every 4th request gets a deadline tight enough
                    # to shed under the page-starved pool
                    ddl = 0.02 if i % 4 == 3 else None
                    rids.append(eng.submit(prompt, max_new_tokens=4,
                                           deadline_s=ddl))
                    if i % 5 == 0:
                        time.sleep(0.002)             # jitter the race
            except BaseException as e:   # surfaced on the main thread
                errors.append(e)
            finally:
                done.set()

        t = threading.Thread(target=submitter, name="submitter",
                             daemon=True)
        t.start()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            eng.step()                   # debug mode re-checks every
            eng.check_invariants()       # transition; probe between too
            if done.is_set() and not eng.queue and not eng.num_active \
                    and len(eng.finished) == len(rids):
                break
        t.join(timeout=10)
        assert not t.is_alive(), "submitter wedged"
        assert errors == [], f"submit raised: {errors!r}"

        # every request either completed or was shed — none lost
        assert sorted(eng.finished) == sorted(rids)
        completed = [r for r in eng.finished.values()
                     if r.shed_reason is None]
        shed = [r for r in eng.finished.values()
                if r.shed_reason is not None]
        assert completed, "stress run completed nothing"
        for req in completed:
            assert len(req.output_ids) >= 1
        # the tight deadlines + bounded queue must actually have shed
        # (otherwise the run never left the happy path)
        assert shed, "no shed requests: pool pressure never materialized"
        eng.check_invariants()           # final quiescent partition

    def test_stress_run_exercised_prefix_sharing(self, paged_pred):
        """Cheap determinism companion: the same shared-prefix load on
        the same engine config records cache hits, so the threaded run
        above is hammering the REFCOUNTED path, not a cold cache."""
        eng = ServingEngine(paged_pred, max_batch=3, prefill_chunk=16,
                            pool_pages=8, prefix_cache=True,
                            debug_invariants=True)
        rng = np.random.RandomState(7)
        sysp = rng.randint(1, 256, (2 * PAGE,))
        eng.submit(sysp, max_new_tokens=4)
        eng.run()
        eng.submit(np.concatenate([sysp, rng.randint(1, 256, (4,))]),
                   max_new_tokens=4)
        eng.run()
        assert eng.prefix_cache_stats()["hits"] >= 1
        eng.check_invariants()
