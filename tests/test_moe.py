"""MoE / expert-parallel tests (reference strategy: parallel-vs-single
loss parity, test/collective/fleet + incubate moe unit tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.incubate.distributed.models.moe import (
    GShardGate, MoELayer, NaiveGate, SwitchGate)

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def test_single_expert_equals_ffn():
    """E=1 top-1 MoE is exactly the dense FFN (all tokens, gate=1)."""
    paddle.seed(0)
    d, h = 8, 16
    moe = MoELayer(d, d_hidden=h, num_experts=1, gate="naive",
                   group=False)
    moe.gate.top_k = 1

    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 6, d)
                         .astype("float32"))
    out = moe(x)

    import jax

    w1 = moe.w1._value[0]
    b1 = moe.b1._value[0]
    w2 = moe.w2._value[0]
    b2 = moe.b2._value[0]
    ref = jax.nn.gelu(np.asarray(x._value) @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_grads_flow():
    """Experts, gate, and input all receive gradients; aux loss too."""
    paddle.seed(1)
    moe = MoELayer(8, d_hidden=16, num_experts=4, gate="gshard",
                   group=False)
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 5, 8)
                         .astype("float32"), stop_gradient=False)
    out = moe(x)
    loss = paddle.mean(out ** 2) + 0.01 * moe.aux_loss
    loss.backward()
    for n, p in moe.named_parameters():
        assert p.grad is not None, n
    assert moe.gate.weight.grad is not None
    assert float(paddle.mean(paddle.abs(
        moe.gate.weight.grad))) > 0
    assert x.grad is not None


@pytest.mark.parametrize("gate", ["naive", "switch", "gshard"])
def test_gate_types_run(gate):
    paddle.seed(2)
    moe = MoELayer(8, d_hidden=16, num_experts=4, gate=gate,
                   group=False)
    x = paddle.to_tensor(np.random.RandomState(2).randn(3, 4, 8)
                         .astype("float32"))
    out = moe(x)
    assert out.shape == [3, 4, 8]
    assert moe.gate.get_loss() is not None


def test_expert_parallel_parity():
    """EP over dp=4: loss trajectory matches the single-device MoE
    (naive gate → no token dropping → exact parity)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(7)
    d, h, E = 8, 16, 8
    model = MoELayer(d, d_hidden=h, num_experts=E, gate="naive")
    assert model.world_size == 4  # experts over the dp group

    golden = MoELayer(d, d_hidden=h, num_experts=E, gate="naive")
    golden._group = None  # run the golden copy single-device
    golden.world_size = 1
    golden.set_state_dict(model.state_dict())

    np.random.seed(3)
    x = np.random.randn(8, 4, d).astype("float32")
    y = np.random.randn(8, 4, d).astype("float32")

    # aux loss is intentionally *local* per EP rank (each rank balances
    # its own routing — mean-of-products ≠ product-of-means), so exact
    # parity holds for the task loss only
    def loss_fn(m, batch):
        out = m(batch["x"])
        return paddle.mean((out - batch["y"]) ** 2)

    g_opt = paddle.optimizer.Adam(learning_rate=0.05,
                                  parameters=golden.parameters())
    g_losses = []
    for _ in range(3):
        loss = loss_fn(golden, {"x": paddle.to_tensor(x),
                                "y": paddle.to_tensor(y)})
        loss.backward()
        g_opt.step()
        g_opt.clear_grad()
        g_losses.append(float(loss))

    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(loss_fn)
    for i in range(3):
        loss = step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)})
        np.testing.assert_allclose(float(loss), g_losses[i], rtol=1e-4,
                                   atol=1e-6, err_msg=f"step {i}")

    for (n, pd), (_, pg) in zip(model.named_parameters(),
                                golden.named_parameters()):
        np.testing.assert_allclose(np.asarray(pd._value),
                                   np.asarray(pg._value), rtol=1e-4,
                                   atol=1e-5, err_msg=n)


def test_experts_list_construction():
    """Reference-style construction from a list of expert Layers."""

    class ExpertLayer(paddle.nn.Layer):
        def __init__(self, d, h):
            super().__init__()
            self.htoh4 = paddle.nn.Linear(d, h)
            self.h4toh = paddle.nn.Linear(h, d)

        def forward(self, x):
            return self.h4toh(paddle.nn.functional.gelu(self.htoh4(x)))

    paddle.seed(4)
    experts = [ExpertLayer(8, 16) for _ in range(4)]
    moe = MoELayer(8, experts=experts, gate=NaiveGate(8, 4, topk=1),
                   group=False)
    assert moe.num_experts == 4 and moe.d_hidden == 16
    np.testing.assert_array_equal(np.asarray(moe.w1._value[2]),
                                  np.asarray(experts[2].htoh4.weight._value))
    x = paddle.to_tensor(np.random.RandomState(5).randn(2, 3, 8)
                         .astype("float32"))
    assert moe(x).shape == [2, 3, 8]


def test_gpt_moe_model_trains():
    """GPT-MoE (ERNIE-MoE style) trains end-to-end in the SPMD engine
    with the aux loss in the objective."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_moe_tiny)

    cfg = gpt_moe_tiny()
    paddle.seed(11)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)

    def loss_fn(m, b):
        return crit(m(b["x"]), b["y"]) + m.aux_loss

    step = eng.train_step(loss_fn)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 16))
    batch = {"x": paddle.to_tensor(ids), "y": paddle.to_tensor(ids)}
    first = float(step(batch))
    for _ in range(9):
        last = float(step(batch))
    assert first - last > 1.0, (first, last)


def test_capacity_drops_tokens():
    """Tiny capacity forces drops: output rows for dropped tokens are 0."""
    paddle.seed(6)
    moe = MoELayer(4, d_hidden=8, num_experts=2, gate="switch",
                   group=False)
    moe.gate.capacity_factor = 0.25  # cap ~ ceil(0.25*T/2)
    x = paddle.to_tensor(np.random.RandomState(6).randn(16, 4)
                         .astype("float32"))
    out = np.asarray(moe(x)._value)
    zero_rows = np.sum(np.all(np.abs(out) < 1e-7, axis=-1))
    assert zero_rows > 0  # some tokens were over capacity
