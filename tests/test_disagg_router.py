"""Disaggregated prefill/decode serving (ISSUE 20): the multi-replica
front door (inference/router.py) + live KV page migration
(inference/disagg.py).

The contract under test, end to end on the 8-vdev CPU harness:

- **Bit-identical streams**: a phase-split fleet (1 prefill + 1 decode
  replica) serving a bursty Poisson arrival trace emits EXACTLY the
  token streams a unified fleet (2 co-located replicas) emits for the
  same arrivals — migration moves KV pages bit-exact, greedy decode is
  deterministic, so disaggregation is a pure scheduling change.
- **Ledger-exact migration bytes**: every request's migration wire
  traffic pins to the closed form ``ceil(L/page) * page_bytes +
  block_table_row_bytes``, booked through the comm ledger as
  ``ppermute`` records under the ``migrate`` axis AND on the
  ``paddle_tpu_serving_migration_bytes_total`` counter.
- **CRC on every page**: each migrated page payload carries the SAME
  crc32 shard codec checkpoints use; a corrupted frame is detected,
  dropped, and the request retried on a FRESH prefill replica with the
  same trace identity — final tokens still bit-identical.
- **Zero post-warmup recompiles** on BOTH replica kinds: export reads
  pages through the one compiled page-read program, import writes
  through the one page-write program.
- **Routing policy**: health (in-process + FleetCollector overlay)
  filters, prefix affinity steers shared-prefix traffic to the replica
  already holding the pages, least-loaded breaks ties; placement books
  ``paddle_tpu_router_requests_total{replica, decision}``.
- **Trace stitching**: the router's traceparent follows the request
  across prefill -> migrate -> decode, so per-replica traces stitch on
  one trace_id.

Plus the ISSUE 20 satellites: malformed client traceparent mints a
fresh id (counted, never raised), the prefix-cache hash-table gauge,
and the tpulint zero-finding pin on the two new files.
"""
import json
import sys
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (Config, KVMigrator, MigrationCorruptError,
                                  Router, RouterServer, ServingEngine,
                                  create_predictor)
from paddle_tpu.inference.disagg import (MIGRATE_AXES, migration_nbytes,
                                         pack_migration, unpack_migration)
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability.catalog import serving_metrics
from paddle_tpu.observability.spans import (format_traceparent,
                                            make_span_id, make_trace_id)

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:                 # direct pytest invocation
    sys.path.insert(0, str(REPO))

PAGE = 8


@pytest.fixture(scope="module")
def model():
    paddle.seed(11)
    return LlamaForCausalLM(llama_tiny())


def _engine(model, phase=None, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_chunk", 16)
    kw.setdefault("pool_pages", 32)
    pred = create_predictor(
        Config().set_model(model).enable_paged_kv(page_size=PAGE))
    return ServingEngine(pred, phase=phase, **kw)


def _poisson_trace(n, rate=1.5, seed=5):
    """Bursty Poisson arrivals: [(arrival_step, prompt, n_new)]."""
    r = np.random.RandomState(seed)
    t, out = 0.0, []
    for _ in range(n):
        t += r.exponential(1.0 / rate)
        out.append((int(t), r.randint(1, 256, (int(r.randint(4, 30)),)),
                    int(r.randint(2, 7))))
    return out


def _drive(router, trace):
    """Feed the arrival trace into the router on its step clock; drain;
    returns {trace_index: ServingRequest}."""
    gids = {}
    step = i = 0
    while i < len(trace) or router.pending:
        while i < len(trace) and trace[i][0] <= step:
            _, prompt, n_new = trace[i]
            gids[i] = router.submit(prompt, max_new_tokens=n_new)
            i += 1
        router.step()
        step += 1
        assert step < 5000, "fleet wedged"
    return {k: router.result(g) for k, g in gids.items()}


def _page_bytes(eng):
    mcfg = eng.pred._model.config
    return (2 * mcfg.num_layers * mcfg.num_kv_heads * PAGE
            * mcfg.head_dim * np.dtype(eng._dtype).itemsize)


# ---------------------------------------------------------------------------
# the tentpole: phase-split fleet == unified fleet, bit for bit
# ---------------------------------------------------------------------------
class TestDisaggParity:
    def test_bursty_trace_bit_identical_exact_bytes_zero_recompiles(
            self, model):
        trace = _poisson_trace(10)

        # unified fleet: 2 co-located replicas behind the same router
        uni = Router([("u0", _engine(model)), ("u1", _engine(model))])
        base = _drive(uni, trace)

        # disaggregated fleet: 1 prefill + 1 decode
        peng = _engine(model, phase="prefill")
        deng = _engine(model, phase="decode")
        rt = Router([("prefill0", peng), ("decode0", deng)])
        m = serving_metrics()
        mig_bytes0 = m["migration_bytes"].value()
        comm0 = m["comm_bytes"].value(axis="migrate", op="ppermute")

        # warm both replica kinds through the full path, then pin
        warm = rt.submit(np.arange(1, 20), max_new_tokens=4)
        rt.run()
        assert rt.result(warm) is not None
        pw, dw = peng.stats.compiles, deng.stats.compiles

        got = _drive(rt, trace)

        # 1) bit-identical committed token streams, request by request
        assert {k: list(r.new_tokens) for k, r in got.items()} \
            == {k: list(r.new_tokens) for k, r in base.items()}
        # every request flowed through migration (none decoded locally)
        assert rt.migrator.migrated == len(trace) + 1

        # 2) wire bytes pin to the closed form, on the migrator, the
        #    migration counter, AND the comm ledger's migrate axis
        pb = _page_bytes(peng)
        want = sum((-(-len(p) // PAGE)) * pb + peng.npages * 4
                   for _, p, _ in trace)
        want += (-(-19 // PAGE)) * pb + peng.npages * 4   # the warmup
        assert rt.migrator.wire_bytes == want
        assert m["migration_bytes"].value() - mig_bytes0 == want
        assert m["comm_bytes"].value(axis="migrate",
                                     op="ppermute") - comm0 == want

        # 3) zero post-warmup recompiles on BOTH replica kinds
        assert peng.stats.compiles == pw
        assert deng.stats.compiles == dw

        # phase occupancy gauge exists and was swept back to idle
        assert m["phase_slots"].value(phase="prefill") == 0
        assert m["phase_slots"].value(phase="decode") == 0

    def test_migration_wire_format_crc_roundtrip(self, model):
        peng = _engine(model, phase="prefill")
        deng = _engine(model, phase="decode")
        rt = Router([("p0", peng), ("d0", deng)])
        gid = rt.submit(np.arange(1, 18), max_new_tokens=3)
        # run prefill only until the row parks for migration
        steps = 0
        while not peng.migratable():
            peng.step()
            steps += 1
            assert steps < 200
        pkg = peng.export_request(peng.migratable()[0])
        # payload geometry: one [2L, kv_heads, page, head_dim] per page
        mcfg = model.config
        assert [a.shape for a in pkg["pages"]] == \
            [(2 * mcfg.num_layers, mcfg.num_kv_heads, PAGE,
              mcfg.head_dim)] * (-(-17 // PAGE))
        wire = pack_migration(pkg)
        assert wire["wire_bytes"] == migration_nbytes(pkg)
        assert len(wire["page_crc32"]) == len(wire["pages"])
        assert unpack_migration(wire) is wire    # clean frame passes
        # a single flipped byte in any page is caught
        bad = dict(wire)
        tampered = [a.copy() for a in wire["pages"]]
        tampered[-1].view(np.uint8).reshape(-1)[0] ^= 0xFF
        bad["pages"] = tampered
        with pytest.raises(MigrationCorruptError):
            unpack_migration(bad)
        del rt, gid

    def test_crc_corruption_detected_and_retried_fresh_replica(
            self, model):
        """A corrupted frame must not lose or corrupt the request: the
        router resubmits it to the OTHER prefill replica (same trace),
        and the final stream is still bit-identical to unified."""
        solo = _engine(model)
        srid = solo.submit(np.arange(1, 22), max_new_tokens=5)
        want = list(solo.run()[srid].new_tokens)

        p0 = _engine(model, phase="prefill")
        p1 = _engine(model, phase="prefill")
        deng = _engine(model, phase="decode")
        rt = Router([("p0", p0), ("p1", p1), ("d0", deng)])

        class _CorruptOnce(KVMigrator):
            def _transmit(self, wire):
                if not getattr(self, "tampered", False):
                    self.tampered = True
                    pages = [a.copy() for a in wire["pages"]]
                    pages[0].view(np.uint8).reshape(-1)[3] ^= 0xFF
                    wire = dict(wire, pages=pages)
                return wire

        rt.migrator = _CorruptOnce(rt.migrator.decode)
        m = serving_metrics()
        crc0 = m["migrations"].value(result="crc_error")
        retry0 = m["router_requests"].value(replica="p1",
                                            decision="retry")

        tp = format_traceparent(make_trace_id(), make_span_id())
        gid = rt.submit(np.arange(1, 22), max_new_tokens=5,
                        traceparent=tp)
        res = rt.run(max_steps=2000)
        req = res[gid]
        assert list(req.new_tokens) == want
        assert m["migrations"].value(result="crc_error") - crc0 == 1
        # both empty replicas tie on load, so the first placement goes
        # to p0 and the retry MUST land on the fresh replica p1
        assert m["router_requests"].value(replica="p1",
                                          decision="retry") - retry0 == 1
        # the retried request kept the router's trace identity
        assert req.trace_id == tp.split("-")[1]

    def test_decode_backpressure_parks_rows_until_capacity(self, model):
        """A saturated decode replica refuses imports; parked rows keep
        their pages on the prefill side and drain as capacity frees —
        nothing is lost, everything stays bit-identical."""
        solo = _engine(model)
        prompts = [np.arange(1 + i, 15 + i) for i in range(5)]
        want = []
        for p in prompts:
            rid = solo.submit(p, max_new_tokens=6)
            want.append(list(solo.run()[rid].new_tokens))

        peng = _engine(model, phase="prefill")
        deng = _engine(model, phase="decode", max_batch=1)
        rt = Router([("p0", peng), ("d0", deng)])
        m = serving_metrics()
        refused0 = m["migrations"].value(result="refused")
        gids = [rt.submit(p, max_new_tokens=6) for p in prompts]
        res = rt.run(max_steps=3000)
        assert [list(res[g].new_tokens) for g in gids] == want
        # the 1-slot decode replica must actually have pushed back
        assert m["migrations"].value(result="refused") > refused0

    def test_trace_stitches_across_prefill_migrate_decode(self, model):
        peng = _engine(model, phase="prefill")
        deng = _engine(model, phase="decode")
        rt = Router([("p0", peng), ("d0", deng)])
        tid = make_trace_id()
        tp = format_traceparent(tid, make_span_id())
        gid = rt.submit(np.arange(1, 20), max_new_tokens=4,
                        traceparent=tp)
        req = rt.run()[gid]
        # one trace id across both replicas; the decode-side span's
        # parent is the prefill-side request span
        assert req.trace_id == tid
        ptrace = peng.export_request_traces()
        devents = deng.export_request_traces()["traceEvents"]
        pevents = ptrace["traceEvents"]
        assert any(e["args"].get("trace_id") == tid for e in pevents)
        assert any(e["args"].get("trace_id") == tid for e in devents)
        assert any(e["name"] == "migrate_out" for e in pevents)
        assert any(e["name"] == "migrate_in" for e in devents)
        pspan = next(e["args"]["span_id"] for e in pevents
                     if e["args"].get("trace_id") == tid)
        assert req.parent_span_id == pspan


# ---------------------------------------------------------------------------
# the front door: health -> affinity -> least-loaded, HTTP surface
# ---------------------------------------------------------------------------
class TestRouterSteering:
    def test_prefix_affinity_steers_to_warm_replica(self, model):
        e0 = _engine(model, prefix_cache=True)
        e1 = _engine(model, prefix_cache=True)
        rt = Router([("r0", e0), ("r1", e1)])
        m = serving_metrics()
        aff0 = m["router_requests"].value(replica="r0",
                                          decision="affinity")
        sysp = np.arange(1, 1 + 2 * PAGE)       # two full shared pages
        g0 = rt.submit(sysp, max_new_tokens=2)  # cold: least-loaded->r0
        rt.run()
        assert e0.finished and not e1.finished
        tail = np.arange(200, 206)
        g1 = rt.submit(np.concatenate([sysp, tail]), max_new_tokens=2)
        rt.run()
        assert rt.result(g1) is not None
        # the shared-prefix request steered to the replica holding the
        # pages, and actually hit its cache
        assert m["router_requests"].value(replica="r0",
                                          decision="affinity") \
            - aff0 == 1
        assert e0.prefix_cache_stats()["hits"] >= 1
        del g0

    def test_degraded_replica_skipped_until_fleet_wide(self, model):
        e0 = _engine(model)
        e1 = _engine(model)
        rt = Router([("r0", e0), ("r1", e1)])
        e0.health = lambda: "degraded"          # shedding replica
        gid = rt.submit(np.arange(1, 10), max_new_tokens=2)
        rt.run()
        assert rt.result(gid) is not None
        assert e1.finished and not e0.finished
        assert rt.healthz()["status"] == "degraded"
        # a fully-degraded pool still serves (shed beats blackhole)
        e1.health = lambda: "degraded"
        gid2 = rt.submit(np.arange(1, 10), max_new_tokens=2)
        rt.run()
        assert rt.result(gid2) is not None

    def test_collector_overlay_filters_remote_degraded(self, model):
        """A FleetCollector-style overlay (member_health verdicts from
        scraped /healthz + staleness) vetoes replicas the in-process
        signal can't see failing."""
        class _Overlay:
            def __init__(self, bad):
                self.bad = set(bad)

            def member_health(self, name):
                return {"status": "degraded" if name in self.bad
                        else "ok", "reason": "stale"}

        e0, e1 = _engine(model), _engine(model)
        rt = Router([("r0", e0), ("r1", e1)],
                    collector=_Overlay(["r0"]))
        gid = rt.submit(np.arange(1, 12), max_new_tokens=2)
        rt.run()
        assert rt.result(gid) is not None
        assert e1.finished and not e0.finished
        hz = rt.healthz()
        assert hz["status"] == "degraded"
        assert hz["replicas"]["r0"]["health"] == "degraded"

    def test_http_front_door_round_trip(self, model):
        solo = _engine(model)
        srid = solo.submit(np.arange(1, 14), max_new_tokens=3)
        want = list(solo.run()[srid].new_tokens)

        peng = _engine(model, phase="prefill")
        deng = _engine(model, phase="decode")
        rt = Router([("p0", peng), ("d0", deng)])
        tp = format_traceparent(make_trace_id(), make_span_id())
        out = {}

        with RouterServer(rt) as srv:
            def client():
                req = urllib.request.Request(
                    srv.url + "/v1/generate",
                    data=json.dumps(
                        {"prompt": list(range(1, 14)),
                         "max_new_tokens": 3}).encode(),
                    headers={"Content-Type": "application/json",
                             "traceparent": tp})
                with urllib.request.urlopen(req, timeout=60) as r:
                    out["resp"] = json.loads(r.read())

            t = threading.Thread(target=client, daemon=True)
            t.start()
            deadline = time.monotonic() + 60
            while not rt.pending:       # wait for the POST to enqueue
                assert time.monotonic() < deadline
                time.sleep(0.005)
            srv.serve_pending()
            t.join(timeout=30)
            assert not t.is_alive()
            hz = json.loads(urllib.request.urlopen(
                srv.url + "/healthz", timeout=30).read())
            st = json.loads(urllib.request.urlopen(
                srv.url + "/stats", timeout=30).read())
        assert out["resp"]["tokens"] == want
        assert out["resp"]["trace_id"] == tp.split("-")[1]
        assert out["resp"]["shed_reason"] is None
        assert hz["status"] == "ok"
        assert set(hz["replicas"]) == {"p0", "d0"}
        assert st["migrated"] == 1

    def test_decode_replica_refuses_direct_submission(self, model):
        deng = _engine(model, phase="decode")
        with pytest.raises(Exception):
            deng.submit(np.arange(1, 10), max_new_tokens=2)


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------
class TestTraceParentSatellite:
    def test_malformed_traceparent_mints_fresh_id_and_counts(
            self, model):
        eng = _engine(model)
        m = serving_metrics()
        c0 = m["trace_parse_errors"].value(reason="malformed_traceparent")
        rid = eng.submit(np.arange(1, 10), max_new_tokens=2,
                         trace_id="00-zz-bad-header")
        req = eng.run()[rid]
        assert req.trace_id is not None and len(req.trace_id) == 32
        assert m["trace_parse_errors"].value(
            reason="malformed_traceparent") - c0 == 1

    def test_invalid_bare_trace_id_counts_separately(self, model):
        eng = _engine(model)
        m = serving_metrics()
        c0 = m["trace_parse_errors"].value(reason="invalid_trace_id")
        rid = eng.submit(np.arange(1, 10), max_new_tokens=2,
                         trace_id="nothex")
        req = eng.run()[rid]
        assert req.trace_id is not None and len(req.trace_id) == 32
        assert m["trace_parse_errors"].value(
            reason="invalid_trace_id") - c0 == 1


class TestPrefixGaugeSatellite:
    def test_prefix_hash_entries_gauge_tracks_table(self, model):
        eng = _engine(model, prefix_cache=True)
        eng.submit(np.arange(1, 1 + 3 * PAGE), max_new_tokens=2)
        eng.run()
        m = serving_metrics()
        assert m["prefix_hash_entries"].value() == len(eng._hash_page)
        assert m["prefix_hash_entries"].value() >= 3


class TestDisaggLintPins:
    def test_new_files_lint_zero_findings(self):
        """The router and the migration wire join serving.py's pinned
        zero-baseline scope: every tpulint rule (shared-mutation and
        blocking-under-lock included) must report NOTHING on them."""
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths(
            [REPO / "paddle_tpu/inference/router.py",
             REPO / "paddle_tpu/inference/disagg.py"],
            ALL_RULES, root=REPO)
        assert findings == [], "\n".join(
            f"{f.path}:{f.line}: {f.rule}: {f.message}"
            for f in findings)

    def test_new_files_inside_shared_mutation_scope(self):
        from tools.tpulint.rules.shared_mutation import _in_scope

        assert _in_scope("paddle_tpu/inference/router.py")
        assert _in_scope("paddle_tpu/inference/disagg.py")

    def test_migrate_axis_vocabulary(self):
        assert MIGRATE_AXES == ("migrate",)
