"""Ring attention / context parallelism ('sep') tests.

The reference has no CP/ring attention (SURVEY.md §2.4) — this is the
planned superset feature; parity is checked against plain attention."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.ops.attention import flash_attention
from paddle_tpu.ops.ring_attention import ring_attention, \
    ring_flash_attention

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def test_ring_equals_flash_single_device():
    """axes=() ring (one block) reproduces plain causal attention."""
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 16, 4, 8
    q = paddle.to_tensor(rng.randn(B, S, H, D).astype("float32"))
    k = paddle.to_tensor(rng.randn(B, S, H, D).astype("float32"))
    v = paddle.to_tensor(rng.randn(B, S, H, D).astype("float32"))
    out_r = ring_flash_attention(q, k, v, axes=(), causal=True)
    out_f = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_r._value),
                               np.asarray(out_f._value), rtol=1e-5,
                               atol=1e-5)


def test_ring_attention_sep_parity():
    """sep=4 ring attention == full attention on the gathered sequence."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4,
                               "mp_degree": 1, "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    rng = np.random.RandomState(1)
    B, S, H, D = 2, 32, 4, 8
    qkv = [rng.randn(B, S, H, D).astype("float32") for _ in range(3)]
    golden = flash_attention(*[paddle.to_tensor(a) for a in qkv],
                             causal=True)

    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import collective as C

    def run(q, k, v):
        with C.spmd_region():
            # shard seq over sep, run the ring, gather back
            outs = []
            idx = C.axis_index(("sep",))
            loc = S // 4
            ql, kl, vl = (lax.dynamic_slice_in_dim(a, idx * loc, loc, 1)
                          for a in (q, k, v))
            o = ring_flash_attention(
                paddle.Tensor(ql), paddle.Tensor(kl), paddle.Tensor(vl),
                axes=("sep",), causal=True)
            return lax.all_gather(o._value, "sep", axis=1, tiled=True)

    try:
        from jax import shard_map as _sm

        def shard_map(f, mesh, in_specs, out_specs):
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
    except Exception:
        from jax.experimental.shard_map import shard_map as _sms

        def shard_map(f, mesh, in_specs, out_specs):
            return _sms(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)

    f = shard_map(run, hcg.mesh, (P(), P(), P()), P())
    out = jax.jit(f)(*qkv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden._value),
                               rtol=1e-4, atol=1e-5)


def test_gpt_context_parallel_parity():
    """GPT with sep=4 context parallelism matches single-device training
    losses (exact ring attention + block position offsets)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4,
                               "mp_degree": 1, "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_tiny)

    cfg = gpt_tiny()
    paddle.seed(21)
    model = GPTForCausalLM(cfg)
    golden = GPTForCausalLM(cfg)
    golden.set_state_dict(model.state_dict())
    crit = GPTPretrainingCriterion(cfg)

    ids = np.random.RandomState(2).randint(0, cfg.vocab_size, (4, 32))

    g_opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=golden.parameters())
    g_losses = []
    for _ in range(3):
        loss = crit(golden(paddle.to_tensor(ids)), paddle.to_tensor(ids))
        loss.backward()
        g_opt.step()
        g_opt.clear_grad()
        g_losses.append(float(loss))

    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    for i in range(3):
        loss = step({"x": paddle.to_tensor(ids), "y": paddle.to_tensor(ids)})
        np.testing.assert_allclose(float(loss), g_losses[i], rtol=2e-4,
                                   atol=1e-6, err_msg=f"step {i}")


def test_masked_loss_unbalanced_split_parity():
    """Masked LM loss with wildly unbalanced mask across dp ranks must
    equal the single-device masked mean (global num/den, not
    mean-of-local-means)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                                   gpt_tiny)

    cfg = gpt_tiny()
    paddle.seed(31)
    model = GPTForCausalLM(cfg)
    golden = GPTForCausalLM(cfg)
    golden.set_state_dict(model.state_dict())
    crit = GPTPretrainingCriterion(cfg)

    rng = np.random.RandomState(5)
    ids = rng.randint(0, cfg.vocab_size, (8, 16))
    mask = np.zeros((8, 16), dtype="float32")
    mask[0, :] = 1.0          # almost all valid tokens on rank 0
    mask[1:, 0] = 1.0         # one valid token on each other rank

    g_loss = crit(golden(paddle.to_tensor(ids)), paddle.to_tensor(ids),
                  paddle.to_tensor(mask))

    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(
        lambda m, b: crit(m(b["x"]), b["y"], b["mask"]))
    loss = step({"x": paddle.to_tensor(ids), "y": paddle.to_tensor(ids),
                 "mask": paddle.to_tensor(mask)})
    np.testing.assert_allclose(float(loss), float(g_loss), rtol=1e-4,
                               atol=1e-6)
