"""Distribution tail (reference: python/paddle/distribution/) — scipy
log-prob parity, moment checks, transform roundtrips with numeric
log-det verification."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _t(x):
    return paddle.to_tensor(np.asarray(x, "float32"))


def test_gamma():
    paddle.seed(0)
    g = D.Gamma(_t(2.0), _t(3.0))
    s = np.asarray(g.sample([20000])._value)
    assert abs(s.mean() - 2 / 3) < 0.02
    assert abs(float(g.log_prob(_t(0.5))._value)
               - st.gamma.logpdf(0.5, 2, scale=1 / 3)) < 1e-4
    assert abs(float(g.entropy()._value)
               - st.gamma.entropy(2, scale=1 / 3)) < 1e-4


def test_poisson_binomial_geometric():
    po = D.Poisson(_t(4.0))
    assert abs(float(po.log_prob(_t(3.0))._value)
               - st.poisson.logpmf(3, 4)) < 1e-4
    bi = D.Binomial(_t(10.0), _t(0.3))
    assert abs(float(bi.log_prob(_t(4.0))._value)
               - st.binom.logpmf(4, 10, 0.3)) < 1e-4
    assert abs(float(bi.mean._value if hasattr(bi.mean, "_value")
                     else bi.mean) - 3.0) < 1e-5
    ge = D.Geometric(_t(0.25))
    # scipy's geom counts the success trial; ours counts failures
    assert abs(float(ge.log_prob(_t(2.0))._value)
               - st.geom.logpmf(3, 0.25)) < 1e-4


def test_cauchy():
    ca = D.Cauchy(_t(1.0), _t(2.0))
    assert abs(float(ca.log_prob(_t(0.0))._value)
               - st.cauchy.logpdf(0.0, 1.0, 2.0)) < 1e-4
    assert abs(float(ca.entropy()._value)
               - st.cauchy.entropy(1.0, 2.0)) < 1e-4


def test_continuous_bernoulli():
    paddle.seed(1)
    cb = D.ContinuousBernoulli(_t(0.8))
    s = np.asarray(cb.sample([20000])._value)
    assert (s >= 0).all() and (s <= 1).all()
    # density integrates to ~1
    xs = np.linspace(1e-3, 1 - 1e-3, 2001).astype("float32")
    ps = np.exp(np.asarray(cb.log_prob(_t(xs))._value))
    assert abs(np.trapezoid(ps, xs) - 1.0) < 1e-2


def test_multivariate_normal():
    paddle.seed(2)
    cov = np.array([[2.0, 0.3], [0.3, 1.0]])
    mvn = D.MultivariateNormal(_t([0.0, 1.0]), covariance_matrix=_t(cov))
    v = np.array([0.5, 0.2], "float32")
    assert abs(float(mvn.log_prob(_t(v))._value)
               - st.multivariate_normal.logpdf(v, [0, 1], cov)) < 1e-4
    samp = np.asarray(mvn.sample([30000])._value)
    assert np.abs(np.cov(samp.T) - cov).max() < 0.1
    assert abs(float(mvn.entropy()._value)
               - st.multivariate_normal([0, 1], cov).entropy()) < 1e-4


def test_independent():
    ind = D.Independent(D.Normal(_t(np.zeros(3)), _t(np.ones(3))), 1)
    lp = ind.log_prob(_t(np.zeros(3)))
    assert lp.shape == []
    assert abs(float(lp) - 3 * st.norm.logpdf(0)) < 1e-4


@pytest.mark.parametrize("tr,x0", [
    (D.ExpTransform(), 0.3),
    (D.SigmoidTransform(), 0.4),
    (D.TanhTransform(), 0.2),
    (D.AffineTransform(paddle.to_tensor(1.0), paddle.to_tensor(3.0)),
     0.7),
    (D.PowerTransform(paddle.to_tensor(2.0)), 0.6),
])
def test_transform_roundtrip_and_logdet(tr, x0):
    x = _t(x0)
    y = tr.forward(x)
    assert abs(float(tr.inverse(y)._value) - x0) < 1e-5
    fldj = float(tr.forward_log_det_jacobian(x)._value)
    num = np.log(abs((tr._forward(np.float32(x0 + 1e-4))
                      - tr._forward(np.float32(x0 - 1e-4))) / 2e-4))
    assert abs(fldj - num) < 1e-2
    # inverse log det = -forward log det at the preimage
    ildj = float(tr.inverse_log_det_jacobian(y)._value)
    assert abs(ildj + fldj) < 1e-4


def test_stick_breaking():
    sb = D.StickBreakingTransform()
    x = _t(np.array([0.2, -0.3, 0.4]))
    y = sb.forward(x)
    yv = np.asarray(y._value)
    assert abs(yv.sum() - 1.0) < 1e-5 and (yv > 0).all()
    assert yv.shape == (4,)
    np.testing.assert_allclose(np.asarray(sb.inverse(y)._value),
                               np.asarray(x._value), atol=1e-4)


def test_chain_and_reshape():
    ch = D.ChainTransform([D.ExpTransform(),
                           D.AffineTransform(_t(1.0), _t(2.0))])
    x = _t(0.5)
    y = ch.forward(x)
    assert abs(float(y._value) - (1 + 2 * np.exp(0.5))) < 1e-5
    assert abs(float(ch.inverse(y)._value) - 0.5) < 1e-5
    rs = D.ReshapeTransform((2, 3), (6,))
    out = rs.forward(_t(np.zeros((5, 2, 3))))
    assert out.shape == [5, 6]


def test_transformed_distribution_lognormal():
    td = D.TransformedDistribution(D.Normal(_t(0.0), _t(1.0)),
                                   [D.ExpTransform()])
    assert abs(float(td.log_prob(_t(2.0))._value)
               - st.lognorm.logpdf(2.0, 1.0)) < 1e-4
    paddle.seed(3)
    s = np.asarray(td.sample([20000])._value)
    assert abs(np.median(s) - 1.0) < 0.05  # median of lognormal = 1


def test_independent_transform():
    it = D.IndependentTransform(D.ExpTransform(), 1)
    x = _t(np.array([0.1, 0.2, 0.3]))
    fldj = it.forward_log_det_jacobian(x)
    assert abs(float(fldj._value) - 0.6) < 1e-5  # sum of x
