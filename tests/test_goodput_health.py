"""Run-level goodput ledger + training health monitor.

Under test:
- observability/goodput.py — the closed segment taxonomy, the
  crash-durable JSONL journal (dangling-tail close as
  recovery_restart), nesting pause/resume disjointness, the wall-sum
  identity, offline summarize(), the no-op-when-detached hook
- observability/healthmon.py — rolling median+MAD spike/stall events
  (failpoint-driven loss-spike injection, nonfinite loss, silence on
  smooth descent), flight-record dump, /healthz degraded component,
  single-process straggler gauges
- ParallelEngine wiring — compile vs step_compute attribution, zero
  recompiles and bit-identical losses with the instrumentation on,
  goodput/health gauges in the registry snapshot
- CompileStats across restore_checkpoint — restore books NO compile
  and NO recompile, on the engine counters AND the registry counters
- ServingEngine — shed decisions land in the span ring as zero-length
  "shed" events, exported as Chrome "i" instants
- tools/run_report.py — journal waterfall/timeline + BENCH goodput
  trajectory; tools/step_report.py --strict goodput gate
- SIGKILL matrix (slow): a kill mid-segment leaves a parseable
  journal; the relaunch closes it as recovery_restart and the
  cross-restart goodput_pct matches the straight run
"""
import json
import os
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.distributed import failpoints as fp
from paddle_tpu.observability import goodput as gp
from paddle_tpu.observability import healthmon as hm


@pytest.fixture(autouse=True)
def _clean_goodput_and_failpoints():
    gp.detach()
    fp.clear()
    hm.reset_monitor()
    yield
    gp.detach()
    fp.clear()
    hm.reset_monitor()


def _journal(base):
    return os.path.join(str(base), gp.JOURNAL_NAME)


# ---------------------------------------------------------------------------
# the ledger itself (pure host-side)
# ---------------------------------------------------------------------------
class TestGoodputLedger:
    def test_segments_journal_and_summary(self, tmp_path):
        led = gp.attach_dir(str(tmp_path))
        with gp.segment("step_compute"):
            time.sleep(0.03)
        with gp.segment("input_wait"):
            time.sleep(0.01)
        s = led.summary()
        assert s["segments"]["step_compute"] >= 0.03
        assert s["segments"]["input_wait"] >= 0.01
        assert s["goodput_pct"] > 0
        # the journal holds begin AND end lines, parseable
        recs = gp.read_journal(_journal(tmp_path))
        assert any(r["ev"] == "b" and r["seg"] == "step_compute"
                   for r in recs)
        assert any(r["ev"] == "e" and r["seg"] == "input_wait"
                   for r in recs)

    def test_wall_sum_identity(self, tmp_path):
        led = gp.attach_dir(str(tmp_path))
        for seg in ("compile", "step_compute", "ckpt_stall"):
            with gp.segment(seg):
                time.sleep(0.01)
        time.sleep(0.02)                      # unattributed -> idle
        s = led.summary()
        fg = sum(s["segments"].values())      # incl. synthesized idle
        assert fg == pytest.approx(s["wall_seconds"],
                                   rel=0.01, abs=1e-6)
        assert s["segments"]["idle"] >= 0.015

    def test_nested_segment_pauses_outer(self, tmp_path):
        """An inner segment PAUSES the outer: closed foreground
        intervals are disjoint, so compile-inside-step never double
        counts."""
        led = gp.attach_dir(str(tmp_path))
        with gp.segment("step_compute"):
            time.sleep(0.02)
            with gp.segment("compile"):
                time.sleep(0.03)
            time.sleep(0.02)
        s = led.summary()
        assert s["segments"]["compile"] >= 0.03
        assert s["segments"]["step_compute"] >= 0.04
        # disjoint: totals never exceed wall
        assert sum(s["segments"].values()) <= s["wall_seconds"] + 1e-6
        # the journal shows the split: two step_compute intervals
        recs = [r for r in gp.read_journal(_journal(tmp_path))
                if r["ev"] == "e" and r["seg"] == "step_compute"]
        assert len(recs) == 2

    def test_overlapped_background_excluded_from_wall_sum(self,
                                                          tmp_path):
        led = gp.attach_dir(str(tmp_path))
        t0 = time.time()
        with gp.segment("step_compute"):
            time.sleep(0.02)
        led.record_overlapped("ckpt_async", t0, time.time())
        s = led.summary()
        assert s["overlapped_seconds"]["ckpt_async"] >= 0.02
        assert "ckpt_async" not in s["segments"]

    def test_detached_segment_is_noop(self, tmp_path):
        assert gp.current() is None
        with gp.segment("step_compute"):
            pass
        gp.note_event("nothing")
        assert not os.path.exists(_journal(tmp_path))

    def test_same_dir_reattach_is_not_a_restart(self, tmp_path):
        led = gp.attach_dir(str(tmp_path))
        with gp.segment("step_compute"):
            pass
        assert gp.attach_dir(str(tmp_path)) is led
        assert led.summary()["restarts"] == 0

    def test_dangling_segment_closed_as_recovery_restart(self,
                                                         tmp_path):
        """Crash mid-segment: the journal stays parseable and the next
        process (a fresh ledger object on the same path) closes the
        dangling tail as recovery_restart."""
        led = gp.attach_dir(str(tmp_path))
        with gp.segment("step_compute"):
            time.sleep(0.02)
        led.begin("ckpt_stall")               # ... SIGKILL here
        time.sleep(0.05)
        led2 = gp.GoodputLedger(_journal(tmp_path))
        s = led2.summary()
        assert s["restarts"] == 1
        assert s["segments"]["recovery_restart"] >= 0.045
        assert s["segments"]["step_compute"] >= 0.02
        recs = gp.read_journal(_journal(tmp_path))
        rr = [r for r in recs if r.get("seg") == "recovery_restart"
              and r["ev"] == "e"]
        assert len(rr) == 1
        # offline summarize agrees with the live view
        off = gp.summarize(recs)
        assert off["restarts"] == 1
        assert off["segments"]["recovery_restart"] == pytest.approx(
            s["segments"]["recovery_restart"], abs=0.05)

    def test_truncated_tail_line_tolerated(self, tmp_path):
        led = gp.attach_dir(str(tmp_path))
        with gp.segment("step_compute"):
            time.sleep(0.01)
        # a kill mid-write can truncate the last line
        with open(_journal(tmp_path), "a") as f:
            f.write('{"ev": "b", "seg": "ckpt_st')
        led2 = gp.GoodputLedger(_journal(tmp_path))
        s = led2.summary()
        assert s["restarts"] == 1
        assert s["segments"]["step_compute"] >= 0.01

    def test_events_journaled(self, tmp_path):
        led = gp.attach_dir(str(tmp_path))
        gp.note_event("loss_spike", step=7, value=123.0)
        recs = gp.read_journal(_journal(tmp_path))
        ev = [r for r in recs if r.get("ev") == "h"]
        assert len(ev) == 1 and ev[0]["kind"] == "loss_spike"
        assert ev[0]["step"] == 7
        assert led.summary()["events"] == 1


# ---------------------------------------------------------------------------
# health monitor
# ---------------------------------------------------------------------------
class TestHealthMonitor:
    def test_failpoint_injected_loss_spike(self, tmp_path, monkeypatch):
        """The acceptance path: a deliberately injected loss spike is
        detected within the window — event + flight record + degraded
        status — and the event is journaled to the goodput ledger."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        gp.attach_dir(str(tmp_path))
        mon = hm.HealthMonitor(warmup=8, flight_min_interval_s=0.0)
        fired = []
        with fp.scoped("health.loss_spike=corrupt@12"):
            for i in range(12):
                fired += mon.observe(loss=2.0 + 0.01 * (i % 3),
                                     grad_norm=1.0, step=i)
        assert len(fired) == 1
        ev = fired[0]
        assert ev["kind"] == "loss_spike" and ev["step"] == 11
        assert ev["z"] > 6.0
        assert mon.status() == "degraded"
        assert mon.event_count("loss_spike") == 1
        # the flight record exists and names the spike
        assert os.path.isfile(ev["flight_record"])
        with open(ev["flight_record"]) as f:
            assert "loss_spike" in json.load(f)["reason"]
        # durable: the goodput journal carries it
        recs = gp.read_journal(_journal(tmp_path))
        assert any(r.get("ev") == "h" and r.get("kind") == "loss_spike"
                   for r in recs)
        # counters in the registry
        reg = obs.get_registry().snapshot()["metrics"]
        series = reg["paddle_tpu_health_events_total"]["series"]
        vals = {s["labels"]["kind"]: s["value"] for s in series}
        assert vals.get("loss_spike", 0) >= 1

    def test_silent_on_smooth_descent(self):
        mon = hm.HealthMonitor(warmup=8)
        for i in range(50):
            mon.observe(loss=5.0 * 0.95 ** i,
                        grad_norm=2.0 + 0.05 * (i % 5),
                        step_seconds=0.01 + 0.001 * (i % 4))
        assert mon.event_count() == 0
        assert mon.status() == "ok"

    def test_nonfinite_loss_always_fires(self):
        mon = hm.HealthMonitor(warmup=8, flight_on_spike=False)
        ev = mon.observe(loss=float("nan"), step=3)
        assert ev and ev[0]["kind"] == "loss_nonfinite"
        assert mon.status() == "degraded"

    def test_grad_norm_spike(self):
        mon = hm.HealthMonitor(warmup=8, flight_on_spike=False)
        for i in range(10):
            mon.observe(grad_norm=1.0 + 0.02 * (i % 4))
        ev = mon.observe(grad_norm=500.0, step=10)
        assert ev and ev[0]["kind"] == "grad_norm_spike"

    def test_unarmed_below_warmup(self):
        mon = hm.HealthMonitor(warmup=8, flight_on_spike=False)
        for i in range(4):
            mon.observe(loss=1.0)
        assert not mon.observe(loss=1e9)      # still warming up
        assert mon.event_count() == 0

    def test_healthz_degraded_component(self, tmp_path, monkeypatch):
        from paddle_tpu.observability.exporter import serve_metrics

        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        mon = hm.get_monitor()
        mon.flight_on_spike = False
        mon.observe(loss=float("inf"))        # degrade
        with serve_metrics(0) as srv:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz") as resp:
                doc = json.loads(resp.read())
        assert doc["status"] == "degraded"
        comps = {c["component"]: c["status"]
                 for c in doc.get("components", [])}
        assert comps.get("healthmon") == "degraded"
        hm.reset_monitor()
        assert hm.get_monitor().status() == "ok"

    def test_single_process_skew(self):
        mon = hm.HealthMonitor()
        rep = mon.observe_pod_skew(0.25)
        assert rep["step_time_skew"] == 0.0
        assert rep["slowest_host"] == 0.0
        assert rep["host_step_seconds"] == [0.25]


# ---------------------------------------------------------------------------
# engine wiring (compile vs step_compute; zero perturbation)
# ---------------------------------------------------------------------------
def _tiny_engine(seed=3):
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=16)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    r = np.random.RandomState(0)
    ids = r.randint(0, 64, (2, 9))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    return eng, step, batch


class TestEngineGoodputWiring:
    def test_compile_then_step_compute_attribution(self, tmp_path):
        obs.reset_registry()
        led = gp.attach_dir(str(tmp_path))
        eng, step, batch = _tiny_engine()
        losses = [float(step(batch)) for _ in range(3)]
        s = led.summary()
        # first call traced+compiled under "compile"; the rest are
        # productive step_compute
        assert s["segments"]["compile"] > 0
        assert s["segments"]["step_compute"] > 0
        assert eng.stats.compiles == 1
        recs = gp.read_journal(_journal(tmp_path))
        comp = [r for r in recs if r["ev"] == "e"
                and r["seg"] == "compile"]
        steps = [r for r in recs if r["ev"] == "e"
                 and r["seg"] == "step_compute"]
        assert len(comp) == 1
        assert len(steps) == 2
        # the step index rides on the begin records
        assert [r.get("step") for r in recs
                if r["ev"] == "b" and r["seg"] == "compile"] == [1]
        # goodput gauges in the snapshot
        m = eng.metrics_snapshot()["metrics"]
        assert m["paddle_tpu_goodput_pct"]["series"][0]["value"] > 0
        segs = {s_["labels"]["segment"]: s_["value"] for s_ in
                m["paddle_tpu_goodput_segment_seconds"]["series"]}
        assert segs["compile"] > 0 and segs["step_compute"] > 0
        assert losses[0] != losses[1]         # it actually trained

    def test_instrumentation_changes_nothing(self, tmp_path):
        """Bit-identical losses and an identical compile count with
        the ledger attached vs detached — the same discipline the
        comm/mem ledgers are held to."""
        obs.reset_registry()
        gp.detach()
        eng_a, step_a, batch_a = _tiny_engine(seed=5)
        gold = [float(step_a(batch_a)) for _ in range(3)]
        assert eng_a.stats.compiles == 1

        obs.reset_registry()
        gp.attach_dir(str(tmp_path))
        eng_b, step_b, batch_b = _tiny_engine(seed=5)
        got = [float(step_b(batch_b)) for _ in range(3)]
        assert got == gold
        assert eng_b.stats.compiles == 1
        assert eng_b.stats.cache_hits == 2

    def test_health_gauges_fed_by_engine(self):
        obs.reset_registry()
        eng, step, batch = _tiny_engine(seed=7)
        for _ in range(3):
            float(step(batch))
        m = eng.metrics_snapshot()["metrics"]
        assert "paddle_tpu_health_loss_zscore" in m
        assert "paddle_tpu_health_degraded" in m
        assert m["paddle_tpu_health_degraded"]["series"][0]["value"] \
            == 0.0
        assert eng._health.event_count() == 0
        rep = eng.pod_step_skew()
        assert rep["step_time_skew"] == 0.0

    def test_per_engine_windows_never_mix_runs(self):
        """A fresh model's first loss is judged against ITS OWN empty
        window, never another engine's converged baseline — two
        back-to-back runs raise zero events even though run B's first
        loss towers over run A's last."""
        obs.reset_registry()
        eng_a, step_a, batch_a = _tiny_engine(seed=5)
        for _ in range(10):
            float(step_a(batch_a))
        eng_b, step_b, batch_b = _tiny_engine(seed=6)
        for _ in range(3):
            float(step_b(batch_b))
        assert eng_a._health.event_count() == 0
        assert eng_b._health.event_count() == 0
        assert eng_a._health is not eng_b._health

    def test_scaler_absorbed_overflow_not_an_anomaly(self):
        """An AMP-skipped step (found_inf) is protocol: its inf loss
        never reaches the detector, so no loss_nonfinite event and no
        degraded /healthz for a routine scale-calibration step."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.engine import ParallelEngine

        obs.reset_registry()
        paddle.seed(4)
        model = paddle.nn.Linear(8, 8)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=model.parameters())
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        eng = ParallelEngine(model, opt, hcg.mesh)
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 8,
                                       decr_every_n_nan_or_inf=1)
        step = eng.train_step(
            lambda m, b: paddle.mean((m(b["x"]) - b["y"]) ** 2),
            scaler=scaler)
        r = np.random.RandomState(0)
        x = r.randn(4, 8).astype("float32")
        y = r.randn(4, 8).astype("float32")
        float(step({"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}))
        bad = x.copy()
        bad[0, 0] = np.inf
        step({"x": paddle.to_tensor(bad), "y": paddle.to_tensor(y)})
        float(step({"x": paddle.to_tensor(x),
                    "y": paddle.to_tensor(y)}))
        eng.metrics_snapshot()                # flush the lagged fetch
        assert scaler.last_found_inf is False
        assert eng._health.event_count() == 0
        assert eng._health.status() == "ok"


# ---------------------------------------------------------------------------
# CompileStats across restore (satellite: no double-counted compiles)
# ---------------------------------------------------------------------------
class TestCompileStatsAcrossRestore:
    def test_restore_books_no_compile_and_no_recompile(self, tmp_path):
        obs.reset_registry()
        eng, step, batch = _tiny_engine(seed=11)
        for _ in range(2):
            float(step(batch))
        eng.save_checkpoint(str(tmp_path / "ck"), step=2)
        assert eng.stats.compiles == 1
        # sync the registry counters, then restore into the SAME
        # already-compiled engine and step again
        eng.metrics_snapshot()
        reg_compiles = eng._metrics["compiles"].value(
            site="train_engine")
        hits_before = eng.stats.cache_hits
        eng.restore_checkpoint(str(tmp_path / "ck"))
        float(step(batch))
        # engine counters: no compile, exactly one more cache hit
        assert eng.stats.compiles == 1
        assert eng.stats.cache_hits == hits_before + 1
        # registry counters: the compile counter did NOT move (restore
        # must not book warmup compiles as steady-state recompiles)
        eng.metrics_snapshot()
        assert eng._metrics["compiles"].value(site="train_engine") \
            == reg_compiles == 1.0

    def test_fresh_engine_warmup_after_restore_books_once(self,
                                                          tmp_path):
        obs.reset_registry()
        eng, step, batch = _tiny_engine(seed=11)
        for _ in range(2):
            float(step(batch))
        eng.save_checkpoint(str(tmp_path / "ck"), step=2)
        # "relaunched process": fresh registry + fresh engine, restore
        # BEFORE the first step — the warmup compile books exactly
        # once, as a compile, never as a recompile-after-warmup
        obs.reset_registry()
        eng2, step2, batch2 = _tiny_engine(seed=11)
        eng2.restore_checkpoint(str(tmp_path / "ck"))
        assert eng2.stats.compiles == 0       # restore alone: nothing
        float(step2(batch2))
        warm = eng2.stats.compiles
        float(step2(batch2))
        assert warm == 1
        assert eng2.stats.compiles == 1       # 0 recompiles after warmup
        eng2.metrics_snapshot()
        assert eng2._metrics["compiles"].value(site="train_engine") \
            == 1.0


# ---------------------------------------------------------------------------
# serving: shed decisions in the span ring / Chrome export
# ---------------------------------------------------------------------------
class TestServingShedTraces:
    @pytest.fixture(scope="class")
    def tiny_model(self):
        from paddle_tpu.distributed import fleet as _fleet
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        _fleet._fleet_state.update(initialized=False, hcg=None,
                                   strategy=None)
        paddle.seed(11)
        return LlamaForCausalLM(llama_tiny())

    def _engine(self, tiny_model, **kw):
        from paddle_tpu.inference import (Config, ServingEngine,
                                          create_predictor)

        pred = create_predictor(
            Config().set_model(tiny_model).enable_paged_kv(page_size=8))
        return ServingEngine(pred, max_batch=2, **kw)

    def test_shed_span_in_ring_and_chrome_export(self, tiny_model,
                                                 tmp_path):
        eng = self._engine(tiny_model, max_queue=1)
        V = tiny_model.config.vocab_size
        r = np.random.RandomState(0)
        rids = [eng.submit(r.randint(1, V, (4,)), max_new_tokens=2)
                for _ in range(3)]
        shed = [rid for rid in rids if rid in eng.finished
                and eng.finished[rid].shed]
        assert len(shed) == 2
        # the ring holds the shed traces with a zero-length shed span
        by_rid = {t["rid"]: t for t in eng.request_traces()}
        for rid in shed:
            spans = {s["name"]: s for s in by_rid[rid]["spans"]}
            assert spans["shed"]["seconds"] == 0.0
            assert spans["shed"]["meta"]["reason"] == "queue_full"
            assert spans["queued"]["t1"] is not None
        # Chrome export: shed requests appear as "i" instant events
        doc = eng.export_request_traces(str(tmp_path / "t.json"))
        sheds = [e for e in doc["traceEvents"]
                 if e.get("name") == "shed"]
        assert len(sheds) == 2
        assert all(e["ph"] == "i" and e["args"]["reason"] ==
                   "queue_full" for e in sheds)
        assert {e["tid"] for e in sheds} == set(shed)
        with open(tmp_path / "t.json") as f:
            assert json.load(f)["traceEvents"]

    def test_deadline_shed_span_reason(self, tiny_model):
        eng = self._engine(tiny_model, admission_deadline_s=0.0)
        V = tiny_model.config.vocab_size
        rid = eng.submit(np.random.RandomState(1).randint(1, V, (4,)),
                        max_new_tokens=2)
        time.sleep(0.01)
        eng._admit()                          # sheds before prefill
        tr = {t["rid"]: t for t in eng.request_traces()}[rid]
        spans = {s["name"]: s for s in tr["spans"]}
        assert spans["shed"]["meta"]["reason"] == "deadline"
        assert spans["shed"]["meta"]["queued_seconds"] > 0


# ---------------------------------------------------------------------------
# tools: run_report + step_report goodput gate
# ---------------------------------------------------------------------------
def _import_tools():
    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools import run_report as rr
        from tools import step_report as sr
    finally:
        sys.path.remove(str(repo))
    return rr, sr


def _bench_round(n, goodput_pct):
    line = {"metric": "gpt13b_hybrid_smoke_tokens_per_sec",
            "value": 3000.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "roofline": {"bound": "hbm-bound", "step_seconds": 0.01,
                         "seconds": {}, "headroom_pct": {},
                         "util_pct": {}},
            "goodput": {"goodput_pct": goodput_pct,
                        "wall_seconds": 12.5, "restarts": 0,
                        "segment_pct": {"compile": 90.0,
                                        "step_compute": goodput_pct},
                        "segments": {}}}
    return {"n": n, "cmd": "python bench.py", "rc": 0,
            "tail": json.dumps(line)}


class TestRunReportTool:
    def test_journal_report_and_timeline(self, tmp_path, capsys):
        rr, _ = _import_tools()
        led = gp.attach_dir(str(tmp_path))
        with gp.segment("step_compute"):
            time.sleep(0.02)
        gp.note_event("loss_spike", step=4, value=9.0)
        led.begin("ckpt_stall")
        gp.GoodputLedger(_journal(tmp_path))  # the "relaunch"
        rep = rr.journal_report(str(tmp_path))
        assert rep is not None
        assert rep["summary"]["restarts"] == 1
        whats = [e["what"] for e in rep["timeline"]]
        assert "start" in whats and "resume" in whats
        assert "loss_spike" in whats and "recovery_restart" in whats
        assert rr.main(["--run-dir", str(tmp_path),
                        "--bench-dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "goodput waterfall" in out
        assert "step_compute" in out

    def test_bench_trajectory_and_json(self, tmp_path, capsys):
        rr, _ = _import_tools()
        for i, pct in ((1, 40.0), (2, 55.0)):
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(
                json.dumps(_bench_round(i, pct)))
        traj = rr.goodput_trajectory(
            __import__("tools.bench_compare",
                       fromlist=["load_rounds"]).load_rounds(
                           str(tmp_path)))
        assert traj["gpt13b_hybrid_smoke_tokens_per_sec"] == \
            [40.0, 55.0]
        assert rr.main(["--bench-dir", str(tmp_path), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["bench_goodput_trajectory"][
            "gpt13b_hybrid_smoke_tokens_per_sec"] == [40.0, 55.0]

    def test_nothing_found_exit_code(self, tmp_path):
        rr, _ = _import_tools()
        assert rr.main(["--run-dir", str(tmp_path / "none"),
                        "--bench-dir", str(tmp_path)]) == 2


class TestStepReportGoodputGate:
    def test_goodput_rows_and_column(self, tmp_path, capsys):
        _, sr = _import_tools()
        from tools.bench_compare import load_rounds, parse_metrics

        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(_bench_round(1, 61.0)))
        metrics = parse_metrics(load_rounds(str(tmp_path))[-1][1])
        rows = sr.goodput_rows(metrics)
        assert rows[0]["goodput_pct"] == 61.0
        assert sr.main(["--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "goodput" in out and "61.0" in out

    def test_strict_gate_on_regression(self, tmp_path, capsys):
        _, sr = _import_tools()
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(_bench_round(1, 60.0)))
        (tmp_path / "BENCH_r02.json").write_text(
            json.dumps(_bench_round(2, 40.0)))
        # 20pp drop: flagged under --strict, reported otherwise
        assert sr.main(["--dir", str(tmp_path)]) == 0
        assert sr.main(["--dir", str(tmp_path), "--strict"]) == 1
        capsys.readouterr()
        assert sr.main(["--dir", str(tmp_path), "--strict",
                        "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["goodput_regressions"][0]["drop_pp"] == 20.0
        # a generous tolerance passes
        assert sr.main(["--dir", str(tmp_path), "--strict",
                        "--goodput-drop-pp", "25"]) == 0

    def test_strict_ok_within_tolerance(self, tmp_path):
        _, sr = _import_tools()
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps(_bench_round(1, 60.0)))
        (tmp_path / "BENCH_r02.json").write_text(
            json.dumps(_bench_round(2, 58.0)))
        assert sr.main(["--dir", str(tmp_path), "--strict"]) == 0


# ---------------------------------------------------------------------------
# tpulint: the new modules must stay clean with ZERO baseline entries
# ---------------------------------------------------------------------------
def test_tpulint_goodput_surface_zero_baseline():
    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths(
            [repo / "paddle_tpu" / "observability" / "goodput.py",
             repo / "paddle_tpu" / "observability" / "healthmon.py",
             repo / "tools" / "run_report.py"],
            ALL_RULES, root=repo)
    finally:
        sys.path.remove(str(repo))
    assert findings == [], [str(f) for f in findings]


# ---------------------------------------------------------------------------
# SIGKILL matrix (subprocess; the real preemption)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestGoodputSigkillMatrix:
    REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    WORKER = os.path.join(REPO, "tests", "workers",
                          "goodput_crash_worker.py")

    def _run(self, extra_env, vdevs=1, timeout=600):
        import subprocess

        env = dict(os.environ)
        for k in list(env):
            if k.startswith(("PADDLE_", "JAX_", "XLA_")):
                del env[k]
        env["XLA_FLAGS"] = \
            f"--xla_force_host_platform_device_count={vdevs}"
        env["JAX_PLATFORMS"] = "cpu"
        env["OMP_NUM_THREADS"] = "1"
        env.update({k: str(v) for k, v in extra_env.items()})
        p = subprocess.run(
            [sys.executable, self.WORKER], env=env, cwd=self.REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=timeout)
        return p.returncode, p.stdout.decode(errors="replace")[-3000:]

    def _check_journal_and_result(self, base, out, min_restarts=1):
        recs = gp.read_journal(os.path.join(base, gp.JOURNAL_NAME))
        assert recs, "journal missing or unparseable"
        summ = gp.summarize(recs)
        assert summ["restarts"] >= min_restarts
        assert summ["segments"].get("recovery_restart", 0) > 0
        # the wall identity: foreground segments + idle == wall (±1%)
        fg = sum(summ["segments"].values())
        assert fg == pytest.approx(summ["wall_seconds"], rel=0.01,
                                   abs=1e-3)
        with open(out + ".json") as f:
            doc = json.load(f)
        assert doc["start"] > 0               # genuinely resumed
        # the worker's live summary agrees with the offline journal
        assert doc["goodput"]["restarts"] == summ["restarts"]
        return doc

    @pytest.mark.parametrize("site,n", [
        ("ckpt.write_shard", 2),              # mid ckpt_stall segment
        ("ckpt.commit", 2),                   # later in the same stall
        ("engine.step_dispatch", 6),          # between step segments
    ])
    def test_sigkill_leaves_parseable_journal_resume_closes(
            self, tmp_path, site, n):
        base = str(tmp_path / "ck")
        out = str(tmp_path / "p")
        rc, log = self._run({
            "CKPT_BASE": base, "TOTAL_STEPS": 8, "SAVE_EVERY": 2,
            "TEST_OUT": out + "1",
            "PADDLE_TPU_FAILPOINTS": f"{site}=kill@{n}"})
        assert rc == -9, (site, rc, log)
        # the killed run's journal parses and has a run header
        recs = gp.read_journal(os.path.join(base, gp.JOURNAL_NAME))
        assert recs and recs[-1].get("ev") in ("b", "e", "run", "h")
        rc, log = self._run({"CKPT_BASE": base, "TOTAL_STEPS": 8,
                             "SAVE_EVERY": 2, "TEST_OUT": out})
        assert rc == 0, (site, log)
        self._check_journal_and_result(base, out)

    def test_hybrid_crash_goodput_matches_straight_run(self, tmp_path):
        """The acceptance line: on the gpt13b smoke topology,
        5 + SIGKILL + resume + 5 yields ONE journal whose segment sum
        equals wall time and whose goodput_pct lands within 5pp of the
        uninterrupted 10-step run."""
        gold_base = str(tmp_path / "gold_ck")
        rc, log = self._run({
            "CKPT_BASE": gold_base, "TOTAL_STEPS": 10, "SAVE_EVERY": 2,
            "TEST_OUT": str(tmp_path / "gold"), "HYBRID": 1},
            vdevs=8, timeout=900)
        assert rc == 0, log
        with open(str(tmp_path / "gold") + ".json") as f:
            gold = json.load(f)

        base = str(tmp_path / "ck")
        rc, log = self._run({
            "CKPT_BASE": base, "TOTAL_STEPS": 10, "SAVE_EVERY": 2,
            "TEST_OUT": str(tmp_path / "p1"), "HYBRID": 1,
            "PADDLE_TPU_FAILPOINTS": "engine.step_dispatch=kill@6"},
            vdevs=8, timeout=900)
        assert rc == -9, (rc, log)
        rc, log = self._run({
            "CKPT_BASE": base, "TOTAL_STEPS": 10, "SAVE_EVERY": 2,
            "TEST_OUT": str(tmp_path / "p2"), "HYBRID": 1},
            vdevs=8, timeout=900)
        assert rc == 0, log
        doc = self._check_journal_and_result(base,
                                             str(tmp_path / "p2"))
        # loss curve continues the straight run (the PR-10 guarantee,
        # re-checked here because the journal rides the same commit)
        gold_losses = open(str(tmp_path / "gold") + ".log").read()
        resumed = open(str(tmp_path / "p2") + ".log").read()
        assert gold_losses.splitlines()[doc["start"]:] == \
            resumed.splitlines()
        # goodput within 5 percentage points of the straight run
        assert doc["goodput"]["goodput_pct"] == pytest.approx(
            gold["goodput"]["goodput_pct"], abs=5.0)
