"""CSR sparse tensors + SelectedRows embedding-gradient path.

Reference parity targets:
- paddle/phi/core/sparse_csr_tensor.h:32 (crows/cols/values CSR type)
- paddle/phi/core/selected_rows.h:32 (rows+value row-sparse gradient)
- phi/kernels/cpu|gpu/embedding_sparse_grad_kernel.cc (sparse=True
  embedding grad) and the optimizers' *SparseGradKernel family
  (row-wise SGD; Adam lazy_mode).
"""
import numpy as np

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, sparse
from paddle_tpu.framework import SelectedRows, merge_selected_rows


def _np(t):
    return np.asarray(t._value)


class TestCsr:
    def setup_method(self, _):
        self.dense = np.array([[1., 0., 2., 0.],
                               [0., 0., 3., 0.],
                               [4., 5., 0., 0.]], np.float32)
        crows = [0, 2, 3, 5]
        cols = [0, 2, 2, 0, 1]
        vals = [1., 2., 3., 4., 5.]
        self.csr = sparse.sparse_csr_tensor(crows, cols, vals, (3, 4))

    def test_components_and_dense(self):
        assert self.csr.is_sparse_csr() and not self.csr.is_sparse_coo()
        assert self.csr.nnz == 5
        assert (_np(self.csr.crows()) == [0, 2, 3, 5]).all()
        assert (_np(self.csr.cols()) == [0, 2, 2, 0, 1]).all()
        assert np.allclose(_np(self.csr.to_dense()), self.dense)

    def test_csr_matmul_dense(self):
        y = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        out = sparse.matmul(self.csr, paddle.to_tensor(y))
        assert np.allclose(_np(out), self.dense @ y, atol=1e-5)

    def test_masked_matmul_csr_mask(self):
        r = np.random.RandomState(1)
        a = r.randn(3, 8).astype(np.float32)
        b = r.randn(8, 4).astype(np.float32)
        out = sparse.masked_matmul(paddle.to_tensor(a),
                                   paddle.to_tensor(b), self.csr)
        assert out.is_sparse_csr()
        full = a @ b
        expect = np.where(self.dense != 0, full, 0.0)
        assert np.allclose(_np(out.to_dense()), expect, atol=1e-5)

    def test_coo_csr_roundtrip(self):
        coo = self.csr.to_sparse_coo()
        assert coo.is_sparse_coo()
        assert np.allclose(_np(coo.to_dense()), self.dense)
        back = coo.to_sparse_csr()
        assert back.is_sparse_csr()
        assert np.allclose(_np(back.to_dense()), self.dense)


class TestSelectedRows:
    def test_merge_accumulates_duplicates(self):
        sr = SelectedRows([2, 5, 2], np.array([[1., 1.], [2., 2.],
                                               [3., 3.]], np.float32), 8)
        m = merge_selected_rows(sr)
        d = np.asarray(m.to_dense_value())
        assert np.allclose(d[2], [4., 4.]) and np.allclose(d[5], [2., 2.])
        assert np.allclose(d.sum(), 12.0)  # padding slots inert

    def test_sparse_embedding_grad_is_selected_rows(self):
        paddle.seed(0)
        emb = nn.Embedding(50, 4, sparse=True)
        ids = paddle.to_tensor(np.array([[1, 3], [3, 7]]))
        out = emb(ids)
        out.sum().backward()
        g = emb.weight.grad
        assert isinstance(g, SelectedRows)
        assert g.values.shape == (4, 4)       # batch*seq rows, not vocab
        d = np.asarray(g.to_dense_value())
        assert np.allclose(d[3], 2.0)         # id 3 looked up twice
        assert np.allclose(d[1], 1.0) and np.allclose(d[9], 0.0)

    def test_padding_idx_gets_no_grad(self):
        emb = nn.Embedding(10, 3, sparse=True, padding_idx=0)
        out = emb(paddle.to_tensor(np.array([0, 2])))
        out.sum().backward()
        d = np.asarray(emb.weight.grad.to_dense_value())
        assert np.allclose(d[0], 0.0) and np.allclose(d[2], 1.0)

    def _train(self, sparse_flag, opt_cls, steps=5, **okw):
        paddle.seed(7)
        emb = nn.Embedding(30, 8, sparse=sparse_flag)
        lin = nn.Linear(8, 2)
        params = list(emb.parameters()) + list(lin.parameters())
        opt = opt_cls(learning_rate=0.1, parameters=params, **okw)
        r = np.random.RandomState(3)
        ids = r.randint(0, 30, (6, 4))
        y = r.randint(0, 2, (6,))
        losses = []
        for _ in range(steps):
            loss = nn.functional.cross_entropy(
                lin(emb(paddle.to_tensor(ids)).mean(axis=1)),
                paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses, _np(emb.weight)

    def test_sgd_sparse_matches_dense(self):
        """Row-wise SGD on the SelectedRows grad must equal the dense
        path exactly (same math, scatter vs dense add)."""
        l_d, w_d = self._train(False, paddle.optimizer.SGD)
        l_s, w_s = self._train(True, paddle.optimizer.SGD)
        assert np.allclose(l_d, l_s, atol=1e-5), (l_d, l_s)
        assert np.allclose(w_d, w_s, atol=1e-5)

    def test_adam_nonlazy_sparse_matches_dense(self):
        l_d, w_d = self._train(False, paddle.optimizer.Adam)
        l_s, w_s = self._train(True, paddle.optimizer.Adam)
        assert np.allclose(l_d, l_s, atol=1e-5)
        assert np.allclose(w_d, w_s, atol=1e-5)

    def test_adam_lazy_converges(self):
        """lazy_mode touches only looked-up rows; training still
        converges and untouched rows' moments stay zero."""
        paddle.seed(1)
        emb = nn.Embedding(40, 8, sparse=True)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=emb.parameters(),
                                    lazy_mode=True)
        ids = paddle.to_tensor(np.array([1, 2, 3]))
        target = np.ones((3, 8), np.float32)
        losses = []
        for _ in range(40):
            loss = ((emb(ids) - paddle.to_tensor(target)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < 0.3 * losses[0]
        m1 = np.asarray(opt._states[id(emb.weight)]["moment1"])
        assert np.abs(m1[10:]).max() == 0.0   # untouched rows untouched
        assert np.abs(m1[1:4]).max() > 0.0
