"""yolo_loss parity vs an independent naive-loop numpy reference
(reference op: python/paddle/vision/ops.py:58 over the phi yolo_loss
kernel; formulation from the YOLOv3 loss definition in the reference
docstring: sigmoid-CE xy + weighted L1 wh at assigned anchors,
objectness with IoU-ignore, per-class sigmoid CE with label smoothing).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor
from paddle_tpu.vision.ops import yolo_loss


def _sce(logit, target):
    return np.maximum(logit, 0) - logit * target + \
        np.log1p(np.exp(-np.abs(logit)))


def _sig(v):
    return 1.0 / (1.0 + np.exp(-v))


def naive_yolo_loss(x, gt_box, gt_label, anchors, amask, Cn,
                    ignore_thresh, ds, gt_score=None, smooth=True,
                    scale_x_y=1.0):
    N, C, H, W = x.shape
    S = len(amask)
    B = gt_box.shape[1]
    in_w, in_h = ds * W, ds * H
    xf = x.reshape(N, S, 5 + Cn, H, W).astype(np.float64)
    gs = gt_score if gt_score is not None else np.ones((N, B))
    aw = np.asarray(anchors[0::2], float)
    ah = np.asarray(anchors[1::2], float)
    out = np.zeros(N)
    for n in range(N):
        obj_t = np.zeros((S, H, W))
        score_t = np.zeros((S, H, W))
        ignore = np.zeros((S, H, W), bool)
        loss = 0.0
        # per-gt assignment
        for b in range(B):
            cx, cy, w, h = gt_box[n, b]
            if w <= 0:
                continue
            gw, gh = w * in_w, h * in_h
            inter = np.minimum(gw, aw) * np.minimum(gh, ah)
            iou = inter / (gw * gh + aw * ah - inter)
            best = int(np.argmax(iou))
            if best not in amask:
                continue
            s = amask.index(best)
            gi, gj = min(int(cx * W), W - 1), min(int(cy * H), H - 1)
            obj_t[s, gj, gi] = 1.0
            score_t[s, gj, gi] = gs[n, b]
            bw = 2.0 - w * h
            wgt = gs[n, b] * bw
            tx, ty = xf[n, s, 0, gj, gi], xf[n, s, 1, gj, gi]
            tw, th = xf[n, s, 2, gj, gi], xf[n, s, 3, gj, gi]
            loss += (_sce(tx, cx * W - gi) + _sce(ty, cy * H - gj)) * wgt
            loss += (abs(tw - np.log(gw / anchors[2 * best]))
                     + abs(th - np.log(gh / anchors[2 * best + 1]))) * wgt
            # classification at the assigned cell
            pos = 1.0 - 1.0 / Cn if (smooth and Cn > 1) else 1.0
            neg = 1.0 / Cn if (smooth and Cn > 1) else 0.0
            for c in range(Cn):
                t = pos if c == gt_label[n, b] else neg
                loss += _sce(xf[n, s, 5 + c, gj, gi], t) * gs[n, b]
        # objectness with IoU-ignore over decoded predictions
        for s in range(S):
            a = amask[s]
            for gj in range(H):
                for gi in range(W):
                    px = (_sig(xf[n, s, 0, gj, gi]) * scale_x_y
                          - (scale_x_y - 1) / 2 + gi) / W
                    py = (_sig(xf[n, s, 1, gj, gi]) * scale_x_y
                          - (scale_x_y - 1) / 2 + gj) / H
                    pw = np.exp(xf[n, s, 2, gj, gi]) * aw[a] / in_w
                    ph = np.exp(xf[n, s, 3, gj, gi]) * ah[a] / in_h
                    best_iou = 0.0
                    for b in range(B):
                        cx, cy, w, h = gt_box[n, b]
                        if w <= 0:
                            continue
                        ix = max(min(px + pw / 2, cx + w / 2)
                                 - max(px - pw / 2, cx - w / 2), 0)
                        iy = max(min(py + ph / 2, cy + h / 2)
                                 - max(py - ph / 2, cy - h / 2), 0)
                        inter = ix * iy
                        best_iou = max(best_iou, inter /
                                       (pw * ph + w * h - inter + 1e-10))
                    if obj_t[s, gj, gi] > 0:
                        loss += _sce(xf[n, s, 4, gj, gi], 1.0) \
                            * score_t[s, gj, gi]
                    elif best_iou <= ignore_thresh:
                        loss += _sce(xf[n, s, 4, gj, gi], 0.0)
        out[n] = loss
    return out


def _case(seed=0, gt_score=False, smooth=True, scale_x_y=1.0):
    r = np.random.RandomState(seed)
    N, Cn, H, W = 2, 4, 4, 4
    anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]
    amask = [0, 1, 2]
    S, ds = len(amask), 32
    x = (r.randn(N, S * (5 + Cn), H, W) * 0.2).astype("float32")
    gt = np.zeros((N, 3, 4), "float32")
    gt[0, 0] = [0.3, 0.4, 0.1, 0.15]
    gt[0, 1] = [0.8, 0.7, 0.05, 0.08]
    gt[1, 0] = [0.6, 0.2, 0.25, 0.2]
    gl = np.zeros((N, 3), "int32")
    gl[0, 0], gl[0, 1], gl[1, 0] = 2, 1, 3
    gs = (r.rand(N, 3).astype("float32") * 0.5 + 0.5) if gt_score \
        else None
    ours = np.asarray(yolo_loss(
        paddle.to_tensor(x), paddle.to_tensor(gt), paddle.to_tensor(gl),
        anchors, amask, Cn, 0.7, ds,
        gt_score=paddle.to_tensor(gs) if gs is not None else None,
        use_label_smooth=smooth, scale_x_y=scale_x_y)._value)
    ref = naive_yolo_loss(x, gt, gl, anchors, amask, Cn, 0.7, ds,
                          gt_score=gs, smooth=smooth,
                          scale_x_y=scale_x_y)
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_matches_naive_reference():
    _case(0)


def test_with_mixup_scores_and_no_smooth():
    _case(1, gt_score=True, smooth=False)


def test_scale_x_y():
    _case(2, scale_x_y=1.05)


def test_two_gts_in_one_cell_both_count():
    """Two gts sharing cell AND best anchor: per-gt accumulation means
    both contribute (the scatter-set formulation would drop one)."""
    r = np.random.RandomState(9)
    N, Cn, H, W = 1, 4, 4, 4
    anchors = [10, 13, 16, 30, 33, 23]
    amask = [0, 1, 2]
    x = (r.randn(N, 3 * (5 + Cn), H, W) * 0.2).astype("float32")
    gt = np.zeros((N, 2, 4), "float32")
    gt[0, 0] = [0.3, 0.3, 0.10, 0.12]   # same cell (1,1), similar size
    gt[0, 1] = [0.32, 0.33, 0.11, 0.13]  # -> same best anchor
    gl = np.array([[1, 2]], "int32")
    ours = np.asarray(yolo_loss(
        paddle.to_tensor(x), paddle.to_tensor(gt), paddle.to_tensor(gl),
        anchors, amask, Cn, 0.7, 32)._value)
    ref = naive_yolo_loss(x, gt, gl, anchors, amask, Cn, 0.7, 32)
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_gradient_flows():
    r = np.random.RandomState(3)
    anchors = [10, 13, 16, 30, 33, 23]
    x = Tensor(paddle.to_tensor(
        (r.randn(1, 3 * 9, 4, 4) * 0.2).astype("float32"))._value,
        stop_gradient=False)
    gt = np.zeros((1, 2, 4), "float32")
    gt[0, 0] = [0.4, 0.4, 0.2, 0.2]
    yolo_loss(x, paddle.to_tensor(gt),
              paddle.to_tensor(np.zeros((1, 2), "int32")),
              anchors, [0, 1, 2], 4, 0.7, 32).sum().backward()
    g = np.asarray(x.grad._value)
    assert np.isfinite(g).all() and np.abs(g).max() > 0
