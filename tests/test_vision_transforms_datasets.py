"""ColorJitter/RandomRotation + photometric functional transforms and
folder datasets (reference: vision/transforms/functional.py
adjust_brightness:341/adjust_contrast:381/adjust_saturation:421/
adjust_hue:462/rotate:720; vision/datasets/folder.py DatasetFolder/
ImageFolder)."""
import os
import tempfile

import numpy as np
import pytest

from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder


@pytest.fixture
def img():
    return np.random.RandomState(0).randint(0, 256, (16, 20, 3),
                                            np.uint8)


class TestPhotometric:
    def test_brightness(self, img):
        b = T.adjust_brightness(img, 1.5)
        assert b.dtype == np.uint8
        assert np.allclose(
            b.astype(int),
            np.clip(img.astype(float) * 1.5, 0, 255).astype(int),
            atol=1)

    def test_saturation_zero_is_grayscale(self, img):
        s = T.adjust_saturation(img, 0.0)
        assert np.allclose(s[..., 0].astype(int),
                           s[..., 1].astype(int), atol=1)
        assert np.allclose(s[..., 1].astype(int),
                           s[..., 2].astype(int), atol=1)

    def test_contrast_one_is_identity(self, img):
        c = T.adjust_contrast(img, 1.0)
        assert np.abs(c.astype(int) - img.astype(int)).max() <= 1

    def test_hue_roundtrip(self, img):
        h0 = T.adjust_hue(img, 0.0)
        assert np.abs(h0.astype(int) - img.astype(int)).max() <= 2
        h = T.adjust_hue(img, 0.25)
        assert np.abs(h.astype(int) - img.astype(int)).max() > 5
        with pytest.raises(ValueError):
            T.adjust_hue(img, 0.7)

    def test_color_jitter_runs_and_preserves_shape(self, img):
        out = T.ColorJitter(0.4, 0.4, 0.4, 0.1)(img)
        assert out.shape == img.shape


class TestRotate:
    def test_rotate_90_equals_rot90(self):
        sq = np.random.RandomState(1).randint(0, 255, (9, 9, 3),
                                              np.uint8)
        # PIL/reference convention: positive angle = counter-clockwise
        # on screen = np.rot90(+1) in array terms; pinned so a sign
        # error in the inverse affine map cannot slip through
        assert (T.rotate(sq, 90) == np.rot90(sq, 1)).all()
        assert (T.rotate(sq, -90) == np.rot90(sq, -1)).all()

    def test_rotate_360_identity(self):
        sq = np.random.RandomState(2).randint(0, 255, (8, 8, 3),
                                              np.uint8)
        assert (T.rotate(sq, 360) == sq).all()

    def test_expand_grows_canvas(self):
        sq = np.zeros((10, 20, 3), np.uint8)
        out = T.rotate(sq, 45, expand=True)
        assert out.shape[0] > 10 and out.shape[1] > 20

    def test_pil_parity_expand(self):
        from PIL import Image

        a = np.random.RandomState(5).randint(0, 255, (16, 24), np.uint8)
        for ang in (90, -90):
            pil = np.asarray(Image.fromarray(a).rotate(ang, expand=True))
            ours = T.rotate(a[:, :, None], ang, expand=True)[:, :, 0]
            assert pil.shape == ours.shape and (pil == ours).all()

    def test_random_rotation(self):
        img = np.random.RandomState(3).randint(0, 255, (12, 12, 3),
                                               np.uint8)
        assert T.RandomRotation(30)(img).shape == img.shape


class TestFolderDatasets:
    def _tree(self, d):
        for cls in ("cat", "dog"):
            os.makedirs(os.path.join(d, cls))
            for i in range(3):
                np.save(os.path.join(d, cls, f"{i}.npy"),
                        np.full((4, 4, 3), i, np.uint8))

    def test_dataset_folder(self):
        with tempfile.TemporaryDirectory() as d:
            self._tree(d)
            ds = DatasetFolder(d)
            assert len(ds) == 6
            assert ds.classes == ["cat", "dog"]
            assert ds.class_to_idx == {"cat": 0, "dog": 1}
            img0, y0 = ds[0]
            assert img0.shape == (4, 4, 3) and y0 == 0
            _, y5 = ds[5]
            assert y5 == 1
            # transform applies
            ds2 = DatasetFolder(d, transform=lambda im: im.astype(
                np.float32) / 255.0)
            assert ds2[0][0].dtype == np.float32

    def test_image_folder(self):
        with tempfile.TemporaryDirectory() as d:
            self._tree(d)
            flat = ImageFolder(d)
            assert len(flat) == 6
            assert flat[0][0].shape == (4, 4, 3)

    def test_empty_raises(self):
        with tempfile.TemporaryDirectory() as d:
            with pytest.raises(RuntimeError):
                DatasetFolder(d)
