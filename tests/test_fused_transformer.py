"""FusedMultiTransformer / fused attention layers (reference:
test/legacy_test/test_fused_multi_transformer_op.py — fused vs unfused
parity; decode-vs-full consistency)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import (FusedFeedForward,
                                    FusedMultiHeadAttention,
                                    FusedMultiTransformer)


def test_fused_attention_matches_manual():
    paddle.seed(0)
    B, S, H, NH = 2, 8, 16, 4
    layer = FusedMultiHeadAttention(H, NH, dropout_rate=0.0,
                                    attn_dropout_rate=0.0,
                                    normalize_before=True)
    layer.eval()
    x = paddle.to_tensor(np.random.RandomState(0).randn(B, S, H)
                         .astype("float32"))
    out = layer(x)
    assert out.shape == [B, S, H]

    # manual recomputation
    import jax.numpy as jnp

    from paddle_tpu.nn import functional as F
    from paddle_tpu.ops import manipulation as M
    from paddle_tpu.ops.attention import flash_attention

    h = F.layer_norm(x, layer.pre_ln_scale, layer.pre_ln_bias,
                     epsilon=1e-5)
    qkv = F.linear(h, layer.qkv_weight, layer.qkv_bias)
    qkv = M.reshape(qkv, (B, S, NH, 3 * (H // NH)))
    q, k, v = M.split(qkv, 3, axis=-1)
    a = flash_attention(q, k, v, causal=True)
    a = M.reshape(a, (B, S, H))
    ref = x + F.linear(a, layer.linear_weight, layer.linear_bias)
    np.testing.assert_allclose(np.asarray(out._value),
                               np.asarray(ref._value), rtol=1e-5,
                               atol=1e-5)


def test_fused_feedforward_runs_and_grads():
    paddle.seed(1)
    ffn = FusedFeedForward(16, 64, dropout_rate=0.0,
                           normalize_before=True, activation="gelu")
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 6, 16)
                         .astype("float32"), stop_gradient=False)
    out = ffn(x)
    assert out.shape == [2, 6, 16]
    paddle.mean(out ** 2).backward()
    assert ffn.linear1_weight.grad is not None and x.grad is not None


def test_fused_multi_transformer_decode_consistency():
    """prefill+decode through caches == full causal forward."""
    paddle.seed(2)
    B, S0, H, NH, L = 1, 5, 16, 2, 2
    fmt = FusedMultiTransformer(H, NH, 32, num_layers=L,
                                normalize_before=True)
    fmt.eval()
    rng = np.random.RandomState(3)
    full = rng.randn(B, S0 + 3, H).astype("float32")

    # full forward (no cache)
    ref = np.asarray(fmt(paddle.to_tensor(full))._value)

    # prefill S0 then 3 decode steps
    caches = fmt.empty_caches(B, S0 + 3)
    x, caches = fmt(paddle.to_tensor(full[:, :S0]), caches=caches,
                    time_step=0)
    outs = [np.asarray(x._value)]
    for t in range(3):
        x, caches = fmt(paddle.to_tensor(full[:, S0 + t:S0 + t + 1]),
                        caches=caches, time_step=S0 + t)
        outs.append(np.asarray(x._value))
    stitched = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(stitched, ref, rtol=1e-4, atol=1e-5)
